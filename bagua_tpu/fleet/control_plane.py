"""Multi-tenant fleet control plane: per-gang namespaces over one server.

One :class:`FleetControlPlane` hosts N concurrent gangs.  Each gang gets a
:class:`GangNamespace` — its own rendezvous membership machine + KV + blob
tier (a journaled :class:`~bagua_tpu.distributed.rendezvous.RendezvousState`)
and its own lazily-created
:class:`~bagua_tpu.service.autotune_service.AutotuneService` (so every gang
tunes against its own ``AutotuneTaskManager`` pool, never a neighbor's).
Nothing is shared across gangs except what is *meant* to be shared: the
cross-gang plan cache.

Durability tiers (what the WAL covers):

* **durable** — membership/assignment/epoch, KV, blobs, gang set, the plan
  cache, and the remediation tier (plan adoptions, quarantine/canary
  status, gang directives).  Every mutation is journaled before the
  request is acknowledged; a killed-and-restarted server replays to the
  exact pre-crash state (:meth:`FleetControlPlane.dump` is the bitwise
  witness — remediation state included, so SIGKILL+replay reproduces
  every quarantine and directive the pre-crash engine issued).
* **advisory** — autotune tuning state.  Gangs re-register on reconnect
  (``register_tensors`` already handles restarted gangs), and the part
  worth keeping across jobs — the *winning plan* — is exactly what the
  durable plan cache distills.
* **volatile** — heartbeat ages, lease clocks, token buckets.  Replay
  restarts member ``last_seen`` and leases at *now*: a gang that rode out
  the outage on its retry/breaker machinery must not be reaped for the
  server's own crash.

Lock order (deadlock-free by construction): a gang state's lock and the
fleet lock are never held while waiting on each other; the WAL's lock is a
leaf.  Compaction (which walks every gang) runs only from
:meth:`maybe_compact`, called by the HTTP layer with no locks held.
"""

import base64
import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from bagua_tpu.distributed.rendezvous import RendezvousState
from bagua_tpu.fleet.wal import WriteAheadLog

logger = logging.getLogger("bagua_tpu.fleet")

__all__ = [
    "plan_cache_key",
    "TokenBucket",
    "GangNamespace",
    "FleetControlPlane",
]

#: the plan-cache key dimensions, in canonical order
PLAN_KEY_FIELDS = ("fingerprint", "topology", "algorithm", "wire_precision")


def plan_cache_key(
    fingerprint: str, topology: str, algorithm: str, wire_precision: str
) -> str:
    """Canonical cache key: a plan proven on (model fingerprint, topology,
    algorithm, wire precision) is only valid for an *identical* tuple —
    bucket boundaries depend on the declaration list, and a plan tuned for
    a 32-rank int8 ring says nothing about 8-rank f32."""
    from urllib.parse import quote

    return "/".join(
        quote(str(v), safe="")
        for v in (fingerprint, topology, algorithm, wire_precision)
    )


class TokenBucket:
    """Per-gang admission control (thread-safe).  ``rate`` tokens/second
    refill up to ``burst``; a denied request gets the seconds until one
    token exists — the Retry-After hint.  ``rate <= 0`` admits everything."""

    def __init__(self, rate: float, burst: float, clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._clock = clock
        self._tokens = self.burst
        self._refilled = clock()
        self._lock = threading.Lock()

    def admit(self) -> "tuple[bool, float]":
        """(admitted, retry_after_s)."""
        if self.rate <= 0:
            return True, 0.0
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst, self._tokens + (now - self._refilled) * self.rate)
            self._refilled = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True, 0.0
            return False, (1.0 - self._tokens) / self.rate


class _JournaledState(RendezvousState):
    """A gang's rendezvous state wired into the fleet WAL.

    KV/blob writes journal an idempotent per-op record *inside* the state
    lock (strict replay order).  Membership-mutating entry points re-export
    the durable membership machine after the call, under a dedicated serial
    lock: the export is re-read at append time, so the newest WAL record
    always reflects the newest state even under concurrent joins — full
    replaces, last-write-wins."""

    def __init__(self, gang_id: str, journal: Callable[[dict], None], **kwargs):
        super().__init__(**kwargs)
        self.gang_id = gang_id
        self._journal = journal
        self._journal_serial = threading.Lock()
        self._last_membership: Optional[dict] = None

    # -- membership (journal-after, serialized re-export) ---------------------

    def _journal_membership(self):
        with self._journal_serial:
            snap = self.export_membership()
            if snap != self._last_membership:
                self._last_membership = snap
                self._journal({"op": "rdzv", "gang": self.gang_id, "state": snap})

    def join(self, *args, **kwargs) -> dict:
        out = super().join(*args, **kwargs)
        self._journal_membership()
        return out

    def leave(self, *args, **kwargs) -> dict:
        out = super().leave(*args, **kwargs)
        self._journal_membership()
        return out

    def heartbeat(self, *args, **kwargs) -> dict:
        # A heartbeat itself is volatile, but it can reap/settle.
        out = super().heartbeat(*args, **kwargs)
        self._journal_membership()
        return out

    def request_restart(self, *args, **kwargs) -> dict:
        out = super().request_restart(*args, **kwargs)
        self._journal_membership()
        return out

    def report_crash(self, *args, **kwargs) -> dict:
        out = super().report_crash(*args, **kwargs)
        self._journal_membership()
        return out

    def assignment(self) -> dict:
        out = super().assignment()
        self._journal_membership()  # assignment() may reap/settle
        return out

    # -- KV / blobs (journal-in-lock, per-op) ---------------------------------

    def kv_set(self, key: str, value) -> None:
        with self._lock:
            self._kv[key] = value
            self._journal(
                {"op": "kv", "gang": self.gang_id, "key": key, "value": value}
            )

    def blob_set(self, key: str, data: bytes) -> None:
        with self._lock:
            old = self._blobs.pop(key, None)
            if old is not None:
                self._blob_bytes -= len(old)
            self._blobs[key] = data
            self._blob_bytes += len(data)
            while self._blob_bytes > self.max_blob_bytes and len(self._blobs) > 1:
                _, evicted = self._blobs.popitem(last=False)
                self._blob_bytes -= len(evicted)
            self._journal(
                {"op": "blob", "gang": self.gang_id, "key": key,
                 "b64": base64.b64encode(data).decode("ascii")}
            )

    def blob_get(self, key: str) -> "Optional[bytes]":
        # No LRU touch (unlike the single-tenant base class): reads are not
        # journaled, so eviction order must be a pure function of the
        # journaled sets (FIFO by insertion, re-set moves to the back — the
        # exact order replay_blob reconstructs) or a replayed server would
        # evict a different key than the one it ran before the crash.
        with self._lock:
            return self._blobs.get(key)

    # -- replay (no journaling) -----------------------------------------------

    def replay_kv(self, key: str, value) -> None:
        with self._lock:
            self._kv[key] = value

    def replay_blob(self, key: str, data: bytes) -> None:
        RendezvousState.blob_set(self, key, data)

    def replay_membership(self, snap: dict) -> None:
        self.restore_membership(snap)
        with self._journal_serial:
            self._last_membership = snap


class GangNamespace:
    """One gang's slice of the control plane."""

    def __init__(
        self,
        gang_id: str,
        journal: Callable[[dict], None],
        rdzv_kwargs: Optional[dict] = None,
        autotune_kwargs: Optional[dict] = None,
    ):
        self.gang_id = gang_id
        self.rendezvous = _JournaledState(gang_id, journal, **(rdzv_kwargs or {}))
        self._autotune_kwargs = dict(autotune_kwargs or {})
        self._autotune = None
        self._autotune_lock = threading.Lock()

    def autotune_service(self, world_size: Optional[int] = None):
        """This gang's private AutotuneService (own ``AutotuneTaskManager``
        pool), created on first use.  ``world_size`` only matters at
        creation (the sampling quorum); later calls ignore it."""
        with self._autotune_lock:
            if self._autotune is None:
                from bagua_tpu.env import (
                    get_autotune_max_samples,
                    get_autotune_sampling_confidence_time_s,
                    get_autotune_warmup_time_s,
                )
                from bagua_tpu.service.autotune_service import AutotuneService

                kwargs = dict(
                    autotune_level=1,
                    max_samples=get_autotune_max_samples(),
                    sampling_confidence_time_s=get_autotune_sampling_confidence_time_s(),
                    warmup_time_s=get_autotune_warmup_time_s(),
                )
                kwargs.update(self._autotune_kwargs)
                self._autotune = AutotuneService(
                    world_size=int(world_size or 1), **kwargs
                )
            return self._autotune

    @property
    def autotune_models(self) -> List[str]:
        with self._autotune_lock:
            if self._autotune is None:
                return []
            return sorted(self._autotune._managers)


class FleetControlPlane:
    """The whole fleet's shared state: gang namespaces, leases, admission
    control, the cross-gang plan cache, the WAL, the scheduler view."""

    def __init__(
        self,
        wal_dir: Optional[str] = None,
        lease_ttl_s: Optional[float] = None,
        rate: Optional[float] = None,
        burst: Optional[float] = None,
        compact_every: int = 1000,
        fsync: bool = False,
        rdzv_kwargs: Optional[dict] = None,
        autotune_kwargs: Optional[dict] = None,
        clock: Callable[[], float] = time.monotonic,
        canary_n: int = 2,
    ):
        from bagua_tpu.env import (
            get_fleet_burst, get_fleet_lease_ttl_s, get_fleet_rate_limit,
        )

        self.lease_ttl_s = get_fleet_lease_ttl_s() if lease_ttl_s is None else float(lease_ttl_s)
        self.rate = get_fleet_rate_limit() if rate is None else float(rate)
        self.burst = get_fleet_burst() if burst is None else float(burst)
        self.rdzv_kwargs = dict(rdzv_kwargs or {})
        self.autotune_kwargs = dict(autotune_kwargs or {})
        self._clock = clock
        self._lock = threading.RLock()
        self._gangs: Dict[str, GangNamespace] = {}
        self._leases: Dict[str, float] = {}  # gang_id -> lease deadline
        self._buckets: Dict[str, TokenBucket] = {}
        self._plans: Dict[str, dict] = {}  # cache key -> {"plan", "meta"}
        self._last_sweep = clock()
        self._replaying = False
        self.gangs_gcd = 0
        self.backpressure_denials = 0
        # tracing + metrics tier — volatile by design: span rings and
        # request counters restart empty (like leases and token buckets)
        # and must NEVER appear in dump()/the WAL, or the kill/restart
        # bitwise witness would diff on observability noise.
        self._server_spans: Dict[str, deque] = {}   # gang -> finished server spans
        self._client_spans: Dict[str, deque] = {}   # gang -> ingested client spans
        self._timeline_events: Dict[str, deque] = {}  # gang -> ingested events
        self._incidents: Dict[str, deque] = {}  # gang -> perf_regression events
        self._decisions: Dict[str, deque] = {}  # gang -> plan_decision events
        self._request_counts: Dict[str, int] = {}
        self._deny_counts: Dict[str, int] = {}
        self._incident_counts: Dict[str, int] = {}
        self._decision_counts: Dict[str, int] = {}
        self.plan_hits = 0
        self.plan_misses = 0
        # -- remediation tier (durable): plan adoption/quarantine/canary
        # status + per-gang directives.  Journaled like the plan cache so
        # SIGKILL+replay reproduces the same remediation state bitwise.
        self.canary_n = max(1, int(canary_n))
        self._rem: dict = {"plans": {}, "directives": {}, "actions": {}}
        #: wall time of the last WAL replay (volatile; /fleet/metrics gauge)
        self.wal_replay_ms = 0.0
        self.wal = WriteAheadLog(wal_dir, compact_every=compact_every, fsync=fsync) if wal_dir else None
        if self.wal is not None:
            self._replay()

    # -- WAL ------------------------------------------------------------------

    def journal(self, record: dict) -> None:
        if self.wal is None or self._replaying:
            return
        self.wal.append(record)

    def maybe_compact(self) -> bool:
        """Fold the WAL into a snapshot when due.  Called with no locks
        held (the HTTP layer, after replying): the full-fleet dump below
        takes the fleet lock and every gang lock in turn.  The WAL cursor
        is captured *before* the dump — handler threads keep acknowledging
        mutations while we walk the gangs, and anything they journal past
        the cursor must outlive the compaction in the rewritten log."""
        if self.wal is None or not self.wal.needs_compact():
            return False
        as_of = self.wal.cursor()
        self.wal.compact(self._snapshot_state(), as_of_seq=as_of)
        logger.info("WAL compacted (#%d)", self.wal.compactions)
        return True

    def _snapshot_state(self) -> dict:
        import json as _json

        with self._lock:
            gangs = dict(self._gangs)
            plans = {k: dict(v) for k, v in self._plans.items()}
            # deep copy via JSON round-trip: the snapshot must not alias
            # live remediation dicts a concurrent sweep keeps mutating
            remediation = _json.loads(_json.dumps(self._rem))
        state = {"plans": plans, "gangs": {}, "remediation": remediation}
        for gang_id, ns in sorted(gangs.items()):
            st = ns.rendezvous
            with st._lock:
                kv = dict(st._kv)
                blobs = {
                    k: base64.b64encode(v).decode("ascii")
                    for k, v in st._blobs.items()
                }
            state["gangs"][gang_id] = {
                "rdzv": st.export_membership(),
                "kv": kv,
                "blobs": blobs,
            }
        return state

    def _replay(self) -> None:
        t0 = time.perf_counter()
        snapshot, records = self.wal.load()
        self._replaying = True
        try:
            if snapshot:
                for key, entry in snapshot.get("plans", {}).items():
                    self._plans[key] = dict(entry)
                rem = snapshot.get("remediation")
                if isinstance(rem, dict):
                    self._rem = {
                        "plans": dict(rem.get("plans", {})),
                        "directives": dict(rem.get("directives", {})),
                        "actions": dict(rem.get("actions", {})),
                    }
                for gang_id, gs in snapshot.get("gangs", {}).items():
                    ns = self._ensure_gang(gang_id)
                    ns.rendezvous.replay_membership(gs.get("rdzv", {}))
                    for k, v in gs.get("kv", {}).items():
                        ns.rendezvous.replay_kv(k, v)
                    for k, b64 in gs.get("blobs", {}).items():
                        ns.rendezvous.replay_blob(k, base64.b64decode(b64))
            for rec in records:
                self._apply(rec)
        finally:
            self._replaying = False
        self.wal_replay_ms = round((time.perf_counter() - t0) * 1e3, 3)
        if snapshot or records:
            logger.info(
                "WAL replay: %d gangs, %d cached plans, %d records past "
                "snapshot (%.1f ms)",
                len(self._gangs), len(self._plans), len(records),
                self.wal_replay_ms,
            )

    #: WAL ops owned by the remediation tier (dispatched to _rem_apply)
    _REM_OPS = ("adopt", "quarantine", "canary", "plan_status",
                "directive", "directive_ack")

    def _apply(self, rec: dict) -> None:
        op = rec.get("op")
        if op == "gang":
            self._ensure_gang(rec["gang"])
        elif op == "gang_gc":
            self._gangs.pop(rec["gang"], None)
            self._leases.pop(rec["gang"], None)
            self._buckets.pop(rec["gang"], None)
            self._rem["directives"].pop(rec["gang"], None)
        elif op == "rdzv":
            self._ensure_gang(rec["gang"]).rendezvous.replay_membership(rec["state"])
        elif op == "kv":
            self._ensure_gang(rec["gang"]).rendezvous.replay_kv(rec["key"], rec["value"])
        elif op == "blob":
            self._ensure_gang(rec["gang"]).rendezvous.replay_blob(
                rec["key"], base64.b64decode(rec["b64"])
            )
        elif op == "plan":
            self._plans[rec["key"]] = dict(rec["entry"])
            self._rem_plan_init(rec["key"], rec["entry"])
        elif op in self._REM_OPS:
            self._rem_apply(rec)
        else:
            logger.warning("WAL replay: unknown op %r (skipped)", op)

    # -- gang namespaces, leases, admission -----------------------------------

    def _ensure_gang(self, gang_id: str) -> GangNamespace:
        with self._lock:
            ns = self._gangs.get(gang_id)
            if ns is None:
                ns = GangNamespace(
                    gang_id,
                    self.journal,
                    rdzv_kwargs=self.rdzv_kwargs,
                    autotune_kwargs=self.autotune_kwargs,
                )
                self._gangs[gang_id] = ns
                self.journal({"op": "gang", "gang": gang_id})
                if not self._replaying:
                    logger.info("gang %r: namespace created", gang_id)
            self._leases[gang_id] = self._clock() + self.lease_ttl_s
            return ns

    def gang(self, gang_id: str) -> GangNamespace:
        """Resolve (creating on first touch) a gang's namespace; touches
        its lease and opportunistically sweeps expired neighbors."""
        self.sweep_leases()
        return self._ensure_gang(gang_id)

    def admit(self, gang_id: str) -> "tuple[bool, float]":
        """Token-bucket admission for one request; (admitted, retry_after_s)."""
        with self._lock:
            bucket = self._buckets.get(gang_id)
            if bucket is None:
                bucket = self._buckets[gang_id] = TokenBucket(
                    self.rate, self.burst, clock=self._clock
                )
        ok, retry_after = bucket.admit()
        if not ok:
            with self._lock:
                self.backpressure_denials += 1
                # A denial must not starve the lease: admission runs before
                # fleet.gang(), so a live gang held in backpressure (or
                # pacing on Retry-After) past the TTL would otherwise get
                # its whole durable namespace reaped.  Touch known gangs
                # only — a denied request never *creates* a namespace.
                if gang_id in self._gangs:
                    self._leases[gang_id] = self._clock() + self.lease_ttl_s
        return ok, retry_after

    def sweep_leases(self, min_interval_s: float = 1.0) -> List[str]:
        """Reap gangs whose lease expired: drop the namespace (KV, blobs,
        membership, autotune managers — all of it) and journal the GC so a
        restart doesn't resurrect the dead.  Rate-limited; returns the
        reaped gang ids."""
        now = self._clock()
        reaped = []
        with self._lock:
            if now - self._last_sweep < min_interval_s:
                return reaped
            self._last_sweep = now
            for gang_id, deadline in list(self._leases.items()):
                if now > deadline:
                    reaped.append(gang_id)
                    self._gangs.pop(gang_id, None)
                    self._leases.pop(gang_id, None)
                    self._buckets.pop(gang_id, None)
                    # pending directives die with the namespace (same fate
                    # on replay: _apply("gang_gc") pops the same key)
                    self._rem["directives"].pop(gang_id, None)
                    self.gangs_gcd += 1
                    # Journal inside the removal's critical section (the WAL
                    # lock is a leaf, so this is deadlock-free): journaling
                    # after releasing the fleet lock would let a concurrent
                    # recreation journal its gang/kv records first, and
                    # replay would then GC a gang the pre-crash server
                    # considered alive.
                    self.journal({"op": "gang_gc", "gang": gang_id})
        for gang_id in reaped:
            logger.warning("gang %r: lease expired; namespace GC'd", gang_id)
        return reaped

    def gang_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._gangs)

    # -- cross-gang plan cache -------------------------------------------------

    def plan_put(
        self,
        fingerprint: str,
        topology: str,
        algorithm: str,
        wire_precision: str,
        plan: dict,
        meta: Optional[dict] = None,
    ) -> str:
        key = plan_cache_key(fingerprint, topology, algorithm, wire_precision)
        entry = {
            "plan": plan,
            "meta": dict(meta or {}),
            "key": {
                "fingerprint": str(fingerprint),
                "topology": str(topology),
                "algorithm": str(algorithm),
                "wire_precision": str(wire_precision),
            },
        }
        with self._lock:
            self._plans[key] = entry
            self.journal({"op": "plan", "key": key, "entry": entry})
            self._rem_plan_init(key, entry)
        logger.info("plan cache: stored %s", key)
        return key

    def plan_get(
        self,
        fingerprint: str,
        topology: str,
        algorithm: str,
        wire_precision: str,
        gang: Optional[str] = None,
    ) -> Optional[dict]:
        """Cache lookup.  With a ``gang`` identity the remediation tier
        gates the entry (a quarantined plan is never served; a canary plan
        is served only to its cohort until it graduates) and the adoption
        is journaled — the correlation record the :class:`RemediationEngine`
        sweeps.  ``gang=None`` is the legacy read-only path: no adoption is
        recorded and canary gating does not apply (quarantine still does)."""
        key = plan_cache_key(fingerprint, topology, algorithm, wire_precision)
        with self._lock:
            entry = self._plans.get(key)
            if entry is None:
                self.plan_misses += 1
                return None
            rec = self._rem["plans"].get(key)
            if rec is not None:
                if rec["status"] == "quarantined":
                    self.plan_misses += 1
                    return None
                if gang is not None:
                    if (
                        rec["status"] == "canary"
                        and gang not in rec["cohort"]
                        and len(rec["cohort"]) >= self.canary_n
                    ):
                        # cohort is full: withheld until the canaries report
                        # clean windows and the plan graduates to default
                        self.plan_misses += 1
                        return None
                    if gang not in rec["adopters"]:
                        self._rem_record({
                            "op": "adopt",
                            "key": key,
                            "gang": str(gang),
                            "plan_version": rec["plan_version"],
                            "cohort_add": bool(
                                rec["status"] == "canary"
                                and gang not in rec["cohort"]
                            ),
                        })
            self.plan_hits += 1
            return dict(entry)

    def plan_count(self) -> int:
        with self._lock:
            return len(self._plans)

    # -- remediation tier (durable) ---------------------------------------------

    def _rem_record(self, rec: dict) -> None:
        """Journal one remediation op, then apply it — the single mutation
        path shared by the live API and WAL replay (``journal`` is a no-op
        while replaying), so both produce identical state."""
        self.journal(rec)
        self._rem_apply(rec)

    def _rem_plan_init(self, key: str, entry: dict) -> None:
        """(Re)published plan: a fresh ``plan_version`` starts its canary
        lifecycle; republishing the same version keeps the current status —
        a quarantined version cannot launder itself by republication."""
        plan_version = int((entry.get("meta") or {}).get("plan_version", 0))
        rec = self._rem["plans"].get(key)
        if rec is None or rec.get("plan_version") != plan_version:
            self._rem["plans"][key] = {
                "status": "canary",
                "plan_version": plan_version,
                "adopters": {},
                "cohort": [],
                "clean": [],
            }

    def _rem_apply(self, rec: dict) -> None:
        op = rec["op"]
        if op == "adopt":
            plan = self._rem["plans"].get(rec["key"])
            if plan is not None:
                plan["adopters"][rec["gang"]] = rec["plan_version"]
                if rec.get("cohort_add") and rec["gang"] not in plan["cohort"]:
                    plan["cohort"].append(rec["gang"])
        elif op == "quarantine":
            plan = self._rem["plans"].get(rec["key"])
            if plan is not None and plan["status"] != "quarantined":
                plan["status"] = "quarantined"
                plan["cites"] = list(rec.get("cites", []))
                actions = self._rem["actions"]
                actions["quarantine"] = actions.get("quarantine", 0) + 1
        elif op == "canary":
            plan = self._rem["plans"].get(rec["key"])
            if plan is not None and rec["gang"] not in plan["clean"]:
                plan["clean"].append(rec["gang"])
        elif op == "plan_status":
            plan = self._rem["plans"].get(rec["key"])
            if plan is not None and plan["status"] != rec["status"]:
                if plan["status"] == "canary" and rec["status"] == "default":
                    actions = self._rem["actions"]
                    actions["canary_graduate"] = actions.get("canary_graduate", 0) + 1
                plan["status"] = rec["status"]
        elif op == "directive":
            lst = self._rem["directives"].setdefault(rec["gang"], [])
            lst.append(dict(rec["directive"]))
            action = rec["directive"].get("action", "unknown")
            actions = self._rem["actions"]
            actions[action] = actions.get(action, 0) + 1
        elif op == "directive_ack":
            for d in self._rem["directives"].get(rec["gang"], []):
                if d["id"] == rec["id"]:
                    d["acked"] = True

    def plan_statuses(self) -> Dict[str, dict]:
        """Deep copy of every plan's remediation record (status,
        plan_version, adopters, canary cohort, clean reporters)."""
        import json as _json

        with self._lock:
            return _json.loads(_json.dumps(self._rem["plans"]))

    def mark_plan_quarantined(self, key: str, cites) -> bool:
        """Quarantine one cached plan (idempotent; False when the key is
        unknown or already quarantined).  ``cites`` are the indicting
        incidents' trace_ids — journaled with the quarantine so the
        evidence chain survives SIGKILL+replay."""
        with self._lock:
            rec = self._rem["plans"].get(key)
            if rec is None or rec["status"] == "quarantined":
                return False
            self._rem_record({
                "op": "quarantine", "key": key,
                "cites": [str(t) for t in cites],
            })
        logger.warning("plan cache: QUARANTINED %s (cited: %s)", key, list(cites))
        return True

    def record_canary_clean(self, key: str, gang: str) -> Optional[str]:
        """One canary adopter reported a clean window.  Returns ``"clean"``
        (recorded), ``"graduated"`` (this report met ``canary_n`` and the
        plan was promoted to default), or None (not a canary adopter /
        already counted)."""
        with self._lock:
            rec = self._rem["plans"].get(key)
            if (
                rec is None or rec["status"] != "canary"
                or gang not in rec["cohort"] or gang in rec["clean"]
            ):
                return None
            self._rem_record({"op": "canary", "key": key, "gang": str(gang)})
            if len(rec["clean"]) >= self.canary_n:
                self._rem_record({"op": "plan_status", "key": key,
                                  "status": "default"})
                logger.info("plan cache: %s graduated canary -> default", key)
                return "graduated"
            return "clean"

    def issue_directive(
        self, gang_id: str, action: str, reason: str = "",
        detail: Optional[dict] = None,
    ) -> dict:
        """Durably direct one gang (``rollback_plan``, ``resize``, ...).
        The gang polls ``GET /g/<gang>/directive`` and acks; unacked
        directives surface as the scheduler view's remediation-pending
        marker."""
        with self._lock:
            lst = self._rem["directives"].get(gang_id, [])
            directive = {
                "id": 1 + max((d["id"] for d in lst), default=0),
                "action": str(action),
                "reason": str(reason),
                "acked": False,
            }
            if detail:
                directive["detail"] = dict(detail)
            self._rem_record({"op": "directive", "gang": str(gang_id),
                              "directive": directive})
            return dict(directive)

    def directive(self, gang_id: str) -> Optional[dict]:
        """The gang's oldest pending (unacked) directive, or None."""
        with self._lock:
            for d in self._rem["directives"].get(gang_id, []):
                if not d["acked"]:
                    return dict(d)
            return None

    def ack_directive(self, gang_id: str, directive_id: int) -> bool:
        with self._lock:
            for d in self._rem["directives"].get(gang_id, []):
                if d["id"] == int(directive_id) and not d["acked"]:
                    self._rem_record({"op": "directive_ack",
                                      "gang": str(gang_id),
                                      "id": int(directive_id)})
                    return True
            return False

    def pending_directives(self, gang_id: str) -> List[dict]:
        with self._lock:
            return [dict(d) for d in self._rem["directives"].get(gang_id, [])
                    if not d["acked"]]

    def remediation_summary(self) -> dict:
        """Deep copy of the whole durable remediation tier (the
        ``GET /fleet/remediation`` route)."""
        import json as _json

        with self._lock:
            out = _json.loads(_json.dumps(self._rem))
        out["canary_n"] = self.canary_n
        return out

    def flight_digests(self, gang_id: str) -> List[dict]:
        """The gang's pushed flight digests for its most-advanced attempt —
        the pseudo-dumps the RemediationEngine joins through
        ``build_hang_report`` when the gang goes ``wedged``."""
        with self._lock:
            ns = self._gangs.get(gang_id)
        if ns is None:
            return []
        st = ns.rendezvous
        by_attempt: Dict[str, List[dict]] = {}
        for key in st.kv_keys():
            parts = key.split("/")
            if key.startswith("bagua/flight/") and len(parts) == 4:
                digest = st.kv_get(key)
                if isinstance(digest, dict):
                    by_attempt.setdefault(parts[2], []).append(digest)
        if not by_attempt:
            return []
        def _advance(attempt: str) -> int:
            return max(
                (d["last_seq"] for d in by_attempt[attempt]
                 if isinstance(d.get("last_seq"), int)),
                default=-1,
            )
        return by_attempt[max(by_attempt, key=_advance)]

    def remediate(self, **knobs) -> dict:
        """Run one RemediationEngine sweep over this plane (the
        ``POST /fleet/remediate`` route)."""
        from bagua_tpu.fleet.remediation import RemediationEngine

        return RemediationEngine(self, **knobs).sweep()

    def shard_info(self) -> dict:
        """Shard topology view (one unsharded plane = one shard)."""
        with self._lock:
            n_gangs = len(self._gangs)
        return {
            "n_shards": 1,
            "gangs_per_shard": [n_gangs],
            "wal_replay_ms": [self.wal_replay_ms],
        }

    # -- scheduler view ---------------------------------------------------------

    def scheduler_view(self) -> dict:
        """Fleet-wide verdicts from the streams gangs already push: per-gang
        ``wedged`` (a flight digest landed — some rank dumped its black box)
        > ``straggler`` (StepSummary p50 spread past the threshold) >
        ``regressed`` (the gang's regression sentinel pushed a
        ``perf_regression`` incident) > ``healthy`` (summaries, no
        findings) > ``idle`` (nothing pushed)."""
        from bagua_tpu.observability.aggregate import StepSummary, straggler_score

        self.sweep_leases()
        now = self._clock()
        with self._lock:
            gangs = dict(self._gangs)
            leases = dict(self._leases)
            incidents_by_gang = {g: list(ring) for g, ring in self._incidents.items()}
            decisions_by_gang = {g: list(ring) for g, ring in self._decisions.items()}
            pending_by_gang = {
                g: [dict(d) for d in lst if not d["acked"]]
                for g, lst in self._rem["directives"].items()
            }
        view = {"gangs": {}, "n_gangs": len(gangs)}
        for gang_id, ns in sorted(gangs.items()):
            st = ns.rendezvous
            # group pushed summaries by attempt nonce; judge the newest
            # attempt (max settled step) — dead incarnations' numbers stay
            by_attempt: Dict[str, List[StepSummary]] = {}
            flight_ranks = []
            for key in st.kv_keys():
                parts = key.split("/")
                if key.startswith("bagua/obs/") and len(parts) == 4:
                    try:
                        summary = StepSummary.from_payload(st.kv_get(key))
                    except (TypeError, ValueError):
                        continue
                    by_attempt.setdefault(parts[2], []).append(summary)
                elif key.startswith("bagua/flight/") and len(parts) == 4:
                    flight_ranks.append(parts[3])
            summaries: List[StepSummary] = []
            if by_attempt:
                attempt = max(by_attempt, key=lambda a: max(s.step for s in by_attempt[a]))
                summaries = by_attempt[attempt]
            straggler = straggler_score(summaries) if summaries else None
            # per-rank score vector (p50 / gang median) — who is how far off,
            # not only who crossed the threshold; same math as
            # GangView.rank_scores so the scheduler and gang views agree
            rank_scores = {}
            if len(summaries) >= 2:
                import statistics as _stats

                median = _stats.median(s.p50_ms for s in summaries)
                if median > 0:
                    rank_scores = {
                        str(s.rank): round(s.p50_ms / median, 4) for s in summaries
                    }
            incidents = incidents_by_gang.get(gang_id, [])
            if flight_ranks:
                verdict = "wedged"
            elif straggler is not None:
                verdict = "straggler"
            elif incidents:
                verdict = "regressed"
            elif summaries:
                verdict = "healthy"
            else:
                verdict = "idle"
            last = incidents[-1] if incidents else None
            decisions = decisions_by_gang.get(gang_id, [])
            last_dec = decisions[-1] if decisions else None
            asn = st.export_membership()
            settled = asn.get("settled")
            view["gangs"][gang_id] = {
                "verdict": verdict,
                "straggler": straggler,
                "rank_scores": rank_scores,
                "regressed": bool(incidents),
                "incidents": len(incidents),
                "last_incident": (
                    {"step": last.get("step"), "dominant": last.get("dominant"),
                     "stream": last.get("stream"),
                     # axis-resolved incidents name the mesh axis and link
                     # class (ici/dcn) the sentinel indicted
                     **({"axis": last["axis"]} if last.get("axis") else {}),
                     **({"link_class": last["link_class"]}
                        if last.get("link_class") else {})}
                    if isinstance(last, dict) else None
                ),
                # what the gang's autopilot last did about its incidents —
                # None means no controller is attached (or it never spoke)
                "autopilot": (
                    {"decision": last_dec.get("decision"),
                     "verdict": last_dec.get("verdict"),
                     "step": last_dec.get("step"),
                     "to_config": last_dec.get("to_config"),
                     **({"axis": last_dec["axis"]}
                        if last_dec.get("axis") else {})}
                    if isinstance(last_dec, dict) else None
                ),
                "decisions": len(decisions),
                # remediation-pending marker: the engine already directed
                # this gang and the directive is not yet acked.  A marker,
                # not a verdict rung — the ladder above is unchanged.
                "remediation": (
                    {"pending": len(pending_by_gang[gang_id]),
                     "action": pending_by_gang[gang_id][0]["action"],
                     "id": pending_by_gang[gang_id][0]["id"]}
                    if pending_by_gang.get(gang_id) else None
                ),
                "flight_ranks": sorted(flight_ranks),
                "ranks_reporting": len(summaries),
                "max_step": max((s.step for s in summaries), default=-1),
                "n_members": len(asn.get("members", [])),
                "epoch": asn.get("epoch", 0),
                "generation": asn.get("generation", 0),
                "world_size": settled.get("world_size") if settled else None,
                "lease_remaining_s": round(leases.get(gang_id, now) - now, 3),
            }
        return view

    # -- tracing (volatile tier) -------------------------------------------------

    #: per-gang span-ring capacity; old spans fall off — this is a flight
    #: recorder for the RPC tier, not an archive
    SPAN_RING = 512

    def _ring(self, store: Dict[str, deque], gang_id: str) -> deque:
        with self._lock:
            ring = store.get(gang_id)
            if ring is None:
                ring = store[gang_id] = deque(maxlen=self.SPAN_RING)
            return ring

    def record_server_span(
        self,
        gang_id: str,
        route: str,
        status: int,
        dur_ms: float,
        traceparent: Optional[str] = None,
        retry_after_s: Optional[float] = None,
    ) -> dict:
        """One handled HTTP request as a server-side span.  With a valid
        ``traceparent`` the span joins the caller's trace as a child of the
        in-flight client span; without one it's a root (unattributed
        traffic still shows on the timeline).  Also feeds the per-gang
        request/deny counters ``/fleet/metrics`` exports."""
        from bagua_tpu.observability.tracing import (
            new_span_id, new_trace_id, parse_traceparent,
        )

        ctx = parse_traceparent(traceparent)
        span = {
            "schema": "bagua.span.v1",
            "trace_id": ctx["trace_id"] if ctx else new_trace_id(),
            "span_id": new_span_id(),
            "name": f"http {route}",
            "kind": "server",
            "ts": round(time.time() - max(0.0, float(dur_ms)) / 1e3, 6),
            "dur_ms": round(max(0.0, float(dur_ms)), 4),
            "attrs": {
                "service": "fleet-server",
                "gang": str(gang_id),
                "route": str(route),
                "status": int(status),
            },
        }
        if ctx:
            span["parent_id"] = ctx["span_id"]
        if int(status) == 429:
            span["annotations"] = [{
                "name": "backpressure", "ts": round(time.time(), 6),
                "retry_after_s": round(float(retry_after_s or 0.0), 3),
            }]
        self._ring(self._server_spans, gang_id).append(span)
        with self._lock:
            self._request_counts[gang_id] = self._request_counts.get(gang_id, 0) + 1
            if int(status) == 429:
                self._deny_counts[gang_id] = self._deny_counts.get(gang_id, 0) + 1
        return span

    def ingest_spans(self, gang_id: str, spans, events=None) -> dict:
        """Client-side span batch (the ``/g/<gang>/spans`` route): each
        valid ``bagua.span.v1`` dict lands in the gang's volatile client
        ring; malformed ones are counted and dropped (a trace must never
        poison the control plane).  ``events`` (plain dicts with a ``ts``)
        ride a separate ring so hang/health/rpc_retry events can appear on
        the timeline next to the spans that caused them."""
        from bagua_tpu.observability.tracing import validate_span

        accepted = rejected = 0
        ring = self._ring(self._client_spans, gang_id)
        for span in spans or []:
            if validate_span(span):
                rejected += 1
                continue
            ring.append(dict(span))
            accepted += 1
        ev_ring = self._ring(self._timeline_events, gang_id)
        n_events = 0
        for ev in events or []:
            if isinstance(ev, dict):
                ev_ring.append(dict(ev))
                n_events += 1
        return {"accepted": accepted, "rejected": rejected, "events": n_events}

    def ingest_incidents(self, gang_id: str, incidents) -> dict:
        """A batch of regression-sentinel ``perf_regression`` incidents
        (the ``POST /g/<gang>/incidents`` route).  Same volatile contract
        as the span rings: a bounded per-gang deque, never in the WAL or
        ``dump()``, restarts empty.  An incident must at least carry a
        ``step`` and a ``dominant`` component; anything else is counted
        and dropped (a malformed verdict must never poison the control
        plane)."""
        accepted = rejected = 0
        ring = self._ring(self._incidents, gang_id)
        for inc in incidents or []:
            if (not isinstance(inc, dict) or "step" not in inc
                    or not isinstance(inc.get("dominant"), str)):
                rejected += 1
                continue
            ring.append(dict(inc))
            accepted += 1
        if accepted:
            with self._lock:
                self._incident_counts[gang_id] = (
                    self._incident_counts.get(gang_id, 0) + accepted
                )
        return {"accepted": accepted, "rejected": rejected}

    def ingest_decisions(self, gang_id: str, decisions) -> dict:
        """A batch of autopilot ``plan_decision`` events (the
        ``POST /g/<gang>/decisions`` route).  Volatile like the incident
        tier: bounded per-gang deque, never in the WAL or ``dump()``.  A
        decision must carry string ``decision`` and ``verdict`` fields;
        anything else is counted and dropped."""
        accepted = rejected = 0
        ring = self._ring(self._decisions, gang_id)
        for dec in decisions or []:
            if (not isinstance(dec, dict)
                    or not isinstance(dec.get("decision"), str)
                    or not isinstance(dec.get("verdict"), str)):
                rejected += 1
                continue
            ring.append(dict(dec))
            accepted += 1
        if accepted:
            with self._lock:
                self._decision_counts[gang_id] = (
                    self._decision_counts.get(gang_id, 0) + accepted
                )
        return {"accepted": accepted, "rejected": rejected}

    def decisions(self, gang_id: Optional[str] = None) -> dict:
        """The volatile decision tier (the ``GET /fleet/decisions`` route):
        every gang's recent autopilot ``plan_decision`` events, or one
        gang's when ``gang_id`` is given."""
        with self._lock:
            if gang_id is not None:
                rows = list(self._decisions.get(gang_id, ()))
                return {"gang": str(gang_id), "decisions": rows,
                        "n_decisions": len(rows)}
            gangs = {g: list(ring) for g, ring in sorted(self._decisions.items())
                     if ring}
        return {"gangs": gangs,
                "n_decisions": sum(len(v) for v in gangs.values())}

    def incidents(self, gang_id: Optional[str] = None) -> dict:
        """The volatile incident tier (the ``GET /fleet/incidents`` route):
        every gang's recent ``perf_regression`` events, or one gang's when
        ``gang_id`` is given."""
        with self._lock:
            if gang_id is not None:
                rows = list(self._incidents.get(gang_id, ()))
                return {"gang": str(gang_id), "incidents": rows,
                        "n_incidents": len(rows)}
            gangs = {g: list(ring) for g, ring in sorted(self._incidents.items())
                     if ring}
        return {"gangs": gangs,
                "n_incidents": sum(len(v) for v in gangs.values())}

    def timeline(self, gang_id: str) -> dict:
        """The gang's joined, causally ordered timeline: client spans
        (ingested), server spans (recorded per request), StepSummary
        windows and flight digests (from the gang KV), and ingested
        events — one flat ``items`` list ordered by wall clock, plus a
        ``traces`` index listing each trace's spans parent-before-child
        (the client→server chain the CI lane asserts)."""
        from bagua_tpu.observability.aggregate import StepSummary

        with self._lock:
            ns = self._gangs.get(gang_id)
            client = list(self._client_spans.get(gang_id, ()))
            server = list(self._server_spans.get(gang_id, ()))
            events = list(self._timeline_events.get(gang_id, ()))
            incidents = list(self._incidents.get(gang_id, ()))
            decisions = list(self._decisions.get(gang_id, ()))
        items = []
        # the discriminator is "item", not "kind" — spans already carry a
        # "kind" of their own (internal/client/server) that must survive
        for span in client:
            items.append({"item": "client_span", "ts": span.get("ts"), **span})
        for span in server:
            items.append({"item": "server_span", "ts": span.get("ts"), **span})
        for ev in events:
            items.append({"item": "event", "ts": ev.get("ts"), **ev})
        for inc in incidents:
            items.append({"item": "incident", "ts": inc.get("ts"), **inc})
        for dec in decisions:
            items.append({"item": "decision", "ts": dec.get("ts"), **dec})
        if ns is not None:
            st = ns.rendezvous
            for key in st.kv_keys():
                parts = key.split("/")
                if key.startswith("bagua/obs/") and len(parts) == 4:
                    try:
                        summary = StepSummary.from_payload(st.kv_get(key))
                    except (TypeError, ValueError):
                        continue
                    items.append({
                        "item": "step_summary", "ts": None,
                        "attempt": parts[2], "rank": summary.rank,
                        "step": summary.step, "p50_ms": summary.p50_ms,
                        "p99_ms": summary.p99_ms, "health": summary.health,
                    })
                elif key.startswith("bagua/flight/") and len(parts) == 4:
                    digest = st.kv_get(key)
                    items.append({
                        "item": "flight_digest", "ts": None,
                        "attempt": parts[2], "rank": parts[3],
                        "digest": digest if isinstance(digest, dict) else {},
                    })
        # wall-clock order; ts-less KV items (summaries/digests) lead —
        # they are windows, not instants
        items.sort(key=lambda it: (it.get("ts") is not None, it.get("ts") or 0.0))
        # per-trace causal chains: parent before child, siblings by ts
        by_trace: Dict[str, List[dict]] = {}
        for span in client + server:
            tid = span.get("trace_id")
            if tid:
                by_trace.setdefault(tid, []).append(span)
        traces = {}
        for tid, spans in by_trace.items():
            children: Dict[Optional[str], List[dict]] = {}
            ids = {s["span_id"] for s in spans}
            for s in spans:
                parent = s.get("parent_id")
                children.setdefault(
                    parent if parent in ids else None, []
                ).append(s)
            ordered: List[dict] = []
            stack = sorted(
                children.get(None, []),
                key=lambda s: s.get("ts") or 0.0, reverse=True,
            )
            while stack:
                s = stack.pop()
                ordered.append(s)
                stack.extend(sorted(
                    children.get(s["span_id"], []),
                    key=lambda c: c.get("ts") or 0.0, reverse=True,
                ))
            traces[tid] = ordered
        return {
            "gang": str(gang_id),
            "items": items,
            "traces": traces,
            "n_client_spans": len(client),
            "n_server_spans": len(server),
            "n_events": len(events),
            "n_incidents": len(incidents),
            "n_decisions": len(decisions),
            "n_traces": len(traces),
        }

    def metrics_registry(self):
        """A fresh registry materializing the fleet's own counters — what
        ``/fleet/metrics`` renders with the shared Prometheus formatter.
        Built per scrape (the live counters are plain ints under the fleet
        lock; a registry would be a second copy to keep coherent)."""
        from bagua_tpu.observability.metrics import MetricsRegistry, _prom_name

        self.sweep_leases()
        now = self._clock()
        with self._lock:
            requests = dict(self._request_counts)
            denials = dict(self._deny_counts)
            incidents = dict(self._incident_counts)
            decisions = dict(self._decision_counts)
            leases = {g: d - now for g, d in self._leases.items() if g in self._gangs}
            n_gangs = len(self._gangs)
            plan_hits, plan_misses = self.plan_hits, self.plan_misses
            total_denials = self.backpressure_denials
            n_plans = len(self._plans)
        r = MetricsRegistry(prefix="bagua_fleet")
        r.gauge("gangs", help="live gang namespaces").set(n_gangs)
        r.gauge("plans_cached", help="entries in the cross-gang plan cache").set(n_plans)
        r.counter("plan_cache_hits_total", help="plan-cache lookup hits").inc(plan_hits)
        r.counter("plan_cache_misses_total", help="plan-cache lookup misses").inc(plan_misses)
        r.counter(
            "backpressure_denials_total", help="requests denied 429 (all gangs)"
        ).inc(total_denials)
        r.counter("requests_total", help="gang requests handled (all gangs)").inc(
            sum(requests.values())
        )
        for gang_id, n in sorted(requests.items()):
            r.counter(
                f"requests_total_{_prom_name(gang_id)}",
                help=f"requests handled for gang {gang_id}",
            ).inc(n)
        for gang_id, n in sorted(denials.items()):
            r.counter(
                f"denials_429_total_{_prom_name(gang_id)}",
                help=f"requests denied 429 for gang {gang_id}",
            ).inc(n)
        r.counter(
            "incidents_total",
            help="perf_regression incidents ingested (all gangs)",
        ).inc(sum(incidents.values()))
        for gang_id, n in sorted(incidents.items()):
            r.counter(
                f"incidents_total_{_prom_name(gang_id)}",
                help=f"perf_regression incidents ingested for gang {gang_id}",
            ).inc(n)
        r.counter(
            "plan_decisions_total",
            help="autopilot plan_decision events ingested (all gangs)",
        ).inc(sum(decisions.values()))
        for gang_id, n in sorted(decisions.items()):
            r.counter(
                f"plan_decisions_total_{_prom_name(gang_id)}",
                help=f"autopilot plan_decision events ingested for gang {gang_id}",
            ).inc(n)
        for gang_id, remaining in sorted(leases.items()):
            r.gauge(
                f"lease_remaining_s_{_prom_name(gang_id)}",
                help=f"seconds until gang {gang_id}'s lease expires",
            ).set(round(max(0.0, remaining), 3))
        return r

    def metrics_text(self) -> str:
        """The full ``/fleet/metrics`` exposition: the registry above plus
        the labeled shard/remediation families the registry's label-less
        instruments cannot express (composed by hand — same format)."""
        text = self.metrics_registry().to_prometheus()
        with self._lock:
            actions = dict(self._rem["actions"])
        lines = [
            "# HELP bagua_fleet_shard_count control-plane shards serving this fleet",
            "# TYPE bagua_fleet_shard_count gauge",
            "bagua_fleet_shard_count 1",
        ]
        if self.wal is not None:
            lines += [
                "# HELP bagua_wal_replay_ms wall time of the last WAL replay per shard",
                "# TYPE bagua_wal_replay_ms gauge",
                f'bagua_wal_replay_ms{{shard="0"}} {self.wal_replay_ms}',
            ]
        if actions:
            lines += [
                "# HELP bagua_remediations_total remediation actions journaled, by action",
                "# TYPE bagua_remediations_total counter",
            ]
            for action, n in sorted(actions.items()):
                lines.append(f'bagua_remediations_total{{action="{action}"}} {n}')
        return text + "\n".join(lines) + "\n"

    # -- durable-state witness --------------------------------------------------

    def dump(self) -> dict:
        """Deterministic export of every *durable* tier — the bitwise
        witness the kill/restart tests compare.  Volatile state (heartbeat
        ages, lease clocks, token buckets) and the advisory autotune tier
        are excluded by design; blobs appear as sha256 digests so the dump
        stays small."""
        import hashlib

        state = self._snapshot_state()
        for gs in state["gangs"].values():
            gs["blobs"] = {
                k: hashlib.sha256(base64.b64decode(v)).hexdigest()
                for k, v in gs["blobs"].items()
            }
        state["n_gangs"] = len(state["gangs"])
        state["n_plans"] = len(state["plans"])
        return state

    def close(self) -> None:
        if self.wal is not None:
            as_of = self.wal.cursor()
            self.wal.compact(self._snapshot_state(), as_of_seq=as_of)
            self.wal.close()
