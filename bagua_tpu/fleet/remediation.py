"""Fleet remediation engine: the verdict ladder starts *acting*.

PRs 13–15 built the evidence chain — per-gang verdicts, a structured
incident tier carrying ``plan_version`` + ``trace_id``, pushed flight
digests — but every fleet-level remediation was manual.  This module
closes the loop with three arcs, all driven by one deterministic
:meth:`RemediationEngine.sweep` over the control plane's existing views:

* **Quarantine + rollback** — a cached plan whose *adopters* (journaled by
  ``plan_get`` with a gang identity) report ``regressed`` verdicts with
  incidents naming that exact ``plan_version`` is quarantined in the
  cross-gang cache (never served again) and rolled back fleet-wide: every
  adopter gets a durable ``rollback_plan`` directive.  The correlation is
  *exact* — incident ``plan_version`` must equal the adopted version — so
  a healthy plan can never be quarantined by a neighbor's noise (the
  zero-false-quarantine property the scale lane asserts).  The emitted
  ``plan_quarantine`` event cites the indicting incidents' trace_ids.
* **Hang diagnosis + directed resize** — a ``wedged`` gang's pushed flight
  digests (each carrying a ``tail`` of full records) are synthesized into
  pseudo-dumps and joined through the same first-desync logic as
  ``ci/diagnose_hang.py`` (:func:`build_hang_report`).  On a ``desync`` or
  ``host_wedge`` verdict the gang gets a durable ``resize`` directive with
  a target world size — consumed by the elastic-resume path
  (``ElasticResumeCoordinator.directed_world_size``).
* **Canary graduation** — a freshly published plan starts in ``canary``
  status: only the first ``canary_n`` requesting gangs receive it.  Each
  sweep records a clean window for every canary adopter currently judged
  ``healthy``; at ``canary_n`` clean adopters the plan graduates to
  ``default`` and is served fleet-wide.

Every action lands in the control plane's durable remediation tier (WAL
ops ``adopt``/``quarantine``/``canary``/``plan_status``/``directive``), so
a SIGKILL'd server replays to the same remediation state bitwise.  The
sweep itself is stateless and idempotent: re-running it against the same
views issues nothing new.

The engine works identically against a single :class:`FleetControlPlane`
or the sharded facade (:class:`bagua_tpu.fleet.shards.ShardedControlPlane`)
— it only speaks the fan-out/merge view API.
"""

import logging
import time
from typing import Dict, List, Optional

from bagua_tpu.observability.flight_recorder import build_hang_report
from bagua_tpu.observability.metrics import validate_metrics_event

logger = logging.getLogger("bagua_tpu.fleet")

__all__ = ["RemediationEngine"]

#: hang verdicts that warrant a directed resize (a ``straggler`` verdict is
#: left to the gang's own StalenessDirector — the fleet does not resize a
#: gang for being slow)
RESIZE_VERDICTS = ("desync", "host_wedge")


def _pseudo_dump(digest: dict) -> dict:
    """A pushed flight digest, reshaped into the per-rank dump structure
    ``build_hang_report`` joins (the digest's ``tail`` stands in for the
    full ring)."""
    return {
        "rank": int(digest.get("rank", -1)),
        "last_seq": int(digest.get("last_seq", -1)),
        "records": [dict(r) for r in (digest.get("tail") or [])
                    if isinstance(r, dict)],
        "telemetry": {},
        "mono_at_dump": digest.get("mono"),
        "reason": "fleet_digest",
    }


class RemediationEngine:
    """One sweep of verdict-driven fleet remediation.

    Args:
        plane: a :class:`~bagua_tpu.fleet.control_plane.FleetControlPlane`
            or the sharded facade — anything speaking the view/remediation
            API (``scheduler_view``/``plan_statuses``/``incidents``/
            ``flight_digests``/``mark_plan_quarantined``/
            ``issue_directive``/``record_canary_clean``/``ingest_spans``).
        quarantine_threshold: distinct regressed adopter gangs (with
            version-matched incidents) required to quarantine a plan.
        sink: optional :class:`~bagua_tpu.observability.metrics.JsonlSink`
            receiving every emitted event (schema-validated).
        clock: wall-clock source for event timestamps.
    """

    def __init__(
        self,
        plane,
        quarantine_threshold: int = 1,
        sink=None,
        clock=time.time,
    ):
        self.plane = plane
        self.quarantine_threshold = max(1, int(quarantine_threshold))
        self.sink = sink
        self.clock = clock

    # -- event plumbing -------------------------------------------------------

    def _emit(self, gangs: List[str], event: dict, events: List[dict]) -> None:
        """Validate one remediation event, append it to the sweep's event
        list, push it into each named gang's timeline ring, and tee it to
        the sink.  A schema problem is a bug at this emit site — raise."""
        problems = validate_metrics_event(event)
        if problems:
            raise ValueError(f"invalid remediation event {event!r}: {problems}")
        events.append(event)
        for gang in gangs:
            try:
                self.plane.ingest_spans(gang, [], events=[dict(event)])
            except Exception:
                logger.exception("remediation event push failed (gang %r)", gang)
        if self.sink is not None:
            self.sink.emit(dict(event))

    # -- the sweep ------------------------------------------------------------

    def sweep(self) -> dict:
        view = self.plane.scheduler_view()
        gangs_view: Dict[str, dict] = view.get("gangs", {})
        statuses = self.plane.plan_statuses()
        events: List[dict] = []
        summary = {
            "checked_plans": len(statuses),
            "checked_gangs": len(gangs_view),
            "quarantined": [],
            "rollbacks": [],
            "resized": [],
            "clean": [],
            "graduated": [],
        }
        self._sweep_quarantine(gangs_view, statuses, summary, events)
        self._sweep_wedged(gangs_view, summary, events)
        self._sweep_canary(gangs_view, statuses, summary, events)
        summary["events"] = events
        return summary

    # -- arc 1: quarantine + fleet-wide rollback ------------------------------

    def _sweep_quarantine(self, gangs_view, statuses, summary, events) -> None:
        for key in sorted(statuses):
            rec = statuses[key]
            if rec.get("status") == "quarantined":
                continue
            plan_version = int(rec.get("plan_version", 0))
            indicted: Dict[str, List[str]] = {}
            max_step = 0
            for gang, adopted_version in sorted(rec.get("adopters", {}).items()):
                row = gangs_view.get(gang)
                if not row or not row.get("regressed"):
                    continue
                if int(adopted_version) != plan_version:
                    continue
                incs = self.plane.incidents(gang).get("incidents", [])
                cites = [
                    str(inc.get("trace_id") or "")
                    for inc in incs
                    if isinstance(inc, dict)
                    and inc.get("plan_version") == plan_version
                ]
                if cites:
                    indicted[gang] = cites
                    max_step = max(
                        max_step,
                        max((int(inc.get("step", 0)) for inc in incs
                             if isinstance(inc, dict)
                             and inc.get("plan_version") == plan_version),
                            default=0),
                    )
            if len(indicted) < self.quarantine_threshold:
                continue
            all_cites = sorted({t for ts in indicted.values() for t in ts if t})
            if not self.plane.mark_plan_quarantined(key, all_cites):
                continue
            summary["quarantined"].append(key)
            # fleet-wide rollback: every adopter — indicted or not — must
            # drop the poisoned plan
            for gang in sorted(rec.get("adopters", {})):
                directive = self.plane.issue_directive(
                    gang, "rollback_plan",
                    reason=f"plan_quarantine:v{plan_version}",
                    detail={"cache_key": key, "plan_version": plan_version},
                )
                summary["rollbacks"].append(
                    {"gang": gang, "id": directive["id"]}
                )
                self._emit([gang], {
                    "ts": round(self.clock(), 6),
                    "event": "remediation",
                    "step": max_step,
                    "action": "rollback_plan",
                    "gang": gang,
                    "reason": f"plan_quarantine:v{plan_version}",
                }, events)
            self._emit(sorted(indicted), {
                "ts": round(self.clock(), 6),
                "event": "plan_quarantine",
                "step": max_step,
                "cache_key": key,
                "plan_version": plan_version,
                "cites": all_cites,
                "gangs": sorted(indicted),
                "action": "quarantine",
            }, events)

    # -- arc 2: hang diagnosis + directed resize ------------------------------

    def _sweep_wedged(self, gangs_view, summary, events) -> None:
        for gang in sorted(gangs_view):
            row = gangs_view[gang]
            if row.get("verdict") != "wedged":
                continue
            if (row.get("remediation") or {}).get("pending"):
                continue  # already directed; wait for the ack
            dumps = [_pseudo_dump(d) for d in self.plane.flight_digests(gang)]
            report = build_hang_report(dumps)
            if report["verdict"] not in RESIZE_VERDICTS:
                continue
            implicated = sorted(
                set(report.get("divergent_ranks", []))
                | set(report.get("lagging_ranks", []))
            )
            to_world = max(1, len(report.get("ranks", [])) - max(1, len(implicated)))
            self.plane.issue_directive(
                gang, "resize",
                reason=f"hang:{report['verdict']}",
                detail={
                    "verdict": report["verdict"],
                    "to_world_size": to_world,
                    "implicated_ranks": implicated,
                    "note": report.get("detail", ""),
                },
            )
            summary["resized"].append(
                {"gang": gang, "verdict": report["verdict"],
                 "to_world_size": to_world}
            )
            self._emit([gang], {
                "ts": round(self.clock(), 6),
                "event": "remediation",
                "step": max(0, int(row.get("max_step", 0))),
                "action": "resize",
                "gang": gang,
                "reason": f"hang:{report['verdict']}",
            }, events)

    # -- arc 3: canary graduation ---------------------------------------------

    def _sweep_canary(self, gangs_view, statuses, summary, events) -> None:
        canary_n = int(getattr(self.plane, "canary_n", 1))
        for key in sorted(statuses):
            rec = statuses[key]
            if rec.get("status") != "canary":
                continue
            plan_version = int(rec.get("plan_version", 0))
            clean_now = list(rec.get("clean", []))
            graduated = False
            for gang in rec.get("cohort", []):
                if gang in clean_now:
                    continue
                row = gangs_view.get(gang)
                if not row or row.get("verdict") != "healthy":
                    continue
                outcome = self.plane.record_canary_clean(key, gang)
                if outcome is None:
                    continue
                clean_now.append(gang)
                summary["clean"].append({"cache_key": key, "gang": gang})
                self._emit([gang], {
                    "ts": round(self.clock(), 6),
                    "event": "canary_verdict",
                    "step": max(0, int(row.get("max_step", 0))),
                    "cache_key": key,
                    "plan_version": plan_version,
                    "verdict": "clean",
                    "clean": list(clean_now),
                    "needed": canary_n,
                }, events)
                if outcome == "graduated":
                    graduated = True
                    break
            if graduated:
                summary["graduated"].append(key)
                self._emit(list(clean_now), {
                    "ts": round(self.clock(), 6),
                    "event": "canary_verdict",
                    "step": 0,
                    "cache_key": key,
                    "plan_version": plan_version,
                    "verdict": "graduated",
                    "clean": list(clean_now),
                    "needed": canary_n,
                }, events)
