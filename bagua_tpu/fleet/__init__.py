"""Fleet control plane: one multi-tenant, crash-safe rendezvous + autotune
service for N concurrent gangs.

* :mod:`bagua_tpu.fleet.control_plane` — per-gang namespaces, leases +
  admission control, the cross-gang plan cache (with its durable
  quarantine/canary lifecycle), the scheduler view.
* :mod:`bagua_tpu.fleet.remediation` — the verdict-driven
  :class:`RemediationEngine`: plan quarantine + fleet-wide rollback,
  wedged-gang hang diagnosis + directed resize, canary graduation.
* :mod:`bagua_tpu.fleet.shards` — consistent-hash sharding
  (:class:`ShardedControlPlane`): per-shard WALs cut along gang
  namespaces, ``/fleet/*`` reads fan out and merge.
* :mod:`bagua_tpu.fleet.wal` — the write-ahead log + snapshot compaction
  behind crash-safe restarts.
* :mod:`bagua_tpu.fleet.server` — the HTTP front-end
  (``python -m bagua_tpu.fleet.server``): thread-per-request or the
  selector-based async I/O loop (:func:`start_async_fleet_server`).
* :mod:`bagua_tpu.fleet.client` — :class:`FleetClient`, per-gang client
  factories, and the step-0 cross-gang plan warm start.
"""

from bagua_tpu.fleet.control_plane import (
    FleetControlPlane,
    GangNamespace,
    TokenBucket,
    plan_cache_key,
)
from bagua_tpu.fleet.client import (
    FleetClient,
    adopt_fleet_plan,
    engine_plan_key,
    gang_endpoint,
    model_fingerprint,
    publish_engine_plan,
)
from bagua_tpu.fleet.remediation import RemediationEngine
from bagua_tpu.fleet.server import (
    AsyncFleetServer,
    FleetHandler,
    start_async_fleet_server,
    start_fleet_server,
)
from bagua_tpu.fleet.shards import HashRing, ShardedControlPlane
from bagua_tpu.fleet.wal import WriteAheadLog

__all__ = [
    "FleetControlPlane",
    "GangNamespace",
    "TokenBucket",
    "plan_cache_key",
    "FleetClient",
    "adopt_fleet_plan",
    "engine_plan_key",
    "gang_endpoint",
    "model_fingerprint",
    "publish_engine_plan",
    "RemediationEngine",
    "HashRing",
    "ShardedControlPlane",
    "FleetHandler",
    "start_fleet_server",
    "AsyncFleetServer",
    "start_async_fleet_server",
    "WriteAheadLog",
]
