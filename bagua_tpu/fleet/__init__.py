"""Fleet control plane: one multi-tenant, crash-safe rendezvous + autotune
service for N concurrent gangs.

* :mod:`bagua_tpu.fleet.control_plane` — per-gang namespaces, leases +
  admission control, the cross-gang plan cache, the scheduler view.
* :mod:`bagua_tpu.fleet.wal` — the write-ahead log + snapshot compaction
  behind crash-safe restarts.
* :mod:`bagua_tpu.fleet.server` — the HTTP front-end
  (``python -m bagua_tpu.fleet.server``).
* :mod:`bagua_tpu.fleet.client` — :class:`FleetClient`, per-gang client
  factories, and the step-0 cross-gang plan warm start.
"""

from bagua_tpu.fleet.control_plane import (
    FleetControlPlane,
    GangNamespace,
    TokenBucket,
    plan_cache_key,
)
from bagua_tpu.fleet.client import (
    FleetClient,
    adopt_fleet_plan,
    engine_plan_key,
    gang_endpoint,
    model_fingerprint,
    publish_engine_plan,
)
from bagua_tpu.fleet.server import FleetHandler, start_fleet_server
from bagua_tpu.fleet.wal import WriteAheadLog

__all__ = [
    "FleetControlPlane",
    "GangNamespace",
    "TokenBucket",
    "plan_cache_key",
    "FleetClient",
    "adopt_fleet_plan",
    "engine_plan_key",
    "gang_endpoint",
    "model_fingerprint",
    "publish_engine_plan",
    "FleetHandler",
    "start_fleet_server",
    "WriteAheadLog",
]
