"""HTTP front-end for the fleet control plane.

Route map (one port serves the whole fleet):

    /g/<gang_id>/rdzv/...        per-gang rendezvous/KV/blob (the full
                                 ``distributed.rendezvous`` route table,
                                 delegated per namespace)
    /g/<gang_id>/api/v1/...      per-gang autotune API (the full
                                 ``service.autotune_service`` route table)
    /g/<gang_id>/spans           POST: ingest a batch of client-side spans
                                 (+ timeline events) into the gang's
                                 volatile span ring
    /g/<gang_id>/incidents       POST: ingest a batch of regression-sentinel
                                 ``perf_regression`` incidents into the
                                 gang's volatile incident ring
    /g/<gang_id>/decisions       POST: ingest a batch of autopilot
                                 ``plan_decision`` events into the gang's
                                 volatile decision ring
    /g/<gang_id>/directive       GET: the gang's oldest pending remediation
                                 directive (rollback_plan/resize), or null
    /g/<gang_id>/directive/ack   POST: acknowledge a directive by id
    /fleet/plan/publish          POST: store a proven plan in the cross-gang
                                 cache (fingerprint/topology/algorithm/
                                 wire_precision + plan payload)
    /fleet/plan/lookup           POST: cache lookup by the same key (an
                                 optional ``gang`` identity journals the
                                 adoption and applies canary gating)
    /fleet/remediate             POST: run one RemediationEngine sweep
    /fleet/remediation           GET: the durable remediation tier (plan
                                 statuses, directives, action counters)
    /fleet/shards                GET: shard topology (count, gangs per
                                 shard, per-shard WAL replay wall time)
    /fleet/scheduler             GET: per-gang wedged/straggler/regressed/
                                 healthy/idle verdict view
    /fleet/incidents[?gang=<id>] GET: the volatile perf_regression incident
                                 tier (every gang, or one gang's ring)
    /fleet/decisions[?gang=<id>] GET: the volatile autopilot plan_decision
                                 tier (every gang, or one gang's ring)
    /fleet/gangs                 GET: gang ids + lease remainders
    /fleet/timeline?gang=<id>    GET: the gang's causally ordered timeline
                                 (client+server spans joined by trace_id,
                                 StepSummary windows, flight digests)
    /fleet/metrics               GET: Prometheus text exposition (per-gang
                                 request/429 counts, lease remainders,
                                 plan-cache hits/misses)
    /fleet/dump                  GET: deterministic durable-state dump (the
                                 kill/restart bitwise witness)
    /fleet/health                GET: liveness

Every handled ``/g/...`` request is also recorded as a *server-side span*
(child of the caller's ``traceparent`` when one arrives) in the gang's
volatile ring — the server half of the cross-process trace join.

Every ``/g/...`` request passes the gang's token bucket first — a denial
is ``429`` + ``Retry-After`` (the contract ``retry_call`` paces on and the
circuit breaker ignores) — and touches the gang's lease; an untouched
lease expiring GCs the whole namespace.

Run standalone (what the load lane SIGKILLs and restarts)::

    python -m bagua_tpu.fleet.server --port 29500 --wal-dir /var/lib/bagua
"""

import json
import logging
import math
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Optional, Tuple

from bagua_tpu.distributed.rendezvous import _Handler as _RdzvHandler
from bagua_tpu.fleet.control_plane import FleetControlPlane, GangNamespace
from bagua_tpu.service.autotune_service import AUTOTUNE_POST_ROUTES

logger = logging.getLogger("bagua_tpu.fleet")

__all__ = [
    "FleetHandler",
    "start_fleet_server",
    "AsyncFleetServer",
    "start_async_fleet_server",
    "main",
]


class FleetHandler(_RdzvHandler):
    """Multi-tenant dispatcher reusing the rendezvous handler's route table
    per gang namespace."""

    fleet: FleetControlPlane  # bound by start_fleet_server
    state = None  # the single-tenant binding is never used here

    def _reply(self, payload: dict, code: int = 200, headers=None):
        self._status = code  # server-span attribution (see _record_server_span)
        super()._reply(payload, code, headers)

    def _reply_text(self, text: str, content_type: str = "text/plain; version=0.0.4"):
        body = text.encode()
        self._status = 200
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _record_server_span(self, t0: float) -> None:
        """Record the handled request as a server-side span in its gang's
        volatile ring (no-op for un-ganged ``/fleet/*`` routes).  Fenced:
        span bookkeeping must never turn a served request into a 500."""
        gang_id = getattr(self, "_span_gang", None)
        if gang_id is None:
            return
        try:
            self.fleet.record_server_span(
                gang_id,
                route=self.path.split("?", 1)[0],
                status=int(getattr(self, "_status", 200)),
                dur_ms=(time.monotonic() - t0) * 1e3,
                traceparent=self.headers.get("traceparent"),
                retry_after_s=getattr(self, "_retry_after_s", None),
            )
        except Exception:
            logger.exception("server-span recording failed (gang %r)", gang_id)

    def _gang_route(self, drained: bool) -> Optional[Tuple[GangNamespace, str]]:
        """Resolve ``/g/<gang_id>/<sub>`` → (namespace, sub-path), applying
        admission control + the lease touch.  Replies (429/404) and returns
        None when the request doesn't reach a namespace.  ``drained`` must
        be True for bodied methods — under keep-alive an unread body
        desyncs the connection, so callers drain before any early reply."""
        assert drained or self.command == "GET", "body must be drained first"
        from urllib.parse import unquote

        rest = self.path[len("/g/"):]
        gang_quoted, sep, sub = rest.partition("/")
        gang_id = unquote(gang_quoted)
        if not gang_id or not sep:
            self._reply({"error": "bad gang route"}, 404)
            return None
        self._span_gang = gang_id
        ok, retry_after = self.fleet.admit(gang_id)
        if not ok:
            self._retry_after_s = retry_after
            self._reply(
                {"error": "backpressure", "retry_after_s": round(retry_after, 3)},
                429,
                headers={"Retry-After": max(1, math.ceil(retry_after))},
            )
            return None
        return self.fleet.gang(gang_id), "/" + sub

    def _autotune(self, ns: GangNamespace, sub: str, payload: dict) -> None:
        name = AUTOTUNE_POST_ROUTES.get(sub)
        if name is None:
            self._reply({"error": "not found"}, 404)
            return
        service = ns.autotune_service(world_size=payload.get("world_size"))
        try:
            self._reply(getattr(service, name)(payload))
        except Exception as e:
            logger.exception("autotune endpoint error (gang %r)", ns.gang_id)
            self._reply({"error": str(e)}, 500)

    # -- verbs ----------------------------------------------------------------

    def do_GET(self):
        t0 = time.monotonic()
        try:
            if self.path.startswith("/g/"):
                route = self._gang_route(drained=True)
                if route is not None:
                    ns, sub = route
                    if sub == "/api/v1/health_check":
                        self._reply({"status": "ok"})
                    elif sub == "/directive":
                        self._reply({
                            "gang": ns.gang_id,
                            "directive": self.fleet.directive(ns.gang_id),
                        })
                    else:
                        self._handle_get(ns.rendezvous, sub)
            elif self.path == "/fleet/scheduler":
                self._reply(self.fleet.scheduler_view())
            elif self.path == "/fleet/gangs":
                self._reply({"gangs": self.fleet.gang_ids(),
                             "gangs_gcd": self.fleet.gangs_gcd,
                             "backpressure_denials": self.fleet.backpressure_denials})
            elif self.path == "/fleet/metrics":
                self._reply_text(self.fleet.metrics_text())
            elif self.path == "/fleet/remediation":
                self._reply(self.fleet.remediation_summary())
            elif self.path == "/fleet/shards":
                self._reply(self.fleet.shard_info())
            elif self.path.split("?", 1)[0] == "/fleet/incidents":
                from urllib.parse import parse_qs, urlsplit

                gang = (parse_qs(urlsplit(self.path).query).get("gang") or [None])[0]
                self._reply(self.fleet.incidents(gang))
            elif self.path.split("?", 1)[0] == "/fleet/decisions":
                from urllib.parse import parse_qs, urlsplit

                gang = (parse_qs(urlsplit(self.path).query).get("gang") or [None])[0]
                self._reply(self.fleet.decisions(gang))
            elif self.path.split("?", 1)[0] == "/fleet/timeline":
                from urllib.parse import parse_qs, urlsplit

                gang = (parse_qs(urlsplit(self.path).query).get("gang") or [""])[0]
                if not gang:
                    self._reply({"error": "missing gang parameter"}, 400)
                else:
                    self._reply(self.fleet.timeline(gang))
            elif self.path == "/fleet/dump":
                self._reply(self.fleet.dump())
            elif self.path == "/fleet/health":
                self._reply({"status": "ok", "gangs": len(self.fleet.gang_ids()),
                             "plans": self.fleet.plan_count()})
            else:
                self._reply({"error": "not found"}, 404)
        finally:
            self._record_server_span(t0)
            self.fleet.maybe_compact()

    def do_PUT(self):
        t0 = time.monotonic()
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        try:
            if self.path.startswith("/g/"):
                route = self._gang_route(drained=True)
                if route is not None:
                    ns, sub = route
                    self._handle_put(ns.rendezvous, sub, body)
            else:
                self._reply({"error": "not found"}, 404)
        finally:
            self._record_server_span(t0)
            self.fleet.maybe_compact()

    def do_DELETE(self):
        t0 = time.monotonic()
        try:
            if self.path.startswith("/g/"):
                route = self._gang_route(drained=True)
                if route is not None:
                    ns, sub = route
                    self._handle_delete(ns.rendezvous, sub)
            else:
                self._reply({"error": "not found"}, 404)
        finally:
            self._record_server_span(t0)
            self.fleet.maybe_compact()

    def do_POST(self):
        t0 = time.monotonic()
        try:
            payload = self._body()
        except (ValueError, json.JSONDecodeError):
            return self._reply({"error": "bad json"}, 400)
        try:
            if self.path.startswith("/g/"):
                route = self._gang_route(drained=True)
                if route is not None:
                    ns, sub = route
                    if sub.startswith("/api/v1/"):
                        self._autotune(ns, sub, payload)
                    elif sub == "/spans":
                        self._reply(self.fleet.ingest_spans(
                            ns.gang_id,
                            payload.get("spans") or [],
                            payload.get("events") or [],
                        ))
                    elif sub == "/incidents":
                        self._reply(self.fleet.ingest_incidents(
                            ns.gang_id, payload.get("incidents") or [],
                        ))
                    elif sub == "/decisions":
                        self._reply(self.fleet.ingest_decisions(
                            ns.gang_id, payload.get("decisions") or [],
                        ))
                    elif sub == "/directive/ack":
                        try:
                            directive_id = int(payload["id"])
                        except (KeyError, TypeError, ValueError):
                            self._reply({"error": "missing/bad id"}, 400)
                        else:
                            self._reply({"ok": self.fleet.ack_directive(
                                ns.gang_id, directive_id)})
                    else:
                        self._handle_post(ns.rendezvous, sub, payload)
            elif self.path == "/fleet/plan/publish":
                try:
                    key = self.fleet.plan_put(
                        fingerprint=payload["fingerprint"],
                        topology=payload["topology"],
                        algorithm=payload["algorithm"],
                        wire_precision=payload["wire_precision"],
                        plan=payload["plan"],
                        meta=payload.get("meta"),
                    )
                except KeyError as e:
                    self._reply({"error": f"missing field {e}"}, 400)
                else:
                    self._reply({"ok": True, "key": key})
            elif self.path == "/fleet/plan/lookup":
                try:
                    entry = self.fleet.plan_get(
                        fingerprint=payload["fingerprint"],
                        topology=payload["topology"],
                        algorithm=payload["algorithm"],
                        wire_precision=payload["wire_precision"],
                        gang=payload.get("gang"),
                    )
                except KeyError as e:
                    self._reply({"error": f"missing field {e}"}, 400)
                else:
                    if entry is None:
                        self._reply({"found": False})
                    else:
                        self._reply(dict(entry, found=True))
            elif self.path == "/fleet/remediate":
                knobs = {}
                if isinstance(payload.get("quarantine_threshold"), int):
                    knobs["quarantine_threshold"] = payload["quarantine_threshold"]
                self._reply(self.fleet.remediate(**knobs))
            else:
                self._reply({"error": "not found"}, 404)
        finally:
            self._record_server_span(t0)
            self.fleet.maybe_compact()


def start_fleet_server(
    fleet: FleetControlPlane, port: int, host: str = "0.0.0.0"
) -> ThreadingHTTPServer:
    """Serve the control plane in a daemon thread; returns the live server
    (``server_address[1]`` is the bound port — pass 0 for ephemeral)."""
    handler = type("BoundFleetHandler", (FleetHandler,), {"fleet": fleet})
    server = ThreadingHTTPServer((host, port), handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


class AsyncFleetServer:
    """Selector-based single-threaded I/O loop serving the same
    :class:`FleetHandler` route table.

    The thread-per-request :class:`ThreadingHTTPServer` tops out around a
    thousand concurrent keep-alive connections (a stack per idle gang);
    this loop multiplexes them all on one ``selectors`` poll — stdlib
    only, no new deps.  Every fleet/rendezvous/autotune handler is
    non-blocking by construction (in-memory state + a WAL append), so
    dispatching inline on the event loop keeps p99 flat at 1000-gang
    fan-in where the threaded server degrades.

    Request framing: we buffer until the header block plus the declared
    ``Content-Length`` body is complete, then drive the handler over
    ``BytesIO`` files.  Chunked request bodies are not supported — every
    shipped client (urllib + ``http.client``) sends Content-Length.
    Keep-alive follows the handler's ``close_connection`` verdict, so
    HTTP/1.1 clients hold one connection for their whole session.
    """

    _MAX_BUF = 64 * 1024 * 1024  # runaway-request backstop per connection

    def __init__(self, fleet, port: int, host: str = "0.0.0.0"):
        import selectors
        import socket

        self.fleet = fleet
        self._handler_cls = self._make_shim(fleet)
        self._sel = selectors.DefaultSelector()
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, port))
        self._listen.listen(1024)
        self._listen.setblocking(False)
        self.server_address = self._listen.getsockname()
        # self-pipe: shutdown() pokes the loop awake from any thread
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._running = True
        self._conns: dict = {}  # sock -> {"in": bytes, "out": bytes, "close": bool}
        self._sel.register(self._listen, selectors.EVENT_READ, "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")

    @staticmethod
    def _make_shim(fleet):
        """A FleetHandler subclass driven over in-memory files instead of a
        socket: ``__init__`` skips the socketserver machinery, the caller
        feeds ``raw_requestline``/``parse_request`` and invokes the verb."""

        class _Shim(FleetHandler):
            def __init__(self, rfile, wfile, client_address):
                self.rfile = rfile
                self.wfile = wfile
                self.client_address = client_address
                self.close_connection = True
                self.requestline = ""
                self.request_version = self.default_request_version
                self.command = ""

        _Shim.fleet = fleet
        return _Shim

    @staticmethod
    def _split_request(buf: bytes):
        """One complete request (headers + Content-Length body) off the
        front of ``buf``, or (None, buf) while it's still partial."""
        head_end = buf.find(b"\r\n\r\n")
        if head_end < 0:
            return None, buf
        clen = 0
        for line in buf[:head_end].split(b"\r\n")[1:]:
            if line.lower().startswith(b"content-length:"):
                try:
                    clen = int(line.split(b":", 1)[1].strip())
                except ValueError:
                    clen = 0
        total = head_end + 4 + max(0, clen)
        if len(buf) < total:
            return None, buf
        return buf[:total], buf[total:]

    def _dispatch(self, request: bytes, client_address):
        """Drive the handler shim over one framed request; returns
        (response_bytes, keep_alive)."""
        import io

        rfile, wfile = io.BytesIO(request), io.BytesIO()
        h = self._handler_cls(rfile, wfile, client_address)
        try:
            h.raw_requestline = rfile.readline(65537)
            if not h.raw_requestline or not h.parse_request():
                return wfile.getvalue(), False
            method = getattr(h, "do_" + h.command, None)
            if method is None:
                h.send_error(501)
                return wfile.getvalue(), False
            method()
            return wfile.getvalue(), not h.close_connection
        except Exception:
            logger.exception("async dispatch failed")
            body = b'{"error": "internal"}'
            return (
                b"HTTP/1.1 500 Internal Server Error\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                b"Connection: close\r\n\r\n" + body
            ), False

    def serve_forever(self):
        import selectors

        while self._running:
            for key, _events in self._sel.select(timeout=0.5):
                if key.data == "wake":
                    return self._close_all()
                if key.data == "accept":
                    self._accept()
                    continue
                sock = key.fileobj
                conn = self._conns.get(sock)
                if conn is None:
                    continue
                if _events & selectors.EVENT_READ:
                    self._readable(sock, conn)
                if sock in self._conns and _events & selectors.EVENT_WRITE:
                    self._writable(sock, conn)
            if not self._running:
                break
        self._close_all()

    def _accept(self):
        import selectors

        try:
            sock, addr = self._listen.accept()
        except OSError:
            return
        sock.setblocking(False)
        self._conns[sock] = {"in": b"", "out": b"", "close": False, "addr": addr}
        self._sel.register(sock, selectors.EVENT_READ, "conn")

    def _interest(self, sock, conn):
        import selectors

        mask = selectors.EVENT_READ
        if conn["out"]:
            mask |= selectors.EVENT_WRITE
        self._sel.modify(sock, mask, "conn")

    def _readable(self, sock, conn):
        try:
            data = sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            return self._drop(sock)
        if not data:
            return self._drop(sock)
        conn["in"] += data
        if len(conn["in"]) > self._MAX_BUF:
            return self._drop(sock)
        while True:
            request, conn["in"] = self._split_request(conn["in"])
            if request is None:
                break
            response, keep_alive = self._dispatch(request, conn["addr"])
            conn["out"] += response
            if not keep_alive:
                conn["close"] = True
                conn["in"] = b""
                break
        self._interest(sock, conn)
        self._flush(sock, conn)

    def _writable(self, sock, conn):
        self._flush(sock, conn)

    def _flush(self, sock, conn):
        while conn["out"]:
            try:
                n = sock.send(conn["out"])
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                return self._drop(sock)
            if n <= 0:
                break
            conn["out"] = conn["out"][n:]
        if not conn["out"] and conn["close"]:
            return self._drop(sock)
        if sock in self._conns:
            self._interest(sock, conn)

    def _drop(self, sock):
        self._conns.pop(sock, None)
        try:
            self._sel.unregister(sock)
        except (KeyError, ValueError):
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _close_all(self):
        for sock in list(self._conns):
            self._drop(sock)
        for sock in (self._listen, self._wake_r, self._wake_w):
            try:
                self._sel.unregister(sock)
            except (KeyError, ValueError):
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._running = False

    def shutdown(self):
        self._running = False
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass


def start_async_fleet_server(
    fleet, port: int, host: str = "0.0.0.0"
) -> AsyncFleetServer:
    """Serve the control plane on the selector loop in a daemon thread;
    same contract as :func:`start_fleet_server` (``server_address[1]`` is
    the bound port, ``shutdown()`` stops it)."""
    server = AsyncFleetServer(fleet, port, host)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def main(argv=None) -> int:
    """Standalone fleet control plane (the deployment mode: one of these
    outlives every gang it serves; the load lane SIGKILLs it mid-run and
    restarts it on the same port + WAL dir)."""
    import argparse

    p = argparse.ArgumentParser("bagua_tpu.fleet.server")
    p.add_argument("--port", type=int, default=29500)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--wal-dir", default=None,
                   help="durability directory (no WAL = in-memory only)")
    p.add_argument("--lease-ttl-s", type=float, default=None,
                   help="gang lease TTL (default BAGUA_FLEET_LEASE_TTL_S)")
    p.add_argument("--rate", type=float, default=None,
                   help="per-gang admitted requests/s (default BAGUA_FLEET_RATE; 0 = off)")
    p.add_argument("--burst", type=float, default=None,
                   help="per-gang burst capacity (default BAGUA_FLEET_BURST)")
    p.add_argument("--compact-every", type=int, default=1000)
    p.add_argument("--fsync", action="store_true")
    p.add_argument("--min-nodes", type=int, default=1)
    p.add_argument("--settle-s", type=float, default=1.0)
    p.add_argument("--member-ttl-s", type=float, default=30.0)
    p.add_argument("--shards", type=int, default=1,
                   help="consistent-hash control-plane shards (per-shard WALs)")
    p.add_argument("--canary-n", type=int, default=2,
                   help="adopter gangs that must report clean before a "
                        "cached plan graduates canary -> default")
    p.add_argument("--io", choices=("async", "thread"), default="async",
                   help="selector event loop (default) or thread-per-request")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="[bagua_tpu.fleet] %(message)s")
    plane_kwargs = dict(
        lease_ttl_s=args.lease_ttl_s,
        rate=args.rate,
        burst=args.burst,
        compact_every=args.compact_every,
        fsync=args.fsync,
        canary_n=args.canary_n,
        rdzv_kwargs={
            "min_nodes": args.min_nodes,
            "settle_s": args.settle_s,
            "ttl_s": args.member_ttl_s,
        },
    )
    if args.shards > 1:
        from bagua_tpu.fleet.shards import ShardedControlPlane

        fleet = ShardedControlPlane(
            n_shards=args.shards, wal_dir=args.wal_dir, **plane_kwargs
        )
    else:
        fleet = FleetControlPlane(wal_dir=args.wal_dir, **plane_kwargs)
    if args.io == "async":
        server = start_async_fleet_server(fleet, args.port, args.host)
    else:
        server = start_fleet_server(fleet, args.port, args.host)
    # the parent (launcher, CI lane) waits for this line before connecting
    print(f"fleet control plane on port {server.server_address[1]}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.shutdown()
        fleet.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
