"""Write-ahead log + snapshot compaction for the fleet control plane.

The durability contract: every state mutation the control plane wants to
survive a crash is appended (seq-numbered, one JSON object per line) to
``wal.jsonl`` *before* the mutating request is acknowledged; a restarted
server loads ``snapshot.json`` and replays the records past it, arriving at
the exact pre-crash fleet state.  Compaction folds the log into a fresh
snapshot using the snapshot.py discipline — write ``snapshot.json.tmp.<pid>``,
``os.replace`` into place, *then* rewrite the log keeping only records
newer than the snapshot's ``last_seq`` — so every crash point leaves a
loadable pair:

* crash before the snapshot replace: old snapshot + full log (nothing lost);
* crash between the two replaces: new snapshot + the full log; replay
  skips records ``<= last_seq`` (they are idempotent against the snapshot
  that already contains them) and applies the rest;
* crash after the log replace: new snapshot + the preserved suffix.

The caller's state dump is not atomic with ongoing appends, so ``compact``
takes the seq the caller captured *before* dumping (``as_of_seq``): every
record acknowledged after that capture may be missing from the dump and
must survive in the rewritten log — stamping ``last_seq`` at compact time
instead would silently drop it.

Appends ``flush()`` to the OS page cache by default, which survives the
process being SIGKILLed (the failure mode the fleet lane induces); set
``fsync=True`` to also survive kernel/power loss at ~100x the write cost.
"""

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["WriteAheadLog"]


class WriteAheadLog:
    """Single-writer append-only log with snapshot compaction (thread-safe)."""

    def __init__(self, directory: str, compact_every: int = 1000, fsync: bool = False):
        self.directory = directory
        self.compact_every = max(1, int(compact_every))
        self.fsync = fsync
        self.snapshot_path = os.path.join(directory, "snapshot.json")
        self.wal_path = os.path.join(directory, "wal.jsonl")
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.RLock()
        self._fh = None
        self._seq = 0  # newest seq ever issued (snapshot or log)
        self._records_since_compact = 0
        self.compactions = 0

    # -- load ------------------------------------------------------------------

    def load(self) -> Tuple[Optional[dict], List[dict]]:
        """(snapshot state, records past it) — what a restarted server
        replays.  Also primes the seq counter and re-opens the log for
        appending.  A torn final line (crash mid-append: the record was
        never acknowledged) is truncated away — the file must end on a
        clean line boundary or the next append would concatenate onto the
        torn bytes and lose itself to the same torn-tail rule on the
        following restart.  A torn *snapshot* is impossible by construction
        (``os.replace``)."""
        with self._lock:
            snapshot = None
            last_seq = 0
            if os.path.exists(self.snapshot_path):
                with open(self.snapshot_path) as f:
                    wrapped = json.load(f)
                snapshot = wrapped["state"]
                last_seq = int(wrapped["last_seq"])
            records = []
            if os.path.exists(self.wal_path):
                with open(self.wal_path, "rb") as f:
                    data = f.read()
                valid_end = 0
                for raw in data.splitlines(keepends=True):
                    if not raw.endswith(b"\n"):
                        break  # torn tail: the crash point, nothing after it
                    line = raw.strip()
                    if line:
                        try:
                            rec = json.loads(line)
                        except (json.JSONDecodeError, UnicodeDecodeError):
                            break
                        if int(rec.get("seq", 0)) > last_seq:
                            records.append(rec)
                    valid_end += len(raw)
                if valid_end < len(data):
                    with open(self.wal_path, "rb+") as f:
                        f.truncate(valid_end)
            self._seq = max(last_seq, *(int(r["seq"]) for r in records)) if records else last_seq
            self._records_since_compact = len(records)
            self._open_locked()
            return snapshot, records

    # -- append ----------------------------------------------------------------

    def append(self, record: Dict) -> int:
        """Durably append one record; returns its assigned ``seq``."""
        with self._lock:
            if self._fh is None:
                self._open_locked()
            self._seq += 1
            record = dict(record, seq=self._seq)
            self._fh.write(json.dumps(record, sort_keys=True) + "\n")
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())
            self._records_since_compact += 1
            return self._seq

    def needs_compact(self) -> bool:
        with self._lock:
            return self._records_since_compact >= self.compact_every

    def cursor(self) -> int:
        """Newest seq issued so far.  Capture this *before* dumping state
        for :meth:`compact`: any record appended during the dump gets a
        higher seq and is preserved by the compaction instead of being
        covered by ``last_seq`` while absent from the snapshot."""
        with self._lock:
            return self._seq

    # -- compaction ------------------------------------------------------------

    def compact(self, state: Dict, as_of_seq: Optional[int] = None) -> None:
        """Fold the log into ``state`` — the caller's dump, which must
        include every record acknowledged up to ``as_of_seq`` (default: the
        seq at call time, only safe when no appends can race the dump).
        Atomically publish the snapshot, then rewrite the log keeping the
        records newer than ``as_of_seq``: they may be missing from the dump
        and replaying them is idempotent even when the dump caught them."""
        with self._lock:
            as_of = self._seq if as_of_seq is None else int(as_of_seq)
            tmp = f"{self.snapshot_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"last_seq": as_of, "state": state}, f, sort_keys=True)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            os.replace(tmp, self.snapshot_path)
            kept: List[str] = []
            if as_of < self._seq and os.path.exists(self.wal_path):
                with open(self.wal_path) as f:
                    for line in f:
                        line = line.strip()
                        if line and int(json.loads(line).get("seq", 0)) > as_of:
                            kept.append(line)
            if self._fh is not None:
                self._fh.close()
            wal_tmp = f"{self.wal_path}.tmp.{os.getpid()}"
            with open(wal_tmp, "w") as f:
                f.write("".join(line + "\n" for line in kept))
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            os.replace(wal_tmp, self.wal_path)
            self._open_locked()
            self._records_since_compact = len(kept)
            self.compactions += 1

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def _open_locked(self) -> None:
        self._fh = open(self.wal_path, "a")
