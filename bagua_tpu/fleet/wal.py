"""Write-ahead log + snapshot compaction for the fleet control plane.

The durability contract: every state mutation the control plane wants to
survive a crash is appended (seq-numbered, one JSON object per line) to
``wal.jsonl`` *before* the mutating request is acknowledged; a restarted
server loads ``snapshot.json`` and replays the records past it, arriving at
the exact pre-crash fleet state.  Compaction folds the log into a fresh
snapshot using the snapshot.py discipline — write ``snapshot.json.tmp.<pid>``,
``os.replace`` into place, *then* truncate the log — so every crash point
leaves a loadable pair:

* crash before the replace: old snapshot + full log (nothing lost);
* crash between replace and truncate: new snapshot + a log whose records
  are all ``<= last_seq`` (replay skips them — records are idempotent
  against the snapshot that already contains them);
* crash after truncate: new snapshot + empty log.

Appends ``flush()`` to the OS page cache by default, which survives the
process being SIGKILLed (the failure mode the fleet lane induces); set
``fsync=True`` to also survive kernel/power loss at ~100x the write cost.
"""

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["WriteAheadLog"]


class WriteAheadLog:
    """Single-writer append-only log with snapshot compaction (thread-safe)."""

    def __init__(self, directory: str, compact_every: int = 1000, fsync: bool = False):
        self.directory = directory
        self.compact_every = max(1, int(compact_every))
        self.fsync = fsync
        self.snapshot_path = os.path.join(directory, "snapshot.json")
        self.wal_path = os.path.join(directory, "wal.jsonl")
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.RLock()
        self._fh = None
        self._seq = 0  # newest seq ever issued (snapshot or log)
        self._records_since_compact = 0
        self.compactions = 0

    # -- load ------------------------------------------------------------------

    def load(self) -> Tuple[Optional[dict], List[dict]]:
        """(snapshot state, records past it) — what a restarted server
        replays.  Also primes the seq counter and re-opens the log for
        appending.  A torn final line (crash mid-append: the record was
        never acknowledged) is truncated away — the file must end on a
        clean line boundary or the next append would concatenate onto the
        torn bytes and lose itself to the same torn-tail rule on the
        following restart.  A torn *snapshot* is impossible by construction
        (``os.replace``)."""
        with self._lock:
            snapshot = None
            last_seq = 0
            if os.path.exists(self.snapshot_path):
                with open(self.snapshot_path) as f:
                    wrapped = json.load(f)
                snapshot = wrapped["state"]
                last_seq = int(wrapped["last_seq"])
            records = []
            if os.path.exists(self.wal_path):
                with open(self.wal_path, "rb") as f:
                    data = f.read()
                valid_end = 0
                for raw in data.splitlines(keepends=True):
                    if not raw.endswith(b"\n"):
                        break  # torn tail: the crash point, nothing after it
                    line = raw.strip()
                    if line:
                        try:
                            rec = json.loads(line)
                        except (json.JSONDecodeError, UnicodeDecodeError):
                            break
                        if int(rec.get("seq", 0)) > last_seq:
                            records.append(rec)
                    valid_end += len(raw)
                if valid_end < len(data):
                    with open(self.wal_path, "rb+") as f:
                        f.truncate(valid_end)
            self._seq = max(last_seq, *(int(r["seq"]) for r in records)) if records else last_seq
            self._records_since_compact = len(records)
            self._open_locked(append=True)
            return snapshot, records

    # -- append ----------------------------------------------------------------

    def append(self, record: Dict) -> int:
        """Durably append one record; returns its assigned ``seq``."""
        with self._lock:
            if self._fh is None:
                self._open_locked(append=True)
            self._seq += 1
            record = dict(record, seq=self._seq)
            self._fh.write(json.dumps(record, sort_keys=True) + "\n")
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())
            self._records_since_compact += 1
            return self._seq

    def needs_compact(self) -> bool:
        with self._lock:
            return self._records_since_compact >= self.compact_every

    # -- compaction ------------------------------------------------------------

    def compact(self, state: Dict) -> None:
        """Fold the log into ``state`` (the caller's full dump, which must
        already include every acknowledged record): atomically publish the
        snapshot, then truncate the log."""
        with self._lock:
            tmp = f"{self.snapshot_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"last_seq": self._seq, "state": state}, f, sort_keys=True)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            os.replace(tmp, self.snapshot_path)
            if self._fh is not None:
                self._fh.close()
            self._open_locked(append=False)  # truncate
            self._records_since_compact = 0
            self.compactions += 1

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def _open_locked(self, append: bool) -> None:
        self._fh = open(self.wal_path, "a" if append else "w")
