"""Client-side view of the fleet control plane.

:class:`FleetClient` talks to the fleet endpoints (plan cache, scheduler
view, dump) and hands out per-gang clients: the existing
:class:`~bagua_tpu.distributed.rendezvous.RendezvousClient` and
:class:`~bagua_tpu.service.autotune_client.AutotuneClient` work unchanged
against a gang namespace because both concatenate paths onto a base URL —
the namespace is just the ``/g/<gang_id>`` prefix.

The cross-gang warm start: a gang that finishes tuning publishes its
proven plan (:func:`publish_engine_plan`); a brand-new gang with the same
(model fingerprint, topology, algorithm, wire precision) adopts it at
step 0 (:func:`adopt_fleet_plan` → ``plan_source="fleet"``) — the
resilience manifest's warm start, generalized across jobs.
"""

import hashlib
import json
import logging
from typing import Dict, Optional

logger = logging.getLogger("bagua_tpu.fleet")

__all__ = [
    "gang_endpoint",
    "model_fingerprint",
    "engine_plan_key",
    "FleetClient",
    "publish_engine_plan",
    "adopt_fleet_plan",
]


def gang_endpoint(base: str, gang_id: str) -> str:
    """The namespaced endpoint a gang's rendezvous/autotune clients use."""
    from urllib.parse import quote

    if "://" not in base:
        base = "http://" + base
    return f"{base.rstrip('/')}/g/{quote(str(gang_id), safe='')}"


def model_fingerprint(declarations) -> str:
    """Stable fingerprint of a model's communicable-tensor set: sha256 over
    the sorted (name, num_elements, dtype) triples, independent of bucket
    assignment (the thing the cached plan *decides*)."""
    triples = sorted(
        (td.name, int(td.num_elements), str(td.dtype)) for td in declarations
    )
    digest = hashlib.sha256(json.dumps(triples).encode()).hexdigest()
    return digest[:16]


def engine_plan_key(ddp, wire_precision: Optional[str] = None) -> Dict[str, str]:
    """The plan-cache key tuple for a live engine: model fingerprint from
    its declaration list, topology from the gang size, algorithm from the
    impl class, wire precision from the impl knob (or the caller)."""
    decls = [td for bucket in ddp.plan.declarations() for td in bucket]
    if wire_precision is None:
        wire_precision = str(getattr(ddp.impl, "wire_precision", None) or "f32")
    return {
        "fingerprint": model_fingerprint(decls),
        "topology": f"ranks{ddp.group.size}",
        "algorithm": type(ddp.impl).__name__,
        "wire_precision": wire_precision,
    }


class FleetClient:
    """Stdlib-only client for the ``/fleet/*`` endpoints, hardened on the
    same retry/breaker machinery as every other service client."""

    def __init__(self, endpoint: str, timeout_s: Optional[float] = None):
        from bagua_tpu.env import (
            get_rpc_breaker_cooldown_s, get_rpc_breaker_threshold,
            get_rpc_timeout_s,
        )
        from bagua_tpu.resilience.retry import CircuitBreaker, RetryPolicy

        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = get_rpc_timeout_s() if timeout_s is None else timeout_s
        self.retry_policy = RetryPolicy()
        self.breaker = CircuitBreaker(
            failure_threshold=get_rpc_breaker_threshold(),
            cooldown_s=get_rpc_breaker_cooldown_s(),
            name="fleet-rpc",
        )

    # -- transport -------------------------------------------------------------

    def _call_once(self, path: str, payload: Optional[dict] = None) -> dict:
        import urllib.error
        import urllib.request

        from bagua_tpu.observability.tracing import client_span

        url = self.endpoint + path
        with client_span(
            f"rpc {path}", component="fleet", endpoint=path
        ) as (_sp, trace_headers):
            if payload is None:
                req = urllib.request.Request(url, headers=dict(trace_headers))
            else:
                req = urllib.request.Request(
                    url,
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json", **trace_headers},
                )
            try:
                with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as e:
                if e.code == 429:
                    from bagua_tpu.resilience.retry import (
                        BackpressureError, retry_after_hint,
                    )

                    raise BackpressureError(
                        f"{url}: 429 backpressure", retry_after_hint(e) or 0.0
                    ) from e
                raise

    def _call(self, path: str, payload: Optional[dict] = None) -> dict:
        from bagua_tpu.resilience.retry import retry_call

        return retry_call(
            self._call_once, path, payload,
            policy=self.retry_policy, breaker=self.breaker, label=path,
        )

    # -- per-gang clients -------------------------------------------------------

    def gang_endpoint(self, gang_id: str) -> str:
        return gang_endpoint(self.endpoint, gang_id)

    def rendezvous_client(self, gang_id: str, node_rank: int, **kwargs):
        from bagua_tpu.distributed.rendezvous import RendezvousClient

        return RendezvousClient(
            self.gang_endpoint(gang_id), node_rank=node_rank, **kwargs
        )

    def autotune_client(self, gang_id: str, **kwargs):
        from urllib.parse import quote, urlparse

        from bagua_tpu.service.autotune_client import AutotuneClient

        parsed = urlparse(self.endpoint)
        return AutotuneClient(
            host=parsed.hostname,
            port=parsed.port,
            prefix=f"/g/{quote(str(gang_id), safe='')}",
            **kwargs,
        )

    # -- plan cache --------------------------------------------------------------

    def publish_plan(
        self,
        fingerprint: str,
        topology: str,
        algorithm: str,
        wire_precision: str,
        plan: dict,
        meta: Optional[dict] = None,
    ) -> str:
        out = self._call(
            "/fleet/plan/publish",
            {
                "fingerprint": fingerprint,
                "topology": topology,
                "algorithm": algorithm,
                "wire_precision": wire_precision,
                "plan": plan,
                "meta": meta or {},
            },
        )
        return out["key"]

    def lookup_plan(
        self, fingerprint: str, topology: str, algorithm: str,
        wire_precision: str, gang: Optional[str] = None,
    ) -> Optional[dict]:
        """Cache lookup.  Passing the gang's identity journals the adoption
        on the control plane (the remediation tier's correlation record)
        and applies canary gating — a plan still proving itself is only
        served to its cohort.  Without ``gang`` this is the legacy
        read-only lookup."""
        payload = {
            "fingerprint": fingerprint,
            "topology": topology,
            "algorithm": algorithm,
            "wire_precision": wire_precision,
        }
        if gang is not None:
            payload["gang"] = str(gang)
        out = self._call("/fleet/plan/lookup", payload)
        return out if out.get("found") else None

    # -- fleet views --------------------------------------------------------------

    def scheduler_view(self) -> dict:
        return self._call("/fleet/scheduler")

    def gangs(self) -> dict:
        return self._call("/fleet/gangs")

    def dump(self) -> dict:
        return self._call("/fleet/dump")

    def health(self) -> dict:
        return self._call("/fleet/health")

    # -- tracing ------------------------------------------------------------------

    def push_spans(self, gang_id: str, spans, events=None) -> dict:
        """Ship a batch of finished client spans (``bagua.span.v1`` dicts,
        e.g. ``Tracer.finished_spans()``) — plus optional timeline events —
        into the gang's volatile span ring on the control plane, where
        ``/fleet/timeline`` joins them with the server-side request spans."""
        from urllib.parse import quote

        return self._call(
            f"/g/{quote(str(gang_id), safe='')}/spans",
            {"spans": list(spans), "events": list(events or [])},
        )

    def timeline(self, gang_id: str) -> dict:
        """The gang's causally ordered timeline (client spans, server spans,
        StepSummary windows, health alerts, flight digests, incidents)."""
        from urllib.parse import quote

        return self._call(f"/fleet/timeline?gang={quote(str(gang_id), safe='')}")

    # -- incidents ----------------------------------------------------------------

    def push_incidents(self, gang_id: str, incidents) -> dict:
        """Ship a batch of regression-sentinel ``perf_regression``
        incidents (e.g. ``RegressionSentinel.drain_incidents()``) into the
        gang's volatile incident ring — what ``/fleet/scheduler`` folds
        into the ``regressed`` verdict and ``/fleet/incidents`` lists."""
        from urllib.parse import quote

        return self._call(
            f"/g/{quote(str(gang_id), safe='')}/incidents",
            {"incidents": list(incidents)},
        )

    def incidents(self, gang_id: Optional[str] = None) -> dict:
        """The fleet's volatile incident tier — every gang's recent
        ``perf_regression`` events, or one gang's when ``gang_id`` is
        given."""
        from urllib.parse import quote

        if gang_id is None:
            return self._call("/fleet/incidents")
        return self._call(f"/fleet/incidents?gang={quote(str(gang_id), safe='')}")

    # -- autopilot decisions ------------------------------------------------------

    def push_decisions(self, gang_id: str, decisions) -> dict:
        """Ship a batch of autopilot ``plan_decision`` events (e.g.
        ``GangAutopilot.drain_decisions()``) into the gang's volatile
        decision ring — what ``/fleet/scheduler`` surfaces as the gang's
        ``autopilot`` column and ``/fleet/decisions`` lists."""
        from urllib.parse import quote

        return self._call(
            f"/g/{quote(str(gang_id), safe='')}/decisions",
            {"decisions": list(decisions)},
        )

    def decisions(self, gang_id: Optional[str] = None) -> dict:
        """The fleet's volatile decision tier — every gang's recent
        autopilot ``plan_decision`` events, or one gang's when ``gang_id``
        is given."""
        from urllib.parse import quote

        if gang_id is None:
            return self._call("/fleet/decisions")
        return self._call(f"/fleet/decisions?gang={quote(str(gang_id), safe='')}")

    def metrics_text(self) -> str:
        """The server's ``/fleet/metrics`` Prometheus text exposition."""
        import urllib.request

        req = urllib.request.Request(self.endpoint + "/fleet/metrics")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return resp.read().decode()

    # -- remediation --------------------------------------------------------------

    def remediate(self, quarantine_threshold: Optional[int] = None) -> dict:
        """Run one RemediationEngine sweep on the control plane; returns
        the sweep summary (quarantined plans, rollback/resize directives
        issued, canary graduations, emitted events)."""
        payload = {}
        if quarantine_threshold is not None:
            payload["quarantine_threshold"] = int(quarantine_threshold)
        return self._call("/fleet/remediate", payload)

    def remediation(self) -> dict:
        """The durable remediation tier: every plan's quarantine/canary
        status, per-gang directives, and action counters."""
        return self._call("/fleet/remediation")

    def shards(self) -> dict:
        """Shard topology: shard count, gangs per shard, per-shard WAL
        replay wall time."""
        return self._call("/fleet/shards")

    def gang_directive(self, gang_id: str) -> Optional[dict]:
        """The gang's oldest pending remediation directive, or None —
        what the elastic-resume path polls before picking a world size."""
        from urllib.parse import quote

        out = self._call(f"/g/{quote(str(gang_id), safe='')}/directive")
        return out.get("directive")

    def ack_directive(self, gang_id: str, directive_id: int) -> bool:
        """Acknowledge a directive once acted on (clears the scheduler
        view's remediation-pending marker)."""
        from urllib.parse import quote

        out = self._call(
            f"/g/{quote(str(gang_id), safe='')}/directive/ack",
            {"id": int(directive_id)},
        )
        return bool(out.get("ok"))


def publish_engine_plan(
    fleet: FleetClient, ddp, meta: Optional[dict] = None,
    wire_precision: Optional[str] = None,
) -> Optional[str]:
    """Publish a live engine's proven plan to the cross-gang cache
    (best-effort; returns the cache key, or None when the engine has no
    exportable plan or the fleet is unreachable)."""
    payload = ddp.export_plan_payload()
    if payload is None:
        return None
    key = engine_plan_key(ddp, wire_precision=wire_precision)
    try:
        return fleet.publish_plan(plan=payload, meta=meta, **key)
    except (OSError, ConnectionError) as e:
        logger.warning("fleet plan publish failed (advisory): %s", e)
        return None


def adopt_fleet_plan(
    fleet: FleetClient, ddp, telemetry=None,
    wire_precision: Optional[str] = None, gang: Optional[str] = None,
) -> Optional[str]:
    """Step-0 warm start from the cross-gang plan cache.

    Looks up the engine's (fingerprint, topology, algorithm, wire
    precision) tuple; on a hit, adopts the cached plan and returns
    ``"fleet"`` — the ``plan_source`` value generalizing the resilience
    manifest's ``"carried"``.  Returns None on a miss, an unreachable
    fleet, or a payload that no longer fits (all advisory: the gang just
    runs its fresh plan).  With a ``gang`` identity the adoption is
    journaled on the control plane and canary gating applies — a plan
    still proving itself is withheld from gangs outside its cohort."""
    key = engine_plan_key(ddp, wire_precision=wire_precision)
    try:
        entry = fleet.lookup_plan(gang=gang, **key)
    except (OSError, ConnectionError) as e:
        logger.warning("fleet plan lookup failed (advisory): %s", e)
        return None
    if entry is None:
        return None
    try:
        adopted = ddp.adopt_plan_payload(entry["plan"])
    except Exception as e:
        logger.warning("fleet plan %s did not fit this engine: %s", key, e)
        return None
    if not adopted:
        return None
    logger.info("adopted fleet plan for %s at step 0 (plan_source=fleet)", key)
    if telemetry is not None:
        telemetry.on_restart(
            step=0,
            old_world_size=ddp.group.size,
            new_world_size=ddp.group.size,
            plan_source="fleet",
            lost_steps=0,
        )
    return "fleet"
