"""Shared type definitions.

TPU-native analog of the reference's ``bagua/bagua_define.py:12-58``: the
tensor declaration and tunable-hyperparameter records exchanged with the
autotune service, plus the ``ReduceOp`` enum used by the collective API
(reference ``bagua/torch_api/communication.py:63-75``).
"""

import enum
from typing import Dict, List, Optional

from pydantic import BaseModel


class DType(str, enum.Enum):
    F32 = "f32"
    F16 = "f16"
    BF16 = "bf16"
    U8 = "u8"
    I32 = "i32"
    I64 = "i64"


class ReduceOp(enum.IntEnum):
    """Reduction ops for explicit collectives (values mirror the reference)."""

    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    BOR = 4
    BAND = 5
    BXOR = 6
    AVG = 10


class TensorDeclaration(BaseModel):
    """One communicable tensor, as registered with the autotune service."""

    name: str
    num_elements: int
    dtype: str  # DType value


def dtype_itemsize(dtype: str) -> int:
    return {
        DType.F32.value: 4,
        DType.F16.value: 2,
        DType.BF16.value: 2,
        DType.U8.value: 1,
        DType.I32.value: 4,
        DType.I64.value: 8,
    }[dtype]


class BaguaHyperparameter(BaseModel):
    """The tunable hyperparameters the autotune service optimizes.

    Mirrors reference ``bagua_define.py:34-50``: bucket assignment (list of
    buckets, each a list of tensor declarations), the bucket size in bytes,
    and whether hierarchical (intra-axis first) reduction is used.
    """

    buckets: List[List[TensorDeclaration]] = []
    bucket_size: int = 10 * 1024 ** 2
    is_hierarchical_reduce: bool = False
    #: beyond-reference knob: exchange gradients in bfloat16 — half the ICI
    #: bytes, applied only to algorithms exposing ``wire_dtype``.  Tri-state:
    #: ``None`` means the service is NOT tuning this dimension (the client
    #: must leave any user-configured wire dtype untouched); True/False are
    #: live proposals from a ``tune_wire_dtype=True`` service, which then
    #: owns the knob.
    wire_bf16: Optional[bool] = None
    #: execution-mode knob: run each bucket's collective from inside the
    #: backward pass (custom_vjp per bucket) instead of one monolithic
    #: exchange after it.  Same tri-state contract as ``wire_bf16``: ``None``
    #: means the service is not tuning this dimension.
    overlap: Optional[bool] = None
    #: the trace-driven planner's predicted exposed (un-hidden) communication
    #: time for this bucket assignment, in milliseconds — ``None`` when no
    #: measured spans were reported (pure-BO proposals).  Informational:
    #: clients thread it into the telemetry hub's re-bucket record so
    #: predicted-vs-measured drift is auditable per plan swap.
    predicted_exposed_ms: Optional[float] = None

    def update(self, param_dict: Dict) -> "BaguaHyperparameter":
        tmp = self.model_dump()
        for key, value in param_dict.items():
            if key in tmp:
                if key == "buckets":
                    value = [
                        [TensorDeclaration(**td) if isinstance(td, dict) else td for td in bucket]
                        for bucket in value
                    ]
                setattr(self, key, value)
        return self
