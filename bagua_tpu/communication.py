"""Communication substrate: process groups as device meshes + collectives.

TPU-native redesign of the reference's ``bagua/torch_api/communication.py``
(1.4k LoC) and its Rust/Aluminum/NCCL stack:

* The reference builds three NCCL communicators per process group — global,
  inter-node and intra-node (``communication.py:116-163``).  Here a
  :class:`BaguaProcessGroup` owns a ``jax.sharding.Mesh`` with two named axes,
  ``("inter", "intra")``; hierarchical communication is reduction over the
  ``intra`` axis followed by the ``inter`` axis, and the "global communicator"
  is simply both axes at once.  On real hardware ``intra`` should map to an
  ICI slice and ``inter`` to DCN.
* The reference's NCCL-unique-id rendezvous through a torch TCPStore
  (``communication.py:551-560``) maps to ``jax.distributed.initialize``.
* The reference's per-group high-priority CUDA stream + event dance
  (``communication.py:590-596``) has no analog: XLA issues collectives
  asynchronously and overlaps them with compute on its own.

Two collective surfaces are provided:

1. **In-step** (:func:`allreduce_inplace` et al. — suffix kept for API parity
   with reference ``communication.py:922-1000``): traced functions used inside
   a ``shard_map`` / ``pjit`` step over a group's mesh axes.  This is the hot
   path; algorithms compose these.
2. **Eager** (:func:`allreduce`, :func:`allgather`, ...): drop-in analogs of
   the reference's explicit collectives (``communication.py:573-1401``).
   They operate on *stacked per-rank* arrays: single-controller groups pass
   the full ``(group.size, ...)`` stack (JAX sees every rank's value at
   once); multi-host groups pass each process's *local view*
   ``(len(local_ranks(group)), ...)`` and get back their own ranks' results
   (assembled via ``make_array_from_process_local_data``).  Each output
   slice is what that rank would hold after the collective.
"""

import contextlib
import contextvars
import functools
import pickle
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from bagua_tpu.defs import ReduceOp
from bagua_tpu.mesh import MeshSpec

INTER_AXIS = "inter"
INTRA_AXIS = "intra"
ALL_AXES = (INTER_AXIS, INTRA_AXIS)

_default_group: Optional["BaguaProcessGroup"] = None


class BaguaProcessGroup:
    """A group of ranks arranged on a named device mesh.

    Without a ``mesh_spec`` this is the classic 2-D ``(inter, intra)`` mesh:
    ``intra_size`` ranks form the fast inner axis (ICI / one host);
    ``inter_size = size // intra_size`` forms the slower outer axis (DCN),
    and every axis carries the data-parallel exchange.

    With a :class:`bagua_tpu.mesh.MeshSpec` the mesh axes are the spec's
    named axes (e.g. ``dp × tp``): the engine's bucketed exchange rides the
    spec's *data* axes only, while *model* axes (tp/sp/ep/pp) are left to the
    model's own collectives.
    """

    def __init__(
        self,
        devices: Sequence,
        intra_size: Optional[int] = None,
        name: str = "bagua",
        mesh_spec: Optional[MeshSpec] = None,
    ):
        devices = list(devices)
        n = len(devices)
        self.name = name
        self.devices = devices
        self.mesh_spec = mesh_spec
        if mesh_spec is not None:
            if intra_size is not None:
                raise ValueError(
                    "pass either intra_size (legacy inter/intra mesh) or "
                    "mesh_spec (named mesh), not both"
                )
            self.mesh = Mesh(mesh_spec.device_array(devices), mesh_spec.names)
            # Legacy hierarchical split is undefined on a named mesh: the
            # whole group counts as one "intra" domain for consumers that
            # only read the attributes (hierarchical exchange itself is
            # fenced at DDP construction).
            self.intra_size = n
            self.inter_size = 1
            return
        if intra_size is None:
            # Default: devices-per-process (one host = one ICI domain).
            per_proc = max(1, n // max(jax.process_count(), 1))
            intra_size = per_proc if n % per_proc == 0 else n
        if n % intra_size != 0:
            raise ValueError(f"group size {n} not divisible by intra_size {intra_size}")
        self.intra_size = intra_size
        self.inter_size = n // intra_size
        self.mesh = Mesh(
            np.array(devices).reshape(self.inter_size, self.intra_size),
            ALL_AXES,
        )

    @property
    def size(self) -> int:
        return len(self.devices)

    @property
    def all_axes(self) -> Tuple[str, ...]:
        """Every mesh axis name (state stacks/shards over all of them)."""
        if self.mesh_spec is not None:
            return self.mesh_spec.names
        return ALL_AXES

    @property
    def data_axes(self) -> Tuple[str, ...]:
        """Axes the batch shards over and the gradient exchange rides."""
        if self.mesh_spec is not None:
            return self.mesh_spec.data_axes
        return ALL_AXES

    @property
    def model_axes(self) -> Tuple[str, ...]:
        if self.mesh_spec is not None:
            return self.mesh_spec.model_axes
        return ()

    @property
    def exchange_size(self) -> int:
        """Ranks in the gradient-exchange ring (== ``size`` unless model
        axes are present — then the exchange communicates only among ranks
        sharing a model-axis coordinate)."""
        if self.mesh_spec is not None:
            return self.mesh_spec.exchange_size
        return self.size

    @property
    def spans_processes(self) -> bool:
        """True when the group's devices live in more than one OS process
        (multi-host / multi-controller deployment)."""
        return len({d.process_index for d in self.devices}) > 1

    @property
    def ranks(self) -> List[int]:
        return list(range(self.size))

    def __repr__(self) -> str:
        if self.mesh_spec is not None:
            return f"BaguaProcessGroup(size={self.size}, mesh={self.mesh_spec!r})"
        return f"BaguaProcessGroup(size={self.size}, inter={self.inter_size}, intra={self.intra_size})"

    # ---- shard_map helpers -------------------------------------------------

    def shard_map(self, fn: Callable, in_specs, out_specs, check_vma: bool = False):
        """``jax.shard_map`` over this group's mesh."""
        return jax.shard_map(
            fn, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )


def init_process_group(
    devices: Optional[Sequence] = None,
    intra_size: Optional[int] = None,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    mesh_spec: Optional[MeshSpec] = None,
) -> BaguaProcessGroup:
    """Initialize the default process group (reference ``communication.py:446``).

    On multi-host deployments pass ``coordinator_address``/``num_processes``/
    ``process_id`` (or set the usual env) and this calls
    ``jax.distributed.initialize`` — the analog of the reference's
    torch-store/NCCL-unique-id rendezvous.  Single-host callers just get a
    mesh over the local devices.
    """
    global _default_group
    if coordinator_address is not None and not jax.distributed.is_initialized():
        # Must run before anything initializes the XLA backend (jax.distributed
        # requirement); callers on multi-host must call init_process_group first.
        # Skipped when the runtime is already up (e.g. re-initializing the
        # default group after a checkpoint-restart in the same process).
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    if devices is None:
        devices = jax.devices()
        if mesh_spec is not None:
            devices = devices[: mesh_spec.size]
    _default_group = BaguaProcessGroup(
        devices, intra_size=intra_size, mesh_spec=mesh_spec
    )
    return _default_group


def is_initialized() -> bool:
    return _default_group is not None


def get_default_group() -> BaguaProcessGroup:
    if _default_group is None:
        init_process_group()
    return _default_group  # type: ignore


def new_group(
    ranks: Optional[Sequence[int]] = None,
    intra_size: Optional[int] = None,
    mesh_spec: Optional[MeshSpec] = None,
) -> BaguaProcessGroup:
    """Create a new group from ranks of the default group
    (reference ``communication.py:217``)."""
    base = get_default_group()
    if ranks is None:
        devices = base.devices
    else:
        devices = [base.devices[r] for r in ranks]
    return BaguaProcessGroup(devices, intra_size=intra_size, mesh_spec=mesh_spec)


# ---------------------------------------------------------------------------
# In-step collectives (call inside shard_map over a group's mesh axes)
# ---------------------------------------------------------------------------


# The ambient axes an ``axis=None`` collective resolves to.  The engine
# enters :func:`default_axes` inside its shard_map body (the body executes
# during tracing, so the context is live for exactly that trace): on a
# named mesh the algorithm's collectives then ride the group's data axes
# while explicit-axis collectives (the model's tp/sp/ep exchanges) are
# untouched.  Outside any context the legacy ALL_AXES default applies.
_DEFAULT_AXES: contextvars.ContextVar = contextvars.ContextVar(
    "bagua_default_axes", default=None
)


@contextlib.contextmanager
def default_axes(axes: Sequence[str]):
    """Make ``axes`` the resolution of ``axis=None`` collectives within."""
    token = _DEFAULT_AXES.set(tuple(axes))
    try:
        yield
    finally:
        _DEFAULT_AXES.reset(token)


def _axes(axis) -> Tuple[str, ...]:
    if axis is None:
        ambient = _DEFAULT_AXES.get()
        return ambient if ambient is not None else ALL_AXES
    if isinstance(axis, str):
        return (axis,)
    return tuple(axis)


def rank_id(axis=None) -> jnp.ndarray:
    """Linear rank of the caller within the given axes (row-major)."""
    axes = _axes(axis)
    r = jnp.zeros((), jnp.int32)
    for a in axes:
        r = r * jax.lax.axis_size(a) + jax.lax.axis_index(a)
    return r


def axis_size(axis=None) -> int:
    axes = _axes(axis)
    n = 1
    for a in axes:
        n *= jax.lax.axis_size(a)
    return n


def allreduce_inplace(x: jnp.ndarray, op: ReduceOp = ReduceOp.AVG, axis=None) -> jnp.ndarray:
    """Allreduce of the local view over the group axes
    (reference ``communication.py:922``)."""
    axes = _axes(axis)
    op = ReduceOp(op)
    if op == ReduceOp.SUM:
        return jax.lax.psum(x, axes)
    if op == ReduceOp.AVG:
        return jax.lax.pmean(x, axes)
    if op == ReduceOp.MAX:
        return jax.lax.pmax(x, axes)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(x, axes)
    if op == ReduceOp.PRODUCT:
        # No pprod primitive: log-sum-exp trick fails for negatives; use gather.
        gathered = jax.lax.all_gather(x, axes, tiled=False)
        return jnp.prod(gathered.reshape((-1,) + x.shape), axis=0)
    if op in (ReduceOp.BOR, ReduceOp.BAND, ReduceOp.BXOR):
        gathered = jax.lax.all_gather(x, axes, tiled=False).reshape((-1,) + x.shape)
        red = {
            ReduceOp.BOR: jnp.bitwise_or,
            ReduceOp.BAND: jnp.bitwise_and,
            ReduceOp.BXOR: jnp.bitwise_xor,
        }[op]
        out = gathered[0]
        for i in range(1, gathered.shape[0]):
            out = red(out, gathered[i])
        return out
    raise ValueError(f"unsupported op {op}")


def allgather_inplace(x: jnp.ndarray, axis=None, tiled: bool = False) -> jnp.ndarray:
    return jax.lax.all_gather(x, _axes(axis), tiled=tiled)


def reduce_scatter_inplace(x: jnp.ndarray, op: ReduceOp = ReduceOp.SUM, axis=None) -> jnp.ndarray:
    """Reduce-scatter a flat array: returns this rank's 1/n chunk of the
    reduction (reference ``communication.py:1219`` reducescatter)."""
    axes = _axes(axis)
    op = ReduceOp(op)
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError("reduce_scatter supports SUM/AVG")
    out = jax.lax.psum_scatter(x, axes, scatter_dimension=0, tiled=True)
    if op == ReduceOp.AVG:
        out = out / axis_size(axes)
    return out


def broadcast_inplace(x: jnp.ndarray, src_rank: int = 0, axis=None) -> jnp.ndarray:
    """Broadcast rank ``src_rank``'s local view to all ranks."""
    axes = _axes(axis)
    me = rank_id(axes)
    masked = jnp.where(me == src_rank, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axes)


def alltoall_inplace(x: jnp.ndarray, axis=None) -> jnp.ndarray:
    """All-to-all of the leading dim (must divide by group size)."""
    axes = _axes(axis)
    return jax.lax.all_to_all(x, axes, split_axis=0, concat_axis=0, tiled=True)


def alltoall_v_inplace(x: jnp.ndarray, send_counts: jnp.ndarray, axis=None):
    """Variable-count all-to-all (reference ``alltoall_v``,
    ``communication.py:1263``), in the static-shape idiom XLA requires.

    Args:
        x: ``(n, capacity, ...)`` — chunk j (padded to ``capacity``) goes to
           rank j; only the first ``send_counts[j]`` rows of chunk j are
           meaningful.
        send_counts: ``(n,)`` int array — may differ per rank (it is data,
           not shape).

    Returns:
        ``(recv, recv_counts)``: ``recv[j]`` is the (padded) chunk received
        from rank j, valid up to ``recv_counts[j]`` rows.
    """
    axes = _axes(axis)
    n = axis_size(axes)
    if x.shape[0] != n:
        raise ValueError(f"leading dim {x.shape[0]} != group size {n}")
    recv = jax.lax.all_to_all(x, axes, split_axis=0, concat_axis=0, tiled=True)
    recv = recv.reshape((n,) + x.shape[1:])
    recv_counts = jax.lax.all_to_all(
        send_counts.reshape(n, 1), axes, split_axis=0, concat_axis=0, tiled=True
    ).reshape(n)
    return recv, recv_counts


def ppermute_apply(x: jnp.ndarray, perm, axis=None) -> jnp.ndarray:
    """Apply an explicit (src, dst) permutation over the (possibly combined)
    group axes — one point-to-point ``collective-permute``, never a gather.

    ``lax.ppermute`` accepts the combined axes tuple directly, with ranks
    flattened row-major (inter major, intra minor) — exactly this module's
    rank convention — so arbitrary cross-axis routes lower to a single
    XLA collective-permute riding ICI/DCN point-to-point.  Like
    ``lax.ppermute``, destinations absent from ``perm`` receive zeros."""
    axes = _axes(axis)
    return jax.lax.ppermute(x, axes[0] if len(axes) == 1 else axes, perm)


def ppermute_shift(x: jnp.ndarray, shift: int, axis=None) -> jnp.ndarray:
    """Ring shift: rank i receives rank (i - shift) mod n's value (ranks
    row-major over the combined axes).  One collective-permute."""
    axes = _axes(axis)
    n = axis_size(axes)
    shift = shift % n
    perm = [(i, (i + shift) % n) for i in range(n)]
    return ppermute_apply(x, perm, axes)


def hierarchical_allreduce_inplace(x: jnp.ndarray, op: ReduceOp = ReduceOp.AVG) -> jnp.ndarray:
    """Intra-axis reduce, then inter-axis reduce (reference hierarchical
    communicator, ``communicators/mod.rs:262-446``).  Numerically identical to
    a flat allreduce but keeps the two phases separate so algorithms can
    compress between them."""
    op = ReduceOp(op)
    if op == ReduceOp.AVG:
        x = allreduce_inplace(x, op=ReduceOp.SUM, axis=INTRA_AXIS)
        x = allreduce_inplace(x, op=ReduceOp.SUM, axis=INTER_AXIS)
        n = axis_size(ALL_AXES)
        return jax.tree.map(lambda l: l / n, x)  # x may be a pytree (tuple fusion)
    # SUM/MAX/MIN/PRODUCT/bitwise all compose associatively across phases.
    x = allreduce_inplace(x, op=op, axis=INTRA_AXIS)
    return allreduce_inplace(x, op=op, axis=INTER_AXIS)


# ---------------------------------------------------------------------------
# Eager collectives over stacked (size, ...) arrays
# ---------------------------------------------------------------------------


# Jitted eager-collective cache: (mesh, key) -> compiled callable.  Without
# this every eager call would rebuild a closure and re-trace (~80x overhead).
_EAGER_CACHE: dict = {}


def _eager_compiled(group: BaguaProcessGroup, key: tuple, make_fn: Callable):
    cache_key = (group.mesh, key)
    cached = _EAGER_CACHE.get(cache_key)
    if cached is None:
        fn = make_fn()
        axes = group.all_axes

        def per_rank(x):
            # eager collectives span the WHOLE group, whatever its axes are
            # named (the body runs at trace time, so the context is live for
            # the axis=None resolution inside fn)
            with default_axes(axes):
                return fn(x[0])[None]

        cached = jax.jit(
            group.shard_map(per_rank, in_specs=P(axes), out_specs=P(axes))
        )
        _EAGER_CACHE[cache_key] = cached
    return cached


def local_ranks(group: Optional[BaguaProcessGroup] = None) -> List[int]:
    """Ranks of ``group`` whose devices this process owns, in rank order —
    the order of the slices this process passes to (and receives from) the
    eager collectives on a multi-host group."""
    group = group or get_default_group()
    me = jax.process_index()
    return [r for r, d in enumerate(group.devices) if d.process_index == me]


def _eager(group: Optional[BaguaProcessGroup], key: tuple, make_fn: Callable):
    """Lift ``make_fn()(local_value) -> local_value`` over stacked per-rank
    arrays.  The stacked leading axis is sharded over the mesh, so each
    rank's local block is ``(1, ...)``; we strip/restore that axis around the
    collective.  Compiled callables are cached per ``(mesh, key)`` (jit
    handles shape/dtype polymorphism internally).

    **Single-controller groups** take and return the full ``(size, ...)``
    stack — the caller sees every rank's value at once.

    **Multi-host groups** (reference explicit collectives work across nodes,
    ``communication.py:573-1401``) take the *local view*: each process passes
    a ``(n_local_ranks, ...)`` array holding the send values for its own
    ranks (order :func:`local_ranks`) and receives back a numpy array with
    its own ranks' results.  The stacks are assembled into one global array
    with ``jax.make_array_from_process_local_data`` — every process in the
    group must call collectives in the same order (the usual SPMD
    contract)."""
    group = group or get_default_group()
    compiled = _eager_compiled(group, key, make_fn)
    if not group.spans_processes:
        return compiled

    # The local-view wrapper is cached alongside the compiled fn — rebuilding
    # the sharding and rescanning group.devices per call would put O(devices)
    # python work on the eager hot path.
    cache_key = (group.mesh, key, "local_view")
    cached = _EAGER_CACHE.get(cache_key)
    if cached is not None:
        return cached

    from jax.sharding import NamedSharding

    sharding = NamedSharding(group.mesh, P(group.all_axes))
    n_local = len(local_ranks(group))

    def call_local_view(local):
        local = np.asarray(local)
        if local.shape[0] != n_local:
            raise ValueError(
                f"multi-host eager collective: expected this process's "
                f"({jax.process_index()}) local stack of shape ({n_local}, ...) "
                f"for its {n_local} rank(s), got {local.shape}"
            )
        global_shape = (group.size,) + local.shape[1:]
        garr = jax.make_array_from_process_local_data(sharding, local, global_shape)
        out = compiled(garr)
        shards = sorted(
            out.addressable_shards, key=lambda s: s.index[0].start or 0
        )
        return np.concatenate([np.asarray(s.data) for s in shards], axis=0)

    _EAGER_CACHE[cache_key] = call_local_view
    return call_local_view


def allreduce(send, op: ReduceOp = ReduceOp.AVG, comm: Optional[BaguaProcessGroup] = None):
    """Eager allreduce (reference ``communication.py:848``). ``send`` is a
    stacked per-rank array: ``(group.size, ...)`` on a single-controller
    group, or this process's ``(len(local_ranks(group)), ...)`` local view on
    a multi-host group (see :func:`_eager`)."""
    op = ReduceOp(op)
    return _eager(
        comm, ("allreduce", op), lambda: functools.partial(allreduce_inplace, op=op)
    )(send)


def allgather(send, comm: Optional[BaguaProcessGroup] = None):
    """Each output slice is the concatenation of every rank's slice
    (reference ``communication.py:1038``).  ``send`` as in :func:`allreduce`
    (local view on multi-host groups)."""
    return _eager(
        comm, ("allgather",), lambda: functools.partial(allgather_inplace, tiled=True)
    )(send)


def reducescatter(send, op: ReduceOp = ReduceOp.SUM, comm: Optional[BaguaProcessGroup] = None):
    op = ReduceOp(op)
    return _eager(
        comm, ("reducescatter", op), lambda: functools.partial(reduce_scatter_inplace, op=op)
    )(send)


def broadcast(send, src: int = 0, comm: Optional[BaguaProcessGroup] = None):
    """Broadcast rank ``src``'s slice to every rank
    (reference ``communication.py:573``)."""
    return _eager(
        comm, ("broadcast", src), lambda: functools.partial(broadcast_inplace, src_rank=src)
    )(send)


def alltoall(send, comm: Optional[BaguaProcessGroup] = None):
    """Reference ``communication.py:1100`` alltoall: each rank's slice is
    split into ``size`` chunks and chunk j goes to rank j."""
    return _eager(comm, ("alltoall",), lambda: alltoall_inplace)(send)


def reduce(send, dst: int = 0, op: ReduceOp = ReduceOp.AVG, comm: Optional[BaguaProcessGroup] = None):
    """Reduce to rank ``dst``; other ranks keep their input
    (reference ``communication.py:958``)."""
    op = ReduceOp(op)

    def make():
        def fn(x):
            red = allreduce_inplace(x, op=op)
            return jnp.where(rank_id() == dst, red, x)

        return fn

    return _eager(comm, ("reduce", op, dst), make)(send)


def scatter(send, src: int = 0, comm: Optional[BaguaProcessGroup] = None):
    """Rank ``src``'s slice is chunked across ranks; rank i's output is chunk i
    (reference ``communication.py:1155``)."""

    def make():
        def fn(x):
            n = axis_size()
            full = broadcast_inplace(x, src_rank=src)
            chunks = jnp.reshape(full, (n, x.shape[0] // n) + x.shape[1:])
            return jnp.take(chunks, rank_id(), axis=0)

        return fn

    return _eager(comm, ("scatter", src), make)(send)


def gather(send, dst: int = 0, comm: Optional[BaguaProcessGroup] = None):
    """All slices concatenated at rank ``dst``.

    The reference (``communication.py:1081``) leaves the recv buffer on
    non-dst ranks untouched; XLA's uniform output shape forces *some* value
    there, so non-dst ranks receive **zeros** — an unmistakable "no data"
    (matching ``lax.ppermute``'s convention for absent sources) rather than
    fabricated values a caller could mistake for a real gather result."""

    def make():
        def fn(x):
            g = allgather_inplace(x, tiled=True)
            return jnp.where(rank_id() == dst, g, jnp.zeros_like(g))

        return fn

    return _eager(comm, ("gather", dst), make)(send)


def barrier(comm: Optional[BaguaProcessGroup] = None):
    """Barrier as a tiny allreduce (reference ``communication.py:1377-1401``).

    Needs no caller-supplied per-rank data, so unlike the other eager
    collectives it also works on multi-host groups (via a cross-process
    device sync there)."""
    group = comm or get_default_group()
    if group.spans_processes:
        procs = {d.process_index for d in group.devices}
        if len(procs) == jax.process_count():
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("bagua_tpu_barrier")
            return
        # Group-scoped: a tiny collective over the group's own mesh, so
        # processes OUTSIDE the group are not involved (a global sync here
        # would deadlock against them).
        from jax.sharding import NamedSharding

        sharding = NamedSharding(group.mesh, P(group.all_axes))
        n_local = sum(
            1 for d in group.devices if d.process_index == jax.process_index()
        )
        token = jax.make_array_from_process_local_data(
            sharding, np.ones((n_local, 1), np.float32)
        )
        out = jax.jit(
            jnp.sum, out_shardings=NamedSharding(group.mesh, P())
        )(token)
        jax.block_until_ready(out)
        return
    token = jnp.ones((group.size, 1), jnp.float32)
    jax.block_until_ready(allreduce(token, op=ReduceOp.SUM, comm=group))


def broadcast_object(obj, src: int = 0):
    """Broadcast a picklable object across hosts (reference
    ``communication.py:668`` pickles into a ByteTensor).  Single-process: no-op."""
    if jax.process_count() == 1:
        return obj
    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    # broadcast_one_to_all always ships process 0's value, so gather instead
    # and select ``src``'s entry on every process.
    sizes = multihost_utils.process_allgather(np.array([payload.size], np.int64))
    n = int(np.asarray(sizes).reshape(-1)[src])
    buf = np.zeros(n, np.uint8)
    if jax.process_index() == src:
        buf[:] = payload
    data = multihost_utils.process_allgather(buf)
    return pickle.loads(np.asarray(data).reshape(jax.process_count(), n)[src].tobytes())
