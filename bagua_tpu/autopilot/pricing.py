"""Configuration pricing: the autopilot's candidate set and its α–β costs.

A *configuration* here is the joint relaxation choice BAGUA treats as
composable — {algorithm, wire precision} — priced against the planner's
fitted :class:`~bagua_tpu.service.planner.CostModel` on the live bucket
plan's payload sizes.  The model is the same one the bucket planner
minimizes, so "cheapest" means the same thing to both controllers:

* ``gradient_allreduce`` / ``f32`` — one flat (or hierarchical) allreduce
  per bucket, priced on the ``flat`` (``intra``+``inter``) legs.
* ``gradient_allreduce`` / ``int8|int4`` — the blockwise-quantized ring,
  ``2(n-1)`` hops of compressed shards on the ``qr8``/``qr4`` legs.
* ``zero`` / ``f32`` — reduce-scatter (``rs`` leg) plus the deferred
  parameter all-gather (``ag`` leg; it rides the next step's forward, but
  a whole-step cost ranking must still pay for it).
* ``zero`` / ``int8|int4`` — the quantized ring's reduce-scatter half plus
  a full-precision all-gather.
* ``bytegrad`` — fixed int8 compression, priced like the quantized ring.

Bucket sizes are taken from the CURRENT plan — candidate algorithms would
re-bucket slightly differently, but the payload total (the β term that
dominates under a bandwidth collapse) is identical, and only the *ranking*
of candidates feeds decisions.

``bandwidth_factor`` models the collapse itself: it divides every fitted
leg's β (bytes/second) while leaving α (launch latency) untouched — that is
what a congested link physically does, and it is what lets the ranking
*flip*.  At nominal bandwidth a small-payload gang is α-dominated and the
quantized ring's ``2(n-1)`` sequential hops price above one flat allreduce
(so re-promotion is the cheapest move); under a collapse the β term
dominates and the compressed wire wins.  The autopilot derives the factor
from the incident's measured/expected ratio, turning PR 15's attribution
verdict into the operating point the candidates are priced at.  Cost
models without α–β legs (test fakes) fall back to scaling the whole wire
term.
"""

import copy
import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Configuration",
    "candidate_configurations",
    "degraded_cost_model",
    "wire_ms",
    "modeled_step_ms",
    "price_configurations",
]

#: the fitted α–β legs a bandwidth collapse degrades
_COST_MODEL_LEGS = ("flat", "intra", "inter", "rs", "ag", "pp", "qr8", "qr4")

#: precision rungs a quantized-wire configuration can sit on, cheap → safe
PRECISION_RUNGS = ("int4", "int8", "f32")


@dataclasses.dataclass(frozen=True)
class Configuration:
    """One point in the relaxation space the autopilot moves the gang over.

    ``staleness`` is the bounded-staleness budget τ (0 = bulk synchronous).
    It only exists as a knob on the algorithms that implement it
    (``stale``'s error-feedback replay, the gossip decentralized mode);
    ``as_dict``/``label`` omit it at 0 so existing consumers of the
    two-field shape are unchanged."""

    algorithm: str = "gradient_allreduce"
    precision: str = "f32"
    staleness: int = 0

    def as_dict(self) -> Dict:
        d: Dict = {"algorithm": self.algorithm, "precision": self.precision}
        if self.staleness:
            d["staleness"] = int(self.staleness)
        return d

    def label(self) -> str:
        base = f"{self.algorithm}/{self.precision}"
        return f"{base}/tau={self.staleness}" if self.staleness else base


def candidate_configurations(
    algorithms: Sequence[str] = ("gradient_allreduce", "zero"),
    precisions: Sequence[str] = ("f32", "int8"),
    staleness_taus: Sequence[int] = (0,),
) -> List[Configuration]:
    """The cross product, minus combinations that don't exist as knobs
    (``bytegrad`` compresses unconditionally — its precision is pinned;
    nonzero ``staleness`` only composes with the algorithms that carry the
    ``set_staleness_tau`` knob: ``stale`` and the gossip ``decentralized``
    mode — and those exchange at f32 only)."""
    out = []
    for algo, prec, tau in itertools.product(algorithms, precisions, staleness_taus):
        if algo == "bytegrad":
            prec = "int8"
        if tau and algo not in ("stale", "decentralized"):
            continue
        if algo in ("stale", "decentralized"):
            prec = "f32"  # bounded-staleness exchanges are f32-only
        cfg = Configuration(algorithm=algo, precision=prec, staleness=int(tau))
        if cfg not in out:
            out.append(cfg)
    return out


def degraded_cost_model(cost_model, bandwidth_factor: float = 1.0,
                        axis: Optional[str] = None,
                        exchange_axes: Sequence[str] = ()):
    """``cost_model`` at ``bandwidth_factor`` times nominal wire cost: every
    recognizable α–β leg keeps its α and has its β divided by the factor.
    Returns the model unchanged at factor 1.0 or when no leg could be
    scaled (the caller falls back to scaling the whole term — unless the
    degradation was axis-scoped, see below).

    ``axis`` scopes the collapse to one named mesh axis — the incident's
    indicted axis.  When that axis is one of ``exchange_axes`` (the axes
    the gradient exchange actually rides, ``group.data_axes``), the
    exchange legs degrade exactly as in the uniform case — the relaxation
    knobs *can* relieve the congested traffic, so the candidate ranking
    may flip.  When the indicted axis is NOT an exchange axis (a tp/ICI
    brownout under a dp-exchange gang), only that axis's ``axis_legs``
    entry degrades: the exchange pricing is untouched at any factor, the
    ranking cannot flip, and the controller correctly holds — demoting the
    dp wire precision does nothing for a tp collapse."""
    f = max(1e-6, float(bandwidth_factor))
    if abs(f - 1.0) < 1e-9:
        return cost_model
    axis_scoped = axis is not None
    degrade_exchange = (not axis_scoped) or axis in tuple(exchange_axes)
    degraded = copy.copy(cost_model)
    scaled = False
    if degrade_exchange:
        for leg in _COST_MODEL_LEGS:
            ab = getattr(cost_model, leg, None)
            if ab is not None and dataclasses.is_dataclass(ab) and hasattr(ab, "beta"):
                setattr(degraded, leg, dataclasses.replace(ab, beta=ab.beta / f))
                scaled = True
    axis_legs = getattr(cost_model, "axis_legs", None)
    if isinstance(axis_legs, dict):
        degraded.axis_legs = {}
        for ax, ab in axis_legs.items():
            hit = (ax == axis) if axis_scoped else True
            if hit and dataclasses.is_dataclass(ab) and hasattr(ab, "beta"):
                degraded.axis_legs[ax] = dataclasses.replace(ab, beta=ab.beta / f)
                scaled = True
            else:
                degraded.axis_legs[ax] = ab
    return degraded if scaled else cost_model


def wire_ms(
    cost_model,
    plan,
    n_ranks: int,
    config: Configuration,
    hierarchical: bool = False,
    bandwidth_factor: float = 1.0,
    axis: Optional[str] = None,
    exchange_axes: Sequence[str] = (),
) -> float:
    """Modeled per-step wire milliseconds of ``config`` on ``plan``'s
    buckets, at ``bandwidth_factor`` times nominal wire cost (β-degraded
    when the model exposes α–β legs, uniformly scaled otherwise).  With
    ``axis``, the degradation is scoped to the indicted axis's legs
    (see :func:`degraded_cost_model`) — a collapse on a non-exchange axis
    leaves the exchange pricing untouched, and the whole-term uniform
    fallback is suppressed (it would smear the collapse over traffic that
    never rides the indicted axis)."""
    degraded = degraded_cost_model(cost_model, bandwidth_factor,
                                   axis=axis, exchange_axes=exchange_axes)
    uniform = (degraded is cost_model and float(bandwidth_factor) != 1.0
               and axis is None)
    cost_model = degraded
    total = 0.0
    for spec in plan.specs:
        if config.algorithm == "zero":
            if config.precision in ("int8", "int4"):
                rs = cost_model.quantized_ring_wire_time(
                    spec.numel, n_ranks, config.precision
                ) / 2.0
            else:
                rs = cost_model.bucket_wire_time(spec.nbytes, wire_pattern="sharded")
            total += rs + cost_model.ag_time(spec.nbytes)
        elif config.algorithm == "bytegrad" or config.precision in ("int8", "int4"):
            prec = "int8" if config.algorithm == "bytegrad" else config.precision
            total += cost_model.quantized_ring_wire_time(spec.numel, n_ranks, prec)
        else:
            total += cost_model.bucket_wire_time(spec.nbytes, hierarchical=hierarchical)
    if uniform:
        total *= max(1e-6, float(bandwidth_factor))
    return total * 1e3


def modeled_step_ms(
    cost_model,
    plan,
    n_ranks: int,
    config: Configuration,
    compute_ms: float,
    hierarchical: bool = False,
    bandwidth_factor: float = 1.0,
    axis: Optional[str] = None,
    exchange_axes: Sequence[str] = (),
    straggler_excess_ms: float = 0.0,
) -> float:
    """``compute + wire`` — the BENCH_MODELED-style whole-step prediction
    decisions are ranked on (overlap hides part of the wire in practice;
    the hidden fraction is configuration-independent enough that it cancels
    in the ranking).

    ``straggler_excess_ms`` is the per-step excess the gang's worst rank
    adds over the gang-median pace (straggler-score incidents carry the
    measurement).  Under bulk sync the whole gang pays it every step; a
    bounded-staleness configuration lets the indicted rank skip up to τ
    consecutive rounds, so the barrier only lands the excess every τ+1
    rounds — the modeled charge is ``excess / (τ + 1)``.  At τ=0 this is
    exactly the bulk-sync cost, so the term is inert for every legacy
    candidate."""
    excess = max(0.0, float(straggler_excess_ms)) / (int(config.staleness) + 1)
    return float(compute_ms) + excess + wire_ms(
        cost_model, plan, n_ranks, config,
        hierarchical=hierarchical, bandwidth_factor=bandwidth_factor,
        axis=axis, exchange_axes=exchange_axes,
    )


def price_configurations(
    cost_model,
    plan,
    n_ranks: int,
    candidates: Sequence[Configuration],
    compute_ms: float,
    hierarchical: bool = False,
    bandwidth_factor: float = 1.0,
    axis: Optional[str] = None,
    exchange_axes: Sequence[str] = (),
    straggler_excess_ms: float = 0.0,
) -> List[Tuple[Configuration, float]]:
    """Every candidate with its modeled step-ms, cheapest first.  Cost ties
    break toward lower staleness — never pay a convergence tax for goodput
    the model says is free."""
    priced = [
        (
            cfg,
            modeled_step_ms(
                cost_model, plan, n_ranks, cfg, compute_ms,
                hierarchical=hierarchical, bandwidth_factor=bandwidth_factor,
                axis=axis, exchange_axes=exchange_axes,
                straggler_excess_ms=straggler_excess_ms,
            ),
        )
        for cfg in candidates
    ]
    priced.sort(key=lambda it: (it[1], int(it[0].staleness)))
    return priced
