"""The gang autopilot: incident attribution in, cheapest-healthy switches out.

BAGUA's thesis is that {centralized/decentralized, sync/async, full/low
precision} are composable relaxations to pick per workload; sixteen PRs in,
this repo still picked them once at construction.  The autopilot closes the
loop: it consumes the regression sentinel's attributed ``perf_regression``
incidents (PR 15), the health monitor's stability signal, and the planner's
fitted α–β cost model, and continuously moves the gang to the cheapest
configuration the evidence says is healthy — riding the engine's existing
single-recompile actions (``switch_algorithm`` / ``apply_precision_plan``),
every one statically verified before dispatch.

The decision ladder (evaluated in priority order each :meth:`~GangAutopilot.tick`):

1. **Safety** — the health monitor reset its clean streak (loss spike /
   nonfinite) while the gang runs a quantized wire: re-promote to ``f32``
   immediately (``repromote_precision``, no canary — safety moves don't
   gamble on parity).
2. **Canary adjudication** — a pending switch's probation window ended:
   commit if the post-switch loss EWMA is within ``canary_loss_factor`` of
   the pre-switch EWMA, roll back otherwise.
3. **Demotion** — ≥ ``hysteresis_incidents`` wire-dominant incidents since
   the last action and the knob is off cooldown: price every candidate at
   the incident's measured/expected bandwidth factor and switch to the
   cheapest one that models at least ``min_saving_frac`` below stay-put
   (``demote_precision`` / ``switch_algorithm``), entering a canary.
4. **Re-promotion** — ``stabilized(repromote_windows)`` clean windows, no
   wire incident within the same patience window (quarantine: the collapse
   may still be in progress) and off cooldown: re-price at nominal
   bandwidth; if the gang is no longer on
   the cheapest configuration (the collapse ended), move back — the
   goodput-recovery win a one-way demotion ratchet never collects.  Latched
   health actions are re-armed on the same evidence.

Every decision — including holds and strict-verifier rejections — is
emitted as a schema-validated ``plan_decision`` JSONL event citing the
triggering incident's ``trace_id``, so the PR 14 timeline can join
decision ↔ incident ↔ switch.
"""

import dataclasses
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

from bagua_tpu.autopilot.pricing import (
    Configuration,
    candidate_configurations,
    modeled_step_ms,
    price_configurations,
    wire_ms,
)

logger = logging.getLogger(__name__)

__all__ = ["AutopilotConfig", "GangAutopilot"]


@dataclasses.dataclass
class AutopilotConfig:
    """Policy knobs (production-shaped defaults: hysteresis, cooldown,
    canary probation, explicit re-promotion patience)."""

    #: steps a knob stays untouchable after any committed/rolled-back action
    cooldown_steps: int = 50
    #: wire-dominant incidents required before a demotion is considered
    hysteresis_incidents: int = 2
    #: probation steps between an applied switch and its commit/rollback
    canary_steps: int = 8
    #: post-switch loss EWMA must stay within this factor of the pre-switch
    #: EWMA for the canary to commit
    canary_loss_factor: float = 1.25
    #: clean health windows required before re-promotion is considered
    repromote_windows: int = 20
    #: a candidate must model at least this fraction below stay-put
    min_saving_frac: float = 0.05
    #: the precision rungs the controller may move over
    precisions: Tuple[str, ...] = ("f32", "int8")
    #: the algorithm relaxations the controller may move over
    algorithms: Tuple[str, ...] = ("gradient_allreduce", "zero")
    #: loss EWMA smoothing for the canary parity check
    loss_ewma_alpha: float = 0.2
    #: modeled compute milliseconds per step; None reads the sentinel's
    #: self-calibrated budget model
    compute_ms: Optional[float] = None


class GangAutopilot:
    """One controller per gang, driven once per step from the train loop.

    Args:
        ddp: the :class:`~bagua_tpu.ddp.DistributedDataParallel` engine
            (constructed with ``wire_precision="auto"`` if the precision
            knob should participate).
        cost_model: the planner's fitted
            :class:`~bagua_tpu.service.planner.CostModel`.
        config: :class:`AutopilotConfig`.
        sentinel: the gang's
            :class:`~bagua_tpu.observability.regression.RegressionSentinel`
            — incidents are read non-destructively, so the fleet push's
            ``drain_incidents()`` is untouched.
        health: the gang's
            :class:`~bagua_tpu.observability.health.HealthMonitor`.
        telemetry: optional hub for ``plan_decision`` events.
    """

    def __init__(self, ddp, cost_model, config: Optional[AutopilotConfig] = None,
                 sentinel=None, health=None, telemetry=None):
        self.ddp = ddp
        self.cost_model = cost_model
        self.config = config or AutopilotConfig()
        self.sentinel = sentinel
        self.health = health
        self.telemetry = telemetry
        #: every decision this controller took (dicts in plan_decision shape)
        #: — the fleet gang aggregator pushes these to the control plane
        self.decisions: List[Dict] = []
        self._pending_decisions: List[Dict] = []
        self._seen_incidents = 0
        self._wire_evidence: List[Dict] = []
        self._last_incident_trace = ""
        self._last_wire_step: Optional[int] = None
        self._cooldown_until = {"algorithm": -1, "precision": -1, "staleness": -1}
        self._canary: Optional[Dict] = None
        self._loss_ewma: Optional[float] = None
        #: count of strict-verifier rejections the controller absorbed (the
        #: CI lane asserts this stays 0 — rejected programs never dispatch)
        self.verifier_rejections = 0

    # -- introspection -------------------------------------------------------

    def current_configuration(self) -> Configuration:
        algo = self.ddp.impl.algo_name or type(self.ddp.impl).__name__
        precision = "f32"
        if self.ddp.plan is not None and hasattr(self.ddp.impl, "bucket_precisions"):
            precs = self.ddp.impl.bucket_precisions(self.ddp.plan)
            if precs:
                # the controller moves all buckets together; rank the gang by
                # its cheapest (lowest-precision) rung
                order = {"int4": 0, "int8": 1, "f32": 2}
                precision = min(precs, key=lambda p: order.get(str(p), 2))
        tau = getattr(self.ddp.impl, "staleness_tau", None) or 0
        return Configuration(
            algorithm=algo, precision=str(precision), staleness=int(tau)
        )

    def report(self) -> Dict:
        return {
            "configuration": self.current_configuration().as_dict(),
            "decisions": len(self.decisions),
            "canary_active": self._canary is not None,
            "verifier_rejections": self.verifier_rejections,
            "wire_evidence": len(self._wire_evidence),
            "last_decision": self.decisions[-1] if self.decisions else None,
        }

    def drain_decisions(self) -> List[Dict]:
        """Decisions since the last drain — what the gang aggregator pushes
        (best-effort) to the fleet control plane's decision tier."""
        out, self._pending_decisions = self._pending_decisions, []
        return out

    # -- the per-step entry point -------------------------------------------

    def tick(self, state, step: int, loss: Optional[float] = None):
        """Run the decision ladder once; returns the (possibly remapped)
        train state.  Call after ``train_step`` with the step's mean loss."""
        if loss is not None:
            a = self.config.loss_ewma_alpha
            self._loss_ewma = (
                float(loss) if self._loss_ewma is None
                else (1 - a) * self._loss_ewma + a * float(loss)
            )
        self._ingest_incidents()

        out = self._safety_repromote(state, step)
        if out is not None:
            return out
        out = self._adjudicate_canary(state, step)
        if out is not None:
            return out
        if self._canary is not None:
            return state  # probation: no new moves while a canary runs
        out = self._demote_on_wire_evidence(state, step)
        if out is not None:
            return out
        out = self._repromote_on_stability(state, step)
        if out is not None:
            return out
        return state

    # -- evidence ------------------------------------------------------------

    def _ingest_incidents(self) -> None:
        if self.sentinel is None:
            return
        new = self.sentinel.incidents[self._seen_incidents:]
        self._seen_incidents = len(self.sentinel.incidents)
        for inc in new:
            if inc.get("dominant") == "wire_slowdown":
                self._wire_evidence.append(inc)
                self._last_wire_step = int(inc.get("step", 0))
            if inc.get("trace_id"):
                self._last_incident_trace = str(inc["trace_id"])

    def _bandwidth_factor(self, incident: Dict) -> float:
        """The operating point candidates are priced at: how much slower the
        measured step ran than the budget's expectation.  The incident is
        wire-dominant, so the whole overshoot is charged to bandwidth."""
        expected = float(incident.get("expected_ms") or 0.0)
        measured = float(incident.get("measured_ms") or 0.0)
        if expected <= 0.0:
            return 1.0
        return max(1.0, measured / expected)

    def _compute_ms(self) -> float:
        if self.config.compute_ms is not None:
            return float(self.config.compute_ms)
        budget = getattr(self.sentinel, "budget", None)
        return float(getattr(budget, "compute_ms", 0.0) or 0.0)

    def _healthy(self, n_windows: int = 1) -> bool:
        return self.health is None or self.health.stabilized(n_windows)

    def _off_cooldown(self, step: int, knobs: Tuple[str, ...]) -> bool:
        return all(step >= self._cooldown_until[k] for k in knobs)

    def _start_cooldown(self, step: int, knobs: Tuple[str, ...]) -> None:
        for k in knobs:
            self._cooldown_until[k] = step + self.config.cooldown_steps

    @staticmethod
    def _knobs(frm: Configuration, to: Configuration) -> Tuple[str, ...]:
        knobs = []
        if frm.algorithm != to.algorithm:
            knobs.append("algorithm")
        if frm.precision != to.precision:
            knobs.append("precision")
        if frm.staleness != to.staleness:
            knobs.append("staleness")
        return tuple(knobs) or ("precision",)

    # -- ladder rungs ---------------------------------------------------------

    def _safety_repromote(self, state, step: int):
        cur = self.current_configuration()
        if cur.precision == "f32" or self.health is None:
            return None
        if self.health.stabilized(1):
            return None
        if not self._off_cooldown(step, ("precision",)):
            return None
        to = dataclasses.replace(cur, precision="f32")
        try:
            state = self._apply(state, cur, to, "autopilot:loss_spike")
        except Exception as e:
            self._record(step, "repromote_precision", "autopilot:loss_spike",
                         cur, to, "rejected", error=e)
            return state
        self._start_cooldown(step, ("precision",))
        self._record(step, "repromote_precision", "autopilot:loss_spike",
                     cur, to, "committed")
        return state

    def _adjudicate_canary(self, state, step: int):
        c = self._canary
        if c is None or step < c["until_step"]:
            return None
        self._canary = None
        pre = c["pre_ewma"]
        post = self._loss_ewma
        parity = (
            pre is None or post is None
            or post <= pre * self.config.canary_loss_factor
        )
        frm = Configuration(**c["from_config"])
        to = Configuration(**c["to_config"])
        if parity:
            self._record(step, c["decision"], c["reason"], frm, to,
                         "committed", modeled=c.get("modeled"),
                         axis=c.get("axis"))
            return state
        try:
            state = self._apply(state, to, frm, c["reason"])
        except Exception as e:
            self._record(step, "rollback", c["reason"], to, frm, "rejected",
                         error=e, axis=c.get("axis"))
            return state
        self._start_cooldown(step, self._knobs(frm, to))
        self._record(step, "rollback", c["reason"], to, frm, "rolled_back",
                     modeled=c.get("modeled"), axis=c.get("axis"))
        return state

    def _demote_on_wire_evidence(self, state, step: int):
        cfg = self.config
        if len(self._wire_evidence) < cfg.hysteresis_incidents:
            return None
        incident = self._wire_evidence[-1]
        self._wire_evidence = []
        if not self._healthy(1):
            return None  # never chase goodput while the loss is misbehaving
        cur = self.current_configuration()
        factor = self._bandwidth_factor(incident)
        # axis-scoped pricing: an incident that indicts a named mesh axis
        # degrades only that axis's traffic.  When the indicted axis is not
        # one the gradient exchange rides (group.data_axes), the candidate
        # ranking cannot flip and the controller holds — demoting the dp
        # wire precision does nothing for a tp/ICI brownout.
        axis = incident.get("axis")
        axis = str(axis) if axis else None
        exchange_axes = tuple(
            str(a) for a in (getattr(self.ddp.group, "data_axes", ()) or ()) if a
        )
        candidates = candidate_configurations(cfg.algorithms, cfg.precisions)
        if cur not in candidates:
            candidates.append(cur)
        candidates = [
            c for c in candidates
            if self._off_cooldown(step, self._knobs(cur, c)) or c == cur
        ]
        priced = price_configurations(
            self.cost_model, self.ddp.plan, self.ddp.group.exchange_size,
            candidates, self._compute_ms(),
            hierarchical=bool(getattr(self.ddp.impl, "hierarchical", False)),
            bandwidth_factor=factor,
            axis=axis, exchange_axes=exchange_axes,
        )
        stay = next(ms for c, ms in priced if c == cur)
        best, best_ms = priced[0]
        reason = f"autopilot:{incident.get('dominant', 'wire_slowdown')}"
        trace = str(incident.get("trace_id") or "")
        modeled = {
            "stay_ms": stay, "chosen_ms": best_ms, "bandwidth_factor": factor,
        }
        if best == cur or best_ms > stay * (1.0 - cfg.min_saving_frac):
            self._record(step, "hold", reason, cur, cur, "held",
                         trace_id=trace, modeled=modeled, axis=axis)
            return state
        decision = (
            "switch_algorithm" if best.algorithm != cur.algorithm
            else "demote_precision"
        )
        try:
            state = self._apply(state, cur, best, reason)
        except Exception as e:
            self._record(step, decision, reason, cur, best, "rejected",
                         trace_id=trace, modeled=modeled, error=e, axis=axis)
            return state
        self._start_canary(step, decision, reason, cur, best, trace, modeled,
                           axis=axis)
        return state

    def _repromote_on_stability(self, state, step: int):
        cfg = self.config
        if self.health is None or not self.health.stabilized(cfg.repromote_windows):
            return None
        if (self._last_wire_step is not None
                and step - self._last_wire_step < cfg.repromote_windows):
            return None  # quarantine: the collapse may still be in progress
        self.health.rearm()  # latched guardrail actions may fire again
        cur = self.current_configuration()
        candidates = candidate_configurations(cfg.algorithms, cfg.precisions)
        if cur not in candidates:
            candidates.append(cur)
        candidates = [
            c for c in candidates
            if self._off_cooldown(step, self._knobs(cur, c)) or c == cur
        ]
        priced = price_configurations(
            self.cost_model, self.ddp.plan, self.ddp.group.exchange_size,
            candidates, self._compute_ms(),
            hierarchical=bool(getattr(self.ddp.impl, "hierarchical", False)),
            bandwidth_factor=1.0,  # stabilized: price at nominal bandwidth
        )
        stay = next(ms for c, ms in priced if c == cur)
        best, best_ms = priced[0]
        if best == cur or best_ms > stay * (1.0 - cfg.min_saving_frac):
            return None  # already cheapest at nominal bandwidth: quiet
        decision = (
            "switch_algorithm" if best.algorithm != cur.algorithm
            else ("repromote_precision"
                  if best.precision == "f32" else "demote_precision")
        )
        reason = "autopilot:stabilized"
        modeled = {"stay_ms": stay, "chosen_ms": best_ms, "bandwidth_factor": 1.0}
        try:
            state = self._apply(state, cur, best, reason)
        except Exception as e:
            self._record(step, decision, reason, cur, best, "rejected",
                         modeled=modeled, error=e)
            return state
        self._start_canary(step, decision, reason, cur, best,
                           self._last_incident_trace, modeled)
        return state

    # -- actions ---------------------------------------------------------------

    def _apply(self, state, frm: Configuration, to: Configuration, reason: str):
        """Move the engine to ``to`` (algorithm first — it resets the plan —
        then the per-bucket precision).  A strict-verifier rejection raises
        out of here having already rolled the engine back; callers count it
        and never dispatch the rejected program."""
        ddp = self.ddp
        try:
            if to.algorithm != frm.algorithm:
                kwargs = {}
                if to.algorithm in ("gradient_allreduce", "zero"):
                    # keep the per-bucket precision knob live across the switch
                    auto = getattr(ddp.impl, "wire_precision", None) == "auto"
                    kwargs["wire_precision"] = "auto" if auto else "f32"
                state = ddp.switch_algorithm(state, to.algorithm, reason=reason,
                                             **kwargs)
            cur_prec = self.current_configuration().precision
            if to.precision != cur_prec and hasattr(ddp.impl, "set_bucket_precision"):
                ddp.apply_precision_plan(
                    [to.precision] * ddp.plan.num_buckets, reason=reason
                )
        except Exception:
            self.verifier_rejections += 1
            raise
        if self.sentinel is not None:
            self.sentinel.plan_version = ddp.plan_version
            if hasattr(self.sentinel, "rebaseline"):
                # the step wall legitimately moved: re-learn the CUSUM
                # baseline and re-price the budget's wire expectation to
                # the adopted configuration's modeled wire at nominal
                # bandwidth
                self.sentinel.rebaseline(wire_ms=wire_ms(
                    self.cost_model, ddp.plan, ddp.group.exchange_size, to,
                    hierarchical=bool(getattr(ddp.impl, "hierarchical", False)),
                ))
        return state

    def _start_canary(self, step, decision, reason, frm, to, trace, modeled,
                      axis: Optional[str] = None):
        self._canary = {
            "until_step": step + self.config.canary_steps,
            "pre_ewma": self._loss_ewma,
            "from_config": frm.as_dict(),
            "to_config": to.as_dict(),
            "decision": decision,
            "reason": reason,
            "trace_id": trace,
            "modeled": modeled,
            "axis": axis,
        }
        self._start_cooldown(step, self._knobs(frm, to))
        self._record(step, decision, reason, frm, to, "canary",
                     trace_id=trace, modeled=modeled, axis=axis)

    def _record(self, step, decision, reason, frm, to, verdict,
                trace_id: Optional[str] = None, modeled: Optional[Dict] = None,
                error: Optional[BaseException] = None,
                axis: Optional[str] = None) -> None:
        if trace_id is None:
            trace_id = (self._canary or {}).get("trace_id") or self._last_incident_trace
        row = {
            "event": "plan_decision",
            "ts": time.time(),
            "step": int(step),
            "decision": str(decision),
            "reason": str(reason),
            "trace_id": str(trace_id or ""),
            "plan_version": int(self.ddp.plan_version),
            "from_config": frm.as_dict(),
            "to_config": to.as_dict(),
            "verdict": str(verdict),
        }
        if axis:
            row["axis"] = str(axis)
        if modeled:
            row["modeled"] = {k: round(float(v), 4) for k, v in modeled.items()}
        if error is not None:
            logger.warning(
                "autopilot %s %s -> %s rejected before dispatch: %s",
                decision, frm.label(), to.label(), error,
            )
        else:
            logger.info(
                "autopilot %s (%s): %s -> %s [%s]",
                decision, reason, frm.label(), to.label(), verdict,
            )
        self.decisions.append(row)
        self._pending_decisions.append(row)
        if self.telemetry is not None:
            self.telemetry.on_plan_decision(
                step=int(step), decision=str(decision), reason=str(reason),
                trace_id=str(trace_id or ""), plan_version=int(self.ddp.plan_version),
                from_config=frm.as_dict(), to_config=to.as_dict(),
                verdict=str(verdict), modeled=modeled, axis=axis,
            )
