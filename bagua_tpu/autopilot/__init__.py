"""Gang autopilot: online relaxation control over {algorithm, precision,
staleness}.

The controller consumes attributed ``perf_regression`` incidents, the
health monitor's stability signal and the planner's fitted α–β cost model,
and moves the gang to the cheapest healthy configuration through the
engine's statically-verified single-recompile switch actions.  The
staleness director runs the per-rank arm of the same loop: straggler
attribution in, bounded-staleness degradation (with a convergence
guardrail) out.  See ``docs/autopilot.md`` for the policy contract.
"""

from bagua_tpu.autopilot.controller import AutopilotConfig, GangAutopilot
from bagua_tpu.autopilot.pricing import (
    PRECISION_RUNGS,
    Configuration,
    candidate_configurations,
    degraded_cost_model,
    modeled_step_ms,
    price_configurations,
    wire_ms,
)
from bagua_tpu.autopilot.staleness import (
    StalenessConfig,
    StalenessDirector,
    StalenessTightenAction,
)

__all__ = [
    "AutopilotConfig",
    "GangAutopilot",
    "Configuration",
    "PRECISION_RUNGS",
    "StalenessConfig",
    "StalenessDirector",
    "StalenessTightenAction",
    "candidate_configurations",
    "degraded_cost_model",
    "modeled_step_ms",
    "price_configurations",
    "wire_ms",
]
