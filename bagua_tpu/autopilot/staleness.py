"""The staleness director: straggler attribution in, bounded-staleness out.

The gang autopilot (PR 17) moves the *collective* knobs — algorithm and
wire precision — on wire-dominant evidence.  A straggler is a different
failure: one rank is slow, the wire is fine, and demoting everyone's
precision buys nothing.  The correct relaxation is *per-rank*: let the
indicted rank fall up to τ rounds behind (the ``stale`` algorithm's
error-feedback replay, or the gossip decentralized mode's published-weight
skip) so the gang paces at its median instead of the straggler's max.

:class:`StalenessDirector` closes that loop with the same production
discipline as the autopilot's decision ladder:

* **Evidence** — the regression sentinel's ``perf_regression`` incidents
  whose ``dominant`` component is ``straggler`` (the gang aggregator's
  attributed excess, carrying the indicted ``straggler_rank`` and the
  incident ``trace_id``).
* **Hysteresis + cooldown** — ≥ ``hysteresis_incidents`` straggler
  incidents before a degrade, ``cooldown_steps`` between staleness moves.
* **Degrade** — one recompile-free directive flip
  (:meth:`~bagua_tpu.ddp.DistributedDataParallel.apply_degradation_directive`)
  plus, when the engine is still at τ=0, one single-recompile
  :meth:`~bagua_tpu.ddp.DistributedDataParallel.apply_staleness` switch.
  The budget model is told the gang now paces at the median
  (``sentinel.mark_degraded``) so the degraded rank's excess stops
  re-tripping the very detector that indicted it.
* **Convergence guardrail** — :class:`StalenessTightenAction` registered
  on the :class:`~bagua_tpu.observability.health.HealthMonitor` snaps τ
  back to 0 on a loss spike / grad explosion (safety moves don't wait for
  a tick).  The director notices the tightened knob and only re-promotes
  staleness after ``repromote_windows`` clean health windows — the same
  stabilization arc as the precision re-promotion.
* **Heal** — no straggler evidence for ``heal_patience`` steps: restore
  bulk sync (τ=0, directive cleared, budget back to worst-rank pacing).

Every move — including holds — is a schema-valid ``plan_decision`` event
citing the triggering incident's ``trace_id`` and indicted rank, so the
fleet timeline joins decision ↔ incident exactly as it does for the
autopilot's switches.
"""

import dataclasses
import logging
import time
from typing import Dict, List, Optional, Tuple

from bagua_tpu.autopilot.pricing import Configuration, modeled_step_ms

logger = logging.getLogger(__name__)

__all__ = ["StalenessConfig", "StalenessDirector", "StalenessTightenAction"]


@dataclasses.dataclass
class StalenessConfig:
    """Policy knobs for the per-rank degradation loop."""

    #: the staleness bound a degrade moves the gang to (0 disables degrades)
    tau: int = 2
    #: straggler-dominant incidents required before a degrade is considered
    hysteresis_incidents: int = 2
    #: steps the staleness knob stays untouchable after any move
    cooldown_steps: int = 50
    #: clean health windows before a guardrail-tightened τ is re-promoted
    repromote_windows: int = 20
    #: steps without fresh straggler evidence before the degradation heals
    heal_patience: int = 100


class StalenessDirector:
    """One director per gang, driven once per step from the train loop.

    Args:
        ddp: the engine, running an algorithm with the ``set_staleness_tau``
            knob (``stale`` or the gossip ``decentralized`` mode).
        config: :class:`StalenessConfig`.
        sentinel: the gang's
            :class:`~bagua_tpu.observability.regression.RegressionSentinel`
            (incidents read non-destructively, like the autopilot).
        health: the gang's
            :class:`~bagua_tpu.observability.health.HealthMonitor`.
        telemetry: optional hub for ``plan_decision`` events.
        cost_model: optional fitted planner cost model — when present,
            degrade decisions carry a ``modeled`` block pricing bulk sync
            vs the staleness candidate at the incident's measured excess.
    """

    def __init__(self, ddp, config: Optional[StalenessConfig] = None,
                 sentinel=None, health=None, telemetry=None, cost_model=None):
        self.ddp = ddp
        self.config = config or StalenessConfig()
        self.sentinel = sentinel
        self.health = health
        self.telemetry = telemetry
        self.cost_model = cost_model
        self.decisions: List[Dict] = []
        self._pending_decisions: List[Dict] = []
        self._seen_incidents = 0
        self._straggler_evidence: List[Dict] = []
        self._last_straggler_step: Optional[int] = None
        self._last_trace = ""
        self._cooldown_until = -1
        #: ranks currently under a degradation directive
        self.degraded_ranks: Tuple[int, ...] = ()
        #: True while the guardrail holds τ at 0 under an open degradation
        self._tightened = False

    # -- introspection -------------------------------------------------------

    def current_tau(self) -> int:
        return int(getattr(self.ddp.impl, "staleness_tau", None) or 0)

    def _configuration(self, tau: Optional[int] = None) -> Configuration:
        algo = self.ddp.impl.algo_name or type(self.ddp.impl).__name__
        return Configuration(
            algorithm=algo, precision="f32",
            staleness=self.current_tau() if tau is None else int(tau),
        )

    def report(self) -> Dict:
        return {
            "tau": self.current_tau(),
            "degraded_ranks": list(self.degraded_ranks),
            "tightened": self._tightened,
            "decisions": len(self.decisions),
            "straggler_evidence": len(self._straggler_evidence),
            "last_decision": self.decisions[-1] if self.decisions else None,
        }

    def drain_decisions(self) -> List[Dict]:
        """Decisions since the last drain — the gang aggregator pushes these
        to the fleet control plane's decision tier beside the autopilot's."""
        out, self._pending_decisions = self._pending_decisions, []
        return out

    # -- the per-step entry point -------------------------------------------

    def tick(self, state, step: int):
        """Run the degradation ladder once; returns the (possibly updated)
        train state.  Call after ``train_step``."""
        self._ingest_incidents()
        if (self.degraded_ranks and not self._tightened
                and self.current_tau() == 0
                and self.health is not None
                and not self.health.stabilized(1)):
            # a registered StalenessTightenAction snapped τ to 0 outside our
            # ladder — adopt the tightened state so re-promotion can run
            self._tightened = True
            self._cooldown_until = max(
                self._cooldown_until, step + self.config.cooldown_steps
            )
        out = self._tighten_on_anomaly(state, step)
        if out is not None:
            return out
        out = self._repromote_after_guardrail(state, step)
        if out is not None:
            return out
        out = self._heal(state, step)
        if out is not None:
            return out
        out = self._degrade_on_straggler(state, step)
        if out is not None:
            return out
        return state

    # -- evidence ------------------------------------------------------------

    def _ingest_incidents(self) -> None:
        if self.sentinel is None:
            return
        new = self.sentinel.incidents[self._seen_incidents:]
        self._seen_incidents = len(self.sentinel.incidents)
        for inc in new:
            if inc.get("dominant") != "straggler":
                continue
            if int(inc.get("straggler_rank", -1)) < 0:
                continue
            self._straggler_evidence.append(inc)
            self._last_straggler_step = int(inc.get("step", 0))
            if inc.get("trace_id"):
                self._last_trace = str(inc["trace_id"])

    def _modeled(self, incident: Dict, tau: int) -> Optional[Dict]:
        """Price bulk sync vs the τ candidate at the incident's measured
        per-step straggler excess (the gang pays ``excess/(τ+1)`` once the
        indicted rank may skip τ consecutive rounds)."""
        if self.cost_model is None or self.ddp.plan is None:
            return None
        excess = float(
            (incident.get("components") or {}).get("straggler", 0.0)
        )
        budget = getattr(self.sentinel, "budget", None)
        compute = float(getattr(budget, "compute_ms", 0.0) or 0.0)
        kwargs = dict(
            hierarchical=bool(getattr(self.ddp.impl, "hierarchical", False)),
            straggler_excess_ms=excess,
        )
        stay = modeled_step_ms(
            self.cost_model, self.ddp.plan, self.ddp.group.exchange_size,
            self._configuration(tau=0), compute, **kwargs,
        )
        chosen = modeled_step_ms(
            self.cost_model, self.ddp.plan, self.ddp.group.exchange_size,
            self._configuration(tau=tau), compute, **kwargs,
        )
        return {
            "stay_ms": stay,
            "chosen_ms": chosen,
            "straggler_excess_ms": excess,
        }

    # -- ladder rungs ---------------------------------------------------------

    def _tighten_on_anomaly(self, state, step: int):
        """Belt-and-braces mirror of :class:`StalenessTightenAction`: if the
        health monitor's clean streak broke while τ > 0, snap it to 0 now —
        even when the action was never registered."""
        if self.health is None or self.current_tau() == 0:
            return None
        if self.health.stabilized(1):
            return None
        frm = self._configuration()
        to = self._configuration(tau=0)
        reason = "health:anomaly"
        try:
            self.ddp.apply_staleness(0, reason=reason)
        except (AttributeError, ValueError) as e:
            self._record(step, "tighten_staleness", reason, frm, to,
                         "rejected", error=e)
            return state
        self._tightened = bool(self.degraded_ranks)
        self._cooldown_until = step + self.config.cooldown_steps
        self._record(step, "tighten_staleness", reason, frm, to, "committed")
        return state

    def _repromote_after_guardrail(self, state, step: int):
        """The guardrail held τ at 0; after ``repromote_windows`` clean
        windows the degradation (still evidenced) gets its staleness back —
        the same stabilization arc as the precision re-promotion."""
        if not self._tightened or self.current_tau() != 0:
            return None
        if self.health is None or not self.health.stabilized(
            self.config.repromote_windows
        ):
            return None
        if step < self._cooldown_until:
            return None
        self.health.rearm()
        frm = self._configuration()
        to = self._configuration(tau=self.config.tau)
        reason = "autopilot:stabilized"
        try:
            self.ddp.apply_staleness(self.config.tau, reason=reason)
            # replay state froze during the τ=0 stretch: force every
            # directive-carrying rank to a fresh first round
            state = self.ddp.reset_staleness_state(state)
        except (AttributeError, ValueError) as e:
            self._record(step, "repromote_staleness", reason, frm, to,
                         "rejected", error=e)
            return state
        self._tightened = False
        self._cooldown_until = step + self.config.cooldown_steps
        self._record(step, "repromote_staleness", reason, frm, to, "committed")
        return state

    def _heal(self, state, step: int):
        """No fresh straggler evidence for ``heal_patience`` steps: the
        straggler healed — restore bulk sync end to end."""
        if not self.degraded_ranks:
            return None
        if (self._last_straggler_step is not None
                and step - self._last_straggler_step < self.config.heal_patience):
            return None
        frm = self._configuration()
        to = self._configuration(tau=0)
        reason = "autopilot:straggler_healed"
        try:
            if self.current_tau() != 0:
                self.ddp.apply_staleness(0, reason=reason)
            state = self.ddp.apply_degradation_directive(state, ())
        except (AttributeError, ValueError) as e:
            self._record(step, "restore_bulk_sync", reason, frm, to,
                         "rejected", error=e)
            return state
        if self.sentinel is not None and hasattr(self.sentinel, "mark_degraded"):
            self.sentinel.mark_degraded(())
        healed = self.degraded_ranks
        self.degraded_ranks = ()
        self._tightened = False
        self._straggler_evidence = []
        self._cooldown_until = step + self.config.cooldown_steps
        self._record(step, "restore_bulk_sync", reason, frm, to, "committed",
                     ranks=healed)
        return state

    def _degrade_on_straggler(self, state, step: int):
        cfg = self.config
        if cfg.tau <= 0:
            return None
        if len(self._straggler_evidence) < cfg.hysteresis_incidents:
            return None
        incident = self._straggler_evidence[-1]
        self._straggler_evidence = []
        rank = int(incident.get("straggler_rank", -1))
        trace = str(incident.get("trace_id") or "")
        if rank < 0:
            return None
        if step < self._cooldown_until:
            return None
        frm = self._configuration()
        to = self._configuration(tau=cfg.tau)
        reason = "autopilot:straggler"
        if self.health is not None and not self.health.stabilized(1):
            # never relax convergence while the loss is already misbehaving
            self._record(step, "hold", reason, frm, frm, "held",
                         trace_id=trace, ranks=(rank,))
            return state
        if rank in self.degraded_ranks and self.current_tau() >= cfg.tau:
            self._record(step, "hold", reason, frm, frm, "held",
                         trace_id=trace, ranks=(rank,))
            return state
        modeled = self._modeled(incident, cfg.tau)
        try:
            if self.current_tau() < cfg.tau:
                self.ddp.apply_staleness(cfg.tau, reason=reason)
                # don't resume replay from frozen (or init-zero) payloads:
                # the first degraded round must be a fresh contribution
                state = self.ddp.reset_staleness_state(state)
            ranks = tuple(sorted(set(self.degraded_ranks) | {rank}))
            state = self.ddp.apply_degradation_directive(state, ranks)
        except (AttributeError, ValueError) as e:
            self._record(step, "degrade_staleness", reason, frm, to,
                         "rejected", trace_id=trace, ranks=(rank,), error=e)
            return state
        self.degraded_ranks = ranks
        if self.sentinel is not None and hasattr(self.sentinel, "mark_degraded"):
            # the gang now paces at its median: stop charging the degraded
            # rank's excess to the budget (it would re-trip the detector)
            self.sentinel.mark_degraded(ranks)
        self._cooldown_until = step + cfg.cooldown_steps
        self._record(step, "degrade_staleness", reason, frm, to, "committed",
                     trace_id=trace, ranks=ranks, modeled=modeled)
        return state

    # -- the decision record ---------------------------------------------------

    def _record(self, step, decision, reason, frm: Configuration,
                to: Configuration, verdict, trace_id: Optional[str] = None,
                ranks: Tuple[int, ...] = (), modeled: Optional[Dict] = None,
                error: Optional[BaseException] = None) -> None:
        if trace_id is None:
            trace_id = self._last_trace
        row = {
            "event": "plan_decision",
            "ts": time.time(),
            "step": int(step),
            "decision": str(decision),
            "reason": str(reason),
            "trace_id": str(trace_id or ""),
            "plan_version": int(self.ddp.plan_version),
            "from_config": frm.as_dict(),
            "to_config": to.as_dict(),
            "verdict": str(verdict),
        }
        if ranks:
            row["ranks"] = [int(r) for r in ranks]
        if modeled:
            row["modeled"] = {k: round(float(v), 4) for k, v in modeled.items()}
        if error is not None:
            logger.warning(
                "staleness director %s %s -> %s rejected before dispatch: %s",
                decision, frm.label(), to.label(), error,
            )
        else:
            logger.info(
                "staleness director %s (%s): %s -> %s [%s]",
                decision, reason, frm.label(), to.label(), verdict,
            )
        self.decisions.append(row)
        self._pending_decisions.append(row)
        if self.telemetry is not None:
            self.telemetry.on_plan_decision(
                step=int(step), decision=str(decision), reason=str(reason),
                trace_id=str(trace_id or ""),
                plan_version=int(self.ddp.plan_version),
                from_config=frm.as_dict(), to_config=to.as_dict(),
                verdict=str(verdict), modeled=modeled,
            )


class StalenessTightenAction:
    """Health-monitor action snapping the staleness budget back to τ=0 on
    any anomaly — the convergence guardrail of the bounded-staleness modes.
    The divergence bound τ buys goodput only while the loss behaves; a
    loss spike / grad explosion means the slack is being *spent*, so the
    gang returns to bulk synchronous immediately (one verified recompile)
    and only re-earns its staleness through the director's
    stabilization arc.  No-op (returns False) when the algorithm has no
    staleness knob or is already at τ=0."""

    name = "staleness_tighten"

    def __init__(self, ddp):
        self.ddp = ddp

    def __call__(self, alert: Dict, state=None) -> bool:
        ddp = self.ddp
        if not int(getattr(ddp.impl, "staleness_tau", None) or 0):
            return False
        try:
            return bool(ddp.apply_staleness(
                0, reason=f"health:{alert.get('kind', 'anomaly')}"))
        except (AttributeError, ValueError) as e:
            logger.debug("staleness tighten not applicable: %s", e)
            return False
