"""Cross-rank aggregation: per-window step summaries joined into a gang view.

The telemetry hub (PR 3) is strictly per-process — no rank ever sees the
gang.  This module closes that gap without adding a new wire protocol:
each rank serializes a compact :class:`StepSummary` (step, p50/p99
step-wall, wire bytes, MFU, health stats, phase attribution) and pushes it
through the rendezvous KV under the ``BAGUA_ATTEMPT`` nonce, reusing the
retry/breaker-hardened :class:`~bagua_tpu.distributed.rendezvous.RendezvousClient`
from the resilience PR.  Rank 0 collects the set into a :class:`GangView`:
per-rank skew, a straggler score (the rank whose step-wall p50 exceeds the
gang median by a configurable factor, attributed to its slowest phase via
the phase-tagged host-overhead breakdown), and gang-level Prometheus
gauges.

Degradation is a design constraint, not an afterthought: the KV path is
best-effort behind a :class:`~bagua_tpu.resilience.retry.CircuitBreaker` —
a KV outage means the rank falls back to a local-only view (``gang_degraded``
gauge set, push-failure counter bumped) with zero training-path impact.
"""

import dataclasses
import logging
import os
import statistics
from typing import Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)

__all__ = [
    "GangAggregator",
    "GangView",
    "StepSummary",
    "gang_kv_key",
    "straggler_score",
    "summarize_telemetry",
]


def gang_kv_key(attempt: str, rank: int) -> str:
    """KV key one rank's summary lives under — namespaced by the elastic
    attempt nonce so a restarted gang never reads a dead incarnation's
    numbers."""
    return f"bagua/obs/{attempt}/rank{int(rank)}"


@dataclasses.dataclass
class StepSummary:
    """One rank's compact per-window report — small enough to push through
    the rendezvous KV every window without anyone noticing."""

    rank: int
    step: int
    window: int = 0
    p50_ms: float = 0.0
    p99_ms: float = 0.0
    wire_bytes: int = 0
    mfu: float = 0.0
    samples_per_s: float = 0.0
    phase_ms: Dict[str, float] = dataclasses.field(default_factory=dict)
    health: Dict[str, float] = dataclasses.field(default_factory=dict)

    def payload(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_payload(cls, payload: Dict) -> "StepSummary":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in dict(payload).items() if k in fields})


def straggler_score(summaries: Sequence[StepSummary], factor: float = 1.5) -> Optional[Dict]:
    """The gang's straggler, if it has one: the rank whose step-wall p50
    exceeds the gang median by ``factor``, attributed to its slowest phase
    (largest entry of its phase-tagged host-overhead breakdown).  None when
    fewer than two ranks report or nobody crosses the threshold."""
    reports = [s for s in summaries if s is not None]
    if len(reports) < 2:
        return None
    median = statistics.median(s.p50_ms for s in reports)
    worst = max(reports, key=lambda s: s.p50_ms)
    if median <= 0:
        return None
    score = worst.p50_ms / median
    if score < factor:
        return None
    phase = None
    if worst.phase_ms:
        phase = max(worst.phase_ms.items(), key=lambda kv: kv[1])[0]
    return {
        "rank": worst.rank,
        "score": round(score, 4),
        "p50_ms": worst.p50_ms,
        "gang_median_ms": median,
        "phase": phase,
    }


class GangView:
    """The joined picture rank 0 (or a degraded rank, about itself) sees."""

    def __init__(self, world_size: int, summaries: Sequence[StepSummary],
                 straggler_factor: float = 1.5, local_only: bool = False,
                 heartbeat_ages: Optional[Dict[int, float]] = None):
        self.world_size = int(world_size)
        self.summaries = sorted((s for s in summaries if s is not None),
                                key=lambda s: s.rank)
        self.local_only = bool(local_only)
        # coordinator-side seconds since each rank's last heartbeat — a rank
        # can stop heartbeating (hung host) while its stale summary still
        # reads healthy, so this is surfaced per rank, not folded into skew
        self.heartbeat_ages: Dict[int, float] = {
            int(r): float(a) for r, a in (heartbeat_ages or {}).items()
        }
        self.straggler = straggler_score(self.summaries, factor=straggler_factor)
        p50s = [s.p50_ms for s in self.summaries]
        self.p50_median = statistics.median(p50s) if p50s else 0.0
        self.skew = (max(p50s) / self.p50_median
                     if p50s and self.p50_median > 0 else 1.0)
        # per-rank straggler scores (each rank's p50 / gang median), not just
        # the worst rank's — what makes a per-rank degradation decision
        # auditable end-to-end (which ranks were how far off, not only who
        # crossed the threshold)
        self.rank_scores: Dict[int, float] = (
            {s.rank: round(s.p50_ms / self.p50_median, 4) for s in self.summaries}
            if len(self.summaries) >= 2 and self.p50_median > 0 else {}
        )
        mfus = [s.mfu for s in self.summaries if s.mfu]
        self.mfu_mean = sum(mfus) / len(mfus) if mfus else 0.0

    @property
    def ranks_reporting(self) -> int:
        return len(self.summaries)

    def report(self) -> Dict:
        return {
            "world_size": self.world_size,
            "ranks_reporting": self.ranks_reporting,
            "local_only": self.local_only,
            "p50_median_ms": self.p50_median,
            "p50_skew": round(self.skew, 4),
            "mfu_mean": round(self.mfu_mean, 6),
            "straggler": self.straggler,
            "rank_scores": {str(r): v for r, v in sorted(self.rank_scores.items())},
            "heartbeat_ages_s": {str(r): round(a, 3)
                                 for r, a in sorted(self.heartbeat_ages.items())},
            "ranks": [s.payload() for s in self.summaries],
        }

    def export(self, registry) -> None:
        """Gang-level gauges into a metrics registry (rides the same
        Prometheus textfile export as everything else)."""
        g = registry.gauge
        g("gang_ranks_reporting", help="ranks whose summaries reached the gang view").set(
            self.ranks_reporting)
        g("gang_local_only", help="1 when the KV was unreachable and the view is local-only").set(
            1 if self.local_only else 0)
        g("gang_step_p50_ms_median", help="gang median of per-rank step-wall p50").set(
            round(self.p50_median, 3))
        g("gang_step_p50_skew", help="worst rank p50 / gang median p50").set(
            round(self.skew, 4))
        g("gang_mfu_mean", help="mean MFU across reporting ranks").set(
            round(self.mfu_mean, 6))
        g("gang_straggler_rank", help="straggling rank (-1 when none)").set(
            self.straggler["rank"] if self.straggler else -1)
        g("gang_straggler_score", help="straggler p50 / gang median (0 when none)").set(
            self.straggler["score"] if self.straggler else 0.0)
        for r, score in sorted(self.rank_scores.items()):
            g(f"gang_straggler_score_rank{r}",
              help="this rank's step-wall p50 / gang median p50").set(score)
        for r, age in sorted(self.heartbeat_ages.items()):
            g(f"gang_heartbeat_age_s_rank{r}",
              help="seconds since this rank's last rendezvous heartbeat").set(
                round(age, 3))


def summarize_telemetry(telemetry, rank: int, step: int, window: int = 0,
                        phase_ms: Optional[Dict[str, float]] = None) -> StepSummary:
    """Build this rank's :class:`StepSummary` from the telemetry hub's
    registry snapshot (+ an optional phase-tagged host-overhead breakdown,
    e.g. ``ddp.host_overhead_snapshot()`` totals scaled to ms)."""
    snap = telemetry.registry.snapshot()
    wall = snap.get("step_wall_ms") or {}
    health = {}
    for key in ("health_loss", "health_grad_norm", "health_nan_latched"):
        if key in snap:
            health[key] = snap[key]
    if "health_alerts_total" in snap:
        health["alerts_total"] = snap["health_alerts_total"]
    return StepSummary(
        rank=int(rank),
        step=int(step),
        window=int(window),
        p50_ms=float(wall.get("p50", 0.0) or 0.0),
        p99_ms=float(wall.get("p99", 0.0) or 0.0),
        wire_bytes=int(snap.get("wire_bytes_total", 0) or 0),
        mfu=float(snap.get("mfu", 0.0) or 0.0),
        samples_per_s=float(snap.get("samples_per_s", 0.0) or 0.0),
        phase_ms=dict(phase_ms or {}),
        health=health,
    )


class GangAggregator:
    """Window-cadenced push/collect of :class:`StepSummary` through the
    rendezvous KV.

    Every rank :meth:`push`\\ es its summary; rank 0 additionally
    :meth:`collect`\\ s whatever the gang has published and exports the
    joined :class:`GangView`.  All KV traffic is best-effort behind the
    shared circuit-breaker policy (``BAGUA_RPC_BREAKER_*``): when the KV is
    unreachable — or no client was configured at all — the view degrades to
    local-only and training never notices.
    """

    def __init__(self, client, rank: int = 0, world_size: int = 1,
                 attempt: Optional[str] = None, window: int = 20,
                 straggler_factor: float = 1.5, registry=None, breaker=None,
                 incident_push=None):
        from bagua_tpu.env import get_rpc_breaker_cooldown_s, get_rpc_breaker_threshold
        from bagua_tpu.resilience.retry import CircuitBreaker

        self.client = client
        self.rank = int(rank)
        self.world_size = max(1, int(world_size))
        self.attempt = (attempt if attempt is not None
                        else os.environ.get("BAGUA_ATTEMPT", "0"))
        self.window = max(1, int(window))
        self.straggler_factor = float(straggler_factor)
        self.registry = registry
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=get_rpc_breaker_threshold(),
            cooldown_s=get_rpc_breaker_cooldown_s(),
            name="gang-obs",
        )
        # best-effort sink for regression-sentinel incidents, e.g.
        # ``lambda incs: fleet.push_incidents(gang_id, incs)`` — same
        # degradation contract as the KV pushes: failures count, never raise
        self.incident_push = incident_push
        self.last_view: Optional[GangView] = None
        self._last_summary: Optional[StepSummary] = None

    # -- KV plumbing (best-effort, breaker-gated) -----------------------------

    def _kv_call(self, fn, *args):
        from bagua_tpu.resilience.retry import CircuitOpenError

        try:
            self.breaker.before_call()
        except CircuitOpenError:
            return False, None
        try:
            out = fn(*args)
        except Exception as exc:  # any transport failure degrades, never raises
            self.breaker.record_failure()
            logger.debug("gang KV call failed (%s): %s", getattr(fn, "__name__", fn), exc)
            return False, None
        self.breaker.record_success()
        return True, out

    def push(self, summary: StepSummary) -> bool:
        """Publish this rank's summary; False (and a bumped failure
        counter) on any KV trouble."""
        self._last_summary = summary
        if self.client is None:
            return False
        ok, _ = self._kv_call(
            self.client.kv_set, gang_kv_key(self.attempt, summary.rank),
            summary.payload())
        if not ok and self.registry is not None:
            self.registry.counter(
                "gang_push_failures_total",
                help="gang summary KV pushes that failed or were breaker-gated",
            ).inc()
        return ok

    def collect(self) -> List[StepSummary]:
        """All summaries currently published for this attempt (missing or
        unparseable ranks are skipped)."""
        out: List[StepSummary] = []
        if self.client is None:
            return out
        for r in range(self.world_size):
            ok, payload = self._kv_call(
                self.client.kv_get, gang_kv_key(self.attempt, r))
            if not ok or not isinstance(payload, dict):
                continue
            try:
                out.append(StepSummary.from_payload(payload))
            except (TypeError, ValueError):
                logger.debug("gang: discarding malformed summary for rank %d", r)
        return out

    def heartbeat_ages(self) -> Dict[int, float]:
        """Coordinator-reported seconds since each rank's last heartbeat
        (best-effort, breaker-gated; empty on any KV trouble or when the
        client predates the ``ages`` reply field)."""
        if self.client is None or not hasattr(self.client, "heartbeat"):
            return {}
        ok, out = self._kv_call(self.client.heartbeat)
        if not ok or not isinstance(out, dict):
            return {}
        ages = out.get("ages")
        if not isinstance(ages, dict):
            return {}
        try:
            return {int(r): float(a) for r, a in ages.items()}
        except (TypeError, ValueError):
            return {}

    # -- the per-window entry point -------------------------------------------

    def aggregate(self, summary: StepSummary) -> Optional[GangView]:
        """Push this rank's summary; on rank 0 also collect and export the
        gang view (local-only when the KV path is down).  Returns the view
        on rank 0, None elsewhere."""
        pushed = self.push(summary)
        if self.rank != 0:
            return None
        summaries: Sequence[StepSummary] = [summary]
        local_only = True
        if pushed:
            collected = self.collect()
            if collected:
                summaries = collected
                local_only = len(collected) < self.world_size and self.world_size > 1
        ages = self.heartbeat_ages()
        view = GangView(self.world_size, summaries,
                        straggler_factor=self.straggler_factor,
                        local_only=local_only and self.world_size > 1,
                        heartbeat_ages=ages)
        self.last_view = view
        if self.registry is not None:
            try:
                view.export(self.registry)
                self.registry.gauge(
                    "gang_degraded",
                    help="1 while the gang view is local-only (KV unreachable)",
                ).set(1 if view.local_only else 0)
            except Exception:
                logger.exception("gang view export failed")
        return view

    def tick(self, step: int, telemetry, phase_ms: Optional[Dict[str, float]] = None
             ) -> Optional[GangView]:
        """Trainer-loop convenience: every ``window`` steps, summarize the
        local telemetry and aggregate.  Cheap no-op off-cadence."""
        if step == 0 or step % self.window != 0:
            return None
        summary = summarize_telemetry(telemetry, self.rank, step,
                                      window=self.window, phase_ms=phase_ms)
        view = self.aggregate(summary)
        sentinel = getattr(telemetry, "regression", None)
        if sentinel is not None:
            # the gang view is the only place straggler evidence exists:
            # feed the attributed excess (and rank) into the budget model so
            # the sentinel's next incident names it
            if view is not None and view.straggler is not None:
                excess = max(0.0, float(view.straggler["p50_ms"])
                             - float(view.straggler["gang_median_ms"]))
                sentinel.note_straggler(excess, rank=view.straggler["rank"])
            if self.incident_push is not None:
                pending = sentinel.drain_incidents()
                if pending:
                    try:
                        self.incident_push(pending)
                    except Exception as exc:
                        logger.debug("gang incident push failed: %s", exc)
                        if self.registry is not None:
                            self.registry.counter(
                                "gang_incident_push_failures_total",
                                help="fleet incident pushes that failed",
                            ).inc()
        return view
