"""Structured metrics: counters/gauges/histograms, JSONL events, Prometheus.

The reference fed its autotuner from an OTel span pipeline
(``bagua-opentelemetry``) and logged speed through ``StatisticalAverage``;
production TPU jobs additionally need *exportable* per-step evidence — a
metrics registry a dashboard can scrape and an append-only event stream a
post-mortem can replay.  Everything here is host-side, stdlib-only and
thread-safe; nothing touches the traced step.

* :class:`MetricsRegistry` — named counters, gauges and ring-buffer
  histograms (p50/p95/p99), exportable as a plain dict snapshot or in the
  Prometheus text exposition format (the *textfile-collector* pattern:
  write a ``.prom`` file, let node_exporter scrape it — no HTTP server in
  the training process).
* :class:`JsonlSink` — one JSON object per line, schema-checked by
  :func:`validate_metrics_event` (the CI lane validates every emitted
  event, see ``ci/perf_audit.py --quick``).
"""

import json
import math
import os
import threading
import time
from typing import Dict, IO, List, Optional, Union

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "MetricsRegistry",
    "rotated_metrics_files",
    "validate_metrics_event",
    "validate_switch_reason",
    "switch_reason_family",
    "EVENT_REQUIRED_FIELDS",
    "SWITCH_REASON_FAMILIES",
]


class Counter:
    """Monotonic counter."""

    def __init__(self, name: str, help: str = ""):
        self.name, self.help = name, help
        self._lock = threading.Lock()
        self.value: Union[int, float] = 0

    def inc(self, amount: Union[int, float] = 1) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {amount})")
        with self._lock:
            self.value += amount


class Gauge:
    """Last-write-wins scalar."""

    def __init__(self, name: str, help: str = ""):
        self.name, self.help = name, help
        self.value: float = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)


class Histogram:
    """Ring-buffer histogram: O(1) observe, percentiles over the last
    ``window`` observations (recent-tail semantics — a 10-hour job's p99
    should reflect the last minutes, not hour one)."""

    def __init__(self, name: str, help: str = "", window: int = 1024):
        self.name, self.help = name, help
        self._lock = threading.Lock()
        self._ring: List[float] = [0.0] * max(1, window)
        self._n = 0
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        with self._lock:
            self._ring[self._n % len(self._ring)] = float(value)
            self._n += 1
            self.count += 1
            self.sum += float(value)

    def percentiles(self) -> Dict[str, float]:
        """Nearest-rank percentiles (the p-th is the ``ceil(p*n)``-th
        smallest sample — same indexing as ``StepTimer.percentiles``; the
        old ``int(p*n)`` truncation biased small rings high, returning the
        max as the p50 of a 2-sample ring)."""
        with self._lock:
            n = min(self._n, len(self._ring))
            recent = sorted(self._ring[:n]) if n else []
        if not recent:
            return {}
        def q(p):
            n = len(recent)
            return recent[min(n - 1, max(0, math.ceil(p * n) - 1))]
        return {"p50": q(0.50), "p95": q(0.95), "p99": q(0.99)}


def _prom_name(name: str) -> str:
    """Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    out = "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)
    return out if out and not out[0].isdigit() else "_" + out


class MetricsRegistry:
    """Create-on-first-use registry of named metrics.

    ``registry.counter("steps_total").inc()`` — the same name always
    returns the same instrument; mixing kinds under one name raises.
    """

    def __init__(self, prefix: str = "bagua"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._metrics: Dict[str, Union[Counter, Gauge, Histogram]] = {}

    def _get(self, name: str, kind, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = kind(name, **kwargs)
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as {type(m).__name__}, "
                    f"requested {kind.__name__}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, Gauge, help=help)

    def histogram(self, name: str, help: str = "", window: int = 1024) -> Histogram:
        return self._get(name, Histogram, help=help, window=window)

    def snapshot(self) -> Dict:
        """Plain-dict view: counters/gauges as scalars, histograms as
        ``{count, sum, p50, p95, p99}``."""
        with self._lock:
            metrics = dict(self._metrics)
        out: Dict = {}
        for name, m in sorted(metrics.items()):
            if isinstance(m, Histogram):
                out[name] = {"count": m.count, "sum": round(m.sum, 6), **m.percentiles()}
            else:
                out[name] = m.value
        return out

    # -- Prometheus text exposition ------------------------------------------

    #: ring-buffer percentile -> Prometheus summary quantile label
    _QUANTILE_LABELS = (("p50", "0.5"), ("p95", "0.95"), ("p99", "0.99"))

    def to_prometheus(self) -> str:
        """The text exposition format (one family per metric; histograms as
        conformant summaries: ``name{quantile="0.5|0.95|0.99"}`` series
        followed by ``name_count``/``name_sum`` — quantile summaries
        without the streaming-quantile machinery)."""
        with self._lock:
            metrics = dict(self._metrics)
        lines = []
        for name, m in sorted(metrics.items()):
            full = _prom_name(f"{self.prefix}_{name}")
            if m.help:
                lines.append(f"# HELP {full} {m.help}")
            if isinstance(m, Counter):
                lines.append(f"# TYPE {full} counter")
                lines.append(f"{full} {m.value}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {full} gauge")
                lines.append(f"{full} {m.value}")
            else:
                lines.append(f"# TYPE {full} summary")
                pct = m.percentiles()
                for key, q in self._QUANTILE_LABELS:
                    if key in pct:
                        lines.append(f'{full}{{quantile="{q}"}} {pct[key]}')
                lines.append(f"{full}_count {m.count}")
                lines.append(f"{full}_sum {m.sum}")
        return "\n".join(lines) + "\n"

    def write_prometheus(self, path: str) -> None:
        """Atomic textfile export (write-then-rename so a scraper never
        reads a torn file — the node_exporter textfile-collector contract)."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.to_prometheus())
        os.replace(tmp, path)


#: every JSONL event must carry these (the CI schema gate)
EVENT_REQUIRED_FIELDS = {"ts": (int, float), "event": str, "step": int}

#: per-event-type required payload fields
EVENT_PAYLOAD_FIELDS = {
    "step": {
        "wall_ms": (int, float),
        "samples_per_s": (int, float),
        "wire_bytes": int,
        "variant": str,
    },
    "compile": {"variant": str, "retrace": bool},
    "retrace_alert": {"retraces": int, "window": int},
    # one bucket-plan swap adopted by the engine (autotune re-bucket, or an
    # algorithm switch — ``algorithm`` then rides as an optional extra);
    # reason speaks the unified switch vocabulary (validate_switch_reason);
    # predicted/measured exposed-comm ms ride as optional fields
    "rebucket": {"plan_version": int, "n_buckets": int, "reason": str},
    # one async/final state snapshot written by the resilience subsystem
    # (kind: "async" = cadenced background write, "final" = preemption drain)
    "snapshot": {"wall_ms": (int, float), "bytes": int, "kind": str},
    # one elastic resume: the gang restarted from a snapshot (step = the
    # resumed-from step; lost_steps = steps the previous incarnation ran
    # past it, 0 for a drained preemption exit)
    "restart": {
        "old_world_size": int,
        "new_world_size": int,
        "plan_source": str,
        "lost_steps": int,
    },
    # the engine adopted a new per-bucket wire-precision plan (planner-driven
    # under wire_precision="auto", or an operator override): before/after
    # per-bucket precisions plus who asked for the change
    "precision_switch": {
        "plan_version": int,
        "old_precisions": list,
        "new_precisions": list,
        "reason": str,
    },
    # the engine re-bounded the staleness knob (autopilot degradation, the
    # HealthMonitor convergence guardrail tightening tau to 0, or a
    # stabilization re-promotion): before/after bound plus who asked
    "staleness_switch": {
        "plan_version": int,
        "old_tau": int,
        "new_tau": int,
        "reason": str,
    },
    # the health monitor detected an anomaly (kind: loss_spike /
    # grad_norm_explosion / nonfinite); actions lists the registered
    # correctives that reported applying (e.g. precision_demotion)
    "health_alert": {
        "kind": str,
        "value": (int, float),
        "threshold": (int, float),
        "actions": list,
    },
    # the watchdog declared this rank hung (reason: watchdog_timeout /
    # sigterm); emitted + flushed BEFORE any exit path runs, so the event
    # survives the process kill.  Optional extras: dumps (the evidence file
    # paths) and flight_last_seq (the flight recorder's newest sequence
    # number, joining this event to the per-rank flight dump).
    "hang": {
        "reason": str,
        "last_phase": str,
    },
    # one retry_call backoff sleep (resilience/retry.py): the attempt that
    # failed, the delay about to be slept, and why (reason: "backpressure"
    # when a 429 Retry-After hint shaped the delay, "error" otherwise).
    # Optional extras: retry_after_s (the server's hint) and trace_id /
    # span_id when a trace is active.
    "rpc_retry": {
        "endpoint": str,
        "attempt": int,
        "delay_s": (int, float),
        "reason": str,
    },
    # one circuit-breaker state change (resilience/retry.py): states are
    # closed / half-open / open; step is the hub's last known step (-1
    # before the first step — breakers guard out-of-step RPC paths too)
    "breaker_transition": {
        "breaker": str,
        "old_state": str,
        "new_state": str,
    },
    # the regression sentinel tripped (observability/regression.py): the
    # CUSUM stream that fired ("step_wall" / "goodput"), the budget
    # attribution verdict over the recent window — components is the full
    # named partition summing to residual_ms by construction, dominant its
    # largest member — plus the live plan_version and the active trace_id
    # ("" with tracing off).  Optional extra: straggler_rank when the gang
    # aggregator attributed the window to a specific rank.
    "perf_regression": {
        "stream": str,
        "dominant": str,
        "components": dict,
        "residual_ms": (int, float),
        "expected_ms": (int, float),
        "measured_ms": (int, float),
        "plan_version": int,
        "trace_id": str,
    },
    # one autopilot policy decision (autopilot/controller.py): what the
    # controller decided (decision: demote_precision / repromote_precision /
    # switch_algorithm / rollback / hold), why (reason: the validated switch
    # vocabulary, e.g. "autopilot:wire_slowdown"), the triggering incident's
    # trace_id ("" when health- rather than incident-driven), the engine's
    # plan_version AFTER the action, the before/after configuration dicts,
    # and the verdict of the canary protocol (canary / committed /
    # rolled_back / held / rejected).  Optional extra: modeled — the α–β
    # priced step-ms of the stay-put vs chosen configuration.
    "plan_decision": {
        "decision": str,
        "reason": str,
        "trace_id": str,
        "plan_version": int,
        "from_config": dict,
        "to_config": dict,
        "verdict": str,
    },
    # the fleet RemediationEngine quarantined a cached plan: its cache key
    # and plan_version, the indicting incidents' trace_ids (cites), the
    # regressed adopter gangs that indicted it, and the action taken
    # (quarantine — rollback directives to every adopter ride as separate
    # ``remediation`` events)
    "plan_quarantine": {
        "cache_key": str,
        "plan_version": int,
        "cites": list,
        "gangs": list,
        "action": str,
    },
    # one fleet remediation action directed at a gang (action: resize /
    # rollback_plan / ...), with the hang/quarantine verdict that drove it
    "remediation": {
        "action": str,
        "gang": str,
        "reason": str,
    },
    # one canary-lifecycle transition for a cached plan (verdict: clean =
    # an adopter reported a clean window; graduated = the plan was promoted
    # to default after ``needed`` clean adopters)
    "canary_verdict": {
        "cache_key": str,
        "plan_version": int,
        "verdict": str,
        "clean": list,
        "needed": int,
    },
}

#: the unified ``reason`` vocabulary every configuration switch
#: (``apply_precision_plan`` / ``rebucket`` / ``switch_algorithm``) and every
#: ``plan_decision`` event must speak: who asked for the change.
#: ``planner`` and ``manual`` are bare; ``health`` and ``autopilot`` carry a
#: mandatory ``:<detail>`` suffix naming the alert kind / incident dominant.
SWITCH_REASON_FAMILIES = ("planner", "health", "autopilot", "manual")


def validate_switch_reason(reason: str) -> str:
    """Validate a configuration-switch ``reason`` against the unified
    vocabulary (``planner | health:<kind> | autopilot:<incident> | manual``)
    and return it unchanged.  Raises ValueError on anything else — a
    free-text reason is a bug at the switch site, not something the
    timeline joiners should have to fuzzy-match."""
    reason = str(reason)
    family, sep, detail = reason.partition(":")
    if family not in SWITCH_REASON_FAMILIES:
        raise ValueError(
            f"switch reason {reason!r} is not in the validated vocabulary "
            f"(families: {'|'.join(SWITCH_REASON_FAMILIES)})"
        )
    if family in ("health", "autopilot") and not detail:
        raise ValueError(
            f"switch reason {reason!r} needs a detail suffix "
            f"({family}:<{'kind' if family == 'health' else 'incident'}>)"
        )
    if family in ("planner", "manual") and sep:
        raise ValueError(
            f"switch reason {reason!r} must be bare ({family!r} takes no "
            "detail suffix)"
        )
    return reason


def switch_reason_family(reason: str) -> str:
    """The vocabulary family of a (validated) switch reason — the label the
    per-family Prometheus counters aggregate on."""
    return str(reason).partition(":")[0]


def validate_metrics_event(event: Dict) -> List[str]:
    """Schema-check one JSONL event; returns a list of problems (empty =
    valid).  Unknown event types only need the required envelope."""
    problems = []
    if not isinstance(event, dict):
        return [f"event is {type(event).__name__}, not an object"]
    for field, types in EVENT_REQUIRED_FIELDS.items():
        if field not in event:
            problems.append(f"missing required field {field!r}")
        elif not isinstance(event[field], types):
            problems.append(
                f"field {field!r} is {type(event[field]).__name__}, expected {types}"
            )
    for field, types in EVENT_PAYLOAD_FIELDS.get(event.get("event", ""), {}).items():
        if field not in event:
            problems.append(f"{event.get('event')} event missing field {field!r}")
        elif not isinstance(event[field], types):
            problems.append(
                f"field {field!r} is {type(event[field]).__name__}, expected {types}"
            )
    return problems


class JsonlSink:
    """Append-only JSONL event stream (one flat JSON object per line).

    Events are validated on emit; an invalid event raises immediately —
    a malformed stream is a bug at the emit site, not something a reader
    should have to defend against.

    Long jobs can bound the file with size-based rotation: when
    ``max_bytes`` (default: ``BAGUA_METRICS_MAX_MB`` MiB; unset/0 = off)
    would be exceeded, the live file is atomically renamed to ``path.N``
    (``.1`` oldest) and a fresh ``path`` is opened — no line is ever split
    across files, and :func:`validate_metrics_file` validates the whole
    rotated set."""

    def __init__(self, path: str, max_bytes: Optional[int] = None):
        if max_bytes is None:
            from bagua_tpu.env import get_metrics_max_mb

            mb = get_metrics_max_mb()
            max_bytes = int(mb * (1 << 20)) if mb > 0 else 0
        self.path = path
        self.max_bytes = max(0, int(max_bytes))
        self._lock = threading.Lock()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f: Optional[IO] = open(path, "a")

    def _rotate_locked(self) -> None:
        assert self._f is not None
        self._f.close()
        suffixes = [0]
        base = os.path.basename(self.path)
        d = os.path.dirname(os.path.abspath(self.path))
        for entry in os.listdir(d):
            if entry.startswith(base + "."):
                tail = entry[len(base) + 1:]
                if tail.isdigit():
                    suffixes.append(int(tail))
        os.replace(self.path, f"{self.path}.{max(suffixes) + 1}")
        self._f = open(self.path, "a")

    def emit(self, event: Dict) -> None:
        event.setdefault("ts", time.time())
        problems = validate_metrics_event(event)
        if problems:
            raise ValueError(f"invalid metrics event {event!r}: {problems}")
        line = json.dumps(event, sort_keys=True)
        with self._lock:
            if self._f is None:
                raise ValueError(f"JsonlSink({self.path}) is closed")
            if (
                self.max_bytes
                and self._f.tell() > 0
                and self._f.tell() + len(line) + 1 > self.max_bytes
            ):
                self._rotate_locked()
            self._f.write(line + "\n")
            self._f.flush()

    def flush(self) -> None:
        """Push buffered lines to the OS (emit already flushes per line;
        this is the teardown-path belt-and-suspenders).  No-op when closed."""
        with self._lock:
            if self._f is not None:
                self._f.flush()
                os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def rotated_metrics_files(path: str) -> List[str]:
    """The rotated set a :class:`JsonlSink` at ``path`` may have produced,
    oldest first: ``path.1``, ``path.2``, ..., then the live ``path``.
    Just ``[path]`` when rotation never fired."""
    base = os.path.basename(path)
    d = os.path.dirname(os.path.abspath(path))
    suffixes = []
    if os.path.isdir(d):
        for entry in os.listdir(d):
            if entry.startswith(base + "."):
                tail = entry[len(base) + 1:]
                if tail.isdigit():
                    suffixes.append(int(tail))
    out = [f"{path}.{n}" for n in sorted(suffixes)]
    out.append(path)
    return out


def validate_metrics_file(path: str) -> List[str]:
    """Validate every line of a JSONL metrics file — including any rotated
    ``path.N`` segments the sink produced — returning problems with line
    numbers (empty = the whole stream is schema-clean).  Problems in a
    rotated segment are prefixed with its basename."""
    problems = []
    files = [p for p in rotated_metrics_files(path) if os.path.exists(p)]
    if not files:
        files = [path]  # surface the FileNotFoundError from open()
    for fp in files:
        tag = "" if len(files) == 1 else f"{os.path.basename(fp)} "
        with open(fp) as f:
            for i, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except json.JSONDecodeError as e:
                    problems.append(f"{tag}line {i}: not JSON ({e})")
                    continue
                problems += [
                    f"{tag}line {i}: {p}" for p in validate_metrics_event(event)
                ]
    return problems
