"""Observability: spans, step timing, hang watchdog, in-graph bucket
tracing, device-trace overlap analysis, and structured metrics export.

The package splits by layer — :mod:`~bagua_tpu.observability.core` is the
host-side primitives (spans/timer/watchdog/profiler),
:mod:`~bagua_tpu.observability.annotations` the in-graph labels,
:mod:`~bagua_tpu.observability.trace_analysis` the offline trace parser,
:mod:`~bagua_tpu.observability.metrics` the registry/JSONL/Prometheus
plumbing, and :mod:`~bagua_tpu.observability.telemetry` the hub tying them
to the engine — but the public names all live here.
"""

from bagua_tpu.observability.core import (
    ProfilerSession,
    SpanRecorder,
    StepTimer,
    Watchdog,
)
from bagua_tpu.observability.annotations import (
    EXCHANGE_PREFIX,
    STEP_PREFIX,
    bucket_scope,
    mp_scope,
    parse_exchange_label,
    parse_mp_label,
    parse_step_phase,
    step_scope,
)
from bagua_tpu.observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    JsonlSink,
    MetricsRegistry,
    rotated_metrics_files,
    validate_metrics_event,
    validate_metrics_file,
)
from bagua_tpu.observability.telemetry import RecompileDetector, Telemetry
from bagua_tpu.observability.attribution import (
    BUDGET_COMPONENTS,
    BudgetModel,
    StepBudget,
)
from bagua_tpu.observability.regression import Cusum, RegressionSentinel
from bagua_tpu.observability.goodput import (
    GoodputLedger,
    GoodputMeter,
    flops_from_cost_analysis,
    model_flops_per_sample,
    predicted_wire_time,
    register_model_flops,
)
from bagua_tpu.observability.health import (
    HealthConfig,
    HealthMonitor,
    PrecisionDemotionAction,
    SnapshotOnAnomalyAction,
    health_scalars,
)
from bagua_tpu.observability.aggregate import (
    GangAggregator,
    GangView,
    StepSummary,
    straggler_score,
    summarize_telemetry,
)
from bagua_tpu.observability.flight_recorder import (
    FLIGHT_DUMP_SCHEMA,
    HANG_REPORT_SCHEMA,
    VERDICTS,
    FlightRecorder,
    build_hang_report,
    capture_program,
    flight_dump_path,
    push_flight_digest,
    validate_flight_dump,
    validate_hang_report,
)
from bagua_tpu.observability.trace_analysis import (
    COLLECTIVE_OPS,
    analyze_trace,
    find_trace_file,
    hlo_op_labels,
    load_trace_events,
)
from bagua_tpu.observability.tracing import (
    SPAN_SCHEMA,
    Span,
    Tracer,
    client_span,
    format_traceparent,
    get_global_tracer,
    new_span_id,
    new_trace_id,
    parse_traceparent,
    set_global_tracer,
    validate_span,
)

__all__ = [
    # core
    "ProfilerSession",
    "SpanRecorder",
    "StepTimer",
    "Watchdog",
    # annotations
    "EXCHANGE_PREFIX",
    "STEP_PREFIX",
    "bucket_scope",
    "mp_scope",
    "step_scope",
    "parse_exchange_label",
    "parse_mp_label",
    "parse_step_phase",
    # metrics
    "Counter",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "MetricsRegistry",
    "rotated_metrics_files",
    "validate_metrics_event",
    "validate_metrics_file",
    # telemetry
    "RecompileDetector",
    "Telemetry",
    # budget attribution / regression sentinel
    "BUDGET_COMPONENTS",
    "BudgetModel",
    "StepBudget",
    "Cusum",
    "RegressionSentinel",
    # goodput / MFU
    "GoodputLedger",
    "GoodputMeter",
    "flops_from_cost_analysis",
    "model_flops_per_sample",
    "predicted_wire_time",
    "register_model_flops",
    # health guardrail
    "HealthConfig",
    "HealthMonitor",
    "PrecisionDemotionAction",
    "SnapshotOnAnomalyAction",
    "health_scalars",
    # gang aggregation
    "GangAggregator",
    "GangView",
    "StepSummary",
    "straggler_score",
    "summarize_telemetry",
    # flight recorder / hang forensics
    "FLIGHT_DUMP_SCHEMA",
    "HANG_REPORT_SCHEMA",
    "VERDICTS",
    "FlightRecorder",
    "build_hang_report",
    "capture_program",
    "flight_dump_path",
    "push_flight_digest",
    "validate_flight_dump",
    "validate_hang_report",
    # trace analysis
    "COLLECTIVE_OPS",
    "analyze_trace",
    "find_trace_file",
    "hlo_op_labels",
    "load_trace_events",
    # distributed tracing
    "SPAN_SCHEMA",
    "Span",
    "Tracer",
    "client_span",
    "format_traceparent",
    "get_global_tracer",
    "new_span_id",
    "new_trace_id",
    "parse_traceparent",
    "set_global_tracer",
    "validate_span",
]
