"""The one scope-label grammar: formatters + parsers shared by every layer.

Every subsystem that names a collective speaks this grammar — the
``jax.named_scope`` frames emitted at trace time
(:mod:`bagua_tpu.observability.annotations`), the flight recorder's ring
records (``ddp._flight_finalize`` renders labels with
:func:`format_exchange_label`), the device-trace joiner
(:mod:`bagua_tpu.observability.trace_analysis` resolves HLO ``op_name``
metadata through :func:`hlo_op_labels`), and the static verifier
(:mod:`bagua_tpu.analysis` parses jaxpr ``name_stack`` strings).  Keeping
one module as the source of truth is what lets the verifier's *predicted*
program and the recorder's *captured* program join record-for-record on the
label key — a private copy of a regex in any one consumer would silently
fork the grammar.

The three label forms::

    bagua_ex/algo=gradient_allreduce/bucket=3/phase=overlap   (bucket exchanges)
    bagua_ex/axis=tp/phase=rs_ring                            (model-parallel)
    bagua_step/phase=optimizer                                (engine step phases)

plus the quantized-ring sub-scopes nested *inside* a bucket-exchange frame
(``qr8_quant``, ``qr8_hop3``, ``qr4_ag`` — see
:mod:`bagua_tpu.kernels.quantized_ring`), the overlap backward anchor
``bagua_overlap_bwd/bucket=<i>`` (:mod:`bagua_tpu.bucket`), and the
bounded-staleness frame ``bagua_stale/tau=<k>`` wrapping every exchange a
stale-sync/gossip algorithm issues — the sanction marker the static
verifier's taint analysis keys off (a rank-conditional collective inside a
stale frame is bounded-by-construction, not a divergence bug).

Field separators are ``/`` (the scope-nesting separator, which XLA joins
verbatim into ``op_name``) and ``=``; characters like ``@`` are truncated
by the MLIR location plumbing and must not appear in scope names.
"""

import re
from typing import Dict, Optional, Tuple

__all__ = [
    "EXCHANGE_PREFIX",
    "STEP_PREFIX",
    "STALE_PREFIX",
    "EXCHANGE_RE",
    "STEP_RE",
    "MP_RE",
    "QR_RE",
    "OVERLAP_BWD_RE",
    "STALE_RE",
    "format_exchange_label",
    "format_mp_label",
    "format_step_label",
    "format_stale_scope",
    "parse_exchange_label",
    "parse_mp_label",
    "parse_step_phase",
    "parse_qr_scope",
    "parse_overlap_bwd",
    "parse_stale_scope",
    "hlo_op_labels",
]

#: scope-name prefixes (kept short: every annotated HLO op carries them)
EXCHANGE_PREFIX = "bagua_ex"
STEP_PREFIX = "bagua_step"
STALE_PREFIX = "bagua_stale"

EXCHANGE_RE = re.compile(
    EXCHANGE_PREFIX + r"/algo=(?P<algo>[^/]+)/bucket=(?P<bucket>\d+)/phase=(?P<phase>[^/\"]+)"
)
STEP_RE = re.compile(STEP_PREFIX + r"/phase=(?P<phase>[^/\"]+)")
MP_RE = re.compile(
    EXCHANGE_PREFIX + r"/axis=(?P<axis>[^/=]+)/phase=(?P<phase>[^/\"]+)"
)
#: quantized-ring sub-scopes (nested inside a bucket-exchange frame)
QR_RE = re.compile(r"qr(?P<bits>\d+)_(?P<stage>quant|ag|hop(?P<hop>\d+))")
#: the custom_vjp backward anchor wrapping each bucket's overlap exchange
OVERLAP_BWD_RE = re.compile(r"bagua_overlap_bwd/bucket=(?P<bucket>\d+)")
#: the bounded-staleness sanction frame (τ = the staleness bound the
#: algorithm was compiled at)
STALE_RE = re.compile(STALE_PREFIX + r"/tau=(?P<tau>\d+)")


# -- formatters (the single way a label string is ever built) -----------------


def format_exchange_label(algo: str, bucket_idx, phase: str) -> str:
    """Render one bucket-exchange label; the inverse of
    :func:`parse_exchange_label` and the exact string both
    ``annotations.bucket_scope`` and the flight recorder's record templates
    carry."""
    return f"{EXCHANGE_PREFIX}/algo={algo}/bucket={int(bucket_idx)}/phase={phase}"


def format_mp_label(axis: str, phase: str) -> str:
    return f"{EXCHANGE_PREFIX}/axis={axis}/phase={phase}"


def format_step_label(phase: str) -> str:
    return f"{STEP_PREFIX}/phase={phase}"


def format_stale_scope(tau) -> str:
    """Render the bounded-staleness frame a stale-sync/gossip exchange is
    traced under — the marker :func:`parse_stale_scope` (and through it the
    static verifier's sanction) recovers from the jaxpr name stack."""
    return f"{STALE_PREFIX}/tau={int(tau)}"


# -- parsers ------------------------------------------------------------------


def parse_exchange_label(op_name: str) -> Optional[Dict]:
    """Extract ``{algo, bucket, phase}`` from any string carrying a
    bucket-exchange frame (HLO ``op_name`` metadata, a jaxpr ``name_stack``,
    a flight-recorder label); None when no frame is present."""
    m = EXCHANGE_RE.search(op_name or "")
    if not m:
        return None
    return {"algo": m.group("algo"), "bucket": int(m.group("bucket")), "phase": m.group("phase")}


def parse_mp_label(op_name: str) -> Optional[Dict]:
    """Extract ``{axis, phase}`` from a model-parallel exchange frame; None
    for unlabeled ops (bucket-exchange labels use ``algo=``/``bucket=``
    fields and never match)."""
    m = MP_RE.search(op_name or "")
    if not m:
        return None
    return {"axis": m.group("axis"), "phase": m.group("phase")}


def parse_step_phase(op_name: str) -> Optional[str]:
    """The engine step phase an op was traced under, if labeled."""
    m = STEP_RE.search(op_name or "")
    return m.group("phase") if m else None


def parse_qr_scope(op_name: str) -> Optional[Dict]:
    """Extract ``{bits, stage, hop}`` from a quantized-ring sub-scope
    (``stage`` is ``"quant"``, ``"hop"`` or ``"ag"``; ``hop`` is the 1-based
    hop index for hop frames, else None)."""
    m = QR_RE.search(op_name or "")
    if not m:
        return None
    stage = m.group("stage")
    hop = m.group("hop")
    return {
        "bits": int(m.group("bits")),
        "stage": "hop" if hop is not None else stage,
        "hop": int(hop) if hop is not None else None,
    }


def parse_overlap_bwd(op_name: str) -> Optional[int]:
    """Bucket index of a ``bagua_overlap_bwd`` backward anchor, if present."""
    m = OVERLAP_BWD_RE.search(op_name or "")
    return int(m.group("bucket")) if m else None


def parse_stale_scope(op_name: str) -> Optional[int]:
    """The staleness bound τ of a ``bagua_stale`` frame, if present."""
    m = STALE_RE.search(op_name or "")
    return int(m.group("tau")) if m else None


# -- the HLO join table -------------------------------------------------------

_HLO_INSTR = re.compile(r"%([A-Za-z0-9_.\-]+) = .*metadata=\{[^}]*op_name=\"([^\"]*)\"")
_HLO_MODULE = re.compile(r"^HloModule ([^\s,]+)", re.MULTILINE)


def hlo_op_labels(hlo_text: str) -> Tuple[str, Dict[str, str]]:
    """``(module_name, {instruction_name: op_name_metadata})`` from compiled
    HLO text — the join table between trace events and named-scope labels."""
    m = _HLO_MODULE.search(hlo_text)
    module = m.group(1) if m else ""
    return module, {name: op_name for name, op_name in _HLO_INSTR.findall(hlo_text)}
