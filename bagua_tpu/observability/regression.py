"""The performance-regression sentinel: online changepoint detection over
the step-wall and goodput streams, with a budget-attribution verdict.

A perf regression is the failure aggregate dashboards confirm and nobody
explains: the step wall drifts 20% and the job keeps training.  The
sentinel watches the two streams the hub already produces — step wall (up
is bad) and goodput fraction (down is bad) — with one-sided standardized
**CUSUM** detectors: each sample's deviation from an EWMA baseline, in
baseline-σ units, accumulates into ``s = max(0, s + z − k)``; ``s > h``
trips.  CUSUM catches the small-but-sustained drift a single-sample
z-threshold misses, while the drift allowance ``k`` ignores ordinary
jitter; warmup suppresses everything until the baseline settles (the
health monitor's discipline), and a cooldown re-arms the trip so one
incident doesn't become a stream of them.

On trip the sentinel aggregates the recent window of
:class:`~bagua_tpu.observability.attribution.StepBudget` rows, names the
**dominant** component, and emits one schema-validated ``perf_regression``
JSONL event carrying the full component partition, the residual, the live
``plan_version`` and the active ``trace_id`` — the attribution verdict the
fleet scheduler view and the autopilot consume.  Incidents queue in
:meth:`drain_incidents` for the gang's best-effort push to the fleet
control plane's volatile incident tier.

Everything is host-side arithmetic: sentinel on vs off trains
bitwise-identical state (pinned in CI for ``gradient_allreduce`` and
``zero`` with overlap on, the health-monitor/flight-recorder contract).
"""

import collections
import logging
import math
import time
from typing import Dict, List, Optional

from bagua_tpu.observability.attribution import (
    BUDGET_COMPONENTS,
    BudgetModel,
    StepBudget,
)

logger = logging.getLogger(__name__)

__all__ = ["Cusum", "RegressionSentinel"]


class Cusum:
    """One-sided standardized CUSUM over a scalar stream.

    The baseline mean/variance are EWMAs fed only by in-family samples
    (``z < h``) — a sustained shift must trip the detector, not get
    absorbed into the baseline.  ``direction=+1`` watches for upward
    shifts (step wall), ``-1`` for downward (goodput).  The σ floor
    (``rel_floor`` of the mean, plus ``abs_floor``) keeps a near-constant
    clean stream from hair-triggering on numerically tiny variance.
    """

    def __init__(self, k: float = 1.0, h: float = 8.0, warmup: int = 30,
                 alpha: float = 0.05, direction: int = 1,
                 rel_floor: float = 0.02, abs_floor: float = 1e-6):
        self.k = float(k)
        self.h = float(h)
        self.warmup = max(1, int(warmup))
        self.alpha = float(alpha)
        self.direction = 1 if direction >= 0 else -1
        self.rel_floor = float(rel_floor)
        self.abs_floor = float(abs_floor)
        self.mean: Optional[float] = None
        self.var = 0.0
        self.n = 0
        self.s = 0.0
        self.trips = 0

    def _sigma(self) -> float:
        sigma = math.sqrt(max(0.0, self.var))
        floor = max(self.abs_floor, self.rel_floor * abs(self.mean or 0.0))
        return max(sigma, floor)

    def update(self, x: float) -> bool:
        """Feed one sample; True when the accumulated drift trips ``h``
        (the accumulator resets so the caller's cooldown owns re-arming)."""
        x = float(x)
        self.n += 1
        if self.mean is None:
            self.mean = x
            return False
        z = self.direction * (x - self.mean) / self._sigma()
        in_family = z < self.h
        if in_family or self.n <= self.warmup:
            delta = x - self.mean
            self.mean += self.alpha * delta
            self.var = (1.0 - self.alpha) * (self.var + self.alpha * delta * delta)
        if self.n <= self.warmup:
            return False
        self.s = max(0.0, self.s + z - self.k)
        if self.s > self.h:
            self.s = 0.0
            self.trips += 1
            return True
        return False


class RegressionSentinel:
    """Watches the per-step stream, attributes regressions, emits incidents.

    Args:
        budget: the :class:`~bagua_tpu.observability.attribution.BudgetModel`
            pricing the expected step (default: a self-calibrating one).
        sink: a :class:`~bagua_tpu.observability.metrics.JsonlSink` for the
            schema-validated ``perf_regression`` events (None = incidents
            only accumulate in memory).
        registry: a :class:`~bagua_tpu.observability.metrics.MetricsRegistry`
            for the ``perf_regressions_total`` counter.
        warmup / threshold / drift_k / alpha: CUSUM knobs (shared by both
            streams; env defaults ``BAGUA_REGRESSION_WARMUP`` /
            ``BAGUA_REGRESSION_THRESHOLD``).
        cooldown: steps after a trip before the sentinel can trip again.
        window: how many recent budgets an incident's verdict aggregates.
        topology: :class:`~bagua_tpu.perflab.topology.TopologyAssumptions`
            resolving an indicted axis to its physical link class
            (``ici``/``dcn``) on wire-dominant incidents; defaults to
            :data:`~bagua_tpu.perflab.topology.DEFAULT_TOPOLOGY`.

    Beyond the wall/goodput detectors, the sentinel runs **one CUSUM stream
    per mesh axis** over the budgets' per-axis ``wire_slowdown`` split
    (``StepBudget.wire_axis_ms``, lazily created as axes appear).  An axis
    stream's sustained drift trips like the scalar streams do, and any
    wire-dominant incident names the ``axis`` whose windowed slowdown
    dominates plus its ``link_class`` — a tp/ICI brownout and a dp/DCN
    collapse become distinguishable verdicts.
    """

    def __init__(self, budget: Optional[BudgetModel] = None, sink=None,
                 registry=None, warmup: int = 30, threshold: float = 8.0,
                 drift_k: float = 1.0, alpha: float = 0.05,
                 cooldown: int = 50, window: int = 20,
                 max_incidents: int = 256, topology=None):
        self.budget = budget or BudgetModel()
        self.sink = sink
        self.registry = registry
        if topology is None:
            from bagua_tpu.perflab.topology import DEFAULT_TOPOLOGY

            topology = DEFAULT_TOPOLOGY
        self.topology = topology
        self.cooldown = max(0, int(cooldown))
        self.window = max(1, int(window))
        self.max_incidents = max(1, int(max_incidents))
        self._wall = Cusum(k=drift_k, h=threshold, warmup=warmup,
                           alpha=alpha, direction=+1)
        self._goodput = Cusum(k=drift_k, h=threshold, warmup=warmup,
                              alpha=alpha, direction=-1)
        # one lazily-created detector per mesh axis over the per-axis wire
        # slowdown stream; the raised σ floor (0.05 ms vs the default 1e-6)
        # keeps an all-zeros clean split from hair-triggering on noise
        self._axis_cusum_kwargs = dict(k=drift_k, h=threshold, warmup=warmup,
                                       alpha=alpha, direction=+1,
                                       abs_floor=0.05)
        self._axis_cusums: Dict[str, Cusum] = {}
        self._budgets: collections.deque = collections.deque(maxlen=self.window)
        self._cooldown_until = -1
        self._steps_seen = 0
        self.plan_version = 0
        self.incidents: List[Dict] = []
        self._pending: List[Dict] = []

    # -- evidence hooks (delegated to the budget model) -----------------------

    def note_compile(self, wall_ms: float) -> None:
        self.budget.note_compile(wall_ms)

    def note_snapshot(self, wall_ms: float) -> None:
        self.budget.note_snapshot(wall_ms)

    def note_backpressure(self, delay_s: float) -> None:
        self.budget.note_backpressure(delay_s)

    def note_straggler(self, excess_ms: float, rank: int = -1) -> None:
        self.budget.note_straggler(excess_ms, rank=rank)

    def mark_degraded(self, ranks) -> None:
        self.budget.mark_degraded(ranks)

    def note_wire(self, measured_wire_ms: float,
                  by_axis: Optional[Dict[str, float]] = None) -> None:
        self.budget.note_wire(measured_wire_ms, by_axis=by_axis)

    # -- the per-step entry point ---------------------------------------------

    def observe_step(
        self,
        step: int,
        wall_ms: float,
        host_ms: Optional[float] = None,
        wire_bytes: Optional[float] = None,
        wire_bytes_by_axis: Optional[Dict[str, float]] = None,
        goodput_frac: Optional[float] = None,
        trace_id: str = "",
    ) -> StepBudget:
        """Settle this step's budget and run every detector; on trip, emit
        one ``perf_regression`` incident.  Returns the settled budget (the
        hub exports its components as ``step_budget_<component>_ms``
        gauges, and its per-axis wire split as
        ``step_budget_wire_<axis>_ms``)."""
        self._steps_seen += 1
        budget = self.budget.settle(step, wall_ms, host_ms=host_ms,
                                    wire_bytes=wire_bytes,
                                    wire_bytes_by_axis=wire_bytes_by_axis)
        self._budgets.append(budget)
        tripped_wall = self._wall.update(wall_ms)
        tripped_goodput = (goodput_frac is not None
                           and self._goodput.update(goodput_frac))
        tripped_axis = None
        for ax in sorted(budget.wire_axis_ms):
            detector = self._axis_cusums.get(ax)
            if detector is None:
                detector = self._axis_cusums[ax] = Cusum(
                    **self._axis_cusum_kwargs)
            if detector.update(budget.wire_axis_ms[ax]) and tripped_axis is None:
                tripped_axis = ax
        if ((tripped_wall or tripped_goodput or tripped_axis is not None)
                and self._steps_seen > self._cooldown_until):
            if tripped_wall:
                stream = "step_wall"
            elif tripped_goodput:
                stream = "goodput"
            else:
                stream = f"wire_axis:{tripped_axis}"
            self._trip(step, stream, trace_id, axis=tripped_axis)
            self._cooldown_until = self._steps_seen + self.cooldown
        return budget

    # -- the incident ---------------------------------------------------------

    def _verdict(self) -> Dict:
        """Aggregate the recent window into one partition + dominant name."""
        components = dict.fromkeys(BUDGET_COMPONENTS, 0.0)
        wire_axis: Dict[str, float] = {}
        residual = measured = expected = 0.0
        straggler_rank = -1
        for b in self._budgets:
            for c in BUDGET_COMPONENTS:
                components[c] += b.components.get(c, 0.0)
            for ax, ms in b.wire_axis_ms.items():
                wire_axis[ax] = wire_axis.get(ax, 0.0) + ms
            residual += b.residual_ms
            measured += b.measured_ms
            expected += b.expected_ms
            if b.straggler_rank >= 0:
                straggler_rank = b.straggler_rank
        dominant = max(components, key=lambda c: components[c])
        if components[dominant] <= 0:
            dominant = "unattributed"
        return {
            "components": {k: round(v, 4) for k, v in components.items()},
            "dominant": dominant,
            "wire_axis": {k: round(v, 4) for k, v in sorted(wire_axis.items())},
            "residual_ms": round(residual, 4),
            "measured_ms": round(measured, 4),
            "expected_ms": round(expected, 4),
            "straggler_rank": straggler_rank,
        }

    def _trip(self, step: int, stream: str, trace_id: str,
              axis: Optional[str] = None) -> None:
        verdict = self._verdict()
        # ts stamped here (not left to the sink) so drained incidents carry
        # it onto the fleet timeline even when no JSONL sink is attached
        event = {
            "event": "perf_regression",
            "ts": time.time(),
            "step": int(step),
            "stream": stream,
            "dominant": verdict["dominant"],
            "components": verdict["components"],
            "residual_ms": verdict["residual_ms"],
            "expected_ms": verdict["expected_ms"],
            "measured_ms": verdict["measured_ms"],
            "plan_version": int(self.plan_version),
            "trace_id": str(trace_id or ""),
        }
        if verdict["straggler_rank"] >= 0:
            event["straggler_rank"] = verdict["straggler_rank"]
        # a wire-dominant verdict indicts the axis whose windowed slowdown
        # dominates (or the axis whose own CUSUM stream tripped), resolved
        # through the topology to the physical link class it rides
        wire_axis = verdict["wire_axis"]
        if axis is None and verdict["dominant"] == "wire_slowdown" and wire_axis:
            worst = max(sorted(wire_axis), key=lambda a: wire_axis[a])
            if wire_axis[worst] > 0:
                axis = worst
        if axis is not None:
            event["axis"] = str(axis)
            event["link_class"] = self.topology.axis_link(str(axis))
            if wire_axis:
                event["wire_axis_ms"] = wire_axis
        logger.warning(
            "perf regression at step %d (%s stream): dominant=%s "
            "residual=%.2fms over the last %d steps",
            step, stream, event["dominant"], event["residual_ms"],
            len(self._budgets),
        )
        if self.registry is not None:
            self.registry.counter(
                "perf_regressions_total",
                help="regression-sentinel trips (perf_regression incidents)",
            ).inc()
        if self.sink is not None:
            try:
                self.sink.emit(dict(event))
            except ValueError:
                pass  # sink closed under us; the incident still queues
        self.incidents.append(event)
        if len(self.incidents) > self.max_incidents:
            del self.incidents[: len(self.incidents) - self.max_incidents]
        self._pending.append(event)
        if len(self._pending) > self.max_incidents:
            del self._pending[: len(self._pending) - self.max_incidents]

    def drain_incidents(self) -> List[Dict]:
        """Incidents emitted since the last drain — what the gang
        aggregator pushes (best-effort) to the fleet incident tier."""
        out, self._pending = self._pending, []
        return out

    def rebaseline(self, wire_ms: Optional[float] = None,
                   axis_wire_ms: Optional[Dict[str, float]] = None) -> None:
        """A committed configuration change (rebucket, precision switch,
        algorithm switch) legitimately moved the step wall: reset every CUSUM
        baseline — the wall/goodput pair and the per-axis streams — so they
        re-learn over a fresh warmup instead of reading the new steady state
        as a sustained regression, and optionally re-price the budget's wire
        expectation to the new configuration's modeled wire (the autopilot
        passes its α–β prediction at nominal bandwidth; ``axis_wire_ms``
        re-prices the per-axis ledger alongside)."""
        for detector in (self._wall, self._goodput):
            detector.mean = None
            detector.var = 0.0
            detector.n = 0
            detector.s = 0.0
        self._axis_cusums = {}
        self._budgets.clear()
        if wire_ms is not None:
            self.budget.wire_ms = float(wire_ms)
        if axis_wire_ms is not None:
            self.budget.axis_wire_ms = {
                str(k): float(v) for k, v in axis_wire_ms.items()
            }

    def report(self) -> Dict:
        return {
            "steps_seen": self._steps_seen,
            "incidents": len(self.incidents),
            "wall_trips": self._wall.trips,
            "goodput_trips": self._goodput.trips,
            "axis_trips": {
                ax: c.trips for ax, c in sorted(self._axis_cusums.items())
                if c.trips
            },
            "last_incident": self.incidents[-1] if self.incidents else None,
            "budget": self.budget.report(),
        }
