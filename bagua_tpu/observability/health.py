"""Training-health guardrail: cheap in-graph scalars, a host-side anomaly
detector, and actions that close the loop.

BAGUA's relaxed algorithms (quantized wire, decentralized topologies) trade
convergence risk for throughput; that trade is only safe while something is
*watching* the optimization.  This module is that watcher:

* :func:`health_scalars` — loss, global grad L2 norm, and a nonfinite leaf
  count, computed once per step *inside* ``ddp._build_step`` from values the
  step already produced.  Pure reads: the parameter path is untouched, so
  training with the monitor on vs off is bitwise-identical (pinned in
  tests, same discipline as the named-scope labels).
* :class:`HealthMonitor` — host-side detector over the per-step scalars:
  EWMA z-score loss-spike, grad-norm explosion vs its own EWMA, and a NaN
  latch.  Each anomaly emits a schema-validated ``health_alert`` JSONL
  event through the telemetry hub and invokes registered actions.
* Shipped actions: :class:`PrecisionDemotionAction` (int4→int8→f32 via
  ``DistributedDataParallel.apply_precision_plan`` — the planner's
  aggressive wire choice backs off before it diverges) and
  :class:`SnapshotOnAnomalyAction` (a blocking snapshot of the
  still-healthy-enough state on the *first* anomaly, via the
  ``AsyncSnapshotter``).

Everything host-side is opt-in and failure-isolated: a raising action is
logged and skipped, never allowed to take the step loop down.
"""

import dataclasses
import logging
import math
from typing import Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "HealthConfig",
    "HealthMonitor",
    "PrecisionDemotionAction",
    "SnapshotOnAnomalyAction",
    "health_scalars",
]

#: order of the scalars in the in-graph health vector
HEALTH_KEYS = ("loss", "grad_norm", "nonfinite")


def health_scalars(loss, grads):
    """Shape-``(3,)`` f32 vector ``[loss, global_grad_l2_norm,
    nonfinite_leaf_count]`` from a step's loss and gradient tree.  Pure
    reads of values the step already computed — adds reductions to the
    graph but never feeds back into parameters (bitwise-inert, pinned in
    tests).  Called per shard inside ``shard_map``; the host aggregates
    across the rank-stacked output."""
    import jax
    import jax.numpy as jnp

    leaves = [l for l in jax.tree_util.tree_leaves(grads)
              if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.inexact)]
    sq = jnp.asarray(0.0, jnp.float32)
    nonfinite = jnp.asarray(0.0, jnp.float32)
    for leaf in leaves:
        f = leaf.astype(jnp.float32)
        sq = sq + jnp.sum(jnp.square(f))
        nonfinite = nonfinite + jnp.sum((~jnp.isfinite(f)).astype(jnp.float32))
    return jnp.stack([
        jnp.asarray(loss, jnp.float32).reshape(()),
        jnp.sqrt(sq),
        nonfinite,
    ])


@dataclasses.dataclass
class HealthConfig:
    """Detector thresholds.  Warmup suppresses alerts while the EWMA
    statistics are still meaningless; ``min_std`` floors the z-score
    denominator so a perfectly flat loss cannot alert on noise."""

    ewma_alpha: float = 0.2          # EWMA smoothing for loss mean/var and grad norm
    loss_z_threshold: float = 6.0    # |z| of loss vs its EWMA above which we alert
    grad_norm_factor: float = 10.0   # grad_norm > factor * EWMA(grad_norm) alerts
    warmup_steps: int = 5            # observations before the detector may alert
    min_std: float = 1e-6            # floor for the loss z-score denominator
    max_alerts: int = 64             # retained alert dicts (history ring)
    #: clean observations after which latched actions (and the NaN latch)
    #: re-arm automatically; None keeps the historical latch-forever behavior
    rearm_windows: Optional[int] = None


class HealthMonitor:
    """Host-side anomaly detector over the per-step health scalars.

    Attach to the engine via ``DistributedDataParallel(...,
    health_monitor=...)`` (or ``Trainer(health_monitor=...)``): the engine
    computes :func:`health_scalars` in-graph and calls :meth:`observe` after
    every dispatched step.  Detected anomalies (kinds ``loss_spike``,
    ``grad_norm_explosion``, ``nonfinite``) are emitted as ``health_alert``
    events through the telemetry hub and handed to registered actions in
    registration order; an action returning True is recorded as applied,
    a raising action is logged and skipped.
    """

    def __init__(self, telemetry=None, registry=None, config: Optional[HealthConfig] = None,
                 actions=()):
        self.telemetry = telemetry
        self.registry = registry if registry is not None else (
            telemetry.registry if telemetry is not None else None)
        self.config = config or HealthConfig()
        self.actions: List[Callable] = list(actions)
        self.alerts: List[Dict] = []
        self.nan_latched = False
        self._n = 0
        self._loss_mean = 0.0
        self._loss_var = 0.0
        self._grad_ewma = 0.0
        #: consecutive clean (finite, alert-free) observations since the
        #: last anomaly — the stabilization signal re-promotion keys off
        self._clean_streak = 0
        self._rearmed = True  # no alert episode open yet

    def bind_telemetry(self, telemetry) -> None:
        """Adopt the engine's telemetry hub (and its registry) when the
        monitor was constructed before the hub existed."""
        if telemetry is None:
            return
        self.telemetry = telemetry
        if self.registry is None:
            self.registry = telemetry.registry

    def register_action(self, action: Callable) -> None:
        """``action(alert: dict, state) -> bool`` — True means applied.
        ``state`` is the freshly-produced training state (read-only use:
        e.g. snapshot it); may be None for detector-only callers."""
        self.actions.append(action)

    # -- detection ------------------------------------------------------------

    def observe(self, step: int, loss: float, grad_norm: float, nonfinite: int,
                state=None) -> List[Dict]:
        """Feed one step's aggregated scalars; returns the alerts raised
        (empty list when healthy).  Never raises: action/emission failures
        are logged and swallowed — the guardrail must not take down the
        step loop it guards."""
        cfg = self.config
        loss = float(loss)
        grad_norm = float(grad_norm)
        nonfinite = int(nonfinite)
        alerts: List[Dict] = []

        finite = math.isfinite(loss) and math.isfinite(grad_norm)
        if nonfinite > 0 or not finite:
            if not self.nan_latched:
                self.nan_latched = True
                alerts.append({
                    "kind": "nonfinite",
                    "value": float(nonfinite),
                    "threshold": 0.0,
                    "detail": f"nonfinite_leaves={nonfinite} loss={loss} grad_norm={grad_norm}",
                })
            if self.registry is not None:
                self.registry.counter(
                    "health_nonfinite_total",
                    help="gradient leaves observed nonfinite",
                ).inc(max(1, nonfinite))
        elif self._n >= cfg.warmup_steps:
            std = math.sqrt(max(self._loss_var, 0.0))
            z = (loss - self._loss_mean) / max(std, cfg.min_std)
            if abs(z) > cfg.loss_z_threshold:
                alerts.append({
                    "kind": "loss_spike",
                    "value": loss,
                    "threshold": cfg.loss_z_threshold,
                    "detail": f"z={z:.2f} ewma_mean={self._loss_mean:.6g} ewma_std={std:.6g}",
                })
            if self._grad_ewma > 0 and grad_norm > cfg.grad_norm_factor * self._grad_ewma:
                alerts.append({
                    "kind": "grad_norm_explosion",
                    "value": grad_norm,
                    "threshold": cfg.grad_norm_factor * self._grad_ewma,
                    "detail": f"ewma_grad_norm={self._grad_ewma:.6g}",
                })

        if finite:
            # EWMA update (mean + variance via the standard recurrence);
            # skipped on nonfinite steps so one NaN can't poison the stats.
            a = cfg.ewma_alpha
            delta = loss - self._loss_mean
            self._loss_mean += a * delta
            self._loss_var = (1.0 - a) * (self._loss_var + a * delta * delta)
            self._grad_ewma = grad_norm if self._n == 0 else (
                (1.0 - a) * self._grad_ewma + a * grad_norm)
            self._n += 1

        if self.registry is not None:
            try:
                self.registry.gauge("health_loss", help="last observed loss").set(loss)
                self.registry.gauge(
                    "health_grad_norm", help="last observed global grad L2 norm"
                ).set(grad_norm)
                self.registry.gauge(
                    "health_nan_latched", help="1 once any nonfinite value was seen"
                ).set(1 if self.nan_latched else 0)
            except Exception:
                logger.exception("health gauge update failed")

        for alert in alerts:
            alert["step"] = int(step)
            alert["actions"] = self._run_actions(alert, state)
            self.alerts.append(alert)
            if len(self.alerts) > self.config.max_alerts:
                del self.alerts[: len(self.alerts) - self.config.max_alerts]
            if self.registry is not None:
                self.registry.counter(
                    "health_alerts_total", help="health anomalies detected"
                ).inc()
            if self.telemetry is not None:
                try:
                    self.telemetry.on_health_alert(
                        step=int(step), kind=alert["kind"], value=alert["value"],
                        threshold=alert["threshold"], detail=alert["detail"],
                        actions=alert["actions"],
                    )
                except Exception:
                    logger.exception("health_alert emission failed")
        if alerts or nonfinite > 0 or not finite:
            self._clean_streak = 0
            self._rearmed = False
        else:
            self._clean_streak += 1
            if (
                not self._rearmed
                and self.config.rearm_windows is not None
                and self._clean_streak >= self.config.rearm_windows
            ):
                self.rearm()
        return alerts

    # -- stabilization / re-arm ----------------------------------------------

    def stabilized(self, n_windows: int) -> bool:
        """True once ``n_windows`` consecutive clean (finite, alert-free)
        observations have accumulated since the last anomaly — the signal
        the autopilot's precision re-promotion and the auto-re-arm key off.
        A monitor that has never observed anything is not stabilized."""
        return self._clean_streak >= max(1, int(n_windows))

    def rearm(self) -> None:
        """Re-arm latched state after a clean stretch: clear the NaN latch
        and call ``rearm()`` on every registered action that has one
        (``SnapshotOnAnomalyAction`` un-fires; actions without the method
        are untouched).  Called automatically once ``config.rearm_windows``
        clean observations accumulate, or explicitly by a controller that
        watched :meth:`stabilized`."""
        self.nan_latched = False
        self._rearmed = True
        for action in self.actions:
            rearm = getattr(action, "rearm", None)
            if rearm is None:
                continue
            try:
                rearm()
            except Exception:
                name = getattr(action, "name", type(action).__name__)
                logger.exception("health action %s failed to rearm", name)

    def _run_actions(self, alert: Dict, state) -> List[str]:
        applied = []
        for action in self.actions:
            name = getattr(action, "name", type(action).__name__)
            try:
                if action(alert, state):
                    applied.append(name)
            except Exception:
                logger.exception("health action %s failed on %s", name, alert["kind"])
        return applied

    def report(self) -> Dict:
        return {
            "observed_steps": self._n,
            "nan_latched": self.nan_latched,
            "alerts": list(self.alerts),
            "ewma_loss": self._loss_mean,
            "ewma_grad_norm": self._grad_ewma,
            "clean_streak": self._clean_streak,
        }


class PrecisionDemotionAction:
    """Demote every bucket one rung on the wire-precision ladder
    (int4→int8, int8→f32) via ``apply_precision_plan`` — the corrective the
    planner's guardrail allow-list (PR 8) deliberately left to a human; the
    health monitor now closes that loop.  No-op (returns False) when the
    algorithm has no precision knob, everything is already f32, or the
    precision is user-pinned (a uniform ``wire_precision="int8"`` is an
    explicit operator choice — only planner-chosen per-bucket plans under
    ``"auto"`` are demotable, the same rule ``set_bucket_precision``
    enforces)."""

    name = "precision_demotion"
    DEMOTE = {"int4": "int8", "int8": "f32"}

    def __init__(self, ddp):
        self.ddp = ddp

    def __call__(self, alert: Dict, state=None) -> bool:
        ddp = self.ddp
        impl = getattr(ddp, "impl", None)
        if ddp.plan is None or impl is None or not hasattr(impl, "bucket_precisions"):
            return False
        try:
            current = list(impl.bucket_precisions(ddp.plan))
        except Exception:
            logger.exception("precision demotion: could not read bucket precisions")
            return False
        demoted = [self.DEMOTE.get(p, p) for p in current]
        if demoted == current:
            return False
        try:
            return bool(ddp.apply_precision_plan(
                demoted, reason=f"health:{alert.get('kind', 'anomaly')}"))
        except (AttributeError, ValueError) as e:
            # no precision knob, or user-pinned precision: not ours to touch
            logger.debug("precision demotion not applicable: %s", e)
            return False


class SnapshotOnAnomalyAction:
    """Blocking snapshot of the training state on the *first* anomaly
    (``kind="anomaly"`` in the snapshot store), so a diverging run leaves a
    restorable point from before the damage compounds.  Fires once."""

    name = "snapshot_on_anomaly"

    def __init__(self, snapshotter):
        self.snapshotter = snapshotter
        self.fired = False

    def rearm(self) -> None:
        """Allow the next anomaly (after a clean stretch) its own snapshot —
        called by ``HealthMonitor.rearm`` once the run re-stabilizes."""
        self.fired = False

    def __call__(self, alert: Dict, state=None) -> bool:
        if self.fired or state is None or self.snapshotter is None:
            return False
        self.fired = True
        self.snapshotter.snapshot(state, int(alert.get("step", 0)),
                                  blocking=True, kind="anomaly")
        return True
