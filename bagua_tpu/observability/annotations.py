"""In-graph labeling: attribute device-trace ops to buckets and step phases.

The overlap relaxations only pay off if each bucket's collective really
rides the backward pass — and the only ground truth is the device trace.
XLA carries a per-instruction ``op_name`` metadata string assembled from
``jax.named_scope`` frames, and the profiler's trace events can be joined
back to it through the instruction name (``args.hlo_op`` in
``trace.json.gz``).  These helpers emit a *parseable* scope grammar so
:mod:`bagua_tpu.observability.trace_analysis` can attribute every
collective span to its ``algo``/``bucket``/``phase`` (the transparent
fine-grained tracking of T3, arXiv:2401.16677; the reference shipped the
host-side analog as OTel spans in ``bagua-opentelemetry``):

    bagua_ex/algo=gradient_allreduce/bucket=3/phase=overlap   (bucket exchanges)
    bagua_ex/axis=tp/phase=rs_ring                             (model-parallel)
    bagua_step/phase=optimizer                                 (step phases)

The second form labels *model-parallel* exchanges — the tensor-parallel
``psum``/ring ``ppermute``s and the MoE dispatch/combine all-to-alls — which
have no bucket index: they are keyed by the logical parallelism axis (``tp``
or ``ep``) plus a phase naming the exchange (``row_psum``, ``ag_ring``,
``rs_ring``, ``row_allgather``, ``dispatch``, ``combine``).  The trace
analyzer aggregates them into per-scope ``measured_overlap_frac`` rows.

``named_scope`` only decorates metadata — it never changes the traced
computation, so annotated and unannotated steps are bitwise-identical and
the scopes stay on unconditionally.

Field separators are ``/`` (the scope-nesting separator, which XLA joins
verbatim into ``op_name``) and ``=``; characters like ``@`` are truncated
by the MLIR location plumbing and must not appear in scope names.
"""

import re
from typing import Dict, Optional

import jax

#: scope-name prefixes (kept short: every annotated HLO op carries them)
EXCHANGE_PREFIX = "bagua_ex"
STEP_PREFIX = "bagua_step"

_EXCHANGE_RE = re.compile(
    EXCHANGE_PREFIX + r"/algo=(?P<algo>[^/]+)/bucket=(?P<bucket>\d+)/phase=(?P<phase>[^/\"]+)"
)
_STEP_RE = re.compile(STEP_PREFIX + r"/phase=(?P<phase>[^/\"]+)")
_MP_RE = re.compile(
    EXCHANGE_PREFIX + r"/axis=(?P<axis>[^/=]+)/phase=(?P<phase>[^/\"]+)"
)


def bucket_scope(algo: str, bucket_idx, phase: str):
    """Named scope labeling one bucket's exchange ops.

    ``algo`` is the algorithm's registry-style name, ``phase`` distinguishes
    the monolithic tail exchange (``mono``) from the backward-anchored one
    (``overlap``).  Use as a context manager around the traced exchange."""
    return jax.named_scope(f"{EXCHANGE_PREFIX}/algo={algo}/bucket={int(bucket_idx)}/phase={phase}")


def step_scope(phase: str):
    """Named scope labeling one engine phase of the train step
    (``fwd_bwd``, ``optimizer``, ``algo_start``, ``algo_end``,
    ``finalize``...)."""
    return jax.named_scope(f"{STEP_PREFIX}/phase={phase}")


def mp_scope(axis: str, phase: str):
    """Named scope labeling one model-parallel exchange.

    ``axis`` is the *logical* parallelism scope — ``"tp"`` for tensor-parallel
    exchanges, ``"ep"`` for expert-parallel ones — not the mesh axis name
    (which is deployment-specific and may be a tuple).  ``phase`` names the
    exchange within the scope (``row_psum``, ``ag_ring``, ``rs_ring``,
    ``row_allgather``, ``col_allgather``, ``dispatch``, ``combine``).  Use as
    a context manager around the collective, exactly like
    :func:`bucket_scope`."""
    return jax.named_scope(f"{EXCHANGE_PREFIX}/axis={axis}/phase={phase}")


def parse_mp_label(op_name: str) -> Optional[Dict]:
    """Extract ``{axis, phase}`` from an HLO ``op_name`` carrying a
    :func:`mp_scope` frame; None for unlabeled ops (bucket-exchange labels use
    ``algo=``/``bucket=`` fields and never match)."""
    m = _MP_RE.search(op_name or "")
    if not m:
        return None
    return {"axis": m.group("axis"), "phase": m.group("phase")}


def parse_exchange_label(op_name: str) -> Optional[Dict]:
    """Extract ``{algo, bucket, phase}`` from an HLO ``op_name`` metadata
    string (or any string containing a :func:`bucket_scope` frame); None
    when the op is not part of a labeled bucket exchange."""
    m = _EXCHANGE_RE.search(op_name or "")
    if not m:
        return None
    return {"algo": m.group("algo"), "bucket": int(m.group("bucket")), "phase": m.group("phase")}


def parse_step_phase(op_name: str) -> Optional[str]:
    """The engine step phase an op was traced under, if labeled."""
    m = _STEP_RE.search(op_name or "")
    return m.group("phase") if m else None
