"""In-graph labeling: attribute device-trace ops to buckets and step phases.

The overlap relaxations only pay off if each bucket's collective really
rides the backward pass — and the only ground truth is the device trace.
XLA carries a per-instruction ``op_name`` metadata string assembled from
``jax.named_scope`` frames, and the profiler's trace events can be joined
back to it through the instruction name (``args.hlo_op`` in
``trace.json.gz``).  These helpers emit a *parseable* scope grammar so
:mod:`bagua_tpu.observability.trace_analysis` can attribute every
collective span to its ``algo``/``bucket``/``phase`` (the transparent
fine-grained tracking of T3, arXiv:2401.16677; the reference shipped the
host-side analog as OTel spans in ``bagua-opentelemetry``):

    bagua_ex/algo=gradient_allreduce/bucket=3/phase=overlap   (exchanges)
    bagua_step/phase=optimizer                                 (step phases)

``named_scope`` only decorates metadata — it never changes the traced
computation, so annotated and unannotated steps are bitwise-identical and
the scopes stay on unconditionally.

Field separators are ``/`` (the scope-nesting separator, which XLA joins
verbatim into ``op_name``) and ``=``; characters like ``@`` are truncated
by the MLIR location plumbing and must not appear in scope names.
"""

import re
from typing import Dict, Optional

import jax

#: scope-name prefixes (kept short: every annotated HLO op carries them)
EXCHANGE_PREFIX = "bagua_ex"
STEP_PREFIX = "bagua_step"

_EXCHANGE_RE = re.compile(
    EXCHANGE_PREFIX + r"/algo=(?P<algo>[^/]+)/bucket=(?P<bucket>\d+)/phase=(?P<phase>[^/\"]+)"
)
_STEP_RE = re.compile(STEP_PREFIX + r"/phase=(?P<phase>[^/\"]+)")


def bucket_scope(algo: str, bucket_idx, phase: str):
    """Named scope labeling one bucket's exchange ops.

    ``algo`` is the algorithm's registry-style name, ``phase`` distinguishes
    the monolithic tail exchange (``mono``) from the backward-anchored one
    (``overlap``).  Use as a context manager around the traced exchange."""
    return jax.named_scope(f"{EXCHANGE_PREFIX}/algo={algo}/bucket={int(bucket_idx)}/phase={phase}")


def step_scope(phase: str):
    """Named scope labeling one engine phase of the train step
    (``fwd_bwd``, ``optimizer``, ``algo_start``, ``algo_end``,
    ``finalize``...)."""
    return jax.named_scope(f"{STEP_PREFIX}/phase={phase}")


def parse_exchange_label(op_name: str) -> Optional[Dict]:
    """Extract ``{algo, bucket, phase}`` from an HLO ``op_name`` metadata
    string (or any string containing a :func:`bucket_scope` frame); None
    when the op is not part of a labeled bucket exchange."""
    m = _EXCHANGE_RE.search(op_name or "")
    if not m:
        return None
    return {"algo": m.group("algo"), "bucket": int(m.group("bucket")), "phase": m.group("phase")}


def parse_step_phase(op_name: str) -> Optional[str]:
    """The engine step phase an op was traced under, if labeled."""
    m = _STEP_RE.search(op_name or "")
    return m.group("phase") if m else None
