"""In-graph labeling: attribute device-trace ops to buckets and step phases.

The overlap relaxations only pay off if each bucket's collective really
rides the backward pass — and the only ground truth is the device trace.
XLA carries a per-instruction ``op_name`` metadata string assembled from
``jax.named_scope`` frames, and the profiler's trace events can be joined
back to it through the instruction name (``args.hlo_op`` in
``trace.json.gz``).  These helpers emit a *parseable* scope grammar so
:mod:`bagua_tpu.observability.trace_analysis` can attribute every
collective span to its ``algo``/``bucket``/``phase`` (the transparent
fine-grained tracking of T3, arXiv:2401.16677; the reference shipped the
host-side analog as OTel spans in ``bagua-opentelemetry``):

    bagua_ex/algo=gradient_allreduce/bucket=3/phase=overlap   (bucket exchanges)
    bagua_ex/axis=tp/phase=rs_ring                             (model-parallel)
    bagua_step/phase=optimizer                                 (step phases)

The second form labels *model-parallel* exchanges — the tensor-parallel
``psum``/ring ``ppermute``s and the MoE dispatch/combine all-to-alls — which
have no bucket index: they are keyed by the logical parallelism axis (``tp``
or ``ep``) plus a phase naming the exchange (``row_psum``, ``ag_ring``,
``rs_ring``, ``row_allgather``, ``dispatch``, ``combine``).  The trace
analyzer aggregates them into per-scope ``measured_overlap_frac`` rows.

``named_scope`` only decorates metadata — it never changes the traced
computation, so annotated and unannotated steps are bitwise-identical and
the scopes stay on unconditionally.

The grammar itself — prefixes, regexes, formatters and parsers — lives in
:mod:`bagua_tpu.observability.scope_grammar`, shared with the device-trace
joiner, the flight recorder's record templates and the static verifier
(:mod:`bagua_tpu.analysis`); this module re-exports the parsers and adds
the ``jax.named_scope`` factories.
"""

import jax

from bagua_tpu.observability.scope_grammar import (
    EXCHANGE_PREFIX,
    STEP_PREFIX,
    format_exchange_label,
    format_mp_label,
    format_step_label,
    parse_exchange_label,
    parse_mp_label,
    parse_step_phase,
)

# Back-compat aliases for the pre-hoist private names.
from bagua_tpu.observability.scope_grammar import EXCHANGE_RE as _EXCHANGE_RE  # noqa: F401
from bagua_tpu.observability.scope_grammar import MP_RE as _MP_RE  # noqa: F401
from bagua_tpu.observability.scope_grammar import STEP_RE as _STEP_RE  # noqa: F401

__all__ = [
    "EXCHANGE_PREFIX",
    "STEP_PREFIX",
    "bucket_scope",
    "step_scope",
    "mp_scope",
    "parse_exchange_label",
    "parse_mp_label",
    "parse_step_phase",
]


def bucket_scope(algo: str, bucket_idx, phase: str):
    """Named scope labeling one bucket's exchange ops.

    ``algo`` is the algorithm's registry-style name, ``phase`` distinguishes
    the monolithic tail exchange (``mono``) from the backward-anchored one
    (``overlap``).  Use as a context manager around the traced exchange."""
    return jax.named_scope(format_exchange_label(algo, bucket_idx, phase))


def step_scope(phase: str):
    """Named scope labeling one engine phase of the train step
    (``fwd_bwd``, ``optimizer``, ``algo_start``, ``algo_end``,
    ``finalize``...)."""
    return jax.named_scope(format_step_label(phase))


def mp_scope(axis: str, phase: str):
    """Named scope labeling one model-parallel exchange.

    ``axis`` is the *logical* parallelism scope — ``"tp"`` for tensor-parallel
    exchanges, ``"ep"`` for expert-parallel ones — not the mesh axis name
    (which is deployment-specific and may be a tuple).  ``phase`` names the
    exchange within the scope (``row_psum``, ``ag_ring``, ``rs_ring``,
    ``row_allgather``, ``col_allgather``, ``dispatch``, ``combine``).  Use as
    a context manager around the collective, exactly like
    :func:`bucket_scope`."""
    return jax.named_scope(format_mp_label(axis, phase))
