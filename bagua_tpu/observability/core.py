"""Tracing, metrics and hang detection.

TPU-native analog of the reference's auxiliary subsystems (SURVEY §5.1-5.2):

* **Spans** (reference: Rust OTel ``tensor_ready`` spans POSTed to the
  autotune server, ``bagua-opentelemetry/src/exporter/mod.rs:15-62``): a
  host-side :class:`SpanRecorder` collects ``(action, tensor_name, start,
  end)`` records — e.g. bucket execution order derived from the jitted step —
  and ships them to the autotune service to learn tensor ordering.
* **Step timing** (reference: CUDA-event pairs + ``StatisticalAverage``,
  ``bagua_distributed.py:113-131``): :class:`StepTimer` wraps
  ``block_until_ready`` wall-time into the engine's ``SpeedMeter``.
* **Hang watchdog** (reference: comm monitor thread panicking after 300 s,
  ``src/lib.rs:255-265``, and the panic→process-exit hook,
  ``bagua-core-py/src/lib.rs:547-553``): :class:`Watchdog` kills the process
  with a full thread dump if no heartbeat arrives within the timeout, so a
  wedged worker can't hang a gang-scheduled job.
"""

import faulthandler
import logging
import math
import os
import sys
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)


class SpanRecorder:
    """Collects spans; thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self.spans: List[Dict] = []

    def record(self, action: str, tensor_name: str, start_time: float, end_time: float):
        with self._lock:
            self.spans.append(
                {
                    "action": action,
                    "tensor_name": tensor_name,
                    "start_time": start_time,
                    "end_time": end_time,
                }
            )

    def record_measured_order(self, plan, bucket_times) -> None:
        """Convert measured per-bucket readiness costs (seconds, aligned with
        ``plan.specs`` — see ``DistributedDataParallel.profile_bucket_order``)
        into ``tensor_ready`` spans: a tensor's start time is its bucket's
        measured cost, with a sub-microsecond offset keeping slots within a
        bucket in a stable order.  The autotune service sorts by start time,
        so cheap (early-ready) buckets come first."""
        for spec, cost in zip(plan.specs, bucket_times):
            for j, slot in enumerate(spec.slots):
                start = cost + j * 1e-9
                self.record("tensor_ready", slot.name, start, start + 1e-9)

    def record_wire_timings(
        self, plan, analysis: Dict, intra_size: int = 1, hierarchical: bool = False,
        leg: Optional[str] = None,
    ) -> None:
        """Convert a device-trace analysis
        (:func:`~bagua_tpu.observability.trace_analysis.analyze_trace`) into
        ``bucket_wire`` spans — the planner's α–β cost-model input.  Each
        attributed per-bucket row becomes one sample carrying the bucket's
        wire bytes (from the plan), measured collective seconds and hidden
        fraction; hierarchical captures tag the leg so intra/inter paths are
        fitted separately.  An explicit ``leg`` overrides the tag — sharded
        exchanges pass ``"rs"``/``"ag"`` so the planner fits the
        reduce-scatter and all-gather wire paths independently."""
        for row in analysis.get("per_bucket", []):
            bi = row.get("bucket")
            if bi is None or bi >= len(plan.specs):
                continue
            seconds = float(row.get("collective_ms", 0.0)) / 1e3
            if seconds <= 0.0:
                continue
            with self._lock:
                self.spans.append(
                    {
                        "action": "bucket_wire",
                        "tensor_name": f"bucket{bi}",
                        "start_time": 0.0,
                        "end_time": seconds,
                        "nbytes": int(plan.specs[bi].nbytes),
                        "seconds": seconds,
                        "leg": leg or ("intra" if hierarchical else "flat"),
                        "hidden_frac": float(row.get("overlap_frac", 0.0)),
                        "intra_size": int(intra_size),
                    }
                )

    def drain(self) -> List[Dict]:
        with self._lock:
            out, self.spans = self.spans, []
        return out

    def report_to_autotune(self, client, model_name: str) -> None:
        spans = self.drain()
        if spans:
            client.report_tensor_execution_order(model_name, spans)


class StepTimer:
    """Times jitted steps; feeds a SpeedMeter and keeps simple aggregates.

    Use ``with timer.step(n_samples): ...`` around dispatch+wait, or call
    ``tick`` manually.  ``tick`` is thread-safe (the async averager's
    background thread and the fit loop may both time work), and the last
    ``window`` step times are kept in a ring buffer so
    :meth:`percentiles` can report p50/p95/p99 tail latency — the number
    that catches a stalling input pipeline or a periodic retrace long
    before the mean moves.
    """

    def __init__(self, speed_meter=None, window: int = 1024):
        self.speed_meter = speed_meter
        self.n_steps = 0
        self.total_time = 0.0
        self.last_step_time = 0.0
        self._lock = threading.Lock()
        self._ring = [0.0] * max(1, window)
        self._ring_n = 0  # total ticks ever; ring holds the last len(_ring)

    class _Ctx:
        def __init__(self, timer, n_samples):
            self.timer = timer
            self.n_samples = n_samples

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.timer.tick(time.perf_counter() - self.t0, self.n_samples)
            return False

    def step(self, n_samples: int = 0) -> "_Ctx":
        return StepTimer._Ctx(self, n_samples)

    def tick(self, elapsed: float, n_samples: int = 0) -> None:
        with self._lock:
            self.n_steps += 1
            self.total_time += elapsed
            self.last_step_time = elapsed
            self._ring[self._ring_n % len(self._ring)] = elapsed
            self._ring_n += 1
        if self.speed_meter is not None and n_samples:
            self.speed_meter.record(n_samples)

    @property
    def mean_step_time(self) -> float:
        return self.total_time / self.n_steps if self.n_steps else 0.0

    def percentiles(self) -> Dict[str, float]:
        """p50/p95/p99 over the ring-buffered recent step times (seconds);
        empty dict until the first tick.  Nearest-rank indexing: the p-th
        percentile of n samples is the ``ceil(p*n)``-th smallest, so the
        p50 of a 2-sample ring is the *lower* sample (the old ``int(p*n)``
        truncation returned the max)."""
        with self._lock:
            n = min(self._ring_n, len(self._ring))
            recent = sorted(self._ring[:n]) if n else []
        if not recent:
            return {}
        def q(p):
            n = len(recent)
            return recent[min(n - 1, max(0, math.ceil(p * n) - 1))]
        return {"p50": q(0.50), "p95": q(0.95), "p99": q(0.99)}


class Watchdog:
    """Fail-fast hang detector.

    Call :meth:`beat` at least every ``timeout_s`` seconds (typically once
    per training step).  If the heartbeat stops — a wedged collective, a
    deadlocked host thread — the watchdog dumps every thread's stack and
    kills the process (exit code 42), letting the launcher's restart logic
    take over.  ``on_timeout`` can override the kill for tests.

    ``BAGUA_WATCHDOG_TIMEOUT_S`` in the environment overrides ``timeout_s``
    (an operator knob for gang-scheduled jobs whose launch script can't be
    edited).  ``beat(phase=...)`` tags each heartbeat with the step phase
    the host was in (``dispatch``/``wait``/``data``), and
    ``snapshot_provider`` — a zero-arg callable returning a dict, normally
    :meth:`Telemetry.snapshot <bagua_tpu.observability.telemetry.Telemetry.snapshot>`
    — is queried at timeout so the dump says *where* the step was stuck
    (step number, phase, bucket), not just that it stopped.
    """

    def __init__(self, timeout_s: float = 300.0, check_interval_s: Optional[float] = None,
                 on_timeout=None, snapshot_provider=None):
        env = os.environ.get("BAGUA_WATCHDOG_TIMEOUT_S")
        if env:
            try:
                timeout_s = float(env)
                logger.info("watchdog timeout overridden by BAGUA_WATCHDOG_TIMEOUT_S=%s", env)
            except ValueError:
                logger.warning("ignoring non-numeric BAGUA_WATCHDOG_TIMEOUT_S=%r", env)
        self.timeout_s = timeout_s
        self.check_interval_s = check_interval_s or min(10.0, timeout_s / 3)
        self.on_timeout = on_timeout
        self.snapshot_provider = snapshot_provider
        # Hang-evidence wiring (all optional; see _dump_evidence): where the
        # dumps land (None = BAGUA_DUMP_DIR or CWD), the rank's flight
        # recorder, a hook the telemetry hub binds to emit the ``hang``
        # JSONL event, and a zero-arg digest pusher (rendezvous KV,
        # best-effort) the trainer binds.
        self.dump_dir: Optional[str] = None
        self.flight_recorder = None
        self.hang_hook = None
        self.digest_pusher = None
        self.last_dump_paths: Dict[str, str] = {}
        self.last_phase: Optional[str] = None
        self._last_beat = time.monotonic()
        self._armed = False
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True, name="bagua-watchdog")
            self._thread.start()
        return self

    def beat(self, phase: Optional[str] = None) -> None:
        if phase is not None:
            self.last_phase = phase
        self._last_beat = time.monotonic()
        self._armed = True

    def stop(self) -> None:
        self._stopped.set()

    def _timeout_context(self) -> Dict:
        """What the host was doing when the heartbeat stopped."""
        ctx: Dict = {"last_phase": self.last_phase}
        if self.snapshot_provider is not None:
            try:
                ctx["telemetry"] = self.snapshot_provider()
            except Exception as e:  # the dump must never be lost to a bad hook
                ctx["telemetry_error"] = f"{type(e).__name__}: {e}"
        return ctx

    def _dump_evidence(self, silent: float, ctx: Dict) -> Dict[str, str]:
        """Persist the hang's evidence before any exit path: an atomic
        ``watchdog_dump.json`` (the timeout context), the rank's flight-
        recorder ring as ``flight_<rank>.json``, the best-effort digest push
        and the hub's ``hang`` JSONL event.  Every stage is fenced — a
        failing disk or KV must not stop the stack dump / process kill."""
        from bagua_tpu.observability.flight_recorder import (
            flight_dump_path, write_json_atomic,
        )

        if self.dump_dir is not None:
            d = self.dump_dir
        else:
            from bagua_tpu.env import get_dump_dir

            d = get_dump_dir()
        paths: Dict[str, str] = {}
        try:
            path = os.path.join(d, "watchdog_dump.json")
            write_json_atomic(path, {
                "reason": "watchdog_timeout",
                "silent_s": round(silent, 3),
                "timeout_s": self.timeout_s,
                "mono_at_dump": time.monotonic(),
                "unix_at_dump": time.time(),
                **ctx,
            })
            paths["watchdog_dump"] = path
        except Exception:
            logger.exception("watchdog dump failed")
        fr = self.flight_recorder
        if fr is not None:
            try:
                path = flight_dump_path(d, fr.rank)
                fr.dump(path, reason="watchdog_timeout",
                        telemetry=ctx.get("telemetry"))
                paths["flight_dump"] = path
            except Exception:
                logger.exception("flight dump failed")
            if self.digest_pusher is not None:
                try:
                    self.digest_pusher()
                except Exception:
                    logger.exception("flight digest push failed")
        if self.hang_hook is not None:
            try:
                self.hang_hook("watchdog_timeout", ctx, paths)
            except Exception:
                logger.exception("hang hook failed")
        self.last_dump_paths = paths
        return paths

    def _run(self) -> None:
        while not self._stopped.wait(self.check_interval_s):
            if not self._armed:
                continue
            silent = time.monotonic() - self._last_beat
            if silent > self.timeout_s:
                ctx = self._timeout_context()
                logger.error(
                    "watchdog: no heartbeat for %.1fs (timeout %.1fs); last known "
                    "position: %s; dumping threads",
                    silent,
                    self.timeout_s,
                    ctx,
                )
                # evidence first — the dump files and the hub's ``hang``
                # event must exist before any exit path (on_timeout or the
                # os._exit below) can erase the scene
                self._dump_evidence(silent, ctx)
                if self.on_timeout is not None:
                    self.on_timeout(silent)
                    self._armed = False
                    continue
                print(f"bagua watchdog timeout context: {ctx}", file=sys.stderr)
                faulthandler.dump_traceback(file=sys.stderr)
                sys.stderr.flush()
                os._exit(42)


class ProfilerSession:
    """XLA profiler capture (reference: Jaeger tracing + per-op OTel spans,
    ``bagua-net/src/lib.rs:66-80``; on TPU the ground truth is the XLA
    profiler's device trace: per-HLO timing, collective overlap, MXU
    utilization, HBM traffic — viewable in TensorBoard/xprof).

        prof = ProfilerSession("/tmp/bagua_trace")
        prof.start()
        ... a few training steps ...
        prof.stop()           # trace under /tmp/bagua_trace/plugins/profile

    Or scoped::

        with ProfilerSession("/tmp/bagua_trace"):
            state, _ = ddp.train_step(state, batch)

    ``trace_steps(fn, state, batches)`` captures exactly the supplied steps
    with a ``block_until_ready`` barrier on each side so device work from
    outside the window never bleeds into the capture.
    """

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._active = False

    def start(self) -> None:
        import jax

        jax.profiler.start_trace(self.log_dir)
        self._active = True

    def stop(self) -> None:
        import jax

        if self._active:
            jax.profiler.stop_trace()
            self._active = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def trace_steps(self, step_fn, state, batches):
        """Run ``state, aux = step_fn(state, batch)`` over ``batches`` inside
        one clean capture window; returns the final ``(state, aux)``."""
        import jax

        jax.block_until_ready(state)
        aux = None
        with self:
            for batch in batches:
                state, aux = step_fn(state, batch)
            jax.block_until_ready((state, aux))
        return state, aux
