"""Device-trace overlap analysis: measure — don't assert — the overlap.

``PERF_AUDIT`` proves the *structural* claim (per-bucket collectives
anchored inside the backward HLO) and ``TRACE_VGG16`` the *wall-clock*
delta; this module closes the loop with the device's own account, T3-style
(arXiv:2401.16677: fine-grained compute/collective overlap must be tracked
transparently to be trusted).  It parses the XLA profiler's
``trace.json.gz`` (written by
:class:`~bagua_tpu.observability.core.ProfilerSession` /
``jax.profiler.trace``; plain gzip+JSON, no protobuf deps) and computes,
for every collective span, the fraction of its duration *hidden under
compute* — compute ops executing concurrently on other lanes/streams.

Attribution: trace events carry only the HLO instruction name
(``args.hlo_op`` = ``all-reduce.3``), not the ``op_name`` metadata with the
:mod:`~bagua_tpu.observability.annotations` bucket labels.  The join runs
through the compiled HLO text (``compiled.as_text()``): instruction name →
``op_name`` → ``algo``/``bucket``/``phase``.  Pass ``hlo_text`` to
:func:`analyze_trace` to get per-bucket rows; without it the analysis still
reports the aggregate overlap fraction with every span unattributed.

The metric::

    measured_overlap_frac = hidden_collective_time / total_collective_time

1.0 = every collective microsecond ran under concurrent compute (fully
hidden wire); 0.0 = strictly serialized exchange.  On the CPU sim the
"device" lanes are the XLA:CPU client threads (one per simulated device)
— the geometry differs from a TPU's async collective streams but the
interval math is identical, so the CI lane can regression-test the
analyzer end-to-end.
"""

import bisect
import glob
import gzip
import json
import logging
import os
import re
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from bagua_tpu.observability.scope_grammar import (
    hlo_op_labels,
    parse_exchange_label,
    parse_mp_label,
)

logger = logging.getLogger(__name__)

__all__ = [
    "COLLECTIVE_OPS",
    "find_trace_file",
    "load_trace_events",
    "hlo_op_labels",
    "analyze_trace",
]

#: HLO instruction-name prefixes that move bytes between devices
COLLECTIVE_OPS = (
    "all-reduce",
    "reduce-scatter",
    "all-gather",
    "all-to-all",
    "collective-permute",
    "collective-broadcast",
)

# The HLO instruction → op_name join table (_HLO_INSTR/_HLO_MODULE) moved to
# scope_grammar so the static verifier shares one parser; hlo_op_labels is
# re-exported above for the existing callers.


def find_trace_file(log_dir: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` under a profiler log dir (the capture
    lands in ``plugins/profile/<timestamp>/<host>.trace.json.gz``)."""
    paths = glob.glob(
        os.path.join(log_dir, "**", "*.trace.json.gz"), recursive=True
    )
    return max(paths, key=os.path.getmtime) if paths else None


_TRACE_EVENTS_KEY = re.compile(r'"traceEvents"\s*:\s*\[')


def _iter_trace_events(f, chunk: int = 1 << 22):
    """Stream the objects of the top-level ``traceEvents`` array without
    materializing the document — a few steps of a conv net on the CPU sim
    produce multi-GB trace JSONs (every thread-pool slice is an event), and
    ``json.load`` would need an order of magnitude more RAM than the file."""
    dec = json.JSONDecoder()
    buf = ""
    while True:  # locate the array, tolerating a chunk-straddling key
        more = f.read(chunk)
        if not more:
            return
        buf += more
        m = _TRACE_EVENTS_KEY.search(buf)
        if m:
            buf = buf[m.end():]
            break
        buf = buf[-32:]
    idx = 0
    while True:
        while True:  # skip separators; refill when the buffer runs dry
            while idx < len(buf) and buf[idx] in " \t\r\n,":
                idx += 1
            if idx < len(buf):
                break
            buf = f.read(chunk)
            idx = 0
            if not buf:
                return
        if buf[idx] == "]":
            return
        try:
            obj, idx = dec.raw_decode(buf, idx)
        except ValueError:  # object truncated at the buffer edge: refill
            more = f.read(chunk)
            if not more:
                return
            buf, idx = buf[idx:] + more, 0
            continue
        yield obj
        if idx > chunk:  # compact so the buffer stays O(chunk)
            buf, idx = buf[idx:], 0


def load_trace_events(log_dir: str) -> List[Dict]:
    """All complete-event (``ph == "X"``) XLA op events — those carrying an
    ``args.hlo_op`` — with ``ts``/``dur`` in microseconds.  The file is
    stream-parsed; only the XLA op events are kept in memory."""
    path = log_dir if log_dir.endswith(".gz") else find_trace_file(log_dir)
    if path is None:
        raise FileNotFoundError(f"no trace.json.gz under {log_dir}")
    out = []
    with gzip.open(path, "rt") as f:
        try:
            for ev in _iter_trace_events(f):
                if ev.get("ph") != "X" or "dur" not in ev:
                    continue
                args = ev.get("args") or {}
                hlo_op = args.get("hlo_op")
                if not hlo_op:
                    continue  # host-side python/runtime event, not a device op
                out.append(
                    {
                        "hlo_op": hlo_op,
                        "hlo_module": args.get("hlo_module", ""),
                        "lane": (ev.get("pid"), ev.get("tid")),
                        "ts": float(ev["ts"]),
                        "dur": float(ev["dur"]),
                    }
                )
        except (EOFError, gzip.BadGzipFile, OSError, zlib.error) as e:
            # a truncated capture (job killed mid-profile) is the common
            # case, not a parse bug: degrade to the events salvaged so far
            logger.warning(
                "trace %s truncated/corrupt after %d op events (%s); "
                "analyzing the salvaged prefix", path, len(out), e,
            )
    return out


def _merge_intervals(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    if not intervals:
        return []
    intervals.sort()
    merged = [list(intervals[0])]
    for s, e in intervals[1:]:
        if s <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([s, e])
    return [tuple(iv) for iv in merged]


def _covered(start: float, end: float, merged: List[Tuple[float, float]],
             starts: List[float]) -> float:
    """Length of [start, end] ∩ union(merged) (merged sorted, disjoint)."""
    if end <= start or not merged:
        return 0.0
    covered = 0.0
    i = max(0, bisect.bisect_right(starts, start) - 1)
    while i < len(merged) and merged[i][0] < end:
        s, e = merged[i]
        covered += max(0.0, min(e, end) - max(s, start))
        i += 1
    return covered


def _is_collective(hlo_op: str) -> bool:
    return hlo_op.lstrip("%").startswith(COLLECTIVE_OPS)


def analyze_trace(
    log_dir: str,
    hlo_text: Optional[str] = None,
    module: Optional[str] = None,
) -> Dict:
    """Per-bucket measured overlap efficiency from one profiler capture.

    Args:
        log_dir: profiler log dir (or a direct ``.trace.json.gz`` path).
        hlo_text: compiled HLO of the step whose execution was captured;
            enables bucket attribution (instruction → ``op_name`` labels).
        module: restrict to events of this ``hlo_module`` (defaults to the
            module named in ``hlo_text``; None + no hlo_text = all modules).

    Returns a dict with the aggregate ``measured_overlap_frac``, a
    ``per_bucket`` list (one row per labeled ``(algo, bucket)``), a
    ``per_scope`` list (one row per model-parallel scope axis — ``tp``/``ep``
    exchanges labeled via :func:`~bagua_tpu.observability.annotations.mp_scope`,
    each row carrying its own ``measured_overlap_frac``), and an
    ``unattributed`` bucket for collective spans without any label.
    """
    events = load_trace_events(log_dir)
    labels: Dict[str, str] = {}
    if hlo_text is not None:
        hlo_module, labels = hlo_op_labels(hlo_text)
        if module is None:
            module = hlo_module
    if module:
        scoped = [e for e in events if e["hlo_module"] == module]
        # a lowered-vs-executed name drift must degrade to "unattributed",
        # not to an empty analysis
        if scoped:
            events = scoped
    collectives = [e for e in events if _is_collective(e["hlo_op"])]
    compute = [e for e in events if not _is_collective(e["hlo_op"])]

    merged = _merge_intervals([(e["ts"], e["ts"] + e["dur"]) for e in compute])
    starts = [s for s, _ in merged]

    per_key: Dict[Tuple, Dict] = {}
    per_scope_key: Dict[str, Dict] = {}
    total_us = hidden_us = 0.0
    for e in collectives:
        hid = _covered(e["ts"], e["ts"] + e["dur"], merged, starts)
        total_us += e["dur"]
        hidden_us += hid
        op_name = labels.get(e["hlo_op"], "")
        lab = parse_exchange_label(op_name)
        mp = None if lab else parse_mp_label(op_name)
        if mp is not None:
            srow = per_scope_key.setdefault(
                mp["axis"],
                {
                    "axis": mp["axis"],
                    "phases": set(),
                    "hlo_ops": set(),
                    "spans": 0,
                    "collective_us": 0.0,
                    "hidden_us": 0.0,
                },
            )
            srow["phases"].add(mp["phase"])
            srow["hlo_ops"].add(e["hlo_op"])
            srow["spans"] += 1
            srow["collective_us"] += e["dur"]
            srow["hidden_us"] += hid
            continue
        key = (lab["algo"], lab["bucket"]) if lab else None
        row = per_key.setdefault(
            key,
            {
                "algo": lab["algo"] if lab else None,
                "bucket": lab["bucket"] if lab else None,
                "phases": set(),
                "hlo_ops": set(),
                "spans": 0,
                "collective_us": 0.0,
                "hidden_us": 0.0,
            },
        )
        if lab:
            row["phases"].add(lab["phase"])
        row["hlo_ops"].add(e["hlo_op"])
        row["spans"] += 1
        row["collective_us"] += e["dur"]
        row["hidden_us"] += hid

    def finish(row):
        return {
            "algo": row["algo"],
            "bucket": row["bucket"],
            "phases": sorted(row["phases"]),
            "hlo_ops": sorted(row["hlo_ops"]),
            "spans": row["spans"],
            "collective_ms": round(row["collective_us"] / 1e3, 3),
            "hidden_ms": round(row["hidden_us"] / 1e3, 3),
            "overlap_frac": round(row["hidden_us"] / row["collective_us"], 4)
            if row["collective_us"] else 0.0,
        }

    def finish_scope(row):
        return {
            "axis": row["axis"],
            "phases": sorted(row["phases"]),
            "hlo_ops": sorted(row["hlo_ops"]),
            "spans": row["spans"],
            "collective_ms": round(row["collective_us"] / 1e3, 3),
            "hidden_ms": round(row["hidden_us"] / 1e3, 3),
            "measured_overlap_frac": round(
                row["hidden_us"] / row["collective_us"], 4
            )
            if row["collective_us"] else 0.0,
        }

    per_bucket = sorted(
        (finish(r) for k, r in per_key.items() if k is not None),
        key=lambda r: (r["algo"], r["bucket"]),
    )
    per_scope = sorted(
        (finish_scope(r) for r in per_scope_key.values()),
        key=lambda r: r["axis"],
    )
    unattributed = next(
        (finish(r) for k, r in per_key.items() if k is None), None
    )
    return {
        "module": module or "",
        "num_xla_events": len(events),
        "collective_spans": len(collectives),
        "collective_ms": round(total_us / 1e3, 3),
        "hidden_ms": round(hidden_us / 1e3, 3),
        "measured_overlap_frac": round(hidden_us / total_us, 4) if total_us else 0.0,
        "per_bucket": per_bucket,
        "per_scope": per_scope,
        "unattributed": unattributed,
    }
