"""The telemetry hub: one object threading metrics, events, heartbeats and
recompile detection through the training stack.

Attach a :class:`Telemetry` to the engine
(``DistributedDataParallel(..., telemetry=...)`` or
``Trainer(..., telemetry=...)``) and every step feeds it:

* step wall time, samples/s, wire bytes (from the bucket plan) into the
  :class:`~bagua_tpu.observability.metrics.MetricsRegistry` and the JSONL
  event stream;
* a **recompile detector** counting the engine's jit-cache misses per step
  variant — a silent retrace (batch-shape drift, a weak-typed scalar, an
  accidental plan change) is the top real-world TPU perf bug and is
  otherwise invisible: the step just gets 1000x slower for one iteration,
  every few iterations;
* phase-tagged :class:`~bagua_tpu.observability.core.Watchdog` heartbeats
  (``dispatch``/``wait``/``data``) plus a :meth:`snapshot` the watchdog
  embeds in its hang dump, so a timeout says *where* the step was stuck.

Everything is host-side and optional — an unattached engine pays nothing,
an attached one ~a few µs of clock reads and dict updates per step.
"""

import logging
import time
from typing import Dict, Optional

from bagua_tpu.observability.core import StepTimer, Watchdog
from bagua_tpu.observability.metrics import JsonlSink, MetricsRegistry

logger = logging.getLogger(__name__)

__all__ = ["RecompileDetector", "Telemetry"]


class RecompileDetector:
    """Counts jit-cache misses per step variant and alerts on retrace churn.

    The engine reports every compile through :meth:`record_compile` and
    every dispatched step through :meth:`record_step`.  The *first* compile
    of the training run is the expected warmup; every later compile — a
    re-build of a variant that was already compiled (cache cleared by
    ``need_reset``/``rebucket``/shape drift) or a brand-new variant
    appearing mid-run — counts as a **retrace**.  More than
    ``max_retraces_per_window`` retraces inside any ``window``-step window
    raises a rate alert (once per quiet period): steady-state training must
    compile zero times.
    """

    def __init__(self, window: int = 100, max_retraces_per_window: int = 2):
        self.window = window
        self.max_retraces_per_window = max_retraces_per_window
        self.compiles_by_variant: Dict[str, int] = {}
        self.compile_ms_by_variant: Dict[str, float] = {}
        self.compile_ms_total = 0.0
        self.steps = 0
        self.retraces = 0
        self.alerts = 0
        self._retrace_steps = []  # step index of each retrace (rate window)
        self._alerted = False

    def record_compile(self, variant: str, on_alert=None) -> bool:
        """Register one jit-cache miss; returns True when it counts as a
        retrace (anything beyond the run's first compile).  ``on_alert``
        is called with a message when the retrace rate trips the alarm."""
        first_ever = not self.compiles_by_variant
        self.compiles_by_variant[variant] = self.compiles_by_variant.get(variant, 0) + 1
        if first_ever:
            return False
        self.retraces += 1
        self._retrace_steps.append(self.steps)
        logger.warning(
            "recompile detector: retrace #%d at step %d (variant %r, compile #%d "
            "of this variant)",
            self.retraces, self.steps, variant, self.compiles_by_variant[variant],
        )
        recent = [s for s in self._retrace_steps if s > self.steps - self.window]
        if len(recent) > self.max_retraces_per_window and not self._alerted:
            self._alerted = True
            self.alerts += 1
            msg = (
                f"recompile detector ALERT: {len(recent)} retraces in the last "
                f"{self.window} steps (> {self.max_retraces_per_window}); the "
                "step function is churning — look for batch-shape drift, "
                "weak-typed scalars or plan changes"
            )
            logger.error(msg)
            if on_alert is not None:
                on_alert(msg, len(recent))
        return True

    def record_compile_wall(self, variant: str, wall_ms: float) -> None:
        """Attribute one compile's measured wall time to its variant —
        counts say *that* the step function churned, wall time says what
        the churn *cost* (the goodput ledger's compile bucket)."""
        self.compile_ms_by_variant[variant] = (
            self.compile_ms_by_variant.get(variant, 0.0) + float(wall_ms)
        )
        self.compile_ms_total += float(wall_ms)

    def record_step(self) -> None:
        self.steps += 1
        if self._alerted and all(
            s <= self.steps - self.window for s in self._retrace_steps
        ):
            self._alerted = False  # quiet for a full window: re-arm the alarm

    def report(self) -> Dict:
        return {
            "steps": self.steps,
            "retraces": self.retraces,
            "alerts": self.alerts,
            "compiles_by_variant": dict(self.compiles_by_variant),
            "compile_ms_total": round(self.compile_ms_total, 3),
            "compile_ms_by_variant": {
                k: round(v, 3) for k, v in self.compile_ms_by_variant.items()
            },
        }


class Telemetry:
    """Per-process telemetry hub.

    Args:
        metrics_jsonl: path for the JSONL event stream (None = no stream).
        registry: an existing :class:`MetricsRegistry` to feed (default: a
            fresh one, exposed as ``.registry``).
        watchdog: a :class:`Watchdog` to heartbeat from the step path; its
            ``snapshot_provider`` is pointed at :meth:`snapshot` so hang
            dumps carry the last known (step, phase, bucket, variant).
        retrace_window / max_retraces_per_window: recompile alert rate knobs.
        goodput: a :class:`~bagua_tpu.observability.goodput.GoodputMeter` to
            feed (phases → ledger buckets, steps → MFU, compile/snapshot/
            restart walls → their ledger buckets).  The hub points the
            meter's gauges at its own registry.
        flight: the collective flight recorder
            (:class:`~bagua_tpu.observability.flight_recorder.FlightRecorder`)
            the engine replays its collective programs into.  The default
            ``"auto"`` builds one sized by ``BAGUA_FLIGHT_RING`` unless
            ``BAGUA_FLIGHT_RECORDER=0``; pass ``None`` to disable or an
            instance to adopt.  Bitwise-inert either way.
        tracing: the distributed tracer
            (:class:`~bagua_tpu.observability.tracing.Tracer`) the hub
            drives: one sampled root span per step, one child per host
            phase, client spans on every RPC.  The default ``"auto"``
            builds one only under ``BAGUA_TRACING=1`` (sampled by
            ``BAGUA_TRACE_SAMPLE``, span JSONL at ``BAGUA_TRACE_PATH``);
            pass ``None`` to force off or an instance to adopt.  The hub
            installs its tracer as the process-wide ambient tracer and a
            retry observer so ``retry_call`` / the RPC transports see it.
        regression: the performance-regression sentinel
            (:class:`~bagua_tpu.observability.regression.RegressionSentinel`)
            the hub feeds: per-step budget attribution
            (``step_budget_<component>_ms`` gauges) plus CUSUM changepoint
            detection over the step-wall and goodput streams, emitting
            schema-validated ``perf_regression`` incidents on trip.  The
            default ``"auto"`` builds one only under
            ``BAGUA_REGRESSION_SENTINEL=1`` (knobs
            ``BAGUA_REGRESSION_WARMUP`` / ``_THRESHOLD`` / ``_COOLDOWN``),
            priced from the goodput meter's α–β wire model when one is
            attached; pass ``None`` to force off or an instance to adopt.
            Bitwise-inert either way (host-side arithmetic only).
    """

    def __init__(
        self,
        metrics_jsonl: Optional[str] = None,
        registry: Optional[MetricsRegistry] = None,
        watchdog: Optional[Watchdog] = None,
        retrace_window: int = 100,
        max_retraces_per_window: int = 2,
        goodput=None,
        flight="auto",
        tracing="auto",
        regression="auto",
    ):
        self.registry = registry or MetricsRegistry()
        self.goodput = goodput
        if goodput is not None:
            goodput.bind_registry(self.registry)
        self.jsonl = JsonlSink(metrics_jsonl) if metrics_jsonl else None
        self.recompile = RecompileDetector(
            window=retrace_window, max_retraces_per_window=max_retraces_per_window
        )
        self.step_timer = StepTimer()
        if flight == "auto":
            from bagua_tpu.env import (
                get_flight_recorder_enabled,
                get_flight_ring_size,
                get_rank,
                get_world_size,
            )

            flight = None
            if get_flight_recorder_enabled():
                from bagua_tpu.observability.flight_recorder import FlightRecorder

                flight = FlightRecorder(
                    capacity=get_flight_ring_size(),
                    rank=get_rank(),
                    world_size=get_world_size(),
                )
        self.flight = flight
        if tracing == "auto":
            from bagua_tpu.env import (
                get_rank,
                get_trace_path,
                get_trace_sample_every,
                get_tracing_enabled,
            )

            tracing = None
            if get_tracing_enabled():
                from bagua_tpu.observability.tracing import Tracer

                tracing = Tracer(
                    path=get_trace_path(),
                    sample_every=get_trace_sample_every(),
                    rank=get_rank(),
                )
        self.tracer = tracing
        if self.tracer is not None:
            from bagua_tpu.observability.tracing import set_global_tracer

            set_global_tracer(self.tracer)
        if regression == "auto":
            from bagua_tpu.env import (
                get_regression_cooldown,
                get_regression_sentinel_enabled,
                get_regression_threshold,
                get_regression_warmup,
            )

            regression = None
            if get_regression_sentinel_enabled():
                from bagua_tpu.observability.attribution import BudgetModel
                from bagua_tpu.observability.regression import RegressionSentinel

                budget = (BudgetModel.from_meter(goodput)
                          if goodput is not None else BudgetModel())
                regression = RegressionSentinel(
                    budget=budget,
                    warmup=get_regression_warmup(),
                    threshold=get_regression_threshold(),
                    cooldown=get_regression_cooldown(),
                )
        self.regression = regression
        if self.regression is not None:
            if self.regression.sink is None:
                self.regression.sink = self.jsonl
            if self.regression.registry is None:
                self.regression.registry = self.registry
        from bagua_tpu.resilience.retry import set_retry_observer

        set_retry_observer(self.on_rpc_retry)
        self.watchdog = watchdog
        if watchdog is not None:
            self.bind_watchdog(watchdog)
        # last known host position — what the watchdog dump reports
        self.current_phase: str = "init"
        self.current_step: int = -1
        self.current_variant: str = ""
        self._t_start = time.time()

    # -- host position (phases, watchdog) ------------------------------------

    def bind_watchdog(self, watchdog: Watchdog) -> None:
        """Point a watchdog's evidence hooks at this hub (idempotent; only
        unset hooks are claimed): timeout dumps carry :meth:`snapshot`, the
        flight recorder rides along, and the hub's :meth:`on_hang` emits the
        schema-validated ``hang`` event before any exit path runs."""
        self.watchdog = watchdog
        if watchdog.snapshot_provider is None:
            watchdog.snapshot_provider = self.snapshot
        if getattr(watchdog, "flight_recorder", None) is None:
            watchdog.flight_recorder = self.flight
        if getattr(watchdog, "hang_hook", None) is None:
            watchdog.hang_hook = self.on_hang

    def enter_phase(self, phase: str) -> None:
        """Mark the host's position in the step (``data`` → ``dispatch`` →
        ``wait`` → ...) and heartbeat the watchdog with the tag."""
        self.current_phase = phase
        if self.watchdog is not None:
            self.watchdog.beat(phase=phase)
        if self.goodput is not None:
            self.goodput.on_phase(phase)
        if self.tracer is not None:
            self.tracer.on_phase(phase)

    def snapshot(self) -> Dict:
        """The last known position + registry snapshot — embedded in the
        watchdog's timeout dump and exposed for debugging."""
        out = {
            "step": self.current_step,
            "phase": self.current_phase,
            "variant": self.current_variant,
            "uptime_s": round(time.time() - self._t_start, 1),
            "recompile": self.recompile.report(),
            "metrics": self.registry.snapshot(),
        }
        if self.tracer is not None:
            # Watchdog + flight dumps embed this snapshot; the active
            # trace/span ids let forensics join a wedged collective back to
            # the exact in-flight trace on the fleet timeline.
            out["trace"] = self.tracer.trace_context()
        if self.regression is not None:
            out["regression"] = self.regression.report()
        return out

    # -- engine feed ---------------------------------------------------------

    def on_step_start(self, step: int, variant: str = "") -> None:
        """The engine is about to run step ``step``: open the sampled root
        span so the phase children (and any RPC issued inside the step)
        hang off one ``train_step`` trace.  No-op without a tracer."""
        if self.tracer is not None:
            self.tracer.begin_step(int(step), variant=variant)

    def on_compile(self, variant: str, step: int) -> None:
        """The engine's jit cache missed: ``variant`` is being (re)built."""
        self.current_variant = variant
        retrace = self.recompile.record_compile(variant, on_alert=self._emit_alert)
        self.registry.counter(
            "compiles_total", help="step-function compiles (jit cache misses)"
        ).inc()
        if retrace:
            self.registry.counter(
                "retraces_total", help="compiles beyond the warmup compile"
            ).inc()
        if self.jsonl:
            self.jsonl.emit(
                {"event": "compile", "step": int(step), "variant": variant,
                 "retrace": bool(retrace)}
            )

    def on_compile_done(self, variant: str, step: int, wall_ms: float) -> None:
        """The compile announced by :meth:`on_compile` finished; ``wall_ms``
        is its measured wall time (the engine reads it off the first
        dispatch, which jit compiles synchronously).  Feeds the
        ``compile_ms`` histogram, the detector's per-variant wall ledger,
        and the goodput ledger's compile bucket."""
        self.recompile.record_compile_wall(variant, wall_ms)
        self.registry.histogram(
            "compile_ms", help="step-function compile wall time"
        ).observe(float(wall_ms))
        if self.goodput is not None:
            self.goodput.on_compile(float(wall_ms) / 1e3)
        if self.regression is not None:
            self.regression.note_compile(float(wall_ms))

    def on_step(
        self,
        step: int,
        wall_s: float,
        n_samples: int,
        wire_bytes: int,
        variant: str = "default",
        host_overhead: Optional[Dict] = None,
        wire_bytes_by_leg: Optional[Dict[str, int]] = None,
        wire_bytes_by_precision: Optional[Dict[str, int]] = None,
        wire_bytes_by_axis: Optional[Dict[str, int]] = None,
    ) -> None:
        """One dispatched training step's host-side evidence.

        ``wire_bytes_by_leg`` breaks ``wire_bytes`` down by wire pattern leg
        (sharded exchanges report ``{"rs": ..., "ag": ...}``); each leg gets
        its own ``wire_bytes_<leg>_total`` counter and the dict rides the
        ``step`` JSONL event (the schema validator allows extra fields on
        known event types).  ``wire_bytes_by_precision`` breaks the same
        traffic down by wire precision (``f32``/``int8``/``int4`` — the
        quantized-ring exchange's modelled bytes); each precision gets a
        ``wire_bytes_precision_<p>_total`` counter — the flat-name analog of
        a ``wire_bytes{precision=...}`` labeled family.
        ``wire_bytes_by_axis`` breaks the traffic down by the named mesh
        axis it rides (``{"dp": ..., "fsdp": ...}`` — the engine joins the
        variant's flight program records' ``axes`` against the plan);
        per-axis ``wire_bytes_axis_<ax>_total`` counters, the regression
        sentinel's per-axis byte census, and the ``step_budget_wire_<ax>_ms``
        per-axis budget gauges hang off it."""
        self.current_step = int(step)
        self.current_variant = variant
        self.recompile.record_step()
        self.step_timer.tick(wall_s, n_samples)
        if self.goodput is not None:
            self.goodput.on_step(wall_s, n_samples)
        r = self.registry
        r.counter("steps_total", help="training steps dispatched").inc()
        r.counter("samples_total", help="samples processed").inc(max(0, int(n_samples)))
        r.counter(
            "wire_bytes_total",
            help="bytes communicated per rank (bucket-plan census)",
        ).inc(max(0, int(wire_bytes)))
        if wire_bytes_by_leg:
            for leg, nbytes in sorted(wire_bytes_by_leg.items()):
                r.counter(
                    f"wire_bytes_{leg}_total",
                    help=f"bytes communicated per rank on the {leg} leg",
                ).inc(max(0, int(nbytes)))
        if wire_bytes_by_precision:
            for prec, nbytes in sorted(wire_bytes_by_precision.items()):
                r.counter(
                    f"wire_bytes_precision_{prec}_total",
                    help=f"bytes communicated per rank at wire precision {prec}",
                ).inc(max(0, int(nbytes)))
        if wire_bytes_by_axis:
            for ax, nbytes in sorted(wire_bytes_by_axis.items()):
                r.counter(
                    f"wire_bytes_axis_{ax}_total",
                    help=f"bytes communicated per rank on mesh axis {ax}",
                ).inc(max(0, int(nbytes)))
        r.histogram("step_wall_ms", help="host-observed step wall time").observe(
            wall_s * 1e3
        )
        sps = (n_samples / wall_s) if wall_s > 0 else 0.0
        r.gauge("samples_per_s", help="instantaneous throughput").set(round(sps, 3))
        if self.tracer is not None:
            # Stamp the step's vitals on the open root but do NOT close it:
            # the trace stays open across the inter-step gap so the data
            # phase and any RPC the fit loop issues between steps (snapshot
            # agreement, autotune report) join the trace that just ran.
            # The next on_step_start (or teardown) closes it.
            self.tracer.note_step(
                wall_ms=round(wall_s * 1e3, 3), wire_bytes=int(wire_bytes)
            )
        if self.regression is not None:
            host_ms = (sum(host_overhead.values()) * 1e3
                       if host_overhead else None)
            goodput_frac = (self.goodput.ledger.goodput_frac()
                            if self.goodput is not None else None)
            budget = self.regression.observe_step(
                int(step), wall_s * 1e3, host_ms=host_ms,
                wire_bytes=int(wire_bytes),
                wire_bytes_by_axis=wire_bytes_by_axis,
                goodput_frac=goodput_frac,
                trace_id=self._trace_fields().get("trace_id", ""),
            )
            # flat-name analog of a bagua_step_budget_ms{component=...}
            # labeled family, same convention as wire_bytes_precision_<p>
            for comp, ms in budget.components.items():
                r.gauge(
                    f"step_budget_{comp}_ms",
                    help=f"step-budget residual attributed to {comp}",
                ).set(round(ms, 4))
            # the wire_slowdown component's per-axis split — the flat-name
            # analog of step_budget_wire_ms{axis=...}; the sub-components
            # sum to step_budget_wire_slowdown_ms exactly
            for ax, ms in sorted(budget.wire_axis_ms.items()):
                r.gauge(
                    f"step_budget_wire_{ax}_ms",
                    help=f"wire_slowdown budget attributed to mesh axis {ax}",
                ).set(round(ms, 4))
            r.gauge(
                "step_budget_expected_ms",
                help="budget-model expected step wall",
            ).set(round(budget.expected_ms, 4))
            r.gauge(
                "step_budget_residual_ms",
                help="measured minus expected step wall",
            ).set(round(budget.residual_ms, 4))
        if self.jsonl:
            event = {
                "event": "step", "step": int(step),
                "wall_ms": round(wall_s * 1e3, 3),
                "samples_per_s": round(sps, 3),
                "wire_bytes": int(wire_bytes),
                "variant": variant,
            }
            if host_overhead:
                event["host_overhead_ms"] = {
                    k: round(v * 1e3, 4) for k, v in host_overhead.items()
                }
            if wire_bytes_by_leg:
                event["wire_bytes_by_leg"] = {
                    k: int(v) for k, v in sorted(wire_bytes_by_leg.items())
                }
            if wire_bytes_by_precision:
                event["wire_bytes_by_precision"] = {
                    k: int(v) for k, v in sorted(wire_bytes_by_precision.items())
                }
            if wire_bytes_by_axis:
                event["wire_bytes_by_axis"] = {
                    k: int(v) for k, v in sorted(wire_bytes_by_axis.items())
                }
            self.jsonl.emit(event)

    def on_rebucket(
        self,
        plan_version: int,
        n_buckets: int,
        step: int = 0,
        predicted_exposed_ms: Optional[float] = None,
        measured_exposed_ms: Optional[float] = None,
        reason: str = "planner",
        algorithm: Optional[str] = None,
    ) -> None:
        """The engine adopted a new bucket plan (autotune re-bucket, or an
        algorithm switch — ``algorithm`` names the newly adopted relaxation
        in that case).

        Exported as the ``plan_version`` gauge + ``rebucket_total`` counter
        (plus a per-reason-family counter — the unified switch vocabulary) so
        a Prometheus scrape shows when and why the plan changed, and as a
        ``rebucket`` JSONL event carrying the planner's predicted
        exposed-communication time for the new plan next to the measured
        value (when a device-trace analysis supplied one) — the
        predicted-vs-measured drift record."""
        from bagua_tpu.observability.metrics import switch_reason_family

        r = self.registry
        r.counter("rebucket_total", help="bucket-plan swaps adopted by the engine").inc()
        r.counter(
            f"rebucket_reason_{switch_reason_family(reason)}_total",
            help="bucket-plan swaps by requesting reason family",
        ).inc()
        r.gauge("plan_version", help="monotonic bucket-plan version").set(plan_version)
        if self.regression is not None:
            self.regression.plan_version = int(plan_version)
        if predicted_exposed_ms is not None:
            r.gauge(
                "predicted_exposed_comm_ms",
                help="planner-predicted exposed communication for the live plan",
            ).set(round(float(predicted_exposed_ms), 4))
        if measured_exposed_ms is not None:
            r.gauge(
                "measured_exposed_comm_ms",
                help="trace-measured exposed communication for the live plan",
            ).set(round(float(measured_exposed_ms), 4))
        if self.tracer is not None:
            self.tracer.record_event(
                "rebucket",
                attrs={"plan_version": int(plan_version),
                       "n_buckets": int(n_buckets), "reason": str(reason)},
            )
        if self.jsonl:
            event = {
                "event": "rebucket", "step": int(step),
                "plan_version": int(plan_version), "n_buckets": int(n_buckets),
                "reason": str(reason),
            }
            if algorithm is not None:
                event["algorithm"] = str(algorithm)
            if predicted_exposed_ms is not None:
                event["predicted_exposed_ms"] = round(float(predicted_exposed_ms), 4)
            if measured_exposed_ms is not None:
                event["measured_exposed_ms"] = round(float(measured_exposed_ms), 4)
            self.jsonl.emit(event)

    def on_precision_switch(
        self,
        step: int,
        plan_version: int,
        old_precisions,
        new_precisions,
        reason: str = "planner",
    ) -> None:
        """The engine adopted a new per-bucket wire-precision plan
        (``DistributedDataParallel.apply_precision_plan`` — planner-driven
        under ``wire_precision="auto"`` or an operator override).  Exported
        as the ``precision_switch_total`` counter plus per-precision bucket
        counts, and as a schema-validated ``precision_switch`` JSONL event
        carrying the full before/after per-bucket precision lists."""
        from bagua_tpu.observability.metrics import switch_reason_family

        r = self.registry
        r.counter(
            "precision_switch_total",
            help="per-bucket wire-precision plan swaps adopted by the engine",
        ).inc()
        r.counter(
            f"precision_switch_reason_{switch_reason_family(reason)}_total",
            help="wire-precision plan swaps by requesting reason family",
        ).inc()
        if self.regression is not None:
            self.regression.plan_version = int(plan_version)
        new_precisions = [str(p) for p in new_precisions]
        for prec in sorted(set(new_precisions)):
            r.gauge(
                f"buckets_at_precision_{prec}",
                help=f"buckets exchanging at wire precision {prec}",
            ).set(new_precisions.count(prec))
        if self.tracer is not None:
            self.tracer.record_event(
                "precision_switch",
                attrs={"plan_version": int(plan_version), "reason": str(reason)},
            )
        if self.jsonl:
            self.jsonl.emit(
                {"event": "precision_switch", "step": int(step),
                 "plan_version": int(plan_version),
                 "old_precisions": [str(p) for p in old_precisions],
                 "new_precisions": new_precisions,
                 "reason": str(reason)}
            )

    def on_staleness_switch(
        self,
        step: int,
        plan_version: int,
        old_tau: int,
        new_tau: int,
        reason: str = "planner",
    ) -> None:
        """The engine re-bounded the staleness knob
        (``DistributedDataParallel.apply_staleness``): the autopilot degraded
        a straggling gang to bounded-staleness exchange, the HealthMonitor
        guardrail tightened τ back to 0 on a convergence alert, or a
        stabilization window re-promoted it.  Exported as the
        ``staleness_switch_total`` counter, a per-reason-family counter, the
        live ``staleness_tau`` gauge, and a schema-validated
        ``staleness_switch`` JSONL event."""
        from bagua_tpu.observability.metrics import switch_reason_family

        r = self.registry
        r.counter(
            "staleness_switch_total",
            help="bounded-staleness bound (tau) swaps adopted by the engine",
        ).inc()
        r.counter(
            f"staleness_switch_reason_{switch_reason_family(reason)}_total",
            help="staleness bound swaps by requesting reason family",
        ).inc()
        r.gauge(
            "staleness_tau",
            help="current bounded-staleness bound (0 = bulk synchronous)",
        ).set(int(new_tau))
        if self.regression is not None:
            self.regression.plan_version = int(plan_version)
        if self.tracer is not None:
            self.tracer.record_event(
                "staleness_switch",
                attrs={"plan_version": int(plan_version), "reason": str(reason)},
            )
        if self.jsonl:
            self.jsonl.emit(
                {"event": "staleness_switch", "step": int(step),
                 "plan_version": int(plan_version),
                 "old_tau": int(old_tau), "new_tau": int(new_tau),
                 "reason": str(reason)}
            )

    def on_plan_decision(
        self,
        step: int,
        decision: str,
        reason: str,
        trace_id: str,
        plan_version: int,
        from_config: dict,
        to_config: dict,
        verdict: str,
        modeled: Optional[dict] = None,
        axis: Optional[str] = None,
    ) -> None:
        """The gang autopilot made one policy decision
        (:class:`~bagua_tpu.autopilot.GangAutopilot`): demote / re-promote /
        switch / roll back / hold.  ``trace_id`` cites the triggering
        ``perf_regression`` incident (empty when the trigger was a health
        alert or a stabilization window); ``reason`` speaks the unified
        switch vocabulary; ``modeled`` optionally carries the α–β priced
        ``{"stay_ms", "chosen_ms"}`` comparison the decision rests on;
        ``axis`` names the mesh axis the incident indicted (the candidates
        were priced with only that axis's legs degraded).
        Exported as ``plan_decisions_total`` plus a per-verdict counter and
        a schema-validated ``plan_decision`` JSONL event the timeline tools
        join to incidents and switch events by ``trace_id``/``plan_version``."""
        r = self.registry
        r.counter("plan_decisions_total", help="autopilot policy decisions").inc()
        r.counter(
            f"plan_decisions_{verdict}_total",
            help=f"autopilot decisions with verdict {verdict}",
        ).inc()
        if self.tracer is not None:
            self.tracer.record_event(
                "plan_decision",
                attrs={"decision": str(decision), "verdict": str(verdict),
                       "plan_version": int(plan_version)},
            )
        if self.jsonl:
            event = {
                "event": "plan_decision", "step": int(step),
                "decision": str(decision), "reason": str(reason),
                "trace_id": str(trace_id), "plan_version": int(plan_version),
                "from_config": dict(from_config), "to_config": dict(to_config),
                "verdict": str(verdict),
            }
            if modeled is not None:
                event["modeled"] = {
                    k: round(float(v), 4) for k, v in modeled.items()
                }
            if axis:
                event["axis"] = str(axis)
            self.jsonl.emit(event)

    def on_snapshot(
        self, step: int, wall_ms: float, n_bytes: int, kind: str = "async"
    ) -> None:
        """The resilience subsystem wrote one state snapshot (``kind``
        ``"async"`` = cadenced background write off the critical path,
        ``"final"`` = forced synchronous write on the preemption drain).
        ``wall_ms`` is the *writer thread's* wall time — the hot path only
        paid the device-side buffer copy dispatch."""
        r = self.registry
        r.counter("snapshots_total", help="state snapshots written").inc()
        r.histogram(
            "snapshot_wall_ms",
            help="background snapshot write time (off the critical path)",
        ).observe(float(wall_ms))
        r.gauge("snapshot_last_step", help="step of the newest snapshot").set(step)
        if self.goodput is not None:
            self.goodput.on_snapshot(kind, float(wall_ms))
        if self.regression is not None and kind != "async":
            # only blocking writes stall the step loop; cadenced async
            # snapshots ride the background writer and cost the step nothing
            self.regression.note_snapshot(float(wall_ms))
        if self.tracer is not None:
            self.tracer.record_event(
                "snapshot",
                attrs={"kind": str(kind), "bytes": int(n_bytes)},
                wall_ms=float(wall_ms),
            )
        if self.jsonl:
            self.jsonl.emit(
                {"event": "snapshot", "step": int(step),
                 "wall_ms": round(float(wall_ms), 3),
                 "bytes": int(n_bytes), "kind": kind}
            )

    def on_restart(
        self,
        step: int,
        old_world_size: int,
        new_world_size: int,
        plan_source: str = "fresh",
        lost_steps: int = 0,
    ) -> None:
        """The gang resumed from a snapshot (elastic restart).  ``step`` is
        the resumed-from step; ``lost_steps`` counts steps the previous
        incarnation ran past it (0 when the preemption drain landed its
        final snapshot); ``plan_source`` records whether the tuned bucket
        plan was carried over (``"carried"``) or rebuilt (``"fresh"``)."""
        r = self.registry
        r.counter("restarts_total", help="elastic resumes from a snapshot").inc()
        r.counter(
            "lost_steps_total",
            help="training steps lost across restarts (bounded by the snapshot cadence)",
        ).inc(max(0, int(lost_steps)))
        r.gauge("resumed_world_size", help="gang size after the latest resume").set(
            new_world_size
        )
        if self.goodput is not None:
            self.goodput.on_restart(lost_steps)
        if self.jsonl:
            self.jsonl.emit(
                {"event": "restart", "step": int(step),
                 "old_world_size": int(old_world_size),
                 "new_world_size": int(new_world_size),
                 "plan_source": plan_source, "lost_steps": int(lost_steps)}
            )

    def on_health_alert(
        self,
        step: int,
        kind: str,
        value: float,
        threshold: float,
        detail: str = "",
        actions=(),
    ) -> None:
        """The health monitor detected an anomaly (``kind`` one of
        ``loss_spike``/``grad_norm_explosion``/``nonfinite``); ``actions``
        lists the registered corrective actions that reported applying.
        Exported as a per-kind counter and a schema-validated
        ``health_alert`` JSONL event."""
        self.registry.counter(
            f"health_alerts_{kind}_total",
            help=f"health anomalies of kind {kind}",
        ).inc()
        if self.jsonl:
            event = {
                "event": "health_alert", "step": int(step), "kind": str(kind),
                "value": float(value), "threshold": float(threshold),
                "detail": str(detail), "actions": [str(a) for a in actions],
            }
            event.update(self._trace_fields())
            self.jsonl.emit(event)

    def bind_breaker(self, breaker) -> None:
        """Point a :class:`~bagua_tpu.resilience.retry.CircuitBreaker`'s
        transition hook at this hub (idempotent; an already-set listener is
        left alone): every evented state change — closed→open,
        open→half-open, half-open→closed/open — lands as a
        ``breaker_transition`` JSONL event plus ``breaker_state`` gauges."""
        if getattr(breaker, "listener", None) is None:
            breaker.listener = self.on_breaker_transition

    #: breaker state → gauge code (closed=0 half-open=1 open=2): a scrape
    #: alerting on ``breaker_state > 0`` catches both degraded states.
    BREAKER_STATE_CODES = {"closed": 0, "half-open": 1, "open": 2}

    def on_breaker_transition(
        self, name: str, old_state: str, new_state: str
    ) -> None:
        """One circuit-breaker state change (see
        :class:`~bagua_tpu.resilience.retry.CircuitBreaker`): exported as
        the shared ``breaker_state`` gauge, a per-breaker
        ``breaker_state_<name>`` gauge, a ``breaker_transitions_total``
        counter, and the schema-validated ``breaker_transition`` event."""
        code = self.BREAKER_STATE_CODES.get(new_state, -1)
        r = self.registry
        r.gauge(
            "breaker_state",
            help="newest breaker transition (0 closed / 1 half-open / 2 open)",
        ).set(code)
        safe = "".join(c if c.isalnum() else "_" for c in str(name))
        r.gauge(
            f"breaker_state_{safe}",
            help=f"breaker {name} state (0 closed / 1 half-open / 2 open)",
        ).set(code)
        r.counter(
            "breaker_transitions_total", help="circuit-breaker state changes"
        ).inc()
        if self.tracer is not None:
            sp = self.tracer.current_span()
            if sp is not None:
                sp.annotate(
                    "breaker_transition",
                    breaker=str(name), old=str(old_state), new=str(new_state),
                )
        if self.jsonl:
            self.jsonl.emit(
                {"event": "breaker_transition", "step": int(self.current_step),
                 "breaker": str(name), "old_state": str(old_state),
                 "new_state": str(new_state)}
            )

    def on_hang(self, reason: str, ctx: Optional[dict] = None,
                dump_paths: Optional[dict] = None) -> None:
        """The watchdog (or a preemption drain) declared this rank hung:
        bump ``hangs_total`` and emit the schema-validated ``hang`` JSONL
        event, then flush — the process may be about to ``os._exit``, and
        the event must already be on disk when the restart loop's collector
        arrives.  Bound to ``Watchdog.hang_hook`` so it runs *before*
        ``on_timeout``."""
        ctx = ctx or {}
        self.registry.counter(
            "hangs_total", help="watchdog timeouts / hang declarations"
        ).inc()
        if self.jsonl:
            event = {
                "event": "hang", "step": int(self.current_step),
                "reason": str(reason),
                "last_phase": str(ctx.get("last_phase") or self.current_phase),
            }
            if dump_paths:
                event["dumps"] = {k: str(v) for k, v in sorted(dump_paths.items())}
            if self.flight is not None:
                event["flight_last_seq"] = int(self.flight.last_seq)
            event.update(self._trace_fields())
            self.jsonl.emit(event)
            self.flush()

    def _trace_fields(self) -> Dict:
        """``{"trace_id", "span_id"}`` extras for events that should join
        the timeline (hang, health_alert, rpc_retry); empty when no trace
        is active."""
        if self.tracer is None:
            return {}
        return self.tracer.trace_context()

    def on_rpc_retry(
        self,
        endpoint: str,
        attempt: int,
        delay_s: float,
        reason: str = "error",
        retry_after_s: Optional[float] = None,
    ) -> None:
        """One ``retry_call`` backoff sleep (installed as the process-wide
        retry observer): the otherwise-invisible dead time lands as the
        ``rpc_retry_total`` / ``rpc_backoff_s_total`` counters and a
        schema-validated ``rpc_retry`` event.  Emit failures are swallowed —
        a closed sink (hub torn down mid-retry) must never break a live
        RPC retry loop."""
        r = self.registry
        r.counter("rpc_retry_total", help="retry_call backoff sleeps").inc()
        r.counter(
            "rpc_backoff_s_total",
            help="cumulative seconds slept in RPC retry backoff",
        ).inc(max(0.0, float(delay_s)))
        if reason == "backpressure":
            r.counter(
                "rpc_backpressure_total",
                help="retries paced by a server Retry-After hint (429s)",
            ).inc()
        if self.regression is not None:
            self.regression.note_backpressure(float(delay_s))
        if self.jsonl:
            event = {
                "event": "rpc_retry", "step": int(self.current_step),
                "endpoint": str(endpoint), "attempt": int(attempt),
                "delay_s": round(float(delay_s), 4), "reason": str(reason),
            }
            if retry_after_s is not None:
                event["retry_after_s"] = round(float(retry_after_s), 3)
            event.update(self._trace_fields())
            try:
                self.jsonl.emit(event)
            except ValueError:
                pass  # sink closed under us; the counters still landed

    def _emit_alert(self, msg: str, retraces_in_window: int) -> None:
        self.registry.counter(
            "retrace_alerts_total", help="recompile-rate alarms raised"
        ).inc()
        if self.jsonl:
            self.jsonl.emit(
                {"event": "retrace_alert", "step": int(self.current_step),
                 "retraces": int(retraces_in_window),
                 "window": self.recompile.window, "message": msg}
            )

    # -- export / teardown ---------------------------------------------------

    def export_prometheus(self, path: str) -> None:
        """Write the registry as a Prometheus textfile (atomic)."""
        self.registry.write_prometheus(path)

    def flush(self) -> None:
        """Durably flush the JSONL stream without closing it — the trainer's
        exception-safe teardown calls this so a crash mid-``fit`` never loses
        buffered events, while the hub stays usable for a post-mortem."""
        if self.jsonl:
            self.jsonl.flush()

    def close(self) -> None:
        from bagua_tpu.resilience.retry import get_retry_observer, set_retry_observer

        if get_retry_observer() == self.on_rpc_retry:
            set_retry_observer(None)
        if self.tracer is not None:
            from bagua_tpu.observability.tracing import (
                get_global_tracer, set_global_tracer,
            )

            if get_global_tracer() is self.tracer:
                set_global_tracer(None)
            self.tracer.close()
        if self.jsonl:
            self.jsonl.close()

    def __enter__(self) -> "Telemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
