"""Distributed tracing: causal spans from the train step to the control plane.

The observability stack can *name* every symptom — goodput buckets, flight
records, fleet scheduler verdicts — but nothing links them causally: when a
gang's step stalls on a rendezvous RPC the fleet server 429'd, that chain is
spread across three uncorrelated JSONL streams.  This module closes the gap
with a dependency-free span model:

* :class:`Span` — trace_id / span_id / parent_id, a name, a kind
  (``internal`` / ``client`` / ``server``), wall-clock start + duration,
  flat attributes and timestamped annotations.  Serialized as one JSON
  object (``bagua.span.v1``).
* **W3C context propagation** — :func:`format_traceparent` /
  :func:`parse_traceparent` implement the ``traceparent`` header
  (``00-<trace_id>-<span_id>-<flags>``), so the RPC clients inject the
  active span's context and the fleet server's per-request span becomes a
  *child* of the in-flight client span: one trace_id follows a training
  step from ``Trainer`` through the control plane and back.
* :class:`Tracer` — hung off the :class:`~bagua_tpu.observability.telemetry.Telemetry`
  hub (``BAGUA_TRACING=1``), step-sampled (``BAGUA_TRACE_SAMPLE``), with a
  thread-local context stack, a bounded in-memory ring of finished spans,
  and an optional span-JSONL sink ``ci/export_timeline.py`` renders to
  Chrome trace-event JSON (Perfetto).

Everything here is host-side, stdlib-only and bitwise-inert by
construction: spans wrap the host's phase bookkeeping (``enter_phase`` /
``on_step``) and the RPC transports — never the traced computation.  The
CI tracing lane proves on-vs-off training state identical, like the flight
recorder.
"""

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "SPAN_SCHEMA",
    "Span",
    "Tracer",
    "client_span",
    "format_traceparent",
    "get_global_tracer",
    "new_span_id",
    "new_trace_id",
    "parse_traceparent",
    "set_global_tracer",
    "validate_span",
]

#: schema tag every serialized span carries
SPAN_SCHEMA = "bagua.span.v1"

_HEX = set("0123456789abcdef")


def new_trace_id() -> str:
    """A fresh 16-byte (32 hex char) W3C trace id."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """A fresh 8-byte (16 hex char) W3C span id."""
    return os.urandom(8).hex()


def format_traceparent(trace_id: str, span_id: str, sampled: bool = True) -> str:
    """The W3C ``traceparent`` header value:
    ``00-<trace_id>-<span_id>-<flags>`` (version 00, flags 01 = sampled)."""
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def parse_traceparent(header: Optional[str]) -> Optional[Dict]:
    """Parse a ``traceparent`` header; None on anything malformed (wrong
    field count, non-hex, all-zero ids, version ``ff``) — a bad header must
    degrade to "no context", never crash a request handler."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if len(version) != 2 or version == "ff" or not set(version) <= _HEX:
        return None
    if len(trace_id) != 32 or not set(trace_id) <= _HEX or set(trace_id) == {"0"}:
        return None
    if len(span_id) != 16 or not set(span_id) <= _HEX or set(span_id) == {"0"}:
        return None
    if len(flags) != 2 or not set(flags) <= _HEX:
        return None
    return {
        "trace_id": trace_id,
        "span_id": span_id,
        "sampled": bool(int(flags, 16) & 0x01),
    }


class Span:
    """One unit of causally attributed work.

    Mutable while open (``annotate`` / ``set``); :meth:`Tracer.finish` (or
    the ``tracer.span(...)`` context manager) stamps the duration and
    freezes it into the tracer's ring + sink as a plain dict."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "kind",
        "ts", "_mono", "dur_ms", "attrs", "annotations",
    )

    def __init__(
        self,
        name: str,
        kind: str = "internal",
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        attrs: Optional[Dict] = None,
        clock: float = None,
    ):
        self.trace_id = trace_id or new_trace_id()
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.name = str(name)
        self.kind = str(kind)
        self.ts = time.time() if clock is None else float(clock)
        self._mono = time.monotonic()
        self.dur_ms: Optional[float] = None
        self.attrs: Dict = dict(attrs or {})
        self.annotations: List[Dict] = []

    def set(self, key: str, value) -> "Span":
        self.attrs[str(key)] = value
        return self

    def annotate(self, name: str, **attrs) -> "Span":
        """A timestamped point event inside the span (a retry backoff, a
        Retry-After hint, a breaker transition)."""
        self.annotations.append(
            {"name": str(name), "ts": round(time.time(), 6), **attrs}
        )
        return self

    @property
    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id)

    def to_dict(self) -> Dict:
        out = {
            "schema": SPAN_SCHEMA,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "name": self.name,
            "kind": self.kind,
            "ts": round(self.ts, 6),
            "dur_ms": round(self.dur_ms, 4) if self.dur_ms is not None else None,
        }
        if self.parent_id:
            out["parent_id"] = self.parent_id
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        if self.annotations:
            out["annotations"] = list(self.annotations)
        return out


def validate_span(span: Dict) -> List[str]:
    """Schema-check one serialized span dict; returns problems (empty =
    valid).  The fleet's ``/g/<gang>/spans`` ingest and the Perfetto
    exporter both hold incoming spans to this."""
    problems = []
    if not isinstance(span, dict):
        return [f"span is {type(span).__name__}, not an object"]
    tid = span.get("trace_id")
    if not (isinstance(tid, str) and len(tid) == 32 and set(tid) <= _HEX):
        problems.append(f"bad trace_id {tid!r}")
    sid = span.get("span_id")
    if not (isinstance(sid, str) and len(sid) == 16 and set(sid) <= _HEX):
        problems.append(f"bad span_id {sid!r}")
    pid = span.get("parent_id")
    if pid is not None and not (
        isinstance(pid, str) and len(pid) == 16 and set(pid) <= _HEX
    ):
        problems.append(f"bad parent_id {pid!r}")
    if not isinstance(span.get("name"), str) or not span.get("name"):
        problems.append("missing name")
    if span.get("kind") not in ("internal", "client", "server"):
        problems.append(f"bad kind {span.get('kind')!r}")
    if not isinstance(span.get("ts"), (int, float)):
        problems.append("missing ts")
    dur = span.get("dur_ms")
    if dur is not None and not isinstance(dur, (int, float)):
        problems.append(f"bad dur_ms {dur!r}")
    return problems


class Tracer:
    """Per-process span factory + collector.

    Thread-local context stack: :meth:`span` opens a child of the calling
    thread's current span (or a fresh root), so an RPC issued from the fit
    loop inherits the step trace while a background writer thread starts
    its own.  Finished spans land in a bounded ring (``capacity``) and,
    when ``path`` is given, one-JSON-object-per-line in the span file.

    The step machinery (:meth:`begin_step` / :meth:`on_phase` /
    :meth:`end_step`) is what the Telemetry hub drives: one sampled root
    span per training step with one child span per host phase
    (``dispatch`` → ``wait`` → ``data``), so every RPC the step issues
    hangs off the phase it blocked.
    """

    def __init__(
        self,
        path: Optional[str] = None,
        sample_every: int = 1,
        service: str = "trainer",
        rank: int = 0,
        capacity: int = 4096,
    ):
        self.path = path
        self.sample_every = max(1, int(sample_every))
        self.service = str(service)
        self.rank = int(rank)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(16, int(capacity)))
        self._tls = threading.local()
        self._step_span: Optional[Span] = None
        self._phase_span: Optional[Span] = None
        self.n_spans = 0
        self.n_dropped_unsampled = 0
        self._f = None
        if path:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            self._f = open(path, "a")

    # -- context -------------------------------------------------------------

    def _stack(self) -> List[Span]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current_span(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def trace_context(self) -> Dict[str, str]:
        """``{"trace_id", "span_id"}`` of the active span (empty when no
        trace is open) — what ``hang`` / ``health_alert`` events and flight
        dumps embed so forensics can join back to the timeline."""
        sp = self.current_span()
        if sp is None:
            return {}
        return {"trace_id": sp.trace_id, "span_id": sp.span_id}

    def traceparent(self) -> Optional[str]:
        sp = self.current_span()
        return sp.traceparent if sp is not None else None

    # -- span lifecycle ------------------------------------------------------

    def start_span(
        self,
        name: str,
        kind: str = "internal",
        parent: Optional[Span] = None,
        attrs: Optional[Dict] = None,
    ) -> Span:
        if parent is None:
            parent = self.current_span()
        return Span(
            name,
            kind=kind,
            trace_id=parent.trace_id if parent is not None else None,
            parent_id=parent.span_id if parent is not None else None,
            attrs=attrs,
        )

    def finish(self, span: Span) -> Dict:
        if span.dur_ms is None:
            span.dur_ms = (time.monotonic() - span._mono) * 1e3
        span.attrs.setdefault("service", self.service)
        span.attrs.setdefault("rank", self.rank)
        out = span.to_dict()
        with self._lock:
            self._ring.append(out)
            self.n_spans += 1
            if self._f is not None:
                self._f.write(json.dumps(out, sort_keys=True) + "\n")
                self._f.flush()
        return out

    class _SpanCtx:
        def __init__(self, tracer: "Tracer", span: Span):
            self.tracer, self.span = tracer, span

        def __enter__(self) -> Span:
            self.tracer._stack().append(self.span)
            return self.span

        def __exit__(self, exc_type, exc, tb) -> bool:
            stack = self.tracer._stack()
            if stack and stack[-1] is self.span:
                stack.pop()
            if exc is not None:
                # A 429 carries the server's pacing hint; any other failure
                # is just tagged — the span must record the outcome without
                # swallowing it.
                hint = getattr(exc, "retry_after_s", None)
                if hint is not None or getattr(exc, "code", None) == 429:
                    self.span.set("status", 429)
                    self.span.annotate(
                        "backpressure",
                        retry_after_s=round(float(hint or 0.0), 3),
                    )
                else:
                    self.span.set("error", type(exc).__name__)
            self.tracer.finish(self.span)
            return False

    def span(
        self,
        name: str,
        kind: str = "internal",
        parent: Optional[Span] = None,
        attrs: Optional[Dict] = None,
    ) -> "_SpanCtx":
        """``with tracer.span("rpc /rdzv/heartbeat", kind="client") as sp:``
        — opens a child of the calling thread's current span, pushes it as
        the new context, records it (with error / backpressure attribution)
        on exit."""
        return Tracer._SpanCtx(self, self.start_span(name, kind, parent, attrs))

    def record_event(
        self, name: str, attrs: Optional[Dict] = None, wall_ms: float = 0.0
    ) -> Dict:
        """A point-in-time span (snapshot write, rebucket, precision
        switch): child of the current context, duration stamped from the
        reported wall time rather than measured."""
        sp = self.start_span(name, kind="internal", attrs=attrs)
        sp.dur_ms = max(0.0, float(wall_ms))
        sp.ts -= sp.dur_ms / 1e3  # the work *ended* now; start it earlier
        return self.finish(sp)

    # -- the step machinery (driven by the Telemetry hub) --------------------

    def step_sampled(self, step: int) -> bool:
        return int(step) % self.sample_every == 0

    def begin_step(self, step: int, variant: str = "") -> Optional[Span]:
        """Open the sampled step's root span (closing any still-open
        previous step first — the ``data`` phase between steps belongs to
        the trace that just ran)."""
        if self._step_span is not None:
            self.end_step()
        if not self.step_sampled(step):
            self.n_dropped_unsampled += 1
            return None
        root = self.start_span(
            "train_step", kind="internal", parent=None,
            attrs={"step": int(step), **({"variant": variant} if variant else {})},
        )
        self._stack().append(root)
        self._step_span = root
        return root

    def on_phase(self, phase: str) -> None:
        """Host phase transition inside the sampled step: close the open
        phase child, open the next."""
        root = self._step_span
        if root is None:
            return
        self._close_phase()
        child = Span(
            f"phase:{phase}", kind="internal",
            trace_id=root.trace_id, parent_id=root.span_id,
        )
        self._stack().append(child)
        self._phase_span = child

    def _close_phase(self) -> None:
        child = self._phase_span
        if child is None:
            return
        stack = self._stack()
        if stack and stack[-1] is child:
            stack.pop()
        self.finish(child)
        self._phase_span = None

    def note_step(self, **attrs) -> None:
        """Stamp attributes on the open step root *without* closing it —
        the hub calls this when the dispatched step retires, leaving the
        trace open so the inter-step gap (data phase, snapshot/autotune
        RPCs) still hangs off the step that just ran."""
        root = self._step_span
        if root is None:
            return
        for k, v in attrs.items():
            root.set(k, v)

    def end_step(self, **attrs) -> None:
        """Close the step trace (phase child first, then the root)."""
        root = self._step_span
        if root is None:
            return
        self._close_phase()
        for k, v in attrs.items():
            root.set(k, v)
        stack = self._stack()
        if stack and stack[-1] is root:
            stack.pop()
        self.finish(root)
        self._step_span = None

    # -- export --------------------------------------------------------------

    def finished_spans(self, trace_id: Optional[str] = None) -> List[Dict]:
        with self._lock:
            spans = list(self._ring)
        if trace_id is not None:
            spans = [s for s in spans if s.get("trace_id") == trace_id]
        return spans

    def flush(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()

    def close(self) -> None:
        self.end_step()
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


# -- the ambient tracer (what retry_call and the RPC clients consult) ---------

_global_tracer: Optional[Tracer] = None


def set_global_tracer(tracer: Optional[Tracer]) -> None:
    """Install (or clear, with None) the process-wide ambient tracer.  The
    Telemetry hub does this when ``BAGUA_TRACING`` builds one; code that
    cannot be handed a tracer (``retry_call``, the RPC transports) reads it
    back with :func:`get_global_tracer` — None means tracing is off and
    every instrumentation site must be a no-op."""
    global _global_tracer
    _global_tracer = tracer


def get_global_tracer() -> Optional[Tracer]:
    return _global_tracer


class client_span:
    """RPC-transport instrumentation: a no-op context manager when tracing
    is off, else a ``client``-kind span whose W3C context the transport
    injects::

        with client_span(f"rpc {path}", component="rendezvous",
                         endpoint=path) as (sp, headers):
            # headers == {} or {"traceparent": "00-..."}
            req = urllib.request.Request(url, headers={**base, **headers})

    A 429 raised inside the block lands on the span as ``status: 429`` plus
    a ``backpressure`` annotation with the Retry-After hint (see
    :class:`Tracer._SpanCtx`) — the retry child span the CI lane asserts."""

    def __init__(self, name: str, component: str = "rpc", **attrs):
        self.name = name
        self.attrs = {"component": component, **attrs}
        self._ctx = None

    def __enter__(self):
        tracer = get_global_tracer()
        if tracer is None:
            return None, {}
        self._ctx = tracer.span(self.name, kind="client", attrs=self.attrs)
        sp = self._ctx.__enter__()
        return sp, {"traceparent": sp.traceparent}

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._ctx is not None:
            return self._ctx.__exit__(exc_type, exc, tb)
        return False
