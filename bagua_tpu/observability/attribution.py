"""Step-time budget attribution: price the expected step, name the residual.

The stack already *records* every ingredient of a slow step — the perflab
prices compute, the planner's fitted α–β :class:`~bagua_tpu.service.planner.CostModel`
prices the wire, the recompile detector measures compile walls, the
snapshotter reports blocking writes, the gang aggregator scores stragglers,
and ``retry_call`` counts backpressure sleeps — but they are disjoint
streams: a 20% step-wall regression produces five uncorrelated artifacts
and zero verdicts.  This module is the joiner: a per-step **budget model**
that prices the *expected* step wall and decomposes the measured-minus-
expected residual into named components:

``compile``
    measured compile wall charged to this step (jit cache miss — the
    engine reads it off the compiling dispatch).
``snapshot``
    blocking snapshot wall (``kind != "async"``: anomaly/final writes stall
    the step loop; cadenced async writes cost nothing here).
``host_data``
    host-side overhead (pre/lock-wait/post + data wait) above its
    calibrated baseline.
``wire_slowdown``
    measured wire time beyond the α–β prediction, or — when only the
    byte census moved — the priced cost of wire bytes beyond baseline.
``straggler``
    the gang aggregator's attributed excess (straggler p50 − gang median).
``backpressure``
    RPC retry backoff sleeps (429-paced and error retries).
``unattributed``
    whatever remains: ``residual − sum(named)``.  The components therefore
    **sum to the residual by construction** — the same partition guarantee
    the goodput ledger pins (±1% in tests), made exact here because the
    remainder is the definition, not a hope.

The model is host-side arithmetic over numbers the hub already holds —
attaching it never touches the traced program (bitwise-inert, the health-
monitor discipline).  :class:`~bagua_tpu.observability.regression.RegressionSentinel`
consumes the per-step :class:`StepBudget` stream and turns a sustained
regression into one ``perf_regression`` incident carrying the verdict.
"""

import dataclasses
import statistics
from typing import Dict, Optional

__all__ = [
    "BUDGET_COMPONENTS",
    "BudgetModel",
    "StepBudget",
    "priced_axis_wire_ms",
]

#: every attribution component, in report order; ``unattributed`` is always
#: last — it is the constructed remainder that makes the partition exact.
BUDGET_COMPONENTS = (
    "compile",
    "snapshot",
    "host_data",
    "wire_slowdown",
    "straggler",
    "backpressure",
    "unattributed",
)


def priced_axis_wire_ms(cost_model, program) -> Dict[str, float]:
    """Join one step's flight/IR program against the planner's per-axis α–β
    legs: every record carrying ``axes`` (stamped by ``annotate()`` and
    mirrored by ``predict_flight_program``) is priced on each axis's fitted
    leg — :meth:`~bagua_tpu.service.planner.CostModel.axis_leg` falls back
    to the ``flat`` leg on legacy 1-D meshes — with a joint multi-axis
    exchange's bytes split evenly across its axes.  Returns ``{axis: ms}``
    (empty when no record carries axes)."""
    out: Dict[str, float] = {}
    if cost_model is None:
        return out
    for rec in program or ():
        axes = [a for a in (rec.get("axes") or ()) if a]
        nbytes = float(rec.get("nbytes") or 0.0)
        if not axes or nbytes <= 0:
            continue
        share = nbytes / len(axes)
        for ax in axes:
            out[ax] = out.get(ax, 0.0) + cost_model.axis_leg(ax).predict(share) * 1e3
    return out


@dataclasses.dataclass
class StepBudget:
    """One settled step: measured vs expected wall and the named partition
    of the difference.  ``components`` carries every name in
    :data:`BUDGET_COMPONENTS` and sums to ``residual_ms`` exactly.
    ``wire_axis_ms`` splits ``components["wire_slowdown"]`` by mesh axis —
    the sub-partition sums to the component exactly (same construction:
    the component IS the sum) and is empty on axis-blind meshes."""

    step: int
    measured_ms: float
    expected_ms: float
    residual_ms: float
    components: Dict[str, float]
    dominant: str = ""
    calibrated: bool = False
    straggler_rank: int = -1
    wire_axis_ms: Dict[str, float] = dataclasses.field(default_factory=dict)

    def partition_error_ms(self) -> float:
        """|sum(components) − residual| — zero up to float rounding."""
        return abs(sum(self.components.values()) - self.residual_ms)

    def axis_partition_error_ms(self) -> float:
        """|sum(wire_axis_ms) − wire_slowdown| — exactly zero when the axis
        split exists (the component is constructed as the sum)."""
        if not self.wire_axis_ms:
            return 0.0
        return abs(sum(self.wire_axis_ms[ax] for ax in sorted(self.wire_axis_ms))
                   - self.components.get("wire_slowdown", 0.0))

    def payload(self) -> Dict:
        out = dataclasses.asdict(self)
        out["components"] = {k: round(v, 4) for k, v in self.components.items()}
        out["wire_axis_ms"] = {
            k: round(v, 4) for k, v in sorted(self.wire_axis_ms.items())
        }
        for key in ("measured_ms", "expected_ms", "residual_ms"):
            out[key] = round(out[key], 4)
        return out


class BudgetModel:
    """Prices the expected step and settles one :class:`StepBudget` per
    dispatched step.

    Two pricing modes, composable:

    * **priced** — ``compute_ms`` (perflab census / roofline) and a wire
      price (``wire_ms`` directly, or ``cost_model`` + ``bucket_bytes``
      through :func:`~bagua_tpu.observability.goodput.predicted_wire_time`)
      with ``overlap_frac`` naming how much of the wire the schedule hides:
      ``expected = compute + (1 − overlap_frac) × wire``.
    * **self-calibrated** — with no prices given, the expected wall is the
      median of the first ``calibrate_steps`` *clean* steps (no compile,
      snapshot, straggler or backpressure noted).  Until calibration
      settles, ``expected = measured`` so the residual is zero — the model
      cannot cry wolf while it is still learning the baseline.

    The engine/hub feed per-step evidence through the ``note_*`` hooks
    (cleared at every :meth:`settle`); nothing here reads the device or the
    traced program.
    """

    def __init__(
        self,
        compute_ms: Optional[float] = None,
        wire_ms: Optional[float] = None,
        overlap_frac: float = 0.0,
        cost_model=None,
        bucket_bytes=None,
        hierarchical: bool = False,
        wire_pattern: str = "allreduce",
        calibrate_steps: int = 20,
        axis_wire_ms: Optional[Dict[str, float]] = None,
        program=None,
    ):
        self.compute_ms = None if compute_ms is None else float(compute_ms)
        self.overlap_frac = min(1.0, max(0.0, float(overlap_frac)))
        self.cost_model = cost_model
        self.hierarchical = bool(hierarchical)
        self.wire_pattern = str(wire_pattern)
        # per-axis expected wire: given directly, or joined from the step's
        # flight/IR program (records carry ``axes``) against ``axis_legs``
        if axis_wire_ms is None and program is not None:
            axis_wire_ms = priced_axis_wire_ms(cost_model, program) or None
        self.axis_wire_ms: Dict[str, float] = {
            str(k): float(v) for k, v in (axis_wire_ms or {}).items()
        }
        if wire_ms is None and self.axis_wire_ms:
            # the axis-priced ledger IS the wire expectation: the scalar is
            # its sum, so the per-axis split partitions it by construction
            wire_ms = sum(self.axis_wire_ms[ax]
                          for ax in sorted(self.axis_wire_ms))
        if wire_ms is None and cost_model is not None and bucket_bytes:
            from bagua_tpu.observability.goodput import predicted_wire_time

            wire_ms = predicted_wire_time(
                cost_model, bucket_bytes, hierarchical=hierarchical,
                wire_pattern=wire_pattern) * 1e3
        self.wire_ms = None if wire_ms is None else float(wire_ms)
        self.calibrate_steps = max(1, int(calibrate_steps))
        # calibration samples from clean steps: wall, host ms, wire bytes
        self._wall_samples = []
        self._host_samples = []
        self._bytes_samples = []
        self._axis_bytes_samples: Dict[str, list] = {}
        # per-step evidence, cleared on settle
        self._compile_ms = 0.0
        self._snapshot_ms = 0.0
        self._backpressure_s = 0.0
        self._straggler_ms = 0.0
        self._straggler_rank = -1
        self._measured_wire_ms: Optional[float] = None
        self._measured_wire_axis_ms: Optional[Dict[str, float]] = None
        # ranks running under a bounded-staleness degradation directive:
        # their excess over the gang median is the *expected* behavior (the
        # gang paces at the median, not the straggler's max), so straggler
        # evidence naming them must not charge the budget
        self._degraded_ranks: set = set()

    @classmethod
    def from_meter(cls, meter, compute_ms: Optional[float] = None,
                   overlap_frac: float = 0.0, calibrate_steps: int = 20
                   ) -> "BudgetModel":
        """Price the wire from an attached
        :class:`~bagua_tpu.observability.goodput.GoodputMeter` (its fitted
        cost model + live bucket plan, routed through the per-axis legs
        when the plan rides a named mesh); compute stays self-calibrated
        unless supplied."""
        wire_s = meter.predicted_wire_s() if meter is not None else None
        by_axis = (meter.predicted_wire_by_axis_s()
                   if meter is not None
                   and hasattr(meter, "predicted_wire_by_axis_s") else None)
        return cls(
            compute_ms=compute_ms,
            wire_ms=None if wire_s is None else wire_s * 1e3,
            overlap_frac=overlap_frac,
            cost_model=getattr(meter, "cost_model", None),
            calibrate_steps=calibrate_steps,
            axis_wire_ms=(
                {ax: s * 1e3 for ax, s in by_axis.items()} if by_axis else None
            ),
        )

    # -- per-step evidence hooks (cleared at settle) --------------------------

    def note_compile(self, wall_ms: float) -> None:
        """A jit cache miss compiled inside this step's dispatch."""
        self._compile_ms += max(0.0, float(wall_ms))

    def note_snapshot(self, wall_ms: float) -> None:
        """A *blocking* snapshot write stalled this step."""
        self._snapshot_ms += max(0.0, float(wall_ms))

    def note_backpressure(self, delay_s: float) -> None:
        """One RPC retry backoff sleep (429-paced or error retry)."""
        self._backpressure_s += max(0.0, float(delay_s))

    def note_straggler(self, excess_ms: float, rank: int = -1) -> None:
        """The gang aggregator attributed this window to a straggling rank;
        ``excess_ms`` is its p50 over the gang median.  Evidence naming a
        rank the engine already degraded to bounded-staleness exchange is
        dropped: under degradation the gang steps at the *median* pace by
        construction, so the indicted rank's excess no longer stretches the
        step wall and must not trip the sentinel again."""
        if int(rank) in self._degraded_ranks:
            return
        self._straggler_ms = max(self._straggler_ms, max(0.0, float(excess_ms)))
        self._straggler_rank = int(rank)

    def mark_degraded(self, ranks) -> None:
        """Replace the set of ranks running under a degradation directive
        (``mark_degraded(())`` clears it, e.g. after the guardrail returns
        the gang to bulk sync)."""
        self._degraded_ranks = {int(r) for r in ranks}

    def note_wire(self, measured_wire_ms: float,
                  by_axis: Optional[Dict[str, float]] = None) -> None:
        """A measured per-step wire time (trace analysis ``collective_ms``
        or flight-recorder enqueue→retire deltas).  ``by_axis`` optionally
        splits the measurement by mesh axis (per-axis enqueue→retire
        deltas) — the strongest evidence for the per-axis ledger."""
        self._measured_wire_ms = max(0.0, float(measured_wire_ms))
        if by_axis:
            self._measured_wire_axis_ms = {
                str(k): max(0.0, float(v)) for k, v in by_axis.items()
            }

    # -- pricing helpers ------------------------------------------------------

    @property
    def calibrated(self) -> bool:
        return (self.compute_ms is not None
                or len(self._wall_samples) >= self.calibrate_steps)

    def expected(self) -> Optional[float]:
        """The priced (or calibrated) expected step wall in ms; None while
        still calibrating with nothing priced."""
        if self.compute_ms is not None:
            wire = self.wire_ms or 0.0
            return self.compute_ms + (1.0 - self.overlap_frac) * wire
        if len(self._wall_samples) >= 3:
            return statistics.median(self._wall_samples)
        return None

    def _price_bytes_ms(self, nbytes: float) -> Optional[float]:
        if nbytes <= 0:
            return 0.0
        if self.cost_model is not None:
            return self.cost_model.bucket_wire_time(
                float(nbytes), hierarchical=self.hierarchical,
                wire_pattern=self.wire_pattern) * 1e3
        return None

    def _price_axis_bytes_ms(self, axis: str, nbytes: float) -> Optional[float]:
        if nbytes <= 0:
            return 0.0
        if self.cost_model is not None and hasattr(self.cost_model, "axis_leg"):
            return self.cost_model.axis_leg(axis).predict(float(nbytes)) * 1e3
        return None

    def _split_by_axis_share(self, total: float) -> Dict[str, float]:
        """Partition a scalar slowdown over the priced per-axis expectations,
        proportionally by expected share — the last axis takes the exact
        remainder so the parts sum bitwise to ``total``."""
        axes = sorted(self.axis_wire_ms)
        weight = sum(self.axis_wire_ms[ax] for ax in axes)
        if not axes or weight <= 0:
            return {}
        parts: Dict[str, float] = {}
        assigned = 0.0
        for ax in axes[:-1]:
            part = total * self.axis_wire_ms[ax] / weight
            parts[ax] = part
            assigned += part
        parts[axes[-1]] = total - assigned
        return parts

    def _wire_slowdown_parts(
        self,
        wire_bytes: Optional[float],
        wire_bytes_by_axis: Optional[Dict[str, float]] = None,
    ) -> "tuple[float, Dict[str, float]]":
        """``(wire_slowdown_ms, {axis: ms})`` — the per-axis parts sum to
        the scalar exactly whenever they exist (partition by construction:
        either the scalar is computed as their sum, or the last axis takes
        the remainder of a proportional split).  Axis-blind inputs return
        an empty split and the legacy scalar unchanged."""
        # per-axis measured evidence is the strongest: each axis's overshoot
        # of its own priced promise, the scalar defined as the sum (needs a
        # priced per-axis promise — without one, fall to the scalar path)
        if self._measured_wire_axis_ms is not None and self.axis_wire_ms:
            parts = {
                ax: max(0.0, ms - self.axis_wire_ms.get(ax, 0.0))
                for ax, ms in self._measured_wire_axis_ms.items()
            }
            return sum(parts[ax] for ax in sorted(parts)), parts
        # scalar measured wire beyond the α–β promise wins next; with a
        # priced per-axis ledger the overshoot splits by expected share
        if self._measured_wire_ms is not None and self.wire_ms is not None:
            total = max(0.0, self._measured_wire_ms - self.wire_ms)
            return total, self._split_by_axis_share(total)
        # otherwise, price the byte inflation: census bytes over baseline.
        # Per-axis censuses price each axis's excess on its own leg and the
        # scalar is the sum of the parts.
        if wire_bytes_by_axis:
            parts = {}
            for ax in sorted(wire_bytes_by_axis):
                samples = self._axis_bytes_samples.get(ax)
                if not samples:
                    continue
                baseline = statistics.median(samples)
                excess = float(wire_bytes_by_axis[ax]) - baseline
                if excess <= 0 or baseline <= 0:
                    parts[ax] = 0.0
                    continue
                priced = self._price_axis_bytes_ms(ax, excess)
                if priced is not None:
                    parts[ax] = priced
                elif self.axis_wire_ms.get(ax):
                    parts[ax] = self.axis_wire_ms[ax] * excess / baseline
                else:
                    parts[ax] = 0.0
            if parts:
                return sum(parts[ax] for ax in sorted(parts)), parts
        if wire_bytes is None or not self._bytes_samples:
            return 0.0, {}
        baseline = statistics.median(self._bytes_samples)
        excess = float(wire_bytes) - baseline
        if excess <= 0 or baseline <= 0:
            return 0.0, {}
        priced = self._price_bytes_ms(excess)
        if priced is not None:
            return priced, {}
        if self.wire_ms is not None:
            return self.wire_ms * excess / baseline, {}
        return 0.0, {}

    # -- the per-step settle --------------------------------------------------

    def settle(
        self,
        step: int,
        measured_ms: float,
        host_ms: Optional[float] = None,
        wire_bytes: Optional[float] = None,
        wire_bytes_by_axis: Optional[Dict[str, float]] = None,
    ) -> StepBudget:
        """Close one step: compute the residual against the expected wall
        and partition it.  ``host_ms`` is the step's total host-side
        overhead (the engine's pre + lock-wait + post), ``wire_bytes`` the
        step's bucket-plan census (``wire_bytes_by_axis`` the same census
        split by mesh axis).  Clears the per-step evidence hooks."""
        measured_ms = float(measured_ms)
        clean = (self._compile_ms == 0.0 and self._snapshot_ms == 0.0
                 and self._backpressure_s == 0.0 and self._straggler_ms == 0.0)
        expected = self.expected()
        settled = expected is not None
        if expected is None:
            expected = measured_ms  # still calibrating: residual is zero
        residual = measured_ms - expected

        components = dict.fromkeys(BUDGET_COMPONENTS, 0.0)
        components["compile"] = self._compile_ms
        components["snapshot"] = self._snapshot_ms
        components["backpressure"] = self._backpressure_s * 1e3
        components["straggler"] = self._straggler_ms
        if host_ms is not None and self._host_samples:
            components["host_data"] = max(
                0.0, float(host_ms) - statistics.median(self._host_samples))
        wire_slowdown, wire_axis = self._wire_slowdown_parts(
            wire_bytes, wire_bytes_by_axis)
        components["wire_slowdown"] = wire_slowdown
        named = sum(components[c] for c in BUDGET_COMPONENTS[:-1])
        components["unattributed"] = residual - named

        dominant = ""
        if residual > 0:
            dominant = max(components, key=lambda c: components[c])
        budget = StepBudget(
            step=int(step),
            measured_ms=measured_ms,
            expected_ms=expected,
            residual_ms=residual,
            components=components,
            dominant=dominant,
            calibrated=settled,
            straggler_rank=self._straggler_rank,
            wire_axis_ms=wire_axis,
        )

        # clean steps feed the baselines (bounded: keep the newest window).
        # A step that regressed without named evidence (e.g. inflated wire
        # bytes) must not drag the baseline up after it, so a settled model
        # only admits samples inside a 25% band of the expected wall.
        if clean and settled and measured_ms > expected * 1.25:
            clean = False
        if clean:
            self._wall_samples.append(measured_ms)
            if host_ms is not None:
                self._host_samples.append(float(host_ms))
            if wire_bytes is not None:
                self._bytes_samples.append(float(wire_bytes))
            if wire_bytes_by_axis:
                for ax, nbytes in wire_bytes_by_axis.items():
                    self._axis_bytes_samples.setdefault(str(ax), []).append(
                        float(nbytes))
            cap = max(self.calibrate_steps, 64)
            for samples in (self._wall_samples, self._host_samples,
                            self._bytes_samples,
                            *self._axis_bytes_samples.values()):
                if len(samples) > cap:
                    del samples[: len(samples) - cap]

        self._compile_ms = 0.0
        self._snapshot_ms = 0.0
        self._backpressure_s = 0.0
        self._straggler_ms = 0.0
        self._straggler_rank = -1
        self._measured_wire_ms = None
        self._measured_wire_axis_ms = None
        return budget

    def report(self) -> Dict:
        return {
            "priced": self.compute_ms is not None,
            "compute_ms": self.compute_ms,
            "wire_ms": self.wire_ms,
            "axis_wire_ms": {
                k: round(v, 4) for k, v in sorted(self.axis_wire_ms.items())
            },
            "overlap_frac": self.overlap_frac,
            "expected_ms": self.expected(),
            "calibrated": self.calibrated,
            "calibration_samples": len(self._wall_samples),
        }
