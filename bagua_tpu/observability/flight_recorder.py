"""Collective flight recorder: black-box hang forensics for bucketed
collectives.

The BAGUA engines compose every algorithm out of bucketed collectives, so
the dominant production failure is a desynced or wedged collective — and a
post-mortem needs *which rank, which bucket, which collective, which plan
version*, cross-rank, before the restart loop erases the scene.  This
module is the per-rank black box: a sequence-numbered ring of one record
per collective the engine issues, dumped atomically on Watchdog timeout or
SIGTERM and joined offline by ``ci/diagnose_hang.py`` into a
``hang_report`` with first-desync attribution.

Design constraints (and how they are met):

* **Collectives live inside jit.**  A ``record()`` call placed in an
  exchange path would fire once per *trace*, not once per step.  The
  recorder therefore splits into trace-time capture and dispatch-time
  replay: the engine enables :func:`capture_program` around its cache-miss
  dispatch (jit traces synchronously inside the first call), and
  :meth:`AlgorithmImpl.annotate <bagua_tpu.algorithms.base.AlgorithmImpl.annotate>`
  — the single choke point every bucket exchange wraps itself in — calls
  :func:`notify_collective`, yielding an ordered *program* of collective
  descriptors per step variant.  The quantized ring kernels add one
  ``phase="hop"`` descriptor per ring with the hop count in-record
  (:func:`notify_ring`).  Every later dispatch replays the program into
  the ring with monotonic enqueue/retire timestamps from the host dispatch
  window.
* **Bitwise-inert.**  Capture reads trace-time Python values only; the
  traced computation is untouched, so recorder on vs off produces
  bit-identical training state (pinned in tests, the ``health_scalars``
  discipline).
* **Lock-free hot path.**  ``record()`` builds an immutable dict, assigns
  it into a preallocated slot, then bumps the sequence counter — single
  reference assignments, no lock, no device sync.  A dump from another
  thread (the watchdog) reads whole-record references, so a dump during an
  append can never observe a torn record.
* **Degradation.**  The post-dump digest push rides the rendezvous KV
  behind the shared retry policy and a circuit breaker; any KV trouble
  degrades to local-only evidence, never an exception on the dying path.

Record labels reuse the named-scope grammar
(``bagua_ex/algo=<a>/bucket=<i>/phase=<p>``) so ring records and device-
trace labels join on the same key.
"""

import json
import logging
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Sequence, Tuple

from bagua_tpu.observability.annotations import EXCHANGE_PREFIX

logger = logging.getLogger(__name__)

__all__ = [
    "FLIGHT_DUMP_SCHEMA",
    "HANG_REPORT_SCHEMA",
    "VERDICTS",
    "FlightRecorder",
    "build_hang_report",
    "capture_program",
    "flight_dump_path",
    "flight_kv_key",
    "notify_collective",
    "notify_ring",
    "push_flight_digest",
    "thread_stacks",
    "validate_flight_dump",
    "validate_hang_report",
    "write_json_atomic",
]

FLIGHT_DUMP_SCHEMA = "bagua.flight_dump.v1"
HANG_REPORT_SCHEMA = "bagua.hang_report.v1"

#: the analyzer's verdict taxonomy: ``desync`` = ring *content* diverges at
#: a sequence number (a rank issued a different collective — the skipped/
#: extra-collective bug class); ``straggler`` = identical programs but a
#: rank stopped advancing with its host parked in ``wait`` (device-side
#: lag); ``host_wedge`` = the lagging rank's host stopped mid-dispatch
#: (unretired records) or outside ``wait``; ``healthy``/``no_data`` close
#: the taxonomy.
VERDICTS = ("healthy", "desync", "straggler", "host_wedge", "no_data")


# ---------------------------------------------------------------------------
# Trace-time capture
# ---------------------------------------------------------------------------

_tls = threading.local()


class capture_program:
    """Enable collective capture on this thread::

        with capture_program() as events:
            out = jitted_step(state, batch)   # traces -> annotate() notifies

    ``events`` is the ordered list of collective descriptors the trace
    issued.  Reentrant (the previous capture, if any, is restored on exit).
    """

    def __enter__(self) -> List[Dict]:
        self._prev = getattr(_tls, "capture", None)
        self.events: List[Dict] = []
        _tls.capture = self.events
        return self.events

    def __exit__(self, *exc) -> bool:
        _tls.capture = self._prev
        return False


def notify_collective(algo: str, bucket_idx: int, phase: str, **extra) -> None:
    """One bucket collective entered the trace (called by
    ``AlgorithmImpl.annotate``).  No-op unless a capture is active."""
    cap = getattr(_tls, "capture", None)
    if cap is None:
        return
    ev: Dict[str, Any] = {
        "algo": str(algo), "bucket": int(bucket_idx), "phase": str(phase),
    }
    ev.update(extra)
    cap.append(ev)


def notify_ring(*, kind: str, bits: int, hops: int, wire_bytes: int = 0) -> None:
    """One quantized ring (reduce-scatter or all-gather leg) entered the
    trace: a single ``phase="hop"`` descriptor carrying the hop count —
    not one per hop — attributed to the enclosing bucket collective."""
    cap = getattr(_tls, "capture", None)
    if cap is None:
        return
    algo, bucket = "ring", -1
    for ev in reversed(cap):
        if ev.get("phase") != "hop":
            algo, bucket = ev["algo"], ev["bucket"]
            break
    cap.append({
        "algo": algo, "bucket": bucket, "phase": "hop", "ring": str(kind),
        "bits": int(bits), "hops": int(hops), "nbytes": int(wire_bytes),
        "precision": f"int{int(bits)}",
    })


# ---------------------------------------------------------------------------
# The ring
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Per-rank lock-free ring of sequence-numbered collective records.

    The hot path (:meth:`record` / :meth:`record_program` / :meth:`retire`)
    runs on the engine's dispatch thread; :meth:`records` / :meth:`dump`
    may run concurrently on the watchdog thread.  Safety argument: every
    slot holds either ``None`` or a complete immutable record (the dict is
    fully built before the single reference assignment publishes it), so a
    reader sees whole records only — at worst a mix of just-overwritten and
    just-published ones, which the per-record ``seq`` sorts out.
    """

    def __init__(self, capacity: int = 4096, rank: int = 0, world_size: int = 1):
        self._slots: List[Optional[Dict]] = [None] * max(8, int(capacity))
        self._seq = 0  # next sequence number == records ever appended
        self.rank = int(rank)
        self.world_size = int(world_size)

    @property
    def capacity(self) -> int:
        return len(self._slots)

    @property
    def last_seq(self) -> int:
        """Sequence number of the newest record (-1 while empty)."""
        return self._seq - 1

    def record(self, rec: Dict) -> int:
        """Append one collective record; returns its sequence number."""
        seq = self._seq
        rec = dict(rec)
        rec["seq"] = seq
        self._slots[seq % len(self._slots)] = rec  # publish (atomic ref set)
        self._seq = seq + 1
        return seq

    def record_program(self, program: Sequence[Dict], *, step: int,
                       enqueue_t: Optional[float] = None) -> List[int]:
        """Replay one step's captured collective program into the ring with
        ``t_retire=None`` (the dispatch is in flight); returns the sequence
        numbers for :meth:`retire`."""
        t = time.monotonic() if enqueue_t is None else float(enqueue_t)
        seqs = []
        for tmpl in program:
            rec = dict(tmpl)
            rec["step"] = int(step)
            rec["t_enqueue"] = t
            rec["t_retire"] = None
            seqs.append(self.record(rec))
        return seqs

    def retire(self, seqs: Sequence[int], retire_t: Optional[float] = None) -> None:
        """The dispatch window closed: stamp ``t_retire`` on the given
        records (skipping any the ring already evicted)."""
        t = time.monotonic() if retire_t is None else float(retire_t)
        cap = len(self._slots)
        for seq in seqs:
            cur = self._slots[seq % cap]
            if cur is not None and cur.get("seq") == seq and cur.get("t_retire") is None:
                new = dict(cur)
                new["t_retire"] = t
                self._slots[seq % cap] = new

    def records(self) -> List[Dict]:
        """Snapshot of the ring's live records in sequence order.  Safe
        against a concurrent :meth:`record` (see class docstring)."""
        recs = [r for r in list(self._slots) if r is not None]
        recs.sort(key=lambda r: r["seq"])
        return recs

    # -- the dying-path surface ----------------------------------------------

    def dump(self, path: str, *, reason: str = "manual",
             telemetry: Optional[Dict] = None,
             plan_version: Optional[int] = None,
             extra: Optional[Dict] = None) -> Dict:
        """Atomically write this rank's black box (`write-temp +
        os.replace`): the ring, every thread's stack, the telemetry
        snapshot, and monotonic/unix clock anchors so offline analysis can
        convert record timestamps to ages."""
        payload: Dict[str, Any] = {
            "schema": FLIGHT_DUMP_SCHEMA,
            "rank": self.rank,
            "world_size": self.world_size,
            "reason": str(reason),
            "mono_at_dump": time.monotonic(),
            "unix_at_dump": time.time(),
            "capacity": len(self._slots),
            "last_seq": self.last_seq,
            "records": self.records(),
            "threads": thread_stacks(),
            "telemetry": telemetry,
            "plan_version": plan_version,
        }
        if extra:
            payload.update(extra)
        write_json_atomic(path, payload)
        return payload

    def digest(self) -> Dict:
        """The compact cross-rank breadcrumb pushed through the rendezvous
        KV at dump time — enough for a live operator (or the analyzer, when
        a rank's dump file is lost) to place this rank in the gang."""
        recs = self.records()
        last = recs[-1] if recs else None
        return {
            "rank": self.rank,
            "last_seq": self.last_seq,
            "unretired": sum(1 for r in recs if r.get("t_retire") is None),
            "last": (
                {k: last.get(k) for k in
                 ("seq", "step", "label", "bucket", "phase", "plan_version")}
                if last else None
            ),
            # the newest few full records: enough for the fleet's
            # RemediationEngine to synthesize a pseudo-dump per rank and
            # run build_hang_report's first-desync join server-side, even
            # when every dump file died with its host
            "tail": [dict(r) for r in recs[-8:]],
            "mono": time.monotonic(),
        }


def flight_dump_path(dump_dir: str, rank: int) -> str:
    return os.path.join(dump_dir, f"flight_{int(rank)}.json")


def thread_stacks() -> Dict[str, str]:
    """Formatted stacks of every live thread, keyed ``<name>-<ident>``."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        out[f"{names.get(ident, 'thread')}-{ident}"] = "".join(
            traceback.format_stack(frame)
        )
    return out


def write_json_atomic(path: str, payload: Dict) -> None:
    """Write-temp + ``os.replace`` — a reader (or the restart loop's
    collector) never sees a torn file."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True, default=str)
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# Digest push (rendezvous KV, best-effort)
# ---------------------------------------------------------------------------

_breaker = None
_breaker_lock = threading.Lock()


def _default_breaker():
    global _breaker
    with _breaker_lock:
        if _breaker is None:
            from bagua_tpu.env import (
                get_rpc_breaker_cooldown_s,
                get_rpc_breaker_threshold,
            )
            from bagua_tpu.resilience.retry import CircuitBreaker

            _breaker = CircuitBreaker(
                failure_threshold=get_rpc_breaker_threshold(),
                cooldown_s=get_rpc_breaker_cooldown_s(),
                name="flight-digest",
            )
        return _breaker


def flight_kv_key(attempt: str, rank: int) -> str:
    """KV key one rank's flight digest lives under — namespaced by the
    elastic attempt nonce like the gang-observability keys."""
    return f"bagua/flight/{attempt}/rank{int(rank)}"


def push_flight_digest(client, recorder: Optional[FlightRecorder],
                       attempt: Optional[str] = None, breaker=None) -> bool:
    """Best-effort digest push through the rendezvous KV.  The client's
    transport already retries (``RetryPolicy``); this adds the circuit
    breaker and swallows every failure — the dying path degrades to
    local-only dumps, it never raises."""
    if client is None or recorder is None:
        return False
    if attempt is None:
        attempt = os.environ.get("BAGUA_ATTEMPT", "0")
    breaker = breaker or _default_breaker()
    try:
        breaker.before_call()
    except Exception:
        return False
    try:
        client.kv_set(flight_kv_key(attempt, recorder.rank), recorder.digest())
    except Exception as exc:
        breaker.record_failure()
        logger.warning("flight digest push failed (local-only evidence): %s", exc)
        return False
    breaker.record_success()
    return True


# ---------------------------------------------------------------------------
# Schemas
# ---------------------------------------------------------------------------

#: per-record required fields (``t_retire`` is float-or-None, checked apart)
_RECORD_FIELDS = {
    "seq": int,
    "step": int,
    "label": str,
    "algo": str,
    "bucket": int,
    "phase": str,
    "precision": str,
    "nbytes": int,
    "plan_version": int,
    "t_enqueue": (int, float),
}

_DUMP_FIELDS = {
    "rank": int,
    "world_size": int,
    "reason": str,
    "mono_at_dump": (int, float),
    "unix_at_dump": (int, float),
    "capacity": int,
    "last_seq": int,
    "records": list,
    "threads": dict,
}

_REPORT_FIELDS = {
    "ranks": list,
    "last_seq": dict,
    "lagging_ranks": list,
    "divergent_ranks": list,
    "verdict": str,
    "per_rank": dict,
    "detail": str,
}

_BLOCKED_ON_FIELDS = {"seq": int, "label": str, "algo": str, "bucket": int,
                      "phase": str, "plan_version": int}


def _check_fields(obj: Dict, fields: Dict, problems: List[str], where: str) -> None:
    for field, types in fields.items():
        if field not in obj:
            problems.append(f"{where} missing field {field!r}")
        elif not isinstance(obj[field], types) or isinstance(obj[field], bool):
            problems.append(
                f"{where} field {field!r} is {type(obj[field]).__name__}, "
                f"expected {types}"
            )


def validate_flight_record(rec: Dict, where: str = "record") -> List[str]:
    problems: List[str] = []
    if not isinstance(rec, dict):
        return [f"{where} is {type(rec).__name__}, not an object"]
    _check_fields(rec, _RECORD_FIELDS, problems, where)
    t_ret = rec.get("t_retire", None)
    if t_ret is not None and not isinstance(t_ret, (int, float)):
        problems.append(f"{where} field 't_retire' must be a number or null")
    return problems


def validate_flight_dump(dump: Dict) -> List[str]:
    """Schema-check one per-rank flight dump; returns problems (empty =
    valid)."""
    if not isinstance(dump, dict):
        return [f"dump is {type(dump).__name__}, not an object"]
    problems: List[str] = []
    if dump.get("schema") != FLIGHT_DUMP_SCHEMA:
        problems.append(
            f"schema is {dump.get('schema')!r}, expected {FLIGHT_DUMP_SCHEMA!r}"
        )
    _check_fields(dump, _DUMP_FIELDS, problems, "dump")
    records = dump.get("records")
    if isinstance(records, list):
        prev = None
        for i, rec in enumerate(records):
            problems.extend(validate_flight_record(rec, where=f"records[{i}]"))
            seq = rec.get("seq") if isinstance(rec, dict) else None
            if isinstance(seq, int):
                if prev is not None and seq <= prev:
                    problems.append(
                        f"records[{i}] seq {seq} not increasing (prev {prev})"
                    )
                prev = seq
        if records and isinstance(dump.get("last_seq"), int) and prev is not None:
            if prev != dump["last_seq"]:
                problems.append(
                    f"last_seq {dump['last_seq']} != newest record seq {prev}"
                )
    return problems


def validate_hang_report(report: Dict) -> List[str]:
    """Schema-check a joined hang report; returns problems (empty = valid)."""
    if not isinstance(report, dict):
        return [f"report is {type(report).__name__}, not an object"]
    problems: List[str] = []
    if report.get("schema") != HANG_REPORT_SCHEMA:
        problems.append(
            f"schema is {report.get('schema')!r}, expected {HANG_REPORT_SCHEMA!r}"
        )
    _check_fields(report, _REPORT_FIELDS, problems, "report")
    if report.get("verdict") not in VERDICTS:
        problems.append(
            f"verdict {report.get('verdict')!r} not in {VERDICTS}"
        )
    fd = report.get("first_divergence_seq", None)
    if fd is not None and not isinstance(fd, int):
        problems.append("'first_divergence_seq' must be an int or null")
    blocked = report.get("blocked_on", None)
    if blocked is not None:
        if not isinstance(blocked, dict):
            problems.append("'blocked_on' must be an object or null")
        else:
            _check_fields(blocked, _BLOCKED_ON_FIELDS, problems, "blocked_on")
    if report.get("verdict") in ("desync", "straggler", "host_wedge") and blocked is None:
        problems.append(f"verdict {report['verdict']!r} requires 'blocked_on'")
    return problems


# ---------------------------------------------------------------------------
# The join: per-rank rings -> hang report
# ---------------------------------------------------------------------------


def _signature(rec: Dict) -> Tuple:
    """What must agree across ranks for a sequence slot to be 'the same
    collective' (timestamps excluded — those differ by design)."""
    return (
        rec.get("label"), rec.get("step"), rec.get("nbytes"),
        rec.get("precision"), rec.get("plan_version"), rec.get("hops"),
    )


def _blocked_on(rec: Dict) -> Dict:
    out = {k: rec.get(k) for k in
           ("seq", "step", "label", "algo", "bucket", "phase", "precision",
            "nbytes", "plan_version", "variant")}
    if "hops" in rec:
        out["hops"] = rec["hops"]
    if "axes" in rec:
        # which mesh axes the blocked collective rides — lets the hang
        # verdict name the link a wedged gang is stuck behind
        out["axes"] = list(rec["axes"])
    return out


def build_hang_report(dumps: Sequence[Dict]) -> Dict:
    """Join per-rank flight dumps into the forensics verdict.

    * ``first_divergence_seq`` — the first sequence number (within the
      window every surviving ring still covers) where record *content*
      differs across ranks; its majority record is the collective the
      minority desynced from.
    * ``lagging_ranks`` — ranks whose newest sequence number trails the
      most-advanced rank; ``blocked_on`` is then the first collective they
      have not issued (read from an advanced rank's ring) — the collective
      the gang is blocked on.
    * verdict — see :data:`VERDICTS`; the straggler-vs-host-wedge split
      uses per-record enqueue/retire deltas (an unretired record means the
      host never came back from the dispatch) plus the dumped telemetry
      phase.
    """
    dumps = sorted((d for d in dumps if isinstance(d, dict)),
                   key=lambda d: d.get("rank", 0))
    report: Dict[str, Any] = {
        "schema": HANG_REPORT_SCHEMA,
        "ranks": [int(d.get("rank", -1)) for d in dumps],
        "last_seq": {},
        "first_divergence_seq": None,
        "lagging_ranks": [],
        "divergent_ranks": [],
        "blocked_on": None,
        "verdict": "no_data",
        "per_rank": {},
        "detail": "",
    }
    if not dumps:
        report["detail"] = "no flight dumps found"
        return report

    by_rank: Dict[int, Dict[int, Dict]] = {}
    for d in dumps:
        r = int(d.get("rank", -1))
        recs = {rec["seq"]: rec for rec in d.get("records", [])
                if isinstance(rec, dict) and isinstance(rec.get("seq"), int)}
        by_rank[r] = recs
        last = int(d.get("last_seq", -1))
        unretired = [s for s, rec in sorted(recs.items())
                     if rec.get("t_retire") is None]
        tel = d.get("telemetry") or {}
        mono = d.get("mono_at_dump")
        newest = recs.get(last)
        age = None
        if newest is not None and isinstance(mono, (int, float)):
            t_ref = newest.get("t_retire") or newest.get("t_enqueue")
            if isinstance(t_ref, (int, float)):
                age = round(mono - t_ref, 3)
        report["last_seq"][str(r)] = last
        report["per_rank"][str(r)] = {
            "last_seq": last,
            "unretired": len(unretired),
            "first_unretired_seq": unretired[0] if unretired else None,
            "last_record_age_s": age,
            "phase": tel.get("phase"),
            "step": tel.get("step"),
            "reason": d.get("reason"),
        }

    ranks = sorted(by_rank)
    lasts = {r: int(report["last_seq"][str(r)]) for r in ranks}
    min_last, max_last = min(lasts.values()), max(lasts.values())
    report["lagging_ranks"] = [r for r in ranks if lasts[r] < max_last]

    # Content comparison over the window every ring still covers.
    window_lo = 0
    for r in ranks:
        if by_rank[r]:
            window_lo = max(window_lo, min(by_rank[r]))
    divergence, majority_rec = None, None
    for seq in range(window_lo, min_last + 1):
        recs = {r: by_rank[r].get(seq) for r in ranks}
        if any(rec is None for rec in recs.values()):
            continue  # evicted on some rank: nothing to compare
        sigs: Dict[Tuple, List[int]] = {}
        for r, rec in recs.items():
            sigs.setdefault(_signature(rec), []).append(r)
        if len(sigs) > 1:
            major_sig = max(sigs.items(), key=lambda kv: (len(kv[1]), -kv[1][0]))[0]
            divergence = seq
            majority_rec = recs[sigs[major_sig][0]]
            report["divergent_ranks"] = sorted(
                r for sig, rs in sigs.items() if sig != major_sig for r in rs
            )
            break

    if divergence is not None:
        report["first_divergence_seq"] = divergence
        report["verdict"] = "desync"
        report["blocked_on"] = _blocked_on(majority_rec)
        report["detail"] = (
            f"rank(s) {report['divergent_ranks']} issued a different "
            f"collective at seq {divergence}: the gang desynced at "
            f"{majority_rec.get('label')} (plan_version "
            f"{majority_rec.get('plan_version')})"
        )
        return report

    def _wedged(r: int) -> bool:
        pr = report["per_rank"][str(r)]
        return bool(pr["unretired"]) or pr["phase"] not in (None, "wait", "data")

    if report["lagging_ranks"]:
        # The collective the gang is blocked on: the first one the most-
        # lagging ranks have not issued, read from any advanced rank.
        behind = [r for r in ranks if lasts[r] == min_last]
        ahead = [r for r in ranks if lasts[r] > min_last]
        blocked = None
        for r in ahead:
            blocked = by_rank[r].get(min_last + 1)
            if blocked is not None:
                break
        if blocked is not None:
            report["blocked_on"] = _blocked_on(blocked)
        wedged = [r for r in behind if _wedged(r)]
        report["verdict"] = "host_wedge" if wedged else "straggler"
        who = wedged or behind
        report["detail"] = (
            f"rank(s) {who} stopped at seq {min_last} "
            f"({'host wedged mid-dispatch' if wedged else 'device lagging in wait'}); "
            f"gang blocked on "
            f"{report['blocked_on']['label'] if report['blocked_on'] else 'unknown'}"
        )
        return report

    # Aligned rings: a rank that never retired its newest dispatch is a
    # gang-wide host wedge; otherwise the rings show nothing wrong.
    wedged = [r for r in ranks if report["per_rank"][str(r)]["unretired"]]
    if wedged:
        r = wedged[0]
        first = report["per_rank"][str(r)]["first_unretired_seq"]
        report["verdict"] = "host_wedge"
        report["blocked_on"] = _blocked_on(by_rank[r][first])
        report["detail"] = (
            f"rank(s) {wedged} never retired seq {first}: host wedged inside "
            f"the dispatch window"
        )
        return report

    report["verdict"] = "healthy"
    report["detail"] = (
        f"all {len(ranks)} rings aligned through seq {max_last}; nothing to blame"
    )
    return report
