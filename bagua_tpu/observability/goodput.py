"""Goodput / MFU accounting: how much of the wall clock trained the model.

BAGUA's throughput-vs-convergence tradeoff is an *observed* quantity; this
module makes the observation first-class instead of hand-math in
``ci/perf_audit.py``.  Three pieces, all host-side and opt-in:

* **FLOPs estimator** — an analytic per-model registry (VGG16 / MLP built
  in, :func:`register_model_flops` for user models) cross-checkable against
  XLA's own ``compiled.cost_analysis()`` (:func:`flops_from_cost_analysis`).
  The FLOP convention matches the perf-audit roofline: one multiply-accumulate
  counts as one FLOP (VGG16 fwd at 224² = 15.5 GFLOP, ×3 for fwd+bwd).
* **:class:`GoodputMeter`** — per-step ``mfu`` (model FLOPs / wall /
  peak) and ``wire_efficiency`` (α–β-predicted wire time from the planner's
  fitted :class:`~bagua_tpu.service.planner.CostModel` over the live bucket
  plan, divided by the measured wire time a device-trace analysis supplies)
  gauges, fed by the :class:`~bagua_tpu.observability.telemetry.Telemetry`
  hub.
* **:class:`GoodputLedger`** — classifies every wall-second of the run as
  ``productive`` / ``compile`` / ``snapshot`` / ``drain`` / ``data`` /
  ``lost_restart`` from the existing ``compile``/``snapshot``/``restart``
  telemetry events plus the hub's phase transitions, so ``goodput_frac`` is
  a live gauge, not a post-hoc trace read.  The ledger is a state machine
  over the host clock: exactly one bucket owns any instant, so the buckets
  sum to the elapsed wall time by construction (pinned ±1% in tests).
"""

import threading
import time
from typing import Callable, Dict, Optional, Sequence

__all__ = [
    "GoodputLedger",
    "GoodputMeter",
    "LEDGER_BUCKETS",
    "PEAK_FLOPS_PER_CHIP",
    "TRAIN_FLOPS_MULTIPLIER",
    "flops_from_cost_analysis",
    "mlp_fwd_flops",
    "model_flops_per_sample",
    "predicted_axis_wire_time",
    "predicted_wire_time",
    "register_model_flops",
    "vgg16_fwd_flops",
]

#: per-chip peak throughput (FLOP/s) under the audit's MAC-counting
#: convention — the denominators MFU is quoted against.  "v5e" matches the
#: perf-audit roofline (197 bf16 TFLOP/s).
PEAK_FLOPS_PER_CHIP = {
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
}

#: training FLOPs ≈ 3× the forward pass (backward re-computes both the
#: activation and the weight gradient) — the perf-audit convention
#: ("15.5 fwd ×3 for fwd+bwd").
TRAIN_FLOPS_MULTIPLIER = 3.0


# -- analytic FLOPs estimators (1 MAC = 1 FLOP, matching the audit) ----------


def vgg16_fwd_flops(
    image_size: int = 224,
    num_classes: int = 1000,
    classifier_width: int = 4096,
    cfg: Optional[Sequence] = None,
) -> float:
    """Forward-pass FLOPs per image for the VGG16 of
    :mod:`bagua_tpu.models.vgg` (3×3 convs + 2×2 pools + 3 dense layers).
    224²/1000 classes ⇒ 15.5 GFLOP — the operand of the perf-audit
    hand-math (``32 img × 46.5 GFLOP = 1.49 TF/step/chip``)."""
    from bagua_tpu.models.vgg import VGG16_CFG

    cfg = VGG16_CFG if cfg is None else cfg
    h = w = int(image_size)
    cin = 3
    flops = 0.0
    for v in cfg:
        if v == "M":
            h //= 2
            w //= 2
        else:
            flops += float(h * w) * 9.0 * cin * int(v)
            cin = int(v)
    features = h * w * cin
    for width in (classifier_width, classifier_width, num_classes):
        flops += float(features) * width
        features = width
    return flops


def mlp_fwd_flops(sizes: Sequence[int]) -> float:
    """Forward-pass FLOPs per sample for the dense MLP of
    :mod:`bagua_tpu.models.mlp` (``sizes`` = layer widths incl. input)."""
    return float(sum(a * b for a, b in zip(sizes[:-1], sizes[1:])))


_MODEL_FLOPS: Dict[str, Callable[..., float]] = {
    "vgg16": vgg16_fwd_flops,
    "mlp": mlp_fwd_flops,
}


def register_model_flops(name: str, fwd_flops_fn: Callable[..., float]) -> None:
    """Register an analytic forward-FLOPs-per-sample estimator for a model
    name (``fn(**kwargs) -> float``); :func:`model_flops_per_sample` and
    :class:`GoodputMeter` resolve through this registry."""
    _MODEL_FLOPS[name] = fwd_flops_fn


def model_flops_per_sample(name: str, train: bool = True, **kwargs) -> float:
    """Per-sample FLOPs for a registered model (forward pass ×
    :data:`TRAIN_FLOPS_MULTIPLIER` when ``train``)."""
    if name not in _MODEL_FLOPS:
        raise KeyError(
            f"no FLOPs estimator registered for model {name!r} "
            f"(known: {sorted(_MODEL_FLOPS)}); use register_model_flops"
        )
    fwd = float(_MODEL_FLOPS[name](**kwargs))
    return fwd * TRAIN_FLOPS_MULTIPLIER if train else fwd


def flops_from_cost_analysis(compiled) -> Optional[float]:
    """XLA's own FLOP count for a compiled executable
    (``compiled.cost_analysis()``), or None when the backend does not
    report one — the cross-check for the analytic registry.  Note XLA
    counts multiplies and adds separately, so expect ~2× the MAC-counting
    analytic number for matmul-dominated models."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    try:
        flops = float(flops)
    except (TypeError, ValueError):
        return None
    return flops if flops > 0 else None


def predicted_wire_time(
    cost_model,
    bucket_bytes: Sequence[float],
    hierarchical: bool = False,
    wire_pattern: str = "allreduce",
) -> float:
    """α–β-predicted wire seconds for one step's bucketed exchange: the
    planner's fitted :class:`~bagua_tpu.service.planner.CostModel` applied
    to every live bucket — the denominator-side input of the
    ``wire_efficiency`` gauge."""
    return float(
        sum(
            cost_model.bucket_wire_time(b, hierarchical=hierarchical,
                                        wire_pattern=wire_pattern)
            for b in bucket_bytes
        )
    )


def predicted_axis_wire_time(
    cost_model,
    bucket_bytes: Sequence[float],
    axes: Sequence[str],
) -> Dict[str, float]:
    """Per-mesh-axis α–β-predicted wire seconds for one step's bucketed
    exchange: each bucket's bytes split evenly across the exchange axes and
    priced on each axis's fitted leg
    (:meth:`~bagua_tpu.service.planner.CostModel.axis_leg`, falling back to
    ``flat`` on legacy 1-D meshes).  Returns ``{axis: seconds}``."""
    axes = [str(a) for a in axes if a]
    if not axes:
        return {}
    out: Dict[str, float] = {}
    for b in bucket_bytes:
        share = float(b) / len(axes)
        for ax in axes:
            out[ax] = out.get(ax, 0.0) + cost_model.axis_leg(ax).predict(share)
    return out


# -- the wall-clock ledger ----------------------------------------------------

#: every wall-second of the run lands in exactly one of these
LEDGER_BUCKETS = (
    "startup",       # init -> first step activity
    "productive",    # step dispatch + device wait
    "data",          # input pipeline / host idle between steps
    "compile",       # step-function (re)compiles, re-attributed out of productive
    "snapshot",      # blocking state snapshots (anomaly/forced)
    "drain",         # preemption drain (block + final snapshot)
    "lost_restart",  # steps a previous incarnation ran past its last snapshot
)


class GoodputLedger:
    """State machine over the host clock: :meth:`enter` switches the active
    bucket and charges the closed interval to the previous one, so the
    buckets partition the elapsed wall time exactly.  ``lost_restart`` is
    the one synthetic bucket — :meth:`charge` adds the estimated wall of
    steps lost to a restart (they happened in a *previous* incarnation's
    wall clock).  Thread-safe: the async snapshotter's writer thread
    re-attributes blocking snapshot time concurrently with the step loop."""

    def __init__(self, registry=None, clock: Callable[[], float] = time.perf_counter):
        self.registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        self._t0 = clock()
        self._cur = "startup"
        self._t_cur = self._t0
        self.buckets: Dict[str, float] = {b: 0.0 for b in LEDGER_BUCKETS}
        self._synthetic = 0.0  # charged (not clocked) seconds: lost_restart

    def enter(self, bucket: str) -> None:
        """Close the open interval into the active bucket and switch."""
        with self._lock:
            self._flush_locked()
            self._cur = bucket

    def _flush_locked(self) -> None:
        now = self._clock()
        self.buckets[self._cur] = self.buckets.get(self._cur, 0.0) + (now - self._t_cur)
        self._t_cur = now

    def charge(self, bucket: str, seconds: float) -> None:
        """Add synthetic seconds (wall of a *previous* incarnation — the
        lost-restart bucket); tracked separately so the clocked buckets
        still sum to this run's wall time."""
        with self._lock:
            self.buckets[bucket] = self.buckets.get(bucket, 0.0) + float(seconds)
            self._synthetic += float(seconds)

    def reattribute(self, src: str, dst: str, seconds: float) -> None:
        """Move up to ``seconds`` from ``src`` to ``dst`` (e.g. the compile
        embedded in a first dispatch out of ``productive``) — flushing the
        open interval first so ``src`` is current."""
        with self._lock:
            self._flush_locked()
            moved = min(float(seconds), self.buckets.get(src, 0.0))
            if moved <= 0:
                return
            self.buckets[src] -= moved
            self.buckets[dst] = self.buckets.get(dst, 0.0) + moved

    def wall_s(self) -> float:
        return self._clock() - self._t0

    def goodput_frac(self) -> float:
        with self._lock:
            self._flush_locked()
            wall = self._clock() - self._t0
            return self.buckets.get("productive", 0.0) / wall if wall > 0 else 0.0

    def report(self) -> Dict:
        """Bucket seconds + ``goodput_frac``; updates the ``goodput_frac``
        and ``ledger_<bucket>_s`` gauges when a registry is attached.  The
        clocked buckets sum to ``wall_s`` exactly (synthetic lost-restart
        seconds are reported but excluded from the identity)."""
        with self._lock:
            self._flush_locked()
            wall = self._clock() - self._t0
            buckets = {b: round(v, 6) for b, v in sorted(self.buckets.items())}
            synthetic = self._synthetic
        frac = (buckets.get("productive", 0.0) / wall) if wall > 0 else 0.0
        if self.registry is not None:
            self.registry.gauge(
                "goodput_frac", help="fraction of wall time spent in productive steps"
            ).set(round(frac, 6))
            for b, v in buckets.items():
                self.registry.gauge(
                    f"ledger_{b}_s", help=f"wall seconds classified as {b}"
                ).set(v)
        return {
            "wall_s": round(wall, 6),
            "buckets": buckets,
            "synthetic_s": round(synthetic, 6),
            "goodput_frac": round(frac, 6),
        }


#: hub phase -> ledger bucket (phases the engine/trainer already tag)
_PHASE_BUCKET = {
    "dispatch": "productive",
    "wait": "productive",
    "data": "data",
    "init": "startup",
    "drain": "drain",
}


class GoodputMeter:
    """Per-step MFU + wire-efficiency gauges and the goodput ledger, fed by
    the telemetry hub (``Telemetry(goodput=...)``).

    Args:
        model: a name registered with :func:`register_model_flops`
            (``"vgg16"``/``"mlp"`` built in); with ``model_kwargs``
            forwarded to the estimator.  Alternatively pass
            ``flops_per_sample`` directly (wins over ``model``), or
            calibrate later from a compiled step
            (:meth:`calibrate_from_compiled`).
        peak_flops_per_chip: the MFU denominator (a number, or a key of
            :data:`PEAK_FLOPS_PER_CHIP` such as ``"v5e"``).
        n_chips: chips the ``n_samples`` global batch spreads over — MFU is
            quoted per chip.
        cost_model: the planner's fitted
            :class:`~bagua_tpu.service.planner.CostModel`; with
            ``bucket_bytes`` (the live plan's per-bucket bytes) it prices
            the predicted wire time for ``wire_efficiency``.
        registry: metrics registry for the gauges (the hub injects its own
            when attached with ``Telemetry(goodput=...)``).
    """

    def __init__(
        self,
        model: Optional[str] = None,
        model_kwargs: Optional[Dict] = None,
        flops_per_sample: Optional[float] = None,
        peak_flops_per_chip=197e12,
        n_chips: int = 1,
        cost_model=None,
        bucket_bytes: Optional[Sequence[float]] = None,
        hierarchical: bool = False,
        wire_pattern: str = "allreduce",
        exchange_axes: Optional[Sequence[str]] = None,
        registry=None,
        clock: Callable[[], float] = time.perf_counter,
    ):
        if flops_per_sample is None and model is not None:
            flops_per_sample = model_flops_per_sample(model, **(model_kwargs or {}))
        self.flops_per_sample = flops_per_sample
        if isinstance(peak_flops_per_chip, str):
            peak_flops_per_chip = PEAK_FLOPS_PER_CHIP[peak_flops_per_chip]
        self.peak_flops_per_chip = float(peak_flops_per_chip)
        self.n_chips = max(1, int(n_chips))
        self.cost_model = cost_model
        self.bucket_bytes = list(bucket_bytes) if bucket_bytes else None
        self.hierarchical = hierarchical
        self.wire_pattern = wire_pattern
        #: named mesh axes the live plan's exchange rides (the engine's
        #: ``group.data_axes``); set, the wire prediction routes through the
        #: per-axis α–β legs instead of the flat leg
        self.exchange_axes = tuple(str(a) for a in exchange_axes or () if a)
        self.registry = registry
        self.ledger = GoodputLedger(registry=registry, clock=clock)
        self.last_mfu: Optional[float] = None
        self.last_wire_efficiency: Optional[float] = None
        self._step_walls = []  # recent step walls: prices lost_restart

    def bind_registry(self, registry) -> None:
        """Point the gauges (and the ledger's) at a registry — called by the
        telemetry hub when the meter is attached."""
        self.registry = registry
        self.ledger.registry = registry

    # -- per-step gauges ------------------------------------------------------

    def step_flops(self, n_samples: int) -> Optional[float]:
        if self.flops_per_sample is None:
            return None
        return self.flops_per_sample * max(0, int(n_samples))

    def calibrate_from_compiled(self, compiled, n_samples: int) -> Optional[float]:
        """Adopt XLA's ``cost_analysis()`` FLOP count for the compiled step
        as the per-sample estimate (``n_samples`` = the global batch the
        step was lowered at).  Returns the adopted per-sample FLOPs, or
        None (keeping any analytic estimate) when XLA reports nothing."""
        flops = flops_from_cost_analysis(compiled)
        if flops is None or n_samples <= 0:
            return None
        self.flops_per_sample = flops / n_samples
        return self.flops_per_sample

    def on_step(self, wall_s: float, n_samples: int) -> Optional[float]:
        """One dispatched step: update ``mfu`` (and remember the wall for
        lost-restart pricing).  Returns the step's MFU, or None without a
        FLOPs estimate."""
        self._step_walls.append(float(wall_s))
        if len(self._step_walls) > 256:
            del self._step_walls[: len(self._step_walls) - 256]
        flops = self.step_flops(n_samples)
        if flops is None or wall_s <= 0:
            return None
        mfu = flops / self.n_chips / wall_s / self.peak_flops_per_chip
        self.last_mfu = mfu
        if self.registry is not None:
            self.registry.gauge(
                "mfu", help="model FLOPs utilization per chip (analytic estimator)"
            ).set(round(mfu, 6))
            self.registry.gauge(
                "model_flops_per_step", help="estimated model FLOPs per step (global)"
            ).set(flops)
        return mfu

    def predicted_wire_s(self) -> Optional[float]:
        if self.cost_model is None or not self.bucket_bytes:
            return None
        by_axis = self.predicted_wire_by_axis_s()
        if by_axis:
            # named mesh: the expected wire is the sum of the per-axis legs'
            # predictions, NOT the flat leg's — the flat leg mis-prices a
            # dp×tp/dp×fsdp plan and the error lands in ``unattributed``
            return float(sum(by_axis[ax] for ax in sorted(by_axis)))
        return predicted_wire_time(
            self.cost_model, self.bucket_bytes,
            hierarchical=self.hierarchical, wire_pattern=self.wire_pattern,
        )

    def predicted_wire_by_axis_s(self) -> Optional[Dict[str, float]]:
        """Per-axis α–β-predicted wire seconds for the live plan, or None
        when the plan is axis-blind (no ``exchange_axes``)."""
        if (self.cost_model is None or not self.bucket_bytes
                or not self.exchange_axes
                or not hasattr(self.cost_model, "axis_leg")):
            return None
        return predicted_axis_wire_time(
            self.cost_model, self.bucket_bytes, self.exchange_axes,
        )

    def observe_wire(self, measured_wire_s: float,
                     by_axis: Optional[Dict[str, float]] = None
                     ) -> Optional[float]:
        """Feed a *measured* per-step wire time (e.g. the device-trace
        analysis' ``collective_ms``) and update ``wire_efficiency`` =
        predicted / measured — 1.0 means the fabric delivered exactly what
        the fitted α–β model promised; below 1.0 the wire underdelivered
        (congestion, stragglers); above 1.0 the model is stale.  With
        ``by_axis`` (per-axis measured seconds) each axis additionally gets
        a ``wire_efficiency_<axis>`` gauge — the flat-name analog of a
        ``wire_efficiency{axis=...}`` labeled family."""
        predicted = self.predicted_wire_s()
        if predicted is None or measured_wire_s <= 0:
            return None
        eff = predicted / measured_wire_s
        self.last_wire_efficiency = eff
        if self.registry is not None:
            self.registry.gauge(
                "wire_efficiency",
                help="alpha-beta-predicted wire time / measured wire time",
            ).set(round(eff, 6))
            if by_axis:
                predicted_by_axis = self.predicted_wire_by_axis_s() or {}
                for ax, measured_ax in sorted(by_axis.items()):
                    pred_ax = predicted_by_axis.get(ax)
                    if pred_ax is None or measured_ax <= 0:
                        continue
                    self.registry.gauge(
                        f"wire_efficiency_{ax}",
                        help=("alpha-beta-predicted / measured wire time on "
                              f"mesh axis {ax}"),
                    ).set(round(pred_ax / measured_ax, 6))
        return eff

    # -- ledger feed (driven by the telemetry hub) ----------------------------

    def on_phase(self, phase: str) -> None:
        self.ledger.enter(_PHASE_BUCKET.get(phase, "data"))

    def on_compile(self, wall_s: float) -> None:
        """A (re)compile rode inside a dispatch: re-attribute its wall out
        of ``productive`` into ``compile``."""
        self.ledger.reattribute("productive", "compile", wall_s)

    def on_snapshot(self, kind: str, wall_ms: float) -> None:
        """Cadenced (``"async"``) snapshots ride the background writer —
        zero critical-path seconds, nothing to re-attribute.  Blocking kinds
        (anomaly/forced) stalled the step loop for the write."""
        if kind != "async":
            self.ledger.reattribute(self.ledger._cur, "snapshot", wall_ms / 1e3)

    def on_restart(self, lost_steps: int) -> None:
        walls = sorted(self._step_walls)
        p50 = walls[len(walls) // 2] if walls else 0.0
        self.ledger.charge("lost_restart", max(0, int(lost_steps)) * p50)

    def report(self) -> Dict:
        out = {
            "flops_per_sample": self.flops_per_sample,
            "peak_flops_per_chip": self.peak_flops_per_chip,
            "n_chips": self.n_chips,
            "mfu": self.last_mfu,
            "wire_efficiency": self.last_wire_efficiency,
            "predicted_wire_s": self.predicted_wire_s(),
            "predicted_wire_by_axis_s": self.predicted_wire_by_axis_s(),
            "ledger": self.ledger.report(),
        }
        return out
