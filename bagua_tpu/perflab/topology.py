"""The single ICI/DCN topology model shared by the perf lab and
``ci/scaling_projection.py``.

Every constant that used to live as a module global in the projection
script is an explicit field of :class:`TopologyAssumptions` so the
multi-pod ICI-vs-DCN split is a *stated model parameter*, refutable
measurement-by-measurement, not two diverging copies of a cost model.

The collective shapes are the planner's ring models (and the CollectiveIR's
``primitive_wire_bytes``): an all-reduce moves ``2N(n-1)/n`` per chip, a
reduce-scatter/all-to-all ``N(n-1)/n``, an all-gather ``N(n-1)``, and a
neighbor permute ``N`` over one hop.  Within one pod only ICI enters;
beyond ``pod_size`` chips the DP exchange additionally crosses DCN once per
step, shared by each host's chips, with no overlap credit (a worst-case
bound, not a prediction of the tuned multi-pod schedule).
"""

import dataclasses
import math

__all__ = [
    "DEFAULT_TOPOLOGY",
    "TopologyAssumptions",
    "t_axis_collective",
    "t_collective",
    "torus_dims",
]


@dataclasses.dataclass(frozen=True)
class TopologyAssumptions:
    """Explicit, falsifiable fleet-topology parameters (v5e-flavored)."""

    #: usable ICI injection bandwidth per chip, B/s (2D torus, 4×45 GB/s
    #: links at a conservative 50% efficiency — PERF_AUDIT.md's roofline)
    ici_bw_chip: float = 90e9
    #: per-hop ICI latency, seconds
    ici_lat_hop: float = 1e-6
    #: fraction of the step a collective can hide behind (the backward)
    overlap_window_frac: float = 2 / 3
    #: chips in one pod; beyond this the DCN leg enters
    pod_size: int = 256
    #: per-host DCN bandwidth, B/s (conservative)
    dcn_bw_host: float = 25e9
    #: chips sharing one host's DCN links
    chips_per_host: int = 8
    #: async averager: steps per sync interval (amortization)
    steps_per_interval: int = 20

    def dcn_bw_chip(self) -> float:
        """Per-chip share of the host's DCN bandwidth."""
        return self.dcn_bw_host / self.chips_per_host

    def axis_link(self, axis: str, within_pod: bool = False) -> str:
        """The physical leg one named mesh axis's collectives ride in the
        projected fleet layout: model axes (tp/sp/ep/...) are packed inside
        a pod slice and ride ICI; data axes (dp/fsdp/...) span hosts and
        ride DCN once the gang outgrows one pod (``within_pod=False``).
        The legacy hierarchical names keep their historical placement:
        ``intra`` is ICI, ``inter`` follows the data-axis rule."""
        from bagua_tpu.mesh import MODEL_AXIS_NAMES

        if axis == "intra" or axis in MODEL_AXIS_NAMES or within_pod:
            return "ici"
        return "dcn"

    def describe(self) -> dict:
        return {
            "ici_bw_chip_GBps": self.ici_bw_chip / 1e9,
            "ici_lat_per_hop_us": self.ici_lat_hop * 1e6,
            "overlap_window_frac_of_step": self.overlap_window_frac,
            "pod_size": self.pod_size,
            "dcn_GBps_per_host": self.dcn_bw_host / 1e9,
            "chips_per_host": self.chips_per_host,
            "async_steps_per_interval": self.steps_per_interval,
            "collective_model": (
                "ring/torus: allreduce 2(n-1)/n, gather/a2a (n-1)/n, "
                "permute 1 hop; multi-pod adds wire_bytes / dcn_bw_chip "
                "with no overlap credit"
            ),
        }


DEFAULT_TOPOLOGY = TopologyAssumptions()


def torus_dims(n: int):
    """Closest-to-square 2D factorization (v5e topology shapes)."""
    a = max(1, int(math.sqrt(n)))
    while n % a:
        a -= 1
    return a, n // a


def t_collective(
    kind: str,
    bytes_per_chip: float,
    n: int,
    topo: TopologyAssumptions = DEFAULT_TOPOLOGY,
) -> float:
    """Per-chip time of one collective over ``n`` chips on the ICI torus."""
    if n <= 1:
        return 0.0
    dx, dy = torus_dims(n)
    diameter = dx / 2 + dy / 2  # torus wrap-around halves each dim
    lat = diameter * topo.ici_lat_hop
    if kind == "allreduce":
        return 2 * (n - 1) / n * bytes_per_chip / topo.ici_bw_chip + 2 * lat
    if kind in ("allgather", "alltoall", "reducescatter"):
        return (n - 1) / n * bytes_per_chip / topo.ici_bw_chip + lat
    if kind == "permute":  # neighbor exchange: one hop, n-independent
        return bytes_per_chip / topo.ici_bw_chip + topo.ici_lat_hop
    raise ValueError(kind)


def t_axis_collective(
    kind: str,
    bytes_per_chip: float,
    n: int,
    axis: str,
    topo: TopologyAssumptions = DEFAULT_TOPOLOGY,
    within_pod: bool = False,
) -> float:
    """Per-chip time of one collective riding a *named mesh axis* of size
    ``n``: the axis's :meth:`TopologyAssumptions.axis_link` picks the wire.
    ICI legs reuse :func:`t_collective`'s torus model; DCN legs pay the same
    ring byte factor on the per-chip DCN share with no torus latency term
    (host NICs, worst-case bound — the same model as the multi-pod rows)."""
    if n <= 1:
        return 0.0
    if topo.axis_link(axis, within_pod) == "ici":
        return t_collective(kind, bytes_per_chip, n, topo)
    if kind == "allreduce":
        factor = 2 * (n - 1) / n
    elif kind in ("allgather", "alltoall", "reducescatter"):
        factor = (n - 1) / n
    elif kind == "permute":
        factor = 1.0
    else:
        raise ValueError(kind)
    return factor * bytes_per_chip / topo.dcn_bw_chip()
