"""Simulated-fleet performance lab: modeled perf evidence without a chip.

The container's TPU relay has never produced a measurement
(``accepted-then-dropped``), so the repo's perf trajectory must come from a
*model* whose every input is independently proven.  This package closes that
loop (ROADMAP item 5) with two halves:

* **Modeled step-time engine** (:mod:`~bagua_tpu.perflab.engine`): trace the
  real sharded step over abstract shapes (the static verifier's trace,
  PR 11), take the CollectiveIR's exact per-leg wire bytes (census-proved
  against the planner's analytic models), price each leg through the
  planner's fitted α–β :class:`~bagua_tpu.service.planner.CostModel`, count
  the traced matmul/conv FLOPs for the compute span, and compose them with
  an explicit overlap-window assumption into a deterministic
  ``modeled_step_ms`` / ``modeled_goodput`` per algorithm × wire precision ×
  overlap cell (``ci/bench_modeled.py`` → ``BENCH_MODELED.json``).

* **Fleet simulator** (:mod:`~bagua_tpu.perflab.fleetsim`): a discrete-event
  simulation of N gangs of modeled step clocks with injectable stragglers,
  bandwidth collapse, preemption and KV flaps, driving the *real* host-side
  machinery — :class:`~bagua_tpu.observability.aggregate.GangAggregator`
  pushes, straggler scoring, flight-recorder digests, breaker/retry paths —
  against a live rendezvous service, entirely on CPU.

The shared ICI/DCN topology assumptions live in
:mod:`~bagua_tpu.perflab.topology`; ``ci/scaling_projection.py`` imports
them so the repo has exactly one α–β/topology model, not two diverging
copies.
"""

from bagua_tpu.perflab.compute import compute_time_s, flops_census
from bagua_tpu.perflab.costbridge import (
    LEG_FOR_PRIMITIVE,
    PricedProgram,
    census_wire_bytes,
    price_program,
)
from bagua_tpu.perflab.engine import (
    ModeledCell,
    model_step_cell,
    modeled_bench_rows,
    pallas_kernel_basis,
)
from bagua_tpu.perflab.fleetsim import (
    BandwidthCollapse,
    FleetConfig,
    FlakyClient,
    KVFlap,
    Preemption,
    Straggler,
    run_fleet,
)
from bagua_tpu.perflab.topology import (
    DEFAULT_TOPOLOGY,
    TopologyAssumptions,
    t_axis_collective,
    t_collective,
    torus_dims,
)

__all__ = [
    "BandwidthCollapse",
    "DEFAULT_TOPOLOGY",
    "FleetConfig",
    "FlakyClient",
    "KVFlap",
    "LEG_FOR_PRIMITIVE",
    "ModeledCell",
    "Preemption",
    "PricedProgram",
    "Straggler",
    "TopologyAssumptions",
    "census_wire_bytes",
    "compute_time_s",
    "flops_census",
    "model_step_cell",
    "modeled_bench_rows",
    "pallas_kernel_basis",
    "price_program",
    "run_fleet",
    "t_axis_collective",
    "t_collective",
    "torus_dims",
]
