"""Traced compute spans: FLOP census of a step jaxpr.

The modeled step time needs a compute term from the *same trace* that
yields the CollectiveIR, so the provenance chain stays single-source: one
``jax.make_jaxpr`` of the engine's sharded step gives both the wire program
(collectives with exact ring-model bytes) and the compute program (every
``dot_general`` / ``conv_general_dilated`` with its local, per-shard
shapes — the walker descends into the ``shard_map`` sub-jaxpr, so the
counted shapes are per-chip).

Control flow: a ``scan`` body is multiplied by its trip count, sibling
``cond`` branches contribute their maximum (only one executes), a
``while`` body is counted once (trip count is unknowable statically — the
engine's step programs carry no compute-bearing whiles today), and a
``custom_jvp``/``custom_vjp`` call counts only its primal ``call_jaxpr``
(the fwd/bwd thunks shadow the same math).

The census is FLOPs, not seconds; :func:`compute_time_s` turns it into a
compute span under an explicit peak-FLOPs × assumed-MFU model (both
recorded in BENCH_MODELED.json's assumptions block).
"""

from typing import Dict

from jax._src import core as jcore

from bagua_tpu.observability.goodput import PEAK_FLOPS_PER_CHIP

__all__ = ["compute_time_s", "flops_census"]


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


def _dot_flops(eqn) -> float:
    """2·batch·M·N·K for one ``dot_general``."""
    lhs, rhs = (v.aval for v in eqn.invars[:2])
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    batch = _prod(lhs.shape[i] for i in lb)
    contract = _prod(lhs.shape[i] for i in lc)
    lhs_free = _prod(
        d for i, d in enumerate(lhs.shape) if i not in lc and i not in lb
    )
    rhs_free = _prod(
        d for i, d in enumerate(rhs.shape) if i not in rc and i not in rb
    )
    return 2.0 * batch * lhs_free * rhs_free * contract


def _conv_flops(eqn) -> float:
    """2 · output elements · reduction depth for one conv."""
    rhs = eqn.invars[1].aval
    out = eqn.outvars[0].aval
    try:
        out_ch_dim = eqn.params["dimension_numbers"].rhs_spec[0]
        out_ch = int(rhs.shape[out_ch_dim])
    except Exception:  # defensive: dimension-number layout drift
        out_ch = int(max(rhs.shape))
    reduction = _prod(rhs.shape) / max(1, out_ch)
    groups = int(eqn.params.get("feature_group_count", 1) or 1)
    return 2.0 * _prod(out.shape) * reduction / groups


def _closed(j):
    return j.jaxpr if isinstance(j, jcore.ClosedJaxpr) else j


def _walk(jaxpr) -> Dict[str, float]:
    tot = {"flops": 0.0, "dot_flops": 0.0, "conv_flops": 0.0,
           "n_dots": 0, "n_convs": 0}

    def add(sub: Dict[str, float], scale: float = 1.0):
        tot["flops"] += sub["flops"] * scale
        tot["dot_flops"] += sub["dot_flops"] * scale
        tot["conv_flops"] += sub["conv_flops"] * scale
        tot["n_dots"] += sub["n_dots"]
        tot["n_convs"] += sub["n_convs"]

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            f = _dot_flops(eqn)
            tot["flops"] += f
            tot["dot_flops"] += f
            tot["n_dots"] += 1
            continue
        if name == "conv_general_dilated":
            f = _conv_flops(eqn)
            tot["flops"] += f
            tot["conv_flops"] += f
            tot["n_convs"] += 1
            continue
        if name == "cond":
            branches = [
                _walk(_closed(b)) for b in eqn.params.get("branches", ())
            ]
            if branches:
                add(max(branches, key=lambda s: s["flops"]))
            continue
        if name == "scan":
            length = int(eqn.params.get("length", 1) or 1)
            add(_walk(_closed(eqn.params["jaxpr"])), scale=length)
            continue
        if "custom_jvp" in name or "custom_vjp" in name:
            cj = eqn.params.get("call_jaxpr")
            if cj is not None:
                add(_walk(_closed(cj)))
            continue
        for v in eqn.params.values():
            vs = v if isinstance(v, (tuple, list)) else (v,)
            for w in vs:
                if isinstance(w, (jcore.ClosedJaxpr, jcore.Jaxpr)):
                    add(_walk(_closed(w)))
    return tot


def flops_census(closed_jaxpr) -> Dict[str, float]:
    """Per-chip matmul/conv FLOPs of one traced step program."""
    out = _walk(_closed(closed_jaxpr))
    out["n_dots"] = int(out["n_dots"])
    out["n_convs"] = int(out["n_convs"])
    return out


def compute_time_s(flops: float, chip: str = "v5e", mfu: float = 0.3) -> float:
    """Modeled compute span: traced FLOPs at ``mfu`` of the chip's peak.

    ``mfu`` is an explicit assumption (BENCH_MODELED.json records it) — the
    modeled *trend* across algorithms/precisions is exact in the wire term
    and shares one compute scale factor, so ranking is insensitive to it.
    """
    peak = PEAK_FLOPS_PER_CHIP[chip]
    denom = peak * mfu
    if denom <= 0:
        raise ValueError(f"non-positive effective peak: {chip=} {mfu=}")
    return float(flops) / denom
