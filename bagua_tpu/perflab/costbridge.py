"""Price a CollectiveIR through the planner's fitted α–β cost model.

This is the provenance hinge of the modeled bench: the *bytes* come from
the static verifier's CollectiveIR (per-descriptor ring-model wire bytes,
proved equal to the planner's analytic models by ``check_wire_exactness``),
and the *seconds* come from the planner's per-leg
:class:`~bagua_tpu.service.planner.CostModel` (fitted from recorded
:class:`~bagua_tpu.service.planner.WireSample` spans, priors otherwise).
Each issued collective pays its leg's α once; the branch-deduped wire bytes
pay β.  :func:`census_wire_bytes` and :func:`price_program` walk the same
grouping and the same cond-sibling dedup (the verifier's: only one branch
executes, so siblings contribute their max), so summed modeled bytes equal
the census bytes *by construction* — the equality BENCH_MODELED.json
asserts per row.

Leg mapping (the planner's :class:`WireSample` vocabulary):

* quantized-ring hops (``qr`` scope) → ``qr8`` / ``qr4``
* ``reduce_scatter`` → ``rs``; ``all_gather`` → ``ag`` (zero's two legs)
* bare ``ppermute`` → ``pp`` (collective-matmul / decentralized rings)
* ``psum``/``pmax``/``pmin``/``all_to_all`` → ``flat`` (or ``intra`` /
  ``inter`` when the descriptor spans exactly that hierarchical axis)
"""

import dataclasses
from typing import Dict, List, Optional, Tuple

from bagua_tpu.analysis.checks import WireModelConfig, _branch_deduped_bytes
from bagua_tpu.analysis.collective_ir import (
    CollectiveDescriptor,
    CollectiveProgram,
)
from bagua_tpu.service.planner import CostModel

__all__ = [
    "LEG_FOR_PRIMITIVE",
    "PricedProgram",
    "census_wire_bytes",
    "classify_leg",
    "price_program",
]

#: primitive → default α–β leg (before qr/hierarchy refinement)
LEG_FOR_PRIMITIVE = {
    "psum": "flat",
    "pmax": "flat",
    "pmin": "flat",
    "all_to_all": "flat",
    "reduce_scatter": "rs",
    "all_gather": "ag",
    "ppermute": "pp",
}


def classify_leg(d: CollectiveDescriptor, cfg: Optional[WireModelConfig]) -> str:
    """The cost-model leg one descriptor's bytes travel on."""
    if d.qr is not None:
        return "qr8" if d.qr["bits"] == 8 else "qr4"
    leg = LEG_FOR_PRIMITIVE[d.primitive]
    if (
        leg == "flat"
        and cfg is not None
        and cfg.hierarchical
        and len(d.axes) == 1
        and d.axes[0] in ("intra", "inter")
    ):
        return d.axes[0]
    if (
        leg == "flat"
        and cfg is not None
        and len(d.axes) == 1
        and d.axes[0] in getattr(cfg, "mesh_axes", ())
    ):
        # Named-mesh engines: a single-axis collective rides that axis's
        # link (dp ring on DCN, tp ring on ICI, ...); price it on the
        # per-axis fitted leg (CostModel.axis_leg falls back to flat).
        return f"axis:{d.axes[0]}"
    return leg


def _cond_path(d: CollectiveDescriptor) -> Tuple[str, ...]:
    return tuple(p for p in d.path if p.startswith("cond#"))


def _grouped(
    program: CollectiveProgram, cfg: Optional[WireModelConfig]
) -> Dict[Tuple, List[CollectiveDescriptor]]:
    """Shared grouping for census and pricing: ``(algo, bucket, phase,
    leg)`` for labeled descriptors (the verifier's wire-census groups,
    refined by leg), ``(None, None, primitive, leg)`` for unlabeled ones."""
    groups: Dict[Tuple, List[CollectiveDescriptor]] = {}
    for d in program.collectives:
        leg = classify_leg(d, cfg)
        if d.scope is not None:
            key = (d.scope["algo"], d.scope["bucket"], d.scope["phase"], leg)
        else:
            key = (None, None, d.primitive, leg)
        groups.setdefault(key, []).append(d)
    return groups


def _deduped(descs: List[CollectiveDescriptor], value_fn) -> int:
    return _branch_deduped_bytes([(_cond_path(d), value_fn(d)) for d in descs])


def census_wire_bytes(
    program: CollectiveProgram, cfg: Optional[WireModelConfig] = None
) -> int:
    """Branch-deduped per-chip wire bytes of one traced step, summed over
    the same groups :func:`price_program` charges — the modeled-bytes ==
    census-bytes equality is definitional, and within each labeled group
    the dedup is exactly the verifier's wire-table dedup."""
    return sum(
        _deduped(descs, lambda d: d.wire_bytes)
        for descs in _grouped(program, cfg).values()
    )


@dataclasses.dataclass
class PricedProgram:
    """One step program priced leg by leg."""

    rows: List[Dict]          #: per (scope, leg) group: bytes, count, seconds
    total_wire_bytes: int     #: branch-deduped; == :func:`census_wire_bytes`
    total_wire_s: float
    legs_used: List[str]

    def by_leg(self) -> Dict[str, Dict]:
        out: Dict[str, Dict] = {}
        for r in self.rows:
            agg = out.setdefault(
                r["leg"], {"wire_bytes": 0, "collectives": 0, "seconds": 0.0}
            )
            agg["wire_bytes"] += r["wire_bytes"]
            agg["collectives"] += r["collectives"]
            agg["seconds"] += r["seconds"]
        return out


def price_program(
    program: CollectiveProgram,
    cost_model: CostModel,
    cfg: Optional[WireModelConfig] = None,
) -> PricedProgram:
    """Charge every collective of one traced step to its α–β leg.

    Within a group the cond-sibling dedup runs over bytes *and* issue
    counts, then ``seconds = count·α + bytes/β``.
    """
    legs = {
        "flat": cost_model.flat, "intra": cost_model.intra,
        "inter": cost_model.inter, "rs": cost_model.rs, "ag": cost_model.ag,
        "pp": cost_model.pp, "qr8": cost_model.qr8, "qr4": cost_model.qr4,
    }
    rows: List[Dict] = []
    total_bytes = 0
    total_s = 0.0
    for (algo, bucket, phase, leg), descs in _grouped(program, cfg).items():
        nbytes = _deduped(descs, lambda d: d.wire_bytes)
        count = _deduped(descs, lambda d: 1)
        if leg.startswith("axis:"):
            ab = cost_model.axis_leg(leg[len("axis:"):])
        else:
            ab = legs[leg]
        seconds = count * ab.alpha + nbytes / ab.beta
        rows.append({
            "algo": algo, "bucket": bucket, "phase": phase, "leg": leg,
            "collectives": count, "wire_bytes": nbytes,
            "seconds": seconds,
        })
        total_bytes += nbytes
        total_s += seconds
    return PricedProgram(
        rows=rows,
        total_wire_bytes=total_bytes,
        total_wire_s=total_s,
        legs_used=sorted({r["leg"] for r in rows}),
    )
