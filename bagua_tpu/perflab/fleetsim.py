"""Fleet-scale fault injection against the real host-side machinery.

A discrete-event simulation of N gangs, each gang a set of per-rank modeled
step clocks (compute span + wire span from the perf lab's model, plus
deterministic seeded jitter).  The *clocks* are simulated; everything they
drive is the production code path, unmodified:

* per-rank :class:`~bagua_tpu.observability.aggregate.StepSummary` pushes
  through a **live** rendezvous KV service
  (:func:`~bagua_tpu.distributed.rendezvous.start_rendezvous_server`),
* rank-0 :class:`~bagua_tpu.observability.aggregate.GangAggregator`
  collect/aggregate with its straggler scoring and local-only degradation,
* :func:`~bagua_tpu.observability.flight_recorder.push_flight_digest`
  breadcrumbs,
* the shared :class:`~bagua_tpu.resilience.retry.CircuitBreaker` open →
  half-open-probe → reclose arc.

Faults are injected at the only two honest seams: the step clocks
(:class:`Straggler`, :class:`BandwidthCollapse`, :class:`Preemption`) and
the KV transport (:class:`KVFlap`, via :class:`FlakyClient`).  If a fault's
signature fails to surface in the gang view — or a KV flap leaks an
exception into the "training" loop — that is a real bug in the production
observability/resilience code, found without a TPU.

Everything in :func:`run_fleet`'s report is deterministic under a fixed
seed (no wall-clock, no real port numbers), so two runs diff clean.
"""

import dataclasses
import random
import statistics
from typing import Callable, Dict, List, Optional, Tuple

from bagua_tpu.observability.aggregate import GangAggregator, StepSummary
from bagua_tpu.observability.flight_recorder import (
    FlightRecorder,
    push_flight_digest,
)
from bagua_tpu.resilience.retry import CircuitBreaker

__all__ = [
    "BandwidthCollapse",
    "FleetConfig",
    "FlakyClient",
    "KVFlap",
    "KVFlapStorm",
    "Preemption",
    "PreemptionStorm",
    "Straggler",
    "churn_schedule",
    "run_fleet",
]


# ---------------------------------------------------------------------------
# Fault vocabulary
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Straggler:
    """One rank's phase runs ``factor`` slow over ``[start, end)`` windows.

    ``ramp_windows`` makes the fault *transient-shaped*: instead of landing
    at the full factor on its first active window, the slowdown climbs
    linearly over ``ramp_windows`` windows before plateauing (onset →
    ramp → plateau → heal at ``end_window``).  The straggler-tolerance
    lane uses this to prove the degradation ladder rides the whole arc:
    indictment on the ramp, bounded-staleness at the plateau, and the
    guardrail's return to bulk sync after the fault clears."""

    gang: int
    rank: int
    factor: float = 2.0
    phase: str = "wire"  #: "wire" or "compute" — attribution target
    start_window: int = 0
    end_window: Optional[int] = None
    ramp_windows: int = 0

    def active(self, window: int) -> bool:
        return self.start_window <= window and (
            self.end_window is None or window < self.end_window
        )

    def effective_factor(self, window: int) -> float:
        """The slowdown this window actually applies: 1.0 outside the
        active span, a linear climb toward ``factor`` during the ramp,
        the full factor at the plateau."""
        if not self.active(window):
            return 1.0
        elapsed = window - self.start_window
        if elapsed < self.ramp_windows:
            frac = (elapsed + 1) / (self.ramp_windows + 1)
            return 1.0 + (self.factor - 1.0) * frac
        return self.factor


@dataclasses.dataclass(frozen=True)
class BandwidthCollapse:
    """A gang's wire span inflates by ``factor`` (ICI brownout / DCN
    congestion).  With ``axis`` set — and the fleet configured with per-axis
    wire spans (:attr:`FleetConfig.axis_wire_ms`) — only that mesh axis's
    modeled wire leg collapses, so the gang view's per-axis medians carry
    the axis signature a per-axis regression sentinel must attribute."""

    gang: int
    factor: float = 4.0
    start_window: int = 0
    end_window: Optional[int] = None
    axis: Optional[str] = None

    def active(self, window: int) -> bool:
        return self.start_window <= window and (
            self.end_window is None or window < self.end_window
        )


@dataclasses.dataclass(frozen=True)
class Preemption:
    """One rank stops reporting from ``window`` on (host reclaimed).  Its
    last KV summary stays behind — the gang view must surface the
    staleness, not silently average a ghost."""

    gang: int
    rank: int
    window: int

    def active(self, window: int) -> bool:
        return window >= self.window


@dataclasses.dataclass(frozen=True)
class KVFlap:
    """The gang's KV transport fails over ``[start, end)`` windows.  The
    breaker must absorb it (open, then reclose on the first post-flap
    probe) with zero exceptions reaching the step loop."""

    gang: int
    start_window: int = 0
    end_window: Optional[int] = None

    def active(self, window: int) -> bool:
        return self.start_window <= window and (
            self.end_window is None or window < self.end_window
        )


@dataclasses.dataclass(frozen=True)
class PreemptionStorm:
    """Fleet-scale churn profile: a seeded ``fraction`` of all gangs each
    lose one rank at ``window`` (a zone reclaim hitting many tenants at
    once).  :meth:`expand` materializes the concrete per-gang
    :class:`Preemption` faults — deterministic under the seed, so a storm
    at 1000 gangs diffs clean across runs."""

    fraction: float = 0.1
    window: int = 2
    rank: int = 1

    def expand(self, n_gangs: int, seed: int = 0) -> List[Preemption]:
        rng = random.Random(1_000_033 * seed + 7)
        hit = rng.sample(range(n_gangs), max(1, int(n_gangs * self.fraction)))
        return [Preemption(gang=g, rank=self.rank, window=self.window)
                for g in sorted(hit)]


@dataclasses.dataclass(frozen=True)
class KVFlapStorm:
    """Fleet-scale churn profile: a seeded ``fraction`` of all gangs lose
    their KV transport over ``[start, end)`` windows simultaneously (a
    control-plane brownout as seen from the tenants)."""

    fraction: float = 0.1
    start_window: int = 1
    end_window: Optional[int] = 2

    def expand(self, n_gangs: int, seed: int = 0) -> List[KVFlap]:
        rng = random.Random(1_000_037 * seed + 11)
        hit = rng.sample(range(n_gangs), max(1, int(n_gangs * self.fraction)))
        return [KVFlap(gang=g, start_window=self.start_window,
                       end_window=self.end_window)
                for g in sorted(hit)]


def churn_schedule(
    n_gangs: int,
    seed: int = 0,
    preempt_fraction: float = 0.1,
    flap_fraction: float = 0.1,
    windows: int = 3,
) -> Tuple:
    """The default storm mix the 1000-gang scale lane drives: a preemption
    storm mid-run plus a KV-flap brownout in the first window (disjoint
    RNG streams, so the two storms hit independent gang subsets).  Returns
    a concrete fault tuple for :attr:`FleetConfig.faults`."""
    storm = PreemptionStorm(
        fraction=preempt_fraction, window=max(2, windows // 2 + 1)
    )
    flap = KVFlapStorm(fraction=flap_fraction, start_window=1, end_window=2)
    return tuple(storm.expand(n_gangs, seed) + flap.expand(n_gangs, seed))


class FlakyClient:
    """A rendezvous client wrapper whose transport can be failed on demand.

    Injection lives here — the wrapped client and everything above it is
    production code.  While ``failing`` every KV verb raises, exactly like
    a dead coordinator mid-``urlopen``."""

    def __init__(self, inner):
        self._inner = inner
        self.failing = False
        self.calls = 0
        self.injected_failures = 0

    def _gate(self):
        self.calls += 1
        if self.failing:
            self.injected_failures += 1
            raise ConnectionError("injected KV flap")

    def kv_set(self, key, value):
        self._gate()
        return self._inner.kv_set(key, value)

    def kv_get(self, key):
        self._gate()
        return self._inner.kv_get(key)

    def heartbeat(self):
        self._gate()
        return self._inner.heartbeat()


# ---------------------------------------------------------------------------
# Fleet configuration
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FleetConfig:
    """One fleet run: N gangs × M ranks × W windows of modeled clocks."""

    n_gangs: int = 4
    ranks_per_gang: int = 4
    windows: int = 3
    seed: int = 0
    #: baseline modeled spans per step, ms (e.g. a ModeledCell's
    #: ``compute_ms`` / ``exposed_wire_ms``); jitter is ±3% seeded
    compute_ms: float = 6.0
    wire_ms: float = 4.0
    #: optional per-mesh-axis split of the wire span (ms per axis).  When
    #: set it REPLACES ``wire_ms`` as the modeled wire (the base wire is the
    #: sum of the axis spans) and every rank summary's ``phase_ms`` gains
    #: ``wire_<axis>`` sub-spans, so an axis-scoped
    #: :class:`BandwidthCollapse` surfaces per axis in the gang view.
    axis_wire_ms: Optional[Dict[str, float]] = None
    steps_per_window: int = 20
    global_batch: int = 256
    straggler_factor: float = 1.5  #: detection threshold, not injection
    #: tight breaker so one flap window exercises the full open/reclose arc
    breaker_threshold: int = 2
    breaker_cooldown_s: float = 0.0
    faults: Tuple = ()

    def base_wire_ms(self) -> float:
        """The fault-free modeled wire span (the sum of the axis spans when
        the wire is split per axis)."""
        if self.axis_wire_ms:
            return float(sum(self.axis_wire_ms[ax]
                             for ax in sorted(self.axis_wire_ms)))
        return float(self.wire_ms)

    def fault_descriptions(self) -> List[Dict]:
        return [
            {"kind": type(f).__name__, **dataclasses.asdict(f)}
            for f in self.faults
        ]


def _rank_step_ms(
    cfg: FleetConfig, gang: int, rank: int, window: int, rng: random.Random
) -> Tuple[float, Dict[str, float]]:
    """One rank's modeled step p50 for one window, faults applied."""
    compute = cfg.compute_ms
    axis_parts = (
        {str(ax): float(cfg.axis_wire_ms[ax]) for ax in sorted(cfg.axis_wire_ms)}
        if cfg.axis_wire_ms else None
    )
    wire = sum(axis_parts.values()) if axis_parts else cfg.wire_ms
    for f in cfg.faults:
        if not f.active(window) or getattr(f, "gang", None) != gang:
            continue
        if isinstance(f, BandwidthCollapse):
            if axis_parts is not None:
                # axis-scoped collapse hits only the indicted axis's span;
                # an axis-less collapse browns out every leg
                hit = [f.axis] if f.axis else list(axis_parts)
                for ax in hit:
                    if ax in axis_parts:
                        axis_parts[ax] *= f.factor
                wire = sum(axis_parts.values())
            else:
                wire *= f.factor
        elif isinstance(f, Straggler) and f.rank == rank:
            eff = f.effective_factor(window)
            if f.phase == "compute":
                compute *= eff
            else:
                wire *= eff
                if axis_parts is not None:
                    for ax in axis_parts:
                        axis_parts[ax] *= eff
    jitter = 1.0 + 0.03 * (2.0 * rng.random() - 1.0)
    phase_ms = {"compute": round(compute * jitter, 6),
                "wire": round(wire * jitter, 6)}
    if axis_parts is not None:
        for ax in sorted(axis_parts):
            phase_ms[f"wire_{ax}"] = round(axis_parts[ax] * jitter, 6)
    return (compute + wire) * jitter, phase_ms


def _is_preempted(cfg: FleetConfig, gang: int, rank: int, window: int) -> bool:
    return any(
        isinstance(f, Preemption)
        and f.gang == gang and f.rank == rank and f.active(window)
        for f in cfg.faults
    )


def _kv_flapping(cfg: FleetConfig, gang: int, window: int) -> bool:
    return any(
        isinstance(f, KVFlap) and f.gang == gang and f.active(window)
        for f in cfg.faults
    )


# ---------------------------------------------------------------------------
# The simulation loop
# ---------------------------------------------------------------------------


def run_fleet(
    cfg: FleetConfig,
    endpoint: Optional[str] = None,
    gang_endpoint: Optional[Callable[[int], str]] = None,
) -> Dict:
    """Run the fleet; returns a deterministic per-gang verdict report.

    When ``endpoint`` is None a private rendezvous server is started on a
    loopback ephemeral port and torn down before returning.  Clients use
    the KV verbs only (never ``join``), so the shared server's membership
    machine is untouched and ``heartbeat`` deterministically reports no
    member ages.

    ``gang_endpoint`` maps a gang index to its own endpoint — how the fleet
    load lane points each simulated gang at its ``/g/<gang_id>`` namespace
    on one multi-tenant control plane.  Overrides ``endpoint`` per gang.
    """
    from bagua_tpu.distributed.rendezvous import (
        RendezvousState,
        start_rendezvous_server,
    )

    server = None
    if endpoint is None and gang_endpoint is None:
        state = RendezvousState(min_nodes=1, settle_s=0.05)
        server = start_rendezvous_server(state, 0, host="127.0.0.1")
        endpoint = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        return _run(cfg, endpoint, gang_endpoint)
    finally:
        if server is not None:
            server.shutdown()


def _run(
    cfg: FleetConfig,
    endpoint: Optional[str],
    gang_endpoint: Optional[Callable[[int], str]] = None,
) -> Dict:
    from bagua_tpu.distributed.rendezvous import RendezvousClient

    gangs = []
    for g in range(cfg.n_gangs):
        client = FlakyClient(
            RendezvousClient(
                gang_endpoint(g) if gang_endpoint is not None else endpoint,
                node_rank=0,
                timeout_s=10.0,
            )
        )
        # one aggregator per rank, all sharing the gang's transport and a
        # per-gang attempt nonce so KV keys never collide across gangs
        attempt = f"sim-g{g}"
        aggs = [
            GangAggregator(
                client,
                rank=r,
                world_size=cfg.ranks_per_gang,
                attempt=attempt,
                window=cfg.steps_per_window,
                straggler_factor=cfg.straggler_factor,
                breaker=CircuitBreaker(
                    failure_threshold=cfg.breaker_threshold,
                    cooldown_s=cfg.breaker_cooldown_s,
                    name=f"sim-g{g}r{r}",
                ),
            )
            for r in range(cfg.ranks_per_gang)
        ]
        rngs = [
            random.Random(1_000_003 * cfg.seed + 1_009 * g + r)
            for r in range(cfg.ranks_per_gang)
        ]
        recorder = FlightRecorder(
            capacity=8, rank=0, world_size=cfg.ranks_per_gang
        )
        gangs.append({
            "client": client, "aggs": aggs, "rngs": rngs,
            "recorder": recorder, "attempt": attempt,
            "windows": [], "errors": [],
        })

    for window in range(1, cfg.windows + 1):
        step = window * cfg.steps_per_window
        for g, gang in enumerate(gangs):
            gang["client"].failing = _kv_flapping(cfg, g, window)
            view = None
            try:
                # non-coordinator ranks push first, then rank 0 aggregates
                # — one simulated window boundary
                for r in range(1, cfg.ranks_per_gang):
                    if _is_preempted(cfg, g, r, window):
                        continue
                    p50, phase_ms = _rank_step_ms(
                        cfg, g, r, window, gang["rngs"][r]
                    )
                    gang["aggs"][r].push(_summary(cfg, r, step, window,
                                                  p50, phase_ms))
                p50, phase_ms = _rank_step_ms(cfg, g, 0, window,
                                              gang["rngs"][0])
                view = gang["aggs"][0].aggregate(
                    _summary(cfg, 0, step, window, p50, phase_ms)
                )
            except Exception as exc:  # must never happen: the step loop saw it
                gang["errors"].append(f"window {window}: {exc!r}")
            gang["windows"].append(_window_verdict(cfg, g, window, step, view))

    # post-run: one flight-digest push per gang, transport healthy again
    for gang in gangs:
        gang["client"].failing = False
        gang["digest_pushed"] = push_flight_digest(
            gang["client"], gang["recorder"],
            attempt=gang["attempt"], breaker=gang["aggs"][0].breaker,
        )

    return {
        "n_gangs": cfg.n_gangs,
        "ranks_per_gang": cfg.ranks_per_gang,
        "windows": cfg.windows,
        "seed": cfg.seed,
        "faults": cfg.fault_descriptions(),
        "gangs": [_gang_verdict(cfg, g, gang) for g, gang in enumerate(gangs)],
    }


def _summary(cfg: FleetConfig, rank: int, step: int, window: int,
             p50: float, phase_ms: Dict[str, float]) -> StepSummary:
    return StepSummary(
        rank=rank,
        step=step,
        window=cfg.steps_per_window,
        p50_ms=round(p50, 6),
        p99_ms=round(p50 * 1.15, 6),
        wire_bytes=int(phase_ms["wire"] * 1e6),  # nominal: bytes ∝ wire span
        mfu=round(0.3 * phase_ms["compute"] / p50, 6),
        samples_per_s=round(cfg.global_batch * 1e3 / p50, 3),
        phase_ms=phase_ms,
        health={},
    )


def _window_verdict(cfg: FleetConfig, gang: int, window: int, step: int,
                    view) -> Dict:
    if view is None:
        return {"window": window, "view": None}
    stale_ranks = sorted(
        s.rank for s in view.summaries if s.step < step
    )
    out = {
        "window": window,
        "ranks_reporting": view.ranks_reporting,
        "local_only": view.local_only,
        # absolute gang pace, not just skew: what an autopilot driver feeds
        # its regression sentinel to see a fault window's wire collapse
        "gang_p50_ms": round(view.p50_median, 4),
        "p50_skew": round(view.skew, 4),
        "straggler": view.straggler,
        "stale_ranks": stale_ranks,
    }
    # per-axis gang wire medians, present iff ranks report wire_<axis>
    # phase sub-spans — the per-axis sentinel's measured-wire feed
    axis_keys = sorted({
        k for s in view.summaries
        for k in (s.phase_ms or {}) if k.startswith("wire_")
    })
    if axis_keys:
        out["gang_wire_axis_ms"] = {
            k[len("wire_"):]: round(statistics.median(
                s.phase_ms[k] for s in view.summaries
                if k in (s.phase_ms or {})
            ), 4)
            for k in axis_keys
        }
    return out


def _gang_verdict(cfg: FleetConfig, g: int, gang: Dict) -> Dict:
    breaker = gang["aggs"][0].breaker
    detections = [
        {"window": w["window"], **w["straggler"]}
        for w in gang["windows"]
        if w.get("straggler")
    ]
    # detection is on the whole-step p50 ratio, not the phase factor: a
    # 2x-wire straggler with a large compute span may stay under threshold.
    # The 1.07 guard keeps ±3% jitter from flipping a marginal verdict.
    expected_stragglers = sorted({
        (f.rank, f.phase) for f in gang_faults(cfg, g, Straggler)
        if _expected_ratio(cfg, f) >= cfg.straggler_factor * 1.07
    })
    detected_pairs = sorted({(d["rank"], d["phase"]) for d in detections})
    flapped = bool(gang_faults(cfg, g, KVFlap))
    degraded_windows = [
        w["window"] for w in gang["windows"] if w.get("local_only")
    ]
    healthy = (
        not gang["errors"]
        and detected_pairs == expected_stragglers
        and breaker.state == "closed"
        and (breaker.times_opened >= 1) == flapped
        and gang["digest_pushed"]
    )
    return {
        "gang": g,
        "attempt": gang["attempt"],
        "errors": gang["errors"],
        "windows": gang["windows"],
        "straggler_detections": detections,
        "expected_stragglers": [list(p) for p in expected_stragglers],
        "kv_flap_injected": flapped,
        "degraded_windows": degraded_windows,
        "breaker": {
            "times_opened": breaker.times_opened,
            "final_state": breaker.state,
        },
        "kv_calls": gang["client"].calls,
        "kv_injected_failures": gang["client"].injected_failures,
        "flight_digest_pushed": gang["digest_pushed"],
        "healthy": healthy,
    }


def gang_faults(cfg: FleetConfig, gang: int, kind) -> List:
    return [f for f in cfg.faults
            if isinstance(f, kind) and f.gang == gang]


def _expected_ratio(cfg: FleetConfig, f: Straggler) -> float:
    """Peak whole-step slowdown this fault reaches inside the simulated
    window range (a transient straggler whose ramp never plateaus before
    ``end_window`` — or whose active span misses the run — peaks lower
    than its nominal factor)."""
    wire = cfg.base_wire_ms()
    base = cfg.compute_ms + wire
    peak = max(
        f.effective_factor(w) for w in range(1, cfg.windows + 1)
    )
    if f.phase == "compute":
        return (cfg.compute_ms * peak + wire) / base
    return (cfg.compute_ms + wire * peak) / base
