"""Modeled step-time engine: one trace → census bytes → α–β legs → ms.

:func:`model_step_cell` is the per-configuration worker behind
``ci/bench_modeled.py``.  It traces a live engine's sharded step over
abstract shapes (the static verifier's trace — nothing dispatches), runs
the four checkers over the extracted CollectiveIR, prices the IR's
branch-deduped wire bytes through the planner's per-leg cost model
(:mod:`~bagua_tpu.perflab.costbridge`), counts the traced matmul/conv
FLOPs (:mod:`~bagua_tpu.perflab.compute`) and composes the two spans under
the explicit overlap-window assumption of
:class:`~bagua_tpu.perflab.topology.TopologyAssumptions`:

    ``exposed = max(0, wire − window·compute)``   (overlap on)
    ``exposed = wire``                            (overlap off)
    ``modeled_step = compute + exposed``

Every number in the chain is either *proved* (bytes: ``check_wire_exactness``
holds them equal to the planner's analytic models), *fitted* (α–β legs from
recorded spans, priors when a leg has none) or *stated* (MFU, overlap
window, chip peak) — BENCH_MODELED.json records which is which.

Pallas honesty: cells whose wire program rides evidence-gated Pallas
kernels are marked via :func:`pallas_kernel_basis` — on this container the
evidence (PALLAS_TPU.json) is interpret-mode CPU, so such rows carry
``kernel_basis="modeled-jnp-fallback"`` rather than being silently priced
as if the fused kernels had chip evidence.
"""

import dataclasses
import json
import os
from typing import Dict, List, Optional

import jax

from bagua_tpu.analysis.checks import WireModelConfig
from bagua_tpu.analysis.collective_ir import extract_collective_ir
from bagua_tpu.analysis.verify import _abstract, verify_collective_program
from bagua_tpu.observability.flight_recorder import capture_program
from bagua_tpu.perflab.compute import compute_time_s, flops_census
from bagua_tpu.perflab.costbridge import census_wire_bytes, price_program
from bagua_tpu.perflab.topology import DEFAULT_TOPOLOGY, TopologyAssumptions
from bagua_tpu.service.planner import CostModel

__all__ = [
    "ModeledCell",
    "model_step_cell",
    "modeled_bench_rows",
    "pallas_kernel_basis",
]

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@dataclasses.dataclass
class ModeledCell:
    """One algorithm × wire × overlap configuration, modeled."""

    algo: str
    wire: str
    overlap: bool
    verified: bool                  #: the four checkers passed on this trace
    modeled_step_ms: float
    modeled_samples_per_s: float    #: global batch / modeled step
    modeled_goodput_frac: float     #: compute span / modeled step
    modeled_mfu: float              #: traced FLOPs / (modeled step · peak)
    compute_ms: float
    wire_ms: float
    exposed_wire_ms: float
    modeled_wire_bytes: int         #: priced bytes (== census, asserted)
    census_wire_bytes: int          #: branch-deduped IR bytes
    flops_per_step: float
    num_collectives: int
    legs_used: List[str]
    leg_breakdown: Dict[str, Dict]
    kernel_basis: Dict
    findings: List[str]
    #: the engine's mesh shape (``{"inter": 2, "intra": 4}`` or
    #: ``{"dp": 4, "tp": 2}``) — the cell key that lets BENCH_MODELED.json
    #: hold dp×tp / dp×fsdp cells alongside the 1-D rows
    mesh: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: axes the cell's gradient exchange rode (provenance for per-axis legs)
    exchange_axes: List[str] = dataclasses.field(default_factory=list)

    def to_json(self) -> Dict:
        d = dataclasses.asdict(self)
        for k in ("modeled_step_ms", "compute_ms", "wire_ms", "exposed_wire_ms"):
            d[k] = round(d[k], 6)
        d["modeled_samples_per_s"] = round(d["modeled_samples_per_s"], 3)
        d["modeled_goodput_frac"] = round(d["modeled_goodput_frac"], 6)
        d["modeled_mfu"] = round(d["modeled_mfu"], 6)
        for leg in d["leg_breakdown"].values():
            leg["seconds"] = round(leg["seconds"], 9)
        return d


def pallas_kernel_basis(
    algo: str, wire: str, evidence_path: Optional[str] = None
) -> Dict:
    """How the cell's kernel tier is priced: ``measured-chip`` only when
    PALLAS_TPU.json carries real-chip (non-interpret) evidence for every
    kernel the cell's wire program is gated on; ``modeled-jnp-fallback``
    otherwise (the dispatch layer runs the jnp oracle without evidence, so
    pricing must not assume the fused kernel).  Cells with no gated kernel
    are ``jnp-native``."""
    gated: List[str] = []
    if wire in ("int8", "int4"):
        gated = [f"quantized_ring_hop_{wire}", "decompress_reduce_requantize"]
    elif algo in ("bytegrad", "qadam") or (algo == "zero" and wire != "f32"):
        gated = ["minmax_uint8"]
    if not gated:
        return {"basis": "jnp-native", "gated_kernels": []}
    path = evidence_path or os.path.join(_REPO, "PALLAS_TPU.json")
    backend, interpret, known = "", True, set()
    try:
        with open(path) as f:
            ev = json.load(f)
        backend = str(ev.get("backend", ""))
        interpret = bool(ev.get("interpret", True))
        known = {k.get("kernel") for k in ev.get("kernels", [])}
    except (OSError, ValueError):
        pass
    chip_evidence = (
        backend.startswith("tpu")
        and not interpret
        and all(k in known for k in gated)
    )
    return {
        "basis": "measured-chip" if chip_evidence else "modeled-jnp-fallback",
        "gated_kernels": gated,
        "evidence_backend": backend or None,
    }


def model_step_cell(
    ddp,
    state,
    batch,
    cost_model: CostModel,
    topology: TopologyAssumptions = DEFAULT_TOPOLOGY,
    chip: str = "v5e",
    mfu: float = 0.3,
    wire: str = "f32",
) -> ModeledCell:
    """Model one live engine's step from a single abstract-shape trace.

    The caller owns engine construction/teardown (and the fenced/skipped
    taxonomy — an engine that refuses to build never reaches here).
    """
    from bagua_tpu.observability.goodput import PEAK_FLOPS_PER_CHIP

    variant = ddp.impl.step_variant(0)
    cfg = WireModelConfig.from_engine(ddp)
    sharded = ddp._build_sharded(variant)
    with capture_program() as events:
        closed = jax.make_jaxpr(sharded)(_abstract(state), _abstract(batch))
    program = extract_collective_ir(closed, dict(ddp.group.mesh.shape))
    captured = list(ddp._flight_finalize(variant, events))
    report = verify_collective_program(
        program, cfg, captured=captured, variant=variant
    )

    priced = price_program(program, cost_model, cfg)
    census = census_wire_bytes(program, cfg)
    flops = flops_census(closed)
    compute_s = compute_time_s(flops["flops"], chip=chip, mfu=mfu)
    wire_s = priced.total_wire_s
    if ddp.overlap_enabled:
        exposed_s = max(0.0, wire_s - topology.overlap_window_frac * compute_s)
    else:
        exposed_s = wire_s
    step_s = compute_s + exposed_s
    global_batch = int(jax.tree.leaves(batch)[0].shape[0])
    return ModeledCell(
        algo=cfg.algo,
        wire=wire,
        overlap=bool(ddp.overlap_enabled),
        verified=report.ok,
        modeled_step_ms=step_s * 1e3,
        modeled_samples_per_s=global_batch / step_s,
        modeled_goodput_frac=compute_s / step_s,
        modeled_mfu=flops["flops"] / (step_s * PEAK_FLOPS_PER_CHIP[chip]),
        compute_ms=compute_s * 1e3,
        wire_ms=wire_s * 1e3,
        exposed_wire_ms=exposed_s * 1e3,
        modeled_wire_bytes=priced.total_wire_bytes,
        census_wire_bytes=census,
        flops_per_step=flops["flops"],
        num_collectives=len(program.collectives),
        legs_used=priced.legs_used,
        leg_breakdown=priced.by_leg(),
        kernel_basis=pallas_kernel_basis(cfg.algo, wire),
        findings=[str(f) for f in report.errors],
        mesh={k: int(v) for k, v in ddp.group.mesh.shape.items()},
        exchange_axes=list(cfg.exchange_axes),
    )


def modeled_bench_rows(
    metric: str, artifact_path: Optional[str] = None
) -> List[Dict]:
    """The bench harness's modeled-fallback rows, read from the committed
    BENCH_MODELED.json (pure JSON — safe on the dead-tunnel salvage path).

    Returns ``{"mode": "modeled", ...}`` rows for the given bench metric;
    empty when the artifact is missing or carries no matching projection.
    Provenance fields name the artifact and the regeneration command so a
    modeled number can never masquerade as a measurement.
    """
    path = artifact_path or os.path.join(_REPO, "BENCH_MODELED.json")
    try:
        with open(path) as f:
            art = json.load(f)
    except (OSError, ValueError):
        return []
    prov = {
        "mode": "modeled",
        "provenance": "perflab: census-proved wire bytes x fitted alpha-beta",
        "artifact": os.path.basename(path),
        "generated_by": art.get("generated_by", "ci/bench_modeled.py"),
    }
    proj = art.get("vgg16_projection") or {}
    rows: List[Dict] = []
    if metric == "vgg16_img_per_sec_per_chip" and proj:
        rows.append({
            "metric": metric,
            "value": proj.get("modeled_img_per_s_per_chip", 0.0),
            "unit": "img/s/chip",
            "model": "vgg16",
            "algo": "gradient_allreduce",
            **prov,
        })
    elif metric == "vgg16_dp_scaling_efficiency" and proj:
        rows.append({
            "metric": metric,
            "value": proj.get("modeled_scaling_efficiency_8", 0.0),
            "unit": "ratio",
            "model": "vgg16",
            "n_chips": 8,
            **prov,
        })
    # The mlp-fixture trend rides along on every metric: the relative
    # ranking across algorithms/precisions is the falsifiable content.
    trend = [
        {
            "algo": r["algo"], "wire": r["wire"], "overlap": r["overlap"],
            "modeled_step_ms": r["modeled_step_ms"],
            "modeled_wire_bytes": r["modeled_wire_bytes"],
        }
        for r in art.get("rows", [])
        if r.get("status") == "pass"
    ]
    if rows and trend:
        rows[0]["trend"] = trend
    return rows
