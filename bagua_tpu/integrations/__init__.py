"""External-framework integrations.

The reference ships a pytorch-lightning strategy
(``bagua/pytorch_lightning/__init__.py``, tested at
``tests/pytorch_lightning/test_bagua_strategy.py:30-60``) so users of an
external training framework can adopt its algorithms without rewriting
their loop.  The TPU-native analog integrates with the Flax ecosystem:
:mod:`bagua_tpu.integrations.flax` adapts a
``flax.training.train_state.TrainState`` to the bagua engine and back.
"""
