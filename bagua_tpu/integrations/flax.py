"""Flax TrainState integration — the Lightning-strategy analog.

The reference lets a pytorch-lightning user switch a working ``Trainer``
onto bagua by passing ``strategy=BaguaStrategy(...)``, with exact-parity
tests against manual training (``tests/pytorch_lightning/
test_bagua_strategy.py:30-60``).  The Flax ecosystem's equivalent of the
Lightning loop is a ``flax.training.train_state.TrainState`` threaded
through a jitted step; this module adapts one to the bagua engine in three
calls:

.. code-block:: python

    from flax.training import train_state
    import optax
    from bagua_tpu.integrations.flax import FlaxBaguaStrategy

    fstate = train_state.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adam(1e-3))

    strategy = FlaxBaguaStrategy(loss_fn, algorithm="bytegrad")
    bstate = strategy.init_from_flax(fstate)        # enter the DP engine
    for batch in data:                              # global batches
        bstate, losses = strategy.train_step(bstate, batch)
    fstate = strategy.to_flax(bstate, fstate)       # back to flax land

``loss_fn(params, batch) -> scalar`` is the same contract as
:class:`~bagua_tpu.ddp.DistributedDataParallel` (build it from
``model.apply`` exactly as in a plain Flax loop).

Design note — why the hot loop stays on the bagua state: the engine's
state is rank-stacked (leading axis = DP rank) and donated every step;
converting to/from the flax layout per step would add a full parameter
copy each direction.  ``to_flax`` is the checkpoint/eval/export boundary:
it materializes rank 0's view (for the decentralized family, ranks
legitimately differ mid-training — rank 0 is that family's convention for
"the" model, matching the reference's checkpointing) and syncs ``step``
and ``opt_state`` so orbax/flax checkpoints, eval loops, and metric code
keep working unchanged.
"""

from typing import Callable, Optional, Union

import jax

from bagua_tpu.algorithms import build_algorithm
from bagua_tpu.algorithms.base import Algorithm
from bagua_tpu.ddp import DistributedDataParallel, TrainState

# Module-level so repeated to_flax calls hit the jit cache (an eval loop
# may cross this boundary every few hundred steps).
_row0 = jax.jit(lambda t: jax.tree.map(lambda x: x[0], t))


class FlaxBaguaStrategy:
    """Adapt a ``flax.training.train_state.TrainState`` to the bagua engine.

    Args:
        loss_fn: ``loss_fn(params, batch) -> scalar`` on the local batch,
            where ``params`` has the flax state's ``params`` structure.
        algorithm: an algorithm name (``"gradient_allreduce"``, ``"bytegrad"``,
            ...) or an :class:`~bagua_tpu.algorithms.base.Algorithm`.
        process_group: defaults to the global group.
        dp_filter: as for :class:`DistributedDataParallel`.
    """

    def __init__(
        self,
        loss_fn: Callable,
        algorithm: Union[str, Algorithm] = "gradient_allreduce",
        process_group=None,
        dp_filter=None,
        **algorithm_kwargs,
    ):
        if isinstance(algorithm, str):
            algorithm = build_algorithm(algorithm, **algorithm_kwargs)
        elif algorithm_kwargs:
            raise ValueError("algorithm_kwargs require an algorithm name")
        self._loss_fn = loss_fn
        self._algorithm = algorithm
        self._group = process_group
        self._dp_filter = dp_filter
        self.ddp: Optional[DistributedDataParallel] = None

    # -- flax -> bagua -------------------------------------------------------

    def init_from_flax(self, flax_state) -> TrainState:
        """Enter the DP engine from a flax TrainState.

        The flax state supplies the optimizer (``tx``) and initial params;
        the returned rank-stacked :class:`~bagua_tpu.ddp.TrainState` is what
        ``train_step`` consumes.  A non-zero ``flax_state.step`` is
        preserved (resuming mid-run keeps warmup/variant schedules aligned).
        """
        if self.ddp is not None:
            # Re-entering with a new flax state: tear down the previous
            # engine first or its background machinery (the async averager
            # thread) outlives any reachable shutdown() path.
            self.ddp.shutdown()
        self.ddp = DistributedDataParallel(
            self._loss_fn,
            flax_state.tx,
            self._algorithm,
            process_group=self._group,
            dp_filter=self._dp_filter,
        )
        bundled = getattr(self.ddp.impl, "optimizer", None)
        if bundled is not None and hasattr(bundled, "to_optax"):
            # QAdam transforms gradients into the full Adam update direction
            # and requires its own engine-side rule (q_adam.py:23-30);
            # applying the flax state's tx on top would train with updates
            # matching neither QAdam nor the user's optimizer.
            self.ddp.shutdown()
            self.ddp = None
            raise ValueError(
                "this algorithm bundles its own optimizer (e.g. qadam) and "
                "cannot run under a flax TrainState's tx — use "
                "DistributedDataParallel(loss_fn, None, algorithm) directly"
            )
        bstate = self.ddp.init(flax_state.params)
        step = int(jax.device_get(flax_state.step))
        if step:
            bstate = bstate._replace(step=bstate.step + step)
        return bstate

    def train_step(self, bstate: TrainState, batch):
        """One DP step; ``batch`` leaves carry the global batch dim (divisible
        by the group size).  Returns ``(new_bstate, per_rank_losses)``."""
        if self.ddp is None:
            raise RuntimeError("call init_from_flax first")
        return self.ddp.train_step(bstate, batch)

    # -- bagua -> flax -------------------------------------------------------

    def to_flax(self, bstate: TrainState, flax_state):
        """Materialize the flax view of the engine state (rank 0's replica),
        with ``step`` and ``opt_state`` synced — the checkpoint/eval/export
        boundary.  ``flax_state`` supplies the target structure (apply_fn,
        tx are carried over unchanged)."""
        step_arr = bstate.step
        if isinstance(step_arr, jax.Array) and not step_arr.is_fully_addressable:
            # Multi-host group: rank 0's slice may live on another process;
            # read whichever shard this process holds (all ranks agree on
            # the step counter) — same handling as ddp.train_step's seed.
            import jax.numpy as jnp

            local = step_arr.addressable_shards[0].data
            step = int(jnp.reshape(local, (-1,))[0])
        else:
            step = int(jax.device_get(step_arr)[0])
        return flax_state.replace(
            params=_row0(bstate.params),
            opt_state=_row0(bstate.opt_state),
            step=step,
        )

    def shutdown(self):
        if self.ddp is not None:
            self.ddp.shutdown()
