"""Checkpoint save/load with MoE-aware layout.

TPU-native analog of the reference's ``checkpoint/checkpointing.py``:

* a tracker file ``latest_checkpointed_iteration.txt`` at the checkpoint root
  names the newest complete checkpoint (reference ``:87-109``);
* non-expert ("model") state and expert state are stored separately, so a
  job restarted with a different expert-parallel layout can remap experts
  (reference saves per-expert model states + per-expert-parallel-rank
  optimizer states, ``:34-84``).

Arrays are serialized with Orbax (the JAX-native checkpointing library —
replacing ``torch.save``); the train state is any pytree, typically a
:class:`~bagua_tpu.ddp.TrainState`.
"""

import os
from typing import Optional, Tuple

import jax

TRACKER_FILENAME = "latest_checkpointed_iteration.txt"
COMPLETE_FILENAME = ".complete"


def _ckpt_path(ckpt_dir: str, iteration: int) -> str:
    return os.path.join(ckpt_dir, f"iter_{iteration:07d}")


def _atomic_write(path: str, text: str) -> None:
    """Write-temp + rename so no reader ever sees a torn file (the previous
    in-place tracker write could be observed half-written by a concurrently
    restarting rank, sending it to a garbage iteration)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def _is_complete(ckpt_dir: str, iteration: int) -> bool:
    return os.path.exists(os.path.join(_ckpt_path(ckpt_dir, iteration), COMPLETE_FILENAME))


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _default_expert_filter(path: str) -> bool:
    from bagua_tpu.parallel.moe.utils import is_moe_param_path

    return is_moe_param_path(path)


def _split_expert(tree, expert_filter=_default_expert_filter):
    """Partition a pytree into (non-expert, expert) with None placeholders so
    both halves keep the full tree structure.  ``expert_filter`` decides which
    leaf paths are per-rank expert state (defaults to the MoE convention)."""
    is_expert = lambda path: expert_filter(jax.tree_util.keystr(path))
    non_expert = jax.tree_util.tree_map_with_path(
        lambda p, x: None if is_expert(p) else x, tree
    )
    expert = jax.tree_util.tree_map_with_path(
        lambda p, x: x if is_expert(p) else None, tree
    )
    return non_expert, expert


def _merge(non_expert, expert):
    return jax.tree.map(
        lambda a, b: a if a is not None else b,
        non_expert,
        expert,
        is_leaf=lambda x: x is None,
    )


def save_checkpoint(
    iteration: int,
    ckpt_dir: str,
    state,
    moe_split: bool = True,
    expert_filter=_default_expert_filter,
) -> str:
    """Save ``state`` under ``ckpt_dir/iter_XXXXXXX`` and update the tracker
    (reference ``save_checkpoint``, ``checkpointing.py:112``).

    ``expert_filter(leaf_path) -> bool`` names the per-rank (expert) leaves;
    keep it the complement of the engine's ``dp_filter`` if you customized
    expert naming."""
    path = _ckpt_path(ckpt_dir, iteration)
    os.makedirs(path, exist_ok=True)
    ckpt = _checkpointer()
    if moe_split:
        non_expert, expert = _split_expert(state, expert_filter)
        ckpt.save(os.path.join(path, "model_states"), non_expert, force=True)
        if any(l is not None for l in jax.tree.leaves(expert, is_leaf=lambda x: x is None)):
            ckpt.save(os.path.join(path, "expert_states"), expert, force=True)
    else:
        ckpt.save(os.path.join(path, "model_states"), state, force=True)
    # Completion marker inside the checkpoint, then the tracker — both via
    # write-temp + atomic rename.  Ordering matters: the marker certifies
    # the states landed; the tracker is only ever an *optimization* over
    # scanning, and a crash between the two leaves a complete, discoverable
    # checkpoint with a stale tracker (healed by get_latest_iteration's
    # marker check + scan fallback), never the reverse.
    _atomic_write(os.path.join(path, COMPLETE_FILENAME), str(iteration))
    _atomic_write(os.path.join(ckpt_dir, TRACKER_FILENAME), str(iteration))
    return path


def _scan_latest_complete(ckpt_dir: str) -> Optional[int]:
    """Newest ``iter_*`` directory bearing the completion marker."""
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return None
    iterations = []
    for name in names:
        if name.startswith("iter_"):
            try:
                iterations.append(int(name[len("iter_"):]))
            except ValueError:
                continue
    for it in sorted(iterations, reverse=True):
        if _is_complete(ckpt_dir, it):
            return it
    return None


def get_latest_iteration(ckpt_dir: str) -> Optional[int]:
    """The newest *complete* checkpointed iteration, or None.

    The tracker names the candidate, but it is only trusted when the
    checkpoint it points at carries its completion marker — a torn tracker
    (unreadable) or a truncated checkpoint directory (killed writer) falls
    back to scanning ``iter_*`` directories for the newest marked one."""
    tracker = os.path.join(ckpt_dir, TRACKER_FILENAME)
    try:
        with open(tracker) as f:
            it = int(f.read().strip())
        if _is_complete(ckpt_dir, it):
            return it
    except (OSError, ValueError):
        pass
    return _scan_latest_complete(ckpt_dir)


def _restore_to_host(ckpt, path):
    """Restore every leaf as a host numpy array, ignoring the sharding the
    checkpoint was written with — required when resuming on a different
    topology (elastic scale-up/down), where the saved device layout no longer
    exists.  Pair with :func:`remap_world_size`."""
    import numpy as np

    import orbax.checkpoint as ocp

    tree = ckpt.metadata(path).item_metadata.tree
    restore_args = jax.tree.map(
        lambda _: ocp.RestoreArgs(restore_type=np.ndarray), tree
    )
    return ckpt.restore(path, restore_args=restore_args)


def load_checkpoint(
    ckpt_dir: str,
    iteration: Optional[int] = None,
    target=None,
    expert_filter=_default_expert_filter,
    to_host: bool = False,
) -> Tuple[object, int]:
    """Load the checkpoint named by the tracker (or an explicit iteration).
    Returns ``(state, iteration)`` (reference ``load_checkpoint``,
    ``checkpointing.py:165+``).

    Pass ``target`` (a pytree of the same structure, e.g. a freshly built
    ``TrainState``) to restore exact container types — Orbax otherwise
    returns plain dicts/lists, which breaks optax NamedTuple states.

    ``to_host=True`` restores every leaf as a full host numpy array
    regardless of the topology the checkpoint was saved on — the elastic
    resume path: load on the new world, :func:`remap_world_size`, re-init."""
    if iteration is None:
        iteration = get_latest_iteration(ckpt_dir)
        if iteration is None:
            raise FileNotFoundError(f"no tracker file in {ckpt_dir}")
    path = _ckpt_path(ckpt_dir, iteration)
    ckpt = _checkpointer()
    expert_path = os.path.join(path, "expert_states")
    has_expert = os.path.exists(expert_path)
    if to_host:
        non_expert = _restore_to_host(ckpt, os.path.join(path, "model_states"))
        if has_expert:
            state = _merge(non_expert, _restore_to_host(ckpt, expert_path))
        else:
            state = non_expert
        return state, iteration
    target_non_expert = target_expert = None
    if target is not None and has_expert:
        target_non_expert, target_expert = _split_expert(target, expert_filter)
    elif target is not None:
        target_non_expert = target
    non_expert = ckpt.restore(os.path.join(path, "model_states"), item=target_non_expert)
    if has_expert:
        expert = ckpt.restore(expert_path, item=target_expert)
        state = _merge(non_expert, expert)
    else:
        state = non_expert
    return state, iteration


def remap_world_size(
    state,
    new_size: int,
    expert_filter=_default_expert_filter,
):
    """Remap a rank-stacked train state to a different world size (elastic
    scale-up/down restart; the reference's expert-layout remapping on restart
    with a different expert-parallel degree, ``checkpointing.py:34-84``).

    * Replicated leaves (everything centralized algorithms keep bitwise equal
      across ranks — params, optimizer state, step) are sliced to one copy and
      re-stacked to ``new_size``.
    * Expert leaves (``expert_filter`` on the leaf path) hold a *different*
      shard per rank: shape ``(old_size, local_experts, ...)``.  The global
      expert pool ``old_size * local_experts`` is preserved and redistributed
      as ``(new_size, old_size * local_experts / new_size, ...)``; the total
      must divide evenly.

    Decentralized algorithms keep genuinely different weights per rank; remap
    their state only after a sync point (the reference likewise checkpoints
    decentralized runs post-average).
    """
    import jax.numpy as jnp

    def remap(path, x):
        if x is None:
            return None
        if expert_filter(jax.tree_util.keystr(path)):
            old_size, local = x.shape[0], x.shape[1]
            total = old_size * local
            if total % new_size != 0:
                raise ValueError(
                    f"cannot redistribute {total} experts over {new_size} ranks"
                    f" (leaf {jax.tree_util.keystr(path)})"
                )
            return jnp.reshape(
                x, (new_size, total // new_size) + tuple(x.shape[2:])
            )
        one = x[0]
        return jnp.broadcast_to(one[None], (new_size,) + tuple(one.shape))

    return jax.tree_util.tree_map_with_path(remap, state)
