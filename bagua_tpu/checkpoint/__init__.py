"""Checkpoint / resume (reference ``bagua/torch_api/checkpoint/``)."""

from bagua_tpu.checkpoint.checkpointing import (  # noqa: F401
    COMPLETE_FILENAME,
    TRACKER_FILENAME,
    save_checkpoint,
    load_checkpoint,
    get_latest_iteration,
    remap_world_size,
)
