"""Algorithm plugin base: data-parallel relaxations as pure step transforms.

TPU-native redesign of the reference's ``algorithms/base.py`` (``Algorithm`` /
``AlgorithmImpl``, ``base.py:13-208``).  The reference customizes training via
five imperative hooks (forward-pre, backward per-tensor, post-backward,
post-optimizer-step) that drive a Rust scheduler.  Under XLA the whole train
step is one traced function, so an algorithm is instead a set of **pure
stages** the DDP engine composes into the step:

======================  =====================================================
reference hook          bagua_tpu stage (all traced, run inside shard_map)
======================  =====================================================
init_tensors            implicit: the stage an algorithm communicates in
                        determines *which* leaves travel (grads in
                        ``transform_gradients``, weights in
                        ``on_step_start``/``on_step_end``, optimizer state
                        held in the algorithm's own state) — the declarative
                        replacement for proxy-tensor getter closures
                        (reference ``tensor.py:19-34``)
tensors_to_buckets      :meth:`tensors_to_buckets`
init_forward_pre_hook   :meth:`on_step_start`
init_backward_hook +    :meth:`transform_gradients` — gradients in, gradients
init_post_backward_hook out; communication happens here (XLA overlaps it with
                        remaining compute automatically)
init_post_optimizer_    :meth:`on_step_end`
step_hook
init_operations         implicit: the collectives the stages emit
need_reset              :meth:`need_reset` — True triggers a re-trace at a
                        step boundary (e.g. QAdam warmup→compression switch)
======================  =====================================================

Every stage receives a :class:`StepContext` carrying the process group, the
traced step counter, and the bucket plan.
"""

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp

from bagua_tpu.bucket import BucketPlan
from bagua_tpu.communication import BaguaProcessGroup
from bagua_tpu.env import get_default_bucket_size
from bagua_tpu.observability.annotations import bucket_scope
from bagua_tpu.observability.flight_recorder import notify_collective


@dataclasses.dataclass
class StepContext:
    """Per-step info handed to every algorithm stage.

    ``step`` is a traced scalar (int32) so schedules (e.g. shift_one peer
    selection, warmup switches) compile into the step function.
    """

    group: BaguaProcessGroup
    step: jnp.ndarray
    plan: Optional[BucketPlan] = None
    extras: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class OverlapCapability:
    """One algorithm's report on the backward-overlapped execution mode.

    The engine's ``overlap="auto"`` and explicit ``overlap=True`` both
    resolve against this — a per-algorithm capability report instead of the
    blanket "supports_overlap and not holds_bucketized_state" heuristic,
    so algorithms whose per-bucket state IS laid out on the bound plan
    (low-precision decentralized) can opt in, and algorithms whose compiled
    step changes shape across steps can opt out with a concrete reason.

    ``mode`` tells the engine WHAT rides the backward pass:

    * ``"gradient"`` — the exchange consumes each bucket's cotangents; the
      engine wraps parameters in per-bucket ``custom_vjp`` identities and
      the bwd rules call :meth:`AlgorithmImpl.overlap_exchange`.
    * ``"weight"`` — the exchange moves *weights* (decentralized averaging);
      weights don't data-depend on the backward, so the engine calls
      :meth:`overlap_exchange` per bucket with both the bucket's gradients
      (the anchor) and its parameter leaves after ``value_and_grad``.
    * ``"post_step"`` — the exchange already runs per bucket after the
      optimizer update (:meth:`on_step_end`); overlap only switches the
      plan to multi-bucket granularity so each bucket's chain becomes
      issuable as soon as its own update finishes.

    ``auto`` gates the ``"auto"`` resolution separately from explicit
    ``overlap=True``: auto must never change numerics, so algorithms whose
    overlap output is not bitwise-identical to the monolithic path (chunk
    boundaries move under a multi-bucket plan) set ``auto=False`` and stay
    opt-in.  ``reason`` is the concrete rejection message (names the class
    and the cause) surfaced by the engine when explicit ``overlap=True`` is
    refused."""

    supported: bool
    mode: str = "gradient"
    auto: bool = True
    reason: str = ""


class AlgorithmImpl:
    """A reified algorithm bound to a process group."""

    #: registry-style short name carried in in-graph trace annotations
    #: (:func:`bagua_tpu.observability.annotations.bucket_scope`); subclasses
    #: set it to their registered name so device-trace attribution matches
    #: the user-facing algorithm string.
    algo_name = ""

    def __init__(self, process_group: BaguaProcessGroup, hierarchical: bool = False):
        self.process_group = process_group
        self.hierarchical = hierarchical

    def annotate(self, bucket_idx, phase: str):
        """Named scope labeling one bucket's exchange ops in the device trace
        (``bagua_ex/algo=<name>/bucket=<i>/phase=<phase>``).  Pure metadata —
        wrapping traced code in it never changes the computation.

        Doubles as the flight recorder's trace-time capture point: every
        exchange path wraps its bucket collective in ``annotate``, so one
        notification here records the whole collective program of a step
        variant (a no-op unless the engine has a capture active).  The
        record carries the mesh axes the exchange rides (the group's data
        axes) so flight-recorder consumers can tell a dp-ring collective
        from a model-axis one on named meshes."""
        axes = list(self.process_group.data_axes)
        notify_collective(
            self.algo_name or type(self).__name__, bucket_idx, phase, axes=axes
        )
        return bucket_scope(self.algo_name or type(self).__name__, bucket_idx, phase)

    # -- structure ----------------------------------------------------------

    def tensors_to_buckets(
        self, tree, bucket_size_bytes: Optional[int] = None, filter_fn=None
    ) -> BucketPlan:
        """Default: dtype-grouped greedy buckets, aligned to the group size.
        ``filter_fn(name)`` excludes leaves from communication (MoE expert
        params, reference ``bagua_distributed.py:172``)."""
        if bucket_size_bytes is None:
            bucket_size_bytes = get_default_bucket_size()
        return BucketPlan.from_tree(
            tree, bucket_size_bytes, align_elems=self.process_group.exchange_size,
            filter_fn=filter_fn,
        )

    def bind_plan(self, plan: BucketPlan) -> None:
        """Called by the engine whenever the active bucket plan changes (init
        and every rebucket), so algorithms that lay state out per-bucket see
        a consistent plan."""
        self._bound_plan = plan

    def init_state(self, params) -> Any:
        """Algorithm-private state pytree (peer weights, compression stats...)."""
        return ()

    # -- traced stages ------------------------------------------------------

    def on_step_start(self, params, state, ctx: StepContext):
        return params, state

    def transform_gradients(self, grads, params, state, ctx: StepContext):
        """Runs between backward and the optimizer update.  May transform the
        gradients (centralized algorithms) and/or replace the parameters the
        update is applied to (decentralized algorithms copy back the averaged
        peer weights here, the analog of ``copy_back_peer_weight``,
        ``decentralized_full_precision_synchronous.rs:106-124``)."""
        return grads, params, state

    def on_step_end(self, params, state, ctx: StepContext):
        return params, state

    # -- overlap execution mode ---------------------------------------------

    #: Algorithms that implement :meth:`overlap_exchange` set this True; the
    #: engine resolves the mode through :meth:`overlap_capability`.
    #: Algorithms that leave it False keep the monolithic
    #: :meth:`transform_gradients` path regardless of the engine knob
    #: (explicit ``overlap=True`` is rejected at init).
    supports_overlap = False

    #: What the overlap mode exchanges per bucket — see
    #: :class:`OverlapCapability` (``"gradient"`` | ``"weight"`` |
    #: ``"post_step"``).
    overlap_mode = "gradient"

    #: False for algorithms whose :meth:`step_variant` changes across steps:
    #: the overlap wrappers are traced per variant, so a variant-switching
    #: algorithm would re-anchor (and re-run) its exchange differently on
    #: each recompile — ``overlap="auto"`` must never silently enable that.
    stable_step_variant = True

    def overlap_capability(self) -> OverlapCapability:
        """The per-algorithm capability report the engine's ``overlap`` knob
        resolves against (both ``"auto"`` and the explicit ``True``
        validation).  The default covers the common cases with concrete,
        class-naming reasons; algorithms with plan-dependent state that is
        nonetheless per-bucket native (low-precision decentralized) override
        it."""
        name = type(self).__name__
        if not getattr(self, "supports_overlap", False):
            return OverlapCapability(
                False,
                reason=f"{name} does not implement overlap_exchange (no "
                "per-bucket backward hook); pass overlap=False or 'auto'",
            )
        if not getattr(self, "stable_step_variant", True):
            return OverlapCapability(
                False,
                reason=f"{name} switches its compiled step variant across "
                "steps (step_variant); per-bucket backward anchors would be "
                "re-traced inconsistently — pass overlap=False or 'auto'",
            )
        if getattr(self, "holds_bucketized_state", False):
            return OverlapCapability(
                False,
                reason=f"{name} keeps per-bucket state; its exchange cannot "
                "be split into independent backward-time bucket collectives "
                "— pass overlap=False or 'auto'",
            )
        return OverlapCapability(True, mode=getattr(self, "overlap_mode", "gradient"))

    def overlap_exchange(
        self, bucket_idx: int, grads, ctx: StepContext, params_leaves=None
    ):
        """Exchange ONE bucket from inside (or anchored on) the backward pass.

        ``"gradient"`` mode: called by the per-bucket ``custom_vjp`` backward
        rule the engine installs
        (:func:`bagua_tpu.bucket.wrap_params_for_overlap`): ``grads`` is the
        list of this bucket's gradient leaves in slot order, complete at this
        point of the backward computation; return them exchanged (same
        structure/shapes/dtypes).  ``params_leaves`` is None.

        ``"weight"`` mode: called by the engine after ``value_and_grad`` with
        both the bucket's gradient leaves (the readiness anchor — tie the
        collective to them with ``jax.lax.optimization_barrier`` so XLA
        issues it as this bucket's cotangents arrive) and its parameter
        leaves in ``params_leaves``; return the *exchanged parameter* leaves.

        When overlap is on the engine does NOT call
        :meth:`transform_gradients` — this hook (plus
        :meth:`finalize_overlap`) subsumes it bucket-by-bucket.
        :meth:`transform_gradients` remains the fallback whenever overlap is
        off or unsupported."""
        raise NotImplementedError(self.overlap_capability().reason or (
            f"{type(self).__name__} does not implement overlap_exchange"
        ))

    def finalize_overlap(self, grads, params, state, ctx: StepContext):
        """Post-backward stage of the overlap path: receives the per-bucket
        exchanged values assembled back into the gradient tree (``"gradient"``
        mode) or the untouched gradients (``"weight"``/``"post_step"``), and
        may finish whatever whole-tree math :meth:`transform_gradients` runs
        after its communication (QAdam's moment/bias-correction update).
        Same signature/contract as :meth:`transform_gradients`; default is
        the identity."""
        return grads, params, state

    # -- host-side integration (non-traced) ----------------------------------

    #: Optional ``threading.Lock``.  When set, the engine serializes step
    #: *dispatch* (enqueue only, not device execution) with the algorithm's
    #: background threads — required when the step donates buffers a
    #: background thread may be sampling (async model average).
    host_dispatch_lock = None

    def host_pre_dispatch(self, state):
        """Called on the host right before each step dispatch; may return a
        replacement state (async average folds finished results here)."""
        return state

    def host_post_dispatch(self, state, step: int) -> None:
        """Called with each freshly dispatched step's output state and the
        host-side step counter."""

    def host_shutdown(self) -> None:
        """Stop any background machinery (end of training)."""

    # -- control ------------------------------------------------------------

    def need_reset(self, step: int) -> bool:
        """Host-level: does the step function need re-tracing at this step?"""
        return False

    def step_variant(self, step: int) -> str:
        """Host-level choice among compiled step variants (cached per key).
        The async algorithm uses this to arm a time-scheduled sync step."""
        return "default"


class Algorithm:
    """User-facing declarative algorithm config (reference ``base.py:13-48``)."""

    def reify(self, process_group: BaguaProcessGroup) -> AlgorithmImpl:
        raise NotImplementedError

    @classmethod
    def init(cls, name: str, **kwargs) -> "Algorithm":
        return GlobalAlgorithmRegistry.get(name)(**kwargs)


class _Registry:
    """Reference ``GlobalAlgorithmRegistry`` (``base.py:211-263``)."""

    def __init__(self):
        self._algorithms: Dict[str, Tuple[Callable[..., Algorithm], str]] = {}

    def register(self, name: str, factory: Callable[..., Algorithm], description: str = ""):
        if name in self._algorithms:
            raise ValueError(f"algorithm {name!r} already registered")
        self._algorithms[name] = (factory, description)

    def get(self, name: str) -> Callable[..., Algorithm]:
        if name not in self._algorithms:
            raise KeyError(
                f"unknown algorithm {name!r}; registered: {sorted(self._algorithms)}"
            )
        return self._algorithms[name][0]

    def keys(self) -> List[str]:
        return sorted(self._algorithms)


GlobalAlgorithmRegistry = _Registry()
