"""Algorithm plugin base: data-parallel relaxations as pure step transforms.

TPU-native redesign of the reference's ``algorithms/base.py`` (``Algorithm`` /
``AlgorithmImpl``, ``base.py:13-208``).  The reference customizes training via
five imperative hooks (forward-pre, backward per-tensor, post-backward,
post-optimizer-step) that drive a Rust scheduler.  Under XLA the whole train
step is one traced function, so an algorithm is instead a set of **pure
stages** the DDP engine composes into the step:

======================  =====================================================
reference hook          bagua_tpu stage (all traced, run inside shard_map)
======================  =====================================================
init_tensors            implicit: the stage an algorithm communicates in
                        determines *which* leaves travel (grads in
                        ``transform_gradients``, weights in
                        ``on_step_start``/``on_step_end``, optimizer state
                        held in the algorithm's own state) — the declarative
                        replacement for proxy-tensor getter closures
                        (reference ``tensor.py:19-34``)
tensors_to_buckets      :meth:`tensors_to_buckets`
init_forward_pre_hook   :meth:`on_step_start`
init_backward_hook +    :meth:`transform_gradients` — gradients in, gradients
init_post_backward_hook out; communication happens here (XLA overlaps it with
                        remaining compute automatically)
init_post_optimizer_    :meth:`on_step_end`
step_hook
init_operations         implicit: the collectives the stages emit
need_reset              :meth:`need_reset` — True triggers a re-trace at a
                        step boundary (e.g. QAdam warmup→compression switch)
======================  =====================================================

Every stage receives a :class:`StepContext` carrying the process group, the
traced step counter, and the bucket plan.
"""

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp

from bagua_tpu.bucket import BucketPlan
from bagua_tpu.communication import BaguaProcessGroup
from bagua_tpu.env import get_default_bucket_size


@dataclasses.dataclass
class StepContext:
    """Per-step info handed to every algorithm stage.

    ``step`` is a traced scalar (int32) so schedules (e.g. shift_one peer
    selection, warmup switches) compile into the step function.
    """

    group: BaguaProcessGroup
    step: jnp.ndarray
    plan: Optional[BucketPlan] = None
    extras: Dict[str, Any] = dataclasses.field(default_factory=dict)


class AlgorithmImpl:
    """A reified algorithm bound to a process group."""

    def __init__(self, process_group: BaguaProcessGroup, hierarchical: bool = False):
        self.process_group = process_group
        self.hierarchical = hierarchical

    # -- structure ----------------------------------------------------------

    def tensors_to_buckets(
        self, tree, bucket_size_bytes: Optional[int] = None, filter_fn=None
    ) -> BucketPlan:
        """Default: dtype-grouped greedy buckets, aligned to the group size.
        ``filter_fn(name)`` excludes leaves from communication (MoE expert
        params, reference ``bagua_distributed.py:172``)."""
        if bucket_size_bytes is None:
            bucket_size_bytes = get_default_bucket_size()
        return BucketPlan.from_tree(
            tree, bucket_size_bytes, align_elems=self.process_group.size,
            filter_fn=filter_fn,
        )

    def bind_plan(self, plan: BucketPlan) -> None:
        """Called by the engine whenever the active bucket plan changes (init
        and every rebucket), so algorithms that lay state out per-bucket see
        a consistent plan."""
        self._bound_plan = plan

    def init_state(self, params) -> Any:
        """Algorithm-private state pytree (peer weights, compression stats...)."""
        return ()

    # -- traced stages ------------------------------------------------------

    def on_step_start(self, params, state, ctx: StepContext):
        return params, state

    def transform_gradients(self, grads, params, state, ctx: StepContext):
        """Runs between backward and the optimizer update.  May transform the
        gradients (centralized algorithms) and/or replace the parameters the
        update is applied to (decentralized algorithms copy back the averaged
        peer weights here, the analog of ``copy_back_peer_weight``,
        ``decentralized_full_precision_synchronous.rs:106-124``)."""
        return grads, params, state

    def on_step_end(self, params, state, ctx: StepContext):
        return params, state

    # -- overlap execution mode ---------------------------------------------

    #: Algorithms that implement :meth:`overlap_exchange` set this True; the
    #: engine's ``overlap="auto"`` resolves on it.  Algorithms that leave it
    #: False keep the monolithic :meth:`transform_gradients` path regardless
    #: of the engine knob (explicit ``overlap=True`` is rejected at init).
    supports_overlap = False

    def overlap_exchange(self, bucket_idx: int, grads, ctx: StepContext):
        """Exchange ONE bucket's gradients from inside the backward pass.

        Called by the per-bucket ``custom_vjp`` backward rule the engine
        installs in overlap mode (:func:`bagua_tpu.bucket.wrap_params_for_overlap`):
        ``grads`` is the list of this bucket's gradient leaves in slot order,
        complete at this point of the backward computation; return them
        exchanged (same structure/shapes/dtypes).  When overlap is on the
        engine does NOT call :meth:`transform_gradients` — this hook subsumes
        it bucket-by-bucket.  :meth:`transform_gradients` remains the
        fallback whenever overlap is off or unsupported."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement overlap_exchange "
            "(supports_overlap is False); run with overlap=False or 'auto'"
        )

    # -- host-side integration (non-traced) ----------------------------------

    #: Optional ``threading.Lock``.  When set, the engine serializes step
    #: *dispatch* (enqueue only, not device execution) with the algorithm's
    #: background threads — required when the step donates buffers a
    #: background thread may be sampling (async model average).
    host_dispatch_lock = None

    def host_pre_dispatch(self, state):
        """Called on the host right before each step dispatch; may return a
        replacement state (async average folds finished results here)."""
        return state

    def host_post_dispatch(self, state, step: int) -> None:
        """Called with each freshly dispatched step's output state and the
        host-side step counter."""

    def host_shutdown(self) -> None:
        """Stop any background machinery (end of training)."""

    # -- control ------------------------------------------------------------

    def need_reset(self, step: int) -> bool:
        """Host-level: does the step function need re-tracing at this step?"""
        return False

    def step_variant(self, step: int) -> str:
        """Host-level choice among compiled step variants (cached per key).
        The async algorithm uses this to arm a time-scheduled sync step."""
        return "default"


class Algorithm:
    """User-facing declarative algorithm config (reference ``base.py:13-48``)."""

    def reify(self, process_group: BaguaProcessGroup) -> AlgorithmImpl:
        raise NotImplementedError

    @classmethod
    def init(cls, name: str, **kwargs) -> "Algorithm":
        return GlobalAlgorithmRegistry.get(name)(**kwargs)


class _Registry:
    """Reference ``GlobalAlgorithmRegistry`` (``base.py:211-263``)."""

    def __init__(self):
        self._algorithms: Dict[str, Tuple[Callable[..., Algorithm], str]] = {}

    def register(self, name: str, factory: Callable[..., Algorithm], description: str = ""):
        if name in self._algorithms:
            raise ValueError(f"algorithm {name!r} already registered")
        self._algorithms[name] = (factory, description)

    def get(self, name: str) -> Callable[..., Algorithm]:
        if name not in self._algorithms:
            raise KeyError(
                f"unknown algorithm {name!r}; registered: {sorted(self._algorithms)}"
            )
        return self._algorithms[name][0]

    def keys(self) -> List[str]:
        return sorted(self._algorithms)


GlobalAlgorithmRegistry = _Registry()
