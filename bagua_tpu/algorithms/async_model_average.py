"""Asynchronous model averaging.

TPU-native redesign of the reference's ``async_model_average.py`` +
``decentralized_full_precision_asynchronous.rs``.  The reference runs a
background thread that continuously allreduce-averages the live weights on a
dedicated CUDA stream while forward/backward proceeds, with weight locks and
a 1-byte MIN-allreduce abort negotiation
(``async_model_average.py:208-230``,
``decentralized_full_precision_asynchronous.rs:98-171``).  The defining
property: **training never blocks on the average**; staleness is tolerated.

Under XLA arrays are immutable and a step is a pure function, so "average the
live weights in place" does not map directly — but the property does:

* A daemon **averager thread** wakes every ``sync_interval_ms``, snapshots the
  current rank-stacked parameters (a Python ref — jax.Arrays are immutable, so
  the snapshot is free), and dispatches a separately-jitted **delta
  program** ``delta = group_mean - snapshot`` into fresh buffers.  The device
  executes it interleaved with training steps (the role of the reference's
  comm stream); the averager NEVER waits on the result — it publishes the
  in-flight delta and goes back to sleep.  (Returning the delta rather than
  ``(mean, snapshot_copy)`` halves the program's HBM writes and the fold's
  reads.)
* Right before a step dispatch the engine **folds** a published delta into the
  training state — ``params <- params + delta`` — but ONLY if its buffers
  have actually landed (``Array.is_ready()``, a non-blocking query).  An
  in-flight average is simply left pending for a later step, so the training
  loop never blocks on the averager, host- or device-side.  This is the
  well-defined functional analog of the reference's tolerated race between
  the averaging write-back and concurrent optimizer updates: progress made
  since the snapshot survives, staleness in the average is accepted.
* The steady-state train step itself contains **zero collectives** (warmup
  steps route through a ``lax.cond`` gradient allreduce, after which the
  branch is dead) — so step cadence is independent of averaging cadence.
* ``abort()`` mirrors the reference's negotiated abort: the averager
  contributes a 0 to a group MIN every cycle (``_negotiate``); averaging only
  runs when every rank contributes 1.  ``abort()`` waits for any in-flight
  average to drain, discards the undelivered result, and parks the thread;
  ``resume()`` re-arms it.  (Reference ``:232-305``.)

Dispatch-order safety: the engine serializes step dispatch with the averager's
snapshot+dispatch via ``host_dispatch_lock`` (microseconds — only the
*enqueue* is serialized, not device execution).  This is required because the
step donates its input buffers; sampling under the lock guarantees the
averager only ever reads the freshest, not-yet-donated parameters.
"""

import logging
import threading

import jax
import jax.numpy as jnp

from bagua_tpu.algorithms.base import Algorithm, AlgorithmImpl, StepContext
from bagua_tpu.communication import ALL_AXES, ReduceOp, allreduce_inplace
from jax.sharding import PartitionSpec as P


class AsyncModelAverageAlgorithmImpl(AlgorithmImpl):

    def __init__(
        self,
        process_group,
        peer_selection_mode: str = "all",
        sync_interval_ms: int = 500,
        warmup_steps: int = 0,
    ):
        super().__init__(process_group)
        if peer_selection_mode != "all":
            raise ValueError(
                "async model average supports peer_selection_mode='all' "
                "(the reference rejects others too, async_model_average.py:84-90)"
            )
        self.peer_selection_mode = peer_selection_mode
        self.sync_interval_ms = sync_interval_ms
        self.warmup_steps = warmup_steps

        self._status = "running"
        self._latest = None  # rank-stacked params of the newest dispatched step
        self._published_step = 0
        self._pending = None  # (generation, delta tree) awaiting fold
        # Set by the averager thread once the pending delta's buffers have
        # landed; read by host_pre_dispatch.  The r4 chip session showed the
        # per-step per-leaf ``is_ready()`` probes were NOT free on the
        # tunneled PJRT backend (async stayed at 183 img/s with ~130 ms of
        # per-step host overhead both before and after the non-blocking-
        # averager fix) — so the step path now reads this plain bool and
        # performs ZERO backend queries; readiness detection lives on the
        # averager thread (``_watch_pending``).  Guarded by _pending_lock.
        self._pending_ready = False
        # Double-fold guard.  A delta is ``mean(snap) - snap``; applying it is
        # only correct if no OTHER fold landed between its snapshot and its
        # consumption — an intervening fold's correction would be re-applied
        # (observed on the 8-dev CPU sim as the rank spread re-inverting to
        # its full initial magnitude at lr=0).  Optimizer progress in that
        # window is fine (the tolerated staleness); a second fold is not.
        # The counter increments on every fold; stale-generation deltas are
        # dropped.  Guarded by ``_pending_lock``.
        self._fold_generation = 0
        #: deltas dropped while possibly still in flight (stale generation /
        #: unusable) — drained by the next cycle or abort() so no untracked
        #: program outlives the averager's device-quiescence guarantees.
        #: Guarded by ``_pending_lock``.
        self._orphans = []
        self._pending_lock = threading.Lock()
        self._cycle_lock = threading.Lock()  # held across one averaging cycle
        self.host_dispatch_lock = threading.Lock()  # shared with the engine
        self._thread = None
        self._stop_event = threading.Event()  # per-thread; replaced on spawn
        self._wake = threading.Event()
        self._shutdown = False
        self._jit_average = None
        # The delta is consumed exactly once — donate its buffers to the fold.
        self._jit_fold = jax.jit(
            lambda params, delta: jax.tree.map(
                lambda p, d: p + d, params, delta
            ),
            donate_argnums=(1,),
        )
        self.folds_applied = 0  # observability: how many averages landed
        self.folds_failed = 0  # observability: how many folds were dropped

    # -- the average program -------------------------------------------------

    def _build_average(self):
        def local(p):
            def delta_of(x):
                # Uniform stacking: every device holds size/n_dev rows, so the
                # pmean of local means is the group mean.  Emitting the delta
                # (mean - snapshot) keeps the output a fresh buffer — no
                # aliasing with the live training params, which the next step
                # will donate — while halving the traffic of returning
                # (mean, snapshot_copy) pairs.
                m = jax.lax.pmean(jnp.mean(x, axis=0, keepdims=True), ALL_AXES)
                return jnp.broadcast_to(m, x.shape) - x

            return jax.tree.map(delta_of, p)

        return jax.jit(
            self.process_group.shard_map(
                local, in_specs=P(ALL_AXES), out_specs=P(ALL_AXES)
            )
        )

    # -- averager thread -----------------------------------------------------

    def _negotiate(self, ready: bool) -> bool:
        """Group MIN of per-rank readiness (the reference's 1-byte MIN
        allreduce abort negotiation, ``async_model_average.py:272-305``).

        Single-controller: the min over ranks is local.  Multi-process: every
        process's averager contributes each cycle (aborted ranks contribute 0
        but keep negotiating), so the agreed result keeps the collective
        sequence identical on all processes.
        """
        if jax.process_count() == 1:
            return bool(ready)
        from jax.experimental import multihost_utils

        import numpy as np

        flags = multihost_utils.process_allgather(np.int32(1 if ready else 0))
        return bool(flags.min())

    def _cycle(self, stop_event=None, wait: bool = True):
        """One averaging cycle.  ``wait=False`` (the background thread's mode)
        dispatches the delta program and publishes the in-flight result
        without ever blocking — a host-side wait here was measured stalling
        step dispatch on the remote-relay TPU backend (BENCH_TPU.json r3:
        async 183 img/s vs gradient_allreduce 764).  ``wait=True`` (manual /
        test calls) blocks until the delta lands, for determinism."""
        stop_event = stop_event or self._stop_event
        # Multi-process: negotiation is itself a collective, and warmup steps
        # contain gradient allreduces — negotiating mid-warmup would interleave
        # collectives in different orders across processes and hang the job.
        # Every process gates on its *local* warmup completion, making the
        # per-process collective sequence identical: W warmup allreduces, then
        # negotiate rounds (which rate-match by blocking on the slowest peer).
        if jax.process_count() > 1 and self._published_step < self.warmup_steps:
            return
        with self._cycle_lock:
            with self._pending_lock:
                # An unconsumed delta (still in flight, or landed but no
                # step has folded it yet) makes a new dispatch pure waste —
                # the average would be displaced unconsumed.  Folded into
                # the negotiated ``ready`` flag rather than an early return
                # so the multi-process collective sequence stays in
                # lockstep (every rank still negotiates every cycle).
                slot_free = self._pending is None
            ready = (
                self._status == "running"
                and not stop_event.is_set()
                and self._latest is not None
                and self._published_step >= self.warmup_steps
                and slot_free
            )
            if not self._negotiate(ready):
                return
            if self._jit_average is None:
                # AOT-compile OUTSIDE the dispatch lock: the first cycle would
                # otherwise hold the lock for the full XLA compile of the
                # average program, stalling every training-step dispatch for
                # seconds.  The lock below then covers only the enqueue.
                self._jit_average = self._build_average().lower(self._latest).compile()
            with self.host_dispatch_lock:
                with self._pending_lock:
                    gen = self._fold_generation
                    latest = self._latest
                delta = self._jit_average(latest)
            if wait:
                jax.block_until_ready(delta)
            with self._pending_lock:
                if self._status == "running" and gen == self._fold_generation:
                    if self._pending is not None:
                        # An unconsumed previous delta is displaced — drain
                        # it below so no untracked program outlives the cycle.
                        # (Unreachable in the background mode now that
                        # ``slot_free`` gates the dispatch; kept for manual
                        # _cycle() callers.)
                        self._orphans.append(self._pending[1])
                    self._pending = (gen, delta)
                    self._pending_ready = bool(wait)  # wait=True: landed
                else:
                    # Publish suppressed (abort or a racing fold): the
                    # orphaned program still drains below, so abort()'s
                    # exclusive-device-time contract holds — releasing
                    # ``_cycle_lock`` must imply the device is quiet.
                    self._orphans.append(delta)
            self._drain_orphans()

    def _drain_orphans(self):
        """Wait out any dropped-while-in-flight delta programs.  Called from
        the averager thread and abort() — never from the step dispatch path."""
        with self._pending_lock:
            orphans, self._orphans = self._orphans, []
        for delta in orphans:
            try:
                jax.block_until_ready(delta)
            except Exception:
                pass  # a failed orphan is quiet by definition

    def _watch_pending(self, stop_event):
        """Mark the pending delta ready once its buffers land — on THIS
        thread, so the training-step path never queries the backend.

        Polls one representative leaf: all outputs of a single executable
        become ready together when it completes, so one probe stands for the
        tree (and one probe per poll is what keeps this cheap over a
        tunneled PJRT client).  Runs lock-free between probes; bails when
        the pending slot changes under it (fold consumed it / abort)."""
        poll_s = min(0.01, self.sync_interval_ms / 1000.0 / 4)
        warned = False
        t0 = None
        while not stop_event.is_set():
            with self._pending_lock:
                if self._pending is None or self._pending_ready:
                    return
                gen, delta = self._pending
            leaf = next(
                (l for l in jax.tree.leaves(delta) if hasattr(l, "is_ready")),
                None,
            )
            try:
                landed = leaf is None or leaf.is_ready()
            except Exception as e:
                with self._pending_lock:
                    if self._pending is not None and self._pending[0] == gen:
                        self._orphans.append(self._pending[1])
                        self._pending = None
                        self._pending_ready = False
                self._log_fold_failure("pending delta unusable", e)
                return
            if landed:
                with self._pending_lock:
                    if self._pending is not None and self._pending[0] == gen:
                        self._pending_ready = True
                return
            import time as _time

            if t0 is None:
                t0 = _time.monotonic()
            elif not warned and _time.monotonic() - t0 > 30.0:
                warned = True
                logging.getLogger(__name__).warning(
                    "async model average: delta in flight >30s — device "
                    "stalled? averaging is paused until it lands"
                )
            stop_event.wait(poll_s)

    def _run(self, stop_event, wake):
        while True:
            wake.wait(self.sync_interval_ms / 1000.0)
            wake.clear()
            if stop_event.is_set():
                return
            self._cycle(stop_event, wait=False)
            self._watch_pending(stop_event)

    def _ensure_thread(self):
        if self._shutdown:
            return
        if self._thread is None or not self._thread.is_alive():
            # Fresh events per thread: a stuck old thread keeps its own (set)
            # stop event, so it can never be revived by a new spawn.
            self._stop_event = threading.Event()
            self._wake = threading.Event()
            self._thread = threading.Thread(
                target=self._run,
                args=(self._stop_event, self._wake),
                daemon=True,
                name="bagua-async-averager",
            )
            self._thread.start()

    # -- host-side engine hooks ---------------------------------------------

    def _log_fold_failure(self, what: str, exc: Exception) -> None:
        self.folds_failed += 1
        logging.getLogger(__name__).warning(
            "async model average: %s (%s: %s); the average was skipped "
            "(folds_failed=%d)", what, type(exc).__name__, exc, self.folds_failed
        )

    def host_pre_dispatch(self, state):
        """Fold a landed average into the params about to be dispatched.

        ZERO backend queries on this path: readiness is a plain bool set by
        the averager thread (``_watch_pending``).  The r4 chip session
        established that per-leaf ``is_ready()`` probes here cost ~130 ms
        per step over the tunneled PJRT client — 4x the whole VGG16 step —
        while a delta still in flight simply stays pending for a later step
        (the training loop never waits on the averager, the reference's
        defining property, async_model_average.py:208-230)."""
        with self._pending_lock:
            if self._pending is None or not self._pending_ready:
                return state
            gen, delta = self._pending
            if gen != self._fold_generation:
                # Snapshot predates an intervening fold — applying it would
                # double-count that fold's correction.  Drop (to the orphan
                # list: it may still be in flight, and only the averager /
                # abort may wait on it); a fresh delta comes next cycle.
                self._orphans.append(delta)
                self._pending = None
                self._pending_ready = False
                return state
            self._pending = None
            self._pending_ready = False
        try:
            folded = self._jit_fold(state.params, delta)
        except Exception as e:
            # Dispatch-time (structural) failure: param tree / sharding
            # mismatch, e.g. after an in-place model swap.  Loud, counted —
            # a permanent mismatch would otherwise silently stop averaging.
            self._log_fold_failure("fold dispatch failed", e)
            return state
        with self._pending_lock:
            self._fold_generation += 1
            # Retarget the snapshot source at the folded params so a cycle
            # racing this fold can never capture the pre-fold tree.
            self._latest = folded
        self.folds_applied += 1
        return state._replace(params=folded)

    def host_post_dispatch(self, state, step: int) -> None:
        self._latest = state.params
        self._published_step = step
        self._ensure_thread()

    # -- control (reference ``:232-305``) ------------------------------------

    def abort(self):
        """Stop averaging; waits for any in-flight average to drain (both the
        cycle's dispatch and its device-side execution) and discards the
        undelivered result — callers rely on exclusive device time after
        abort() returns (e.g. a timed benchmark window)."""
        if self._status != "running":
            return
        self._status = "aborted"
        with self._cycle_lock:  # drain: in-flight cycle's dispatch first
            with self._pending_lock:
                if self._pending is not None:
                    self._orphans.append(self._pending[1])
                    self._pending = None
                self._pending_ready = False
            self._drain_orphans()  # device-side drain, failures included

    def resume(self):
        self._status = "running"

    def host_shutdown(self):
        """Stop the averager thread permanently (end of training)."""
        self._shutdown = True
        self._stop_event.set()
        if self._thread is not None:
            self._wake.set()
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- traced stages -------------------------------------------------------

    def transform_gradients(self, grads, params, state, ctx: StepContext):
        if self.warmup_steps > 0:
            # Warmup phase: plain gradient allreduce (reference ``:120-141``
            # routes warmup steps through the centralized op).
            def avg(g):
                flats = ctx.plan.bucketize(g)
                return ctx.plan.debucketize(
                    [allreduce_inplace(f, op=ReduceOp.AVG) for f in flats], g
                )

            grads = jax.lax.cond(
                ctx.step < self.warmup_steps, avg, lambda g: g, grads
            )
        return grads, params, state


class AsyncModelAverageAlgorithm(Algorithm):
    def __init__(
        self,
        peer_selection_mode: str = "all",
        sync_interval_ms: int = 500,
        warmup_steps: int = 0,
    ):
        self.peer_selection_mode = peer_selection_mode
        self.sync_interval_ms = sync_interval_ms
        self.warmup_steps = warmup_steps

    def reify(self, process_group) -> AsyncModelAverageAlgorithmImpl:
        return AsyncModelAverageAlgorithmImpl(
            process_group,
            peer_selection_mode=self.peer_selection_mode,
            sync_interval_ms=self.sync_interval_ms,
            warmup_steps=self.warmup_steps,
        )
