"""Asynchronous model averaging.

TPU-native redesign of the reference's ``async_model_average.py`` +
``decentralized_full_precision_asynchronous.rs``.  The reference runs a
background thread that continuously allreduce-averages the live weights on a
dedicated CUDA stream, guarded by weight locks and a 1-byte MIN-allreduce
abort negotiation — machinery that exists because CUDA kernels and NCCL calls
mutate buffers in place while autograd runs.

Under XLA a step is a pure function and collectives are compiler-scheduled,
so in-place cross-thread mutation does not map.  The same *algorithm* —
"train on local data continuously; fold the group average into the weights
every ``sync_interval_ms``, never blocking training on communication" — is
realized with a **host-armed sync variant** of the step function:

* a monotonic timer arms a flag every ``sync_interval_ms``;
* when armed, the next step dispatches the "sync" variant, which averages the
  weights over the group (``pmean`` of the weight buckets) *at step start*,
  exactly where the reference copies peer-averaged weights back between
  steps; otherwise the "plain" variant runs with zero collectives;
* because JAX dispatch is asynchronous, the host never blocks — the sync
  step's collective is overlapped with neighboring steps' compute by XLA's
  latency-hiding scheduler (the role of the reference's comm stream).

``warmup_steps`` of plain gradient allreduce, ``abort()``/``resume()``
(reference ``:232-305``) are preserved.  Both step variants are compiled once
and cached by the engine, so flipping between them costs nothing at runtime.
"""

import time

import jax

from bagua_tpu.algorithms.base import Algorithm, AlgorithmImpl, StepContext
from bagua_tpu.communication import ReduceOp, allreduce_inplace


class AsyncModelAverageAlgorithmImpl(AlgorithmImpl):

    def __init__(
        self,
        process_group,
        peer_selection_mode: str = "all",
        sync_interval_ms: int = 500,
        warmup_steps: int = 0,
    ):
        super().__init__(process_group)
        if peer_selection_mode != "all":
            raise ValueError(
                "async model average supports peer_selection_mode='all' "
                "(the reference rejects others too, async_model_average.py:84-90)"
            )
        self.peer_selection_mode = peer_selection_mode
        self.sync_interval_ms = sync_interval_ms
        self.warmup_steps = warmup_steps
        self._status = "running"
        self._last_sync = 0.0

    # -- host-side scheduling ----------------------------------------------

    def step_variant(self, step: int) -> str:
        if self._status != "running" or step < self.warmup_steps:
            return "plain"
        now = time.monotonic()
        if (now - self._last_sync) * 1000.0 >= self.sync_interval_ms:
            self._last_sync = now
            return "sync"
        return "plain"

    def abort(self):
        """Pause averaging (e.g. around evaluation), reference ``:232-270``."""
        self._status = "aborted"

    def resume(self):
        self._status = "running"
        self._last_sync = 0.0

    # -- traced stages ------------------------------------------------------

    def on_step_start(self, params, state, ctx: StepContext):
        if ctx.extras.get("variant") == "sync":
            flats = ctx.plan.bucketize(params)
            flats = [allreduce_inplace(f, op=ReduceOp.AVG) for f in flats]
            params = ctx.plan.debucketize(flats, params)
        return params, state

    def transform_gradients(self, grads, params, state, ctx: StepContext):
        if self.warmup_steps > 0:
            # Warmup phase: plain gradient allreduce (reference ``:120-141``
            # routes warmup steps through the centralized op).
            def avg(g):
                flats = ctx.plan.bucketize(g)
                return ctx.plan.debucketize(
                    [allreduce_inplace(f, op=ReduceOp.AVG) for f in flats], g
                )

            grads = jax.lax.cond(
                ctx.step < self.warmup_steps, avg, lambda g: g, grads
            )
        return grads, params, state


class AsyncModelAverageAlgorithm(Algorithm):
    def __init__(
        self,
        peer_selection_mode: str = "all",
        sync_interval_ms: int = 500,
        warmup_steps: int = 0,
    ):
        self.peer_selection_mode = peer_selection_mode
        self.sync_interval_ms = sync_interval_ms
        self.warmup_steps = warmup_steps

    def reify(self, process_group) -> AsyncModelAverageAlgorithmImpl:
        return AsyncModelAverageAlgorithmImpl(
            process_group,
            peer_selection_mode=self.peer_selection_mode,
            sync_interval_ms=self.sync_interval_ms,
            warmup_steps=self.warmup_steps,
        )
