"""Centralized synchronous full-precision gradient allreduce.

TPU-native analog of the reference's ``gradient_allreduce.py:31-41``: one
allreduce per bucket, optionally hierarchical (intra-axis reduce, inter-axis
reduce — reference hierarchical communicator ``communicators/mod.rs:262-446``)
and optionally averaging instead of summing.

Under XLA the per-bucket ``pmean`` calls are issued as independent async
collectives, so compute/communication overlap — the reference's Rust
scheduler + dedicated comm stream — comes from the compiler's latency-hiding
scheduler for free.

Bucket fusion is *variadic* by default (``fuse="tuple"``): each bucket's
leaves go into one ``psum`` call, which lowers to a single variadic
``all-reduce`` — the fusion the reference gets from flat bucket buffers
(``bucket.rs`` contiguous storage) with the concat/slice elision guaranteed
by construction.  XLA's optimizer usually rewrites the ``fuse="flat"`` path
into the same program (PERF_AUDIT.md shows identical compiled censuses on
VGG16), but the tuple path never depends on that rewrite firing.
``fuse="flat"`` keeps the materialized-buffer path for parity testing.

``wire_dtype`` (beyond-reference, TPU ICI lever): cast gradients to a
narrower dtype for the exchange only — ``wire_dtype=jnp.bfloat16`` halves
the wire bytes at ~3 decimal digits of mantissa, a far gentler trade than
bytegrad's u8 (the reference's only compression rung below f32).  The
reduction accumulates in the wire dtype (that IS the bandwidth saving);
gradients are cast back to their original dtype afterwards.  Sits between
``gradient_allreduce`` (exact) and ``bytegrad`` (u8) on the
accuracy/bandwidth curve.

``wire_precision`` (the in-collective quantization rung below both): route
a bucket's padded flat buffer through the blockwise-quantized ring
(:mod:`bagua_tpu.kernels.quantized_ring`) — every hop ships int8 or packed
int4 levels plus an 8-byte/block (min, max) sidecar, and each receiving
rank dequantizes, reduces and requantizes in one fused kernel.  ``"int4"``
additionally carries a persistent per-bucket error-feedback residual in
the algorithm state: the requantization error of this step's hops re-enters
the next step's gradient, so the quantization noise telescopes instead of
accumulating.  ``"auto"`` defers the choice to the service planner's
per-bucket precision plan (``set_bucket_precision``), resolving to f32
until one is adopted.  Mutually exclusive with ``wire_dtype``; under
``hierarchical=True`` only the inter-node hops quantize (intra-node stays
an exact f32 sum).  int4/auto disable overlap and re-bucketing — the
residual is per-bucket state the stateless backward hook cannot thread.
"""

import jax
import jax.numpy as jnp

from bagua_tpu.algorithms._precision import WirePrecisionMixin
from bagua_tpu.algorithms.base import Algorithm, AlgorithmImpl, StepContext
from bagua_tpu.bucket import flatten_bucket_leaves, split_bucket_flat
from bagua_tpu.communication import (
    INTER_AXIS,
    INTRA_AXIS,
    ReduceOp,
    allreduce_inplace,
    axis_size,
    hierarchical_allreduce_inplace,
)
from bagua_tpu.kernels.quantized_ring import quantized_ring_allreduce


class GradientAllReduceAlgorithmImpl(WirePrecisionMixin, AlgorithmImpl):
    supports_overlap = True
    algo_name = "gradient_allreduce"

    def __init__(
        self,
        process_group,
        hierarchical: bool = False,
        average: bool = True,
        fuse: str = "tuple",
        wire_dtype=None,
        wire_precision: str = "f32",
        use_pallas=None,
    ):
        super().__init__(process_group, hierarchical=hierarchical)
        self.average = average
        if fuse not in ("tuple", "flat"):
            raise ValueError(f"fuse must be 'tuple' or 'flat', got {fuse!r}")
        self.fuse = fuse
        self.wire_dtype = None if wire_dtype is None else jnp.dtype(wire_dtype)
        if wire_precision != "f32" and self.wire_dtype is not None:
            raise ValueError(
                "wire_dtype and a quantized wire_precision are mutually "
                "exclusive — pick one compression rung"
            )
        self._init_wire_precision(wire_precision, use_pallas)

    def _to_wire(self, tree):
        if self.wire_dtype is None:
            return tree
        return jax.tree.map(
            lambda l: l.astype(self.wire_dtype)
            if jnp.issubdtype(l.dtype, jnp.floating) else l,
            tree,
        )

    def _from_wire(self, tree, like):
        if self.wire_dtype is None:
            return tree
        return jax.tree.map(lambda l, ref: l.astype(ref.dtype), tree, like)

    def init_state(self, params):
        """Error-feedback residuals: one f32 flat buffer per bucket when the
        precision may resolve to int4 (allocated unconditionally for
        ``"auto"`` so the state layout never depends on the adopted plan —
        f32/int8 buckets simply carry zeros through)."""
        if not self._ef_enabled():
            return {}
        return {
            "qr_residual": tuple(
                jnp.zeros((spec.numel,), jnp.float32)
                for spec in self._bound_plan.specs
            )
        }

    def _quantized_bucket_allreduce(self, leaves, spec, precision, residual):
        """All-reduce one bucket's padded flat buffer through the blockwise
        ring; returns ``(flat_out, new_residual)`` (``new_residual`` is None
        when error feedback is off for this bucket).

        Error feedback is sum-space algebra: the ring accumulates *sums* and
        divides once at the end, so a hop's requantization error ``e`` makes
        the average short by ``e/n`` — adding ``e`` to the next step's local
        gradient restores exactly that."""
        bits = 8 if precision == "int8" else 4
        hop = self._ring_hops[bits]
        flat = flatten_bucket_leaves(leaves, spec)
        x = flat.astype(jnp.float32)
        if residual is not None:
            x = x + residual
        if self.hierarchical:
            # Quantize only the slow leg: exact f32 SUM inside the node, then
            # the quantized ring across nodes.  Every rank of an intra group
            # holds the identical inter-ring error, so the residual is scaled
            # by 1/intra_size — the next step's intra sum multiplies it back.
            x = allreduce_inplace(x, op=ReduceOp.SUM, axis=INTRA_AXIS)
            out, err = quantized_ring_allreduce(
                x, INTER_AXIS, bits=bits, average=False, hop=hop
            )
            if self.average:
                out = out / axis_size()
            if residual is not None:
                err = err / axis_size(INTRA_AXIS)
        else:
            out, err = quantized_ring_allreduce(
                x, bits=bits, average=self.average, hop=hop
            )
        return out.astype(flat.dtype), (err if residual is not None else None)

    def transform_gradients(self, grads, params, state, ctx: StepContext):
        op = ReduceOp.AVG if self.average else ReduceOp.SUM
        reduce = hierarchical_allreduce_inplace if self.hierarchical else allreduce_inplace
        precisions = self.bucket_precisions(ctx.plan)
        if all(p == "f32" for p in precisions):
            if self.fuse == "tuple":
                # Variadic fusion: one psum per bucket over the bucket's
                # leaves — a single variadic all-reduce on the wire (the same
                # fusion the flat buffer gives) with zero concat/slice HBM
                # traffic.  psum is elementwise, so the result is
                # bitwise-identical to the flat path (alignment padding
                # reduces to zeros either way).
                groups = ctx.plan.group_leaves(grads)
                reduced = []
                for i, g in enumerate(groups):
                    with self.annotate(i, "mono"):
                        reduced.append(self._from_wire(reduce(self._to_wire(g), op=op), g))
                return ctx.plan.ungroup_leaves(reduced, grads), params, state
            flats = ctx.plan.bucketize(grads)
            out = []
            for i, flat in enumerate(flats):
                with self.annotate(i, "mono"):
                    out.append(self._from_wire(reduce(self._to_wire(flat), op=op), flat))
            return ctx.plan.debucketize(out, grads), params, state
        # Quantized (possibly mixed-precision) path: quantized buckets ride
        # the blockwise ring on their flat buffer; f32 buckets keep their
        # exact program.  int4 buckets thread the error-feedback residual
        # through the algorithm state.
        groups = ctx.plan.group_leaves(grads)
        resid = list(state["qr_residual"]) if "qr_residual" in state else None
        new_groups = []
        for i, spec in enumerate(ctx.plan.specs):
            leaves = [groups[i][s.name] for s in spec.slots]
            prec = precisions[i]
            with self.annotate(i, "mono"):
                if prec == "f32":
                    g = groups[i]
                    new_groups.append(self._from_wire(reduce(self._to_wire(g), op=op), g))
                    continue
                r = resid[i] if (resid is not None and prec == "int4") else None
                out_flat, new_r = self._quantized_bucket_allreduce(leaves, spec, prec, r)
                if new_r is not None:
                    resid[i] = new_r
                red = split_bucket_flat(out_flat, spec)
            new_groups.append({s.name: l for s, l in zip(spec.slots, red)})
        grads = ctx.plan.ungroup_leaves(new_groups, grads)
        if resid is not None:
            state = {**state, "qr_residual": tuple(resid)}
        return grads, params, state

    def overlap_exchange(
        self, bucket_idx: int, grads, ctx: StepContext, params_leaves=None
    ):
        # One bucket's exchange, issued from inside the backward pass (the
        # engine's custom_vjp rule).  Same wire program per bucket as
        # transform_gradients — tuple fuse emits one variadic all-reduce over
        # the leaves, flat fuse materializes the padded bucket buffer first —
        # but anchored at the ops producing this bucket's cotangents instead
        # of after the whole backward.  int8 buckets run the quantized ring
        # here too (stateless, so overlap stays bitwise vs monolithic); int4
        # never reaches this hook (holds_bucketized_state fences it off).
        spec = ctx.plan.specs[bucket_idx]
        prec = self._precision_for_bucket(bucket_idx, spec)
        op = ReduceOp.AVG if self.average else ReduceOp.SUM
        reduce = hierarchical_allreduce_inplace if self.hierarchical else allreduce_inplace
        with self.annotate(bucket_idx, "overlap"):
            if prec != "f32":
                out_flat, _ = self._quantized_bucket_allreduce(
                    list(grads), spec, prec, None
                )
                return split_bucket_flat(out_flat, spec)
            if self.fuse == "tuple":
                grads = list(grads)
                return self._from_wire(reduce(self._to_wire(grads), op=op), grads)
            flat = flatten_bucket_leaves(grads, spec)
            out = self._from_wire(reduce(self._to_wire(flat), op=op), flat)
            return split_bucket_flat(out, spec)


class GradientAllReduceAlgorithm(Algorithm):
    def __init__(
        self,
        hierarchical: bool = False,
        average: bool = True,
        fuse: str = "tuple",
        wire_dtype=None,
        wire_precision: str = "f32",
        use_pallas=None,
    ):
        self.hierarchical = hierarchical
        self.average = average
        self.fuse = fuse
        self.wire_dtype = wire_dtype
        self.wire_precision = wire_precision
        self.use_pallas = use_pallas

    def reify(self, process_group) -> GradientAllReduceAlgorithmImpl:
        return GradientAllReduceAlgorithmImpl(
            process_group,
            hierarchical=self.hierarchical,
            average=self.average,
            fuse=self.fuse,
            wire_dtype=self.wire_dtype,
            wire_precision=self.wire_precision,
            use_pallas=self.use_pallas,
        )
