"""Centralized synchronous full-precision gradient allreduce.

TPU-native analog of the reference's ``gradient_allreduce.py:31-41``: one
allreduce per bucket, optionally hierarchical (intra-axis reduce, inter-axis
reduce — reference hierarchical communicator ``communicators/mod.rs:262-446``)
and optionally averaging instead of summing.

Under XLA the per-bucket ``pmean`` calls are issued as independent async
collectives, so compute/communication overlap — the reference's Rust
scheduler + dedicated comm stream — comes from the compiler's latency-hiding
scheduler for free.
"""

from bagua_tpu.algorithms.base import Algorithm, AlgorithmImpl, StepContext
from bagua_tpu.communication import (
    ReduceOp,
    allreduce_inplace,
    hierarchical_allreduce_inplace,
)


class GradientAllReduceAlgorithmImpl(AlgorithmImpl):
    def __init__(self, process_group, hierarchical: bool = False, average: bool = True):
        super().__init__(process_group, hierarchical=hierarchical)
        self.average = average

    def transform_gradients(self, grads, params, state, ctx: StepContext):
        op = ReduceOp.AVG if self.average else ReduceOp.SUM
        flats = ctx.plan.bucketize(grads)
        out = []
        for flat in flats:
            if self.hierarchical:
                out.append(hierarchical_allreduce_inplace(flat, op=op))
            else:
                out.append(allreduce_inplace(flat, op=op))
        return ctx.plan.debucketize(out, grads), params, state


class GradientAllReduceAlgorithm(Algorithm):
    def __init__(self, hierarchical: bool = False, average: bool = True):
        self.hierarchical = hierarchical
        self.average = average

    def reify(self, process_group) -> GradientAllReduceAlgorithmImpl:
        return GradientAllReduceAlgorithmImpl(
            process_group, hierarchical=self.hierarchical, average=self.average
        )
