"""Centralized synchronous full-precision gradient allreduce.

TPU-native analog of the reference's ``gradient_allreduce.py:31-41``: one
allreduce per bucket, optionally hierarchical (intra-axis reduce, inter-axis
reduce — reference hierarchical communicator ``communicators/mod.rs:262-446``)
and optionally averaging instead of summing.

Under XLA the per-bucket ``pmean`` calls are issued as independent async
collectives, so compute/communication overlap — the reference's Rust
scheduler + dedicated comm stream — comes from the compiler's latency-hiding
scheduler for free.

Bucket fusion is *variadic* by default (``fuse="tuple"``): each bucket's
leaves go into one ``psum`` call, which lowers to a single variadic
``all-reduce`` — the fusion the reference gets from flat bucket buffers
(``bucket.rs`` contiguous storage) with the concat/slice elision guaranteed
by construction.  XLA's optimizer usually rewrites the ``fuse="flat"`` path
into the same program (PERF_AUDIT.md shows identical compiled censuses on
VGG16), but the tuple path never depends on that rewrite firing.
``fuse="flat"`` keeps the materialized-buffer path for parity testing.

``wire_dtype`` (beyond-reference, TPU ICI lever): cast gradients to a
narrower dtype for the exchange only — ``wire_dtype=jnp.bfloat16`` halves
the wire bytes at ~3 decimal digits of mantissa, a far gentler trade than
bytegrad's u8 (the reference's only compression rung below f32).  The
reduction accumulates in the wire dtype (that IS the bandwidth saving);
gradients are cast back to their original dtype afterwards.  Sits between
``gradient_allreduce`` (exact) and ``bytegrad`` (u8) on the
accuracy/bandwidth curve.
"""

import jax
import jax.numpy as jnp

from bagua_tpu.algorithms.base import Algorithm, AlgorithmImpl, StepContext
from bagua_tpu.bucket import flatten_bucket_leaves, split_bucket_flat
from bagua_tpu.communication import (
    ReduceOp,
    allreduce_inplace,
    hierarchical_allreduce_inplace,
)


class GradientAllReduceAlgorithmImpl(AlgorithmImpl):
    supports_overlap = True
    algo_name = "gradient_allreduce"

    def __init__(
        self,
        process_group,
        hierarchical: bool = False,
        average: bool = True,
        fuse: str = "tuple",
        wire_dtype=None,
    ):
        super().__init__(process_group, hierarchical=hierarchical)
        self.average = average
        if fuse not in ("tuple", "flat"):
            raise ValueError(f"fuse must be 'tuple' or 'flat', got {fuse!r}")
        self.fuse = fuse
        self.wire_dtype = None if wire_dtype is None else jnp.dtype(wire_dtype)

    def _to_wire(self, tree):
        if self.wire_dtype is None:
            return tree
        return jax.tree.map(
            lambda l: l.astype(self.wire_dtype)
            if jnp.issubdtype(l.dtype, jnp.floating) else l,
            tree,
        )

    def _from_wire(self, tree, like):
        if self.wire_dtype is None:
            return tree
        return jax.tree.map(lambda l, ref: l.astype(ref.dtype), tree, like)

    def transform_gradients(self, grads, params, state, ctx: StepContext):
        op = ReduceOp.AVG if self.average else ReduceOp.SUM
        reduce = hierarchical_allreduce_inplace if self.hierarchical else allreduce_inplace
        if self.fuse == "tuple":
            # Variadic fusion: one psum per bucket over the bucket's leaves —
            # a single variadic all-reduce on the wire (the same fusion the
            # flat buffer gives) with zero concat/slice HBM traffic.  psum is
            # elementwise, so the result is bitwise-identical to the flat
            # path (alignment padding reduces to zeros either way).
            groups = ctx.plan.group_leaves(grads)
            reduced = []
            for i, g in enumerate(groups):
                with self.annotate(i, "mono"):
                    reduced.append(self._from_wire(reduce(self._to_wire(g), op=op), g))
            return ctx.plan.ungroup_leaves(reduced, grads), params, state
        flats = ctx.plan.bucketize(grads)
        out = []
        for i, flat in enumerate(flats):
            with self.annotate(i, "mono"):
                out.append(self._from_wire(reduce(self._to_wire(flat), op=op), flat))
        return ctx.plan.debucketize(out, grads), params, state

    def overlap_exchange(
        self, bucket_idx: int, grads, ctx: StepContext, params_leaves=None
    ):
        # One bucket's exchange, issued from inside the backward pass (the
        # engine's custom_vjp rule).  Same wire program per bucket as
        # transform_gradients — tuple fuse emits one variadic all-reduce over
        # the leaves, flat fuse materializes the padded bucket buffer first —
        # but anchored at the ops producing this bucket's cotangents instead
        # of after the whole backward.
        spec = ctx.plan.specs[bucket_idx]
        op = ReduceOp.AVG if self.average else ReduceOp.SUM
        reduce = hierarchical_allreduce_inplace if self.hierarchical else allreduce_inplace
        with self.annotate(bucket_idx, "overlap"):
            if self.fuse == "tuple":
                grads = list(grads)
                return self._from_wire(reduce(self._to_wire(grads), op=op), grads)
            flat = flatten_bucket_leaves(grads, spec)
            out = self._from_wire(reduce(self._to_wire(flat), op=op), flat)
            return split_bucket_flat(out, spec)


class GradientAllReduceAlgorithm(Algorithm):
    def __init__(
        self,
        hierarchical: bool = False,
        average: bool = True,
        fuse: str = "tuple",
        wire_dtype=None,
    ):
        self.hierarchical = hierarchical
        self.average = average
        self.fuse = fuse
        self.wire_dtype = wire_dtype

    def reify(self, process_group) -> GradientAllReduceAlgorithmImpl:
        return GradientAllReduceAlgorithmImpl(
            process_group,
            hierarchical=self.hierarchical,
            average=self.average,
            fuse=self.fuse,
            wire_dtype=self.wire_dtype,
        )
