"""ByteGrad: centralized synchronous 8-bit-compressed gradient allreduce.

TPU-native analog of the reference's ``bytegrad.py`` +
``centralized_low_precision_synchronous.rs:30-71``.  The compressed allreduce
is the reference's scatter-gather pipeline, expressed with XLA collectives:

    compress → all_to_all → decompress → chunk-mean → compress(own chunk)
             → all_gather → decompress

Each rank quantizes its bucket per destination chunk (chunk = numel / n,
guaranteed exact by the bucket plan's ``align_elems = n`` padding — the
reference aligns buckets to ``nranks`` for the same reason,
``bytegrad.py:33-45``), reduces the chunk it owns in float32, re-quantizes it,
and gathers everyone's chunk.  All ranks produce bitwise-identical results
because the quantizers run on identical reduced values.

Hierarchical mode (reference's default for ByteGrad) reduces the ``intra``
axis in full precision first, runs the compressed pipeline over the ``inter``
axis only, then needs no explicit intra broadcast: every intra peer already
holds the same value.
"""

import jax.numpy as jnp

from bagua_tpu.algorithms.base import Algorithm, AlgorithmImpl, StepContext
from bagua_tpu.communication import (
    INTER_AXIS,
    INTRA_AXIS,
    ReduceOp,
    allreduce_inplace,
    alltoall_inplace,
    allgather_inplace,
    axis_size,
)
from bagua_tpu.kernels.minmax_uint8 import get_compressors


def compressed_allreduce(
    flat: jnp.ndarray, axes, average: bool = True, use_pallas=None
) -> jnp.ndarray:
    """The scatter-gather compressed allreduce over ``axes`` (traced).

    ``use_pallas`` selects the quantizer implementation (None = auto: Pallas
    kernels on TPU, jnp elsewhere — see ``kernels.get_compressors``)."""
    compress_minmax_uint8, decompress_minmax_uint8 = get_compressors(use_pallas)
    n = axis_size(axes)
    if n == 1:
        return flat
    chunk = flat.shape[0] // n
    chunks = flat.reshape(n, chunk)

    q, mm = compress_minmax_uint8(chunks)
    q_recv = alltoall_inplace(q, axis=axes)  # (n, chunk): everyone's chunk for me
    mm_recv = alltoall_inplace(mm, axis=axes)  # (n, 2)

    x = decompress_minmax_uint8(q_recv, mm_recv)  # (n, chunk) float32
    red = jnp.sum(x, axis=0, keepdims=True)
    if average:
        red = red / n

    q2, mm2 = compress_minmax_uint8(red)  # (1, chunk)
    qg = allgather_inplace(q2, axis=axes, tiled=True)  # (n, chunk)
    mmg = allgather_inplace(mm2, axis=axes, tiled=True)  # (n, 2)
    return decompress_minmax_uint8(qg, mmg).reshape(-1).astype(flat.dtype)


class ByteGradAlgorithmImpl(AlgorithmImpl):
    def __init__(
        self, process_group, hierarchical: bool = True, average: bool = True,
        use_pallas=None,
    ):
        super().__init__(process_group, hierarchical=hierarchical)
        self.average = average
        self.use_pallas = use_pallas

    def transform_gradients(self, grads, params, state, ctx: StepContext):
        flats = ctx.plan.bucketize(grads)
        out = []
        for flat, spec in zip(flats, ctx.plan.specs):
            if spec.dtype not in ("f32", "f16", "bf16"):
                # Non-float buckets fall back to plain allreduce, like the
                # reference rejecting non-float tensors for compression.
                op = ReduceOp.AVG if self.average else ReduceOp.SUM
                out.append(allreduce_inplace(flat, op=op))
                continue
            if self.hierarchical and self.process_group.intra_size > 1:
                intra = allreduce_inplace(flat, op=ReduceOp.SUM, axis=INTRA_AXIS)
                red = compressed_allreduce(
                    intra, (INTER_AXIS,), average=False, use_pallas=self.use_pallas
                )
                if self.average:
                    red = red / self.process_group.size
                out.append(red.astype(flat.dtype))
            else:
                out.append(
                    compressed_allreduce(
                        flat, (INTER_AXIS, INTRA_AXIS), self.average,
                        use_pallas=self.use_pallas,
                    )
                )
        return ctx.plan.debucketize(out, grads), params, state


class ByteGradAlgorithm(Algorithm):
    def __init__(self, hierarchical: bool = True, average: bool = True, use_pallas=None):
        self.hierarchical = hierarchical
        self.average = average
        self.use_pallas = use_pallas

    def reify(self, process_group) -> ByteGradAlgorithmImpl:
        return ByteGradAlgorithmImpl(
            process_group, hierarchical=self.hierarchical, average=self.average,
            use_pallas=self.use_pallas,
        )
