"""ByteGrad: centralized synchronous 8-bit-compressed gradient allreduce.

TPU-native analog of the reference's ``bytegrad.py`` +
``centralized_low_precision_synchronous.rs:30-71``.  The compressed allreduce
is the reference's scatter-gather pipeline, expressed with XLA collectives:

    compress → all_to_all → decompress → chunk-mean → compress(own chunk)
             → all_gather → decompress

Each rank quantizes its bucket per destination chunk (chunk = numel / n,
guaranteed exact by the bucket plan's ``align_elems = n`` padding — the
reference aligns buckets to ``nranks`` for the same reason,
``bytegrad.py:33-45``), reduces the chunk it owns in float32, re-quantizes it,
and gathers everyone's chunk.  All ranks produce bitwise-identical results
because the quantizers run on identical reduced values.

Hierarchical mode (reference's default for ByteGrad) reduces the ``intra``
axis in full precision first, runs the compressed pipeline over the ``inter``
axis only, then needs no explicit intra broadcast: every intra peer already
holds the same value.
"""

import jax.numpy as jnp

from bagua_tpu.algorithms.base import Algorithm, AlgorithmImpl, StepContext
from bagua_tpu.bucket import flatten_bucket_leaves, split_bucket_flat
from bagua_tpu.communication import (
    INTER_AXIS,
    INTRA_AXIS,
    ReduceOp,
    allreduce_inplace,
    alltoall_inplace,
    allgather_inplace,
    axis_size,
)
from bagua_tpu.kernels.minmax_uint8 import get_compressors, get_fused_reducer


def compressed_allreduce(
    flat: jnp.ndarray, axes, average: bool = True, use_pallas=None,
    compressors=None, fused_reducer=None,
) -> jnp.ndarray:
    """The scatter-gather compressed allreduce over ``axes`` (traced).

    ``use_pallas`` selects the quantizer implementation (None = auto: Pallas
    kernels on TPU, jnp elsewhere — see ``kernels.get_compressors``).
    Callers on the hot path pass pre-resolved ``compressors`` /
    ``fused_reducer`` (resolved once at algorithm construction) so the
    evidence-file lookup never runs inside a trace."""
    if compressors is None:
        compressors = get_compressors(use_pallas)
    if fused_reducer is None:
        fused_reducer = get_fused_reducer(use_pallas)
    compress_minmax_uint8, decompress_minmax_uint8 = compressors
    n = axis_size(axes)
    if n == 1:
        return flat
    chunk = flat.shape[0] // n
    chunks = flat.reshape(n, chunk)

    q, mm = compress_minmax_uint8(chunks)
    q_recv = alltoall_inplace(q, axis=axes)  # (n, chunk): everyone's chunk for me
    mm_recv = alltoall_inplace(mm, axis=axes)  # (n, 2)

    # Fused middle stages: decompress → float32 tree-sum → requantize, one
    # kernel instead of three staged HBM passes (jnp composition elsewhere).
    q2, mm2 = fused_reducer(q_recv, mm_recv, average=average)  # (1, chunk)

    qg = allgather_inplace(q2, axis=axes, tiled=True)  # (n, chunk)
    mmg = allgather_inplace(mm2, axis=axes, tiled=True)  # (n, 2)
    return decompress_minmax_uint8(qg, mmg).reshape(-1).astype(flat.dtype)


class ByteGradAlgorithmImpl(AlgorithmImpl):
    supports_overlap = True
    algo_name = "bytegrad"

    def __init__(
        self, process_group, hierarchical: bool = True, average: bool = True,
        use_pallas=None,
    ):
        super().__init__(process_group, hierarchical=hierarchical)
        self.average = average
        self.use_pallas = use_pallas
        # Resolve the quantizer + fused-reducer implementations ONCE here:
        # resolution reads the hardware evidence file, which must not run
        # inside the per-bucket trace path on every compile.
        self._compressors = get_compressors(use_pallas)
        self._fused_reducer = get_fused_reducer(use_pallas)

    def _exchange_flat(self, flat, spec):
        """One bucket's exchange — the single wire program shared by the
        monolithic and overlap paths (bitwise-identical outputs)."""
        if spec.dtype not in ("f32", "f16", "bf16"):
            # Non-float buckets fall back to plain allreduce, like the
            # reference rejecting non-float tensors for compression.
            op = ReduceOp.AVG if self.average else ReduceOp.SUM
            return allreduce_inplace(flat, op=op)
        if self.hierarchical and self.process_group.intra_size > 1:
            intra = allreduce_inplace(flat, op=ReduceOp.SUM, axis=INTRA_AXIS)
            red = compressed_allreduce(
                intra, (INTER_AXIS,), average=False,
                compressors=self._compressors, fused_reducer=self._fused_reducer,
            )
            if self.average:
                red = red / self.process_group.size
            return red.astype(flat.dtype)
        return compressed_allreduce(
            flat, (INTER_AXIS, INTRA_AXIS), self.average,
            compressors=self._compressors, fused_reducer=self._fused_reducer,
        )

    def transform_gradients(self, grads, params, state, ctx: StepContext):
        flats = ctx.plan.bucketize(grads)
        out = []
        for i, (flat, spec) in enumerate(zip(flats, ctx.plan.specs)):
            with self.annotate(i, "mono"):
                out.append(self._exchange_flat(flat, spec))
        return ctx.plan.debucketize(out, grads), params, state

    def overlap_exchange(
        self, bucket_idx: int, grads, ctx: StepContext, params_leaves=None
    ):
        # One bucket's compressed pipeline, issued from this bucket's
        # custom_vjp backward rule: both hierarchical legs (full-precision
        # intra psum + compressed inter scatter-gather) anchor at the ops
        # producing the bucket's cotangents, so XLA overlaps the wire with
        # the rest of the backward.  Flattening here reproduces bucketize's
        # padded layout exactly — same chunk boundaries, same quantizer
        # inputs, bitwise-identical to the monolithic path.
        spec = ctx.plan.specs[bucket_idx]
        with self.annotate(bucket_idx, "overlap"):
            flat = flatten_bucket_leaves(grads, spec)
            return split_bucket_flat(self._exchange_flat(flat, spec), spec)


class ByteGradAlgorithm(Algorithm):
    def __init__(self, hierarchical: bool = True, average: bool = True, use_pallas=None):
        self.hierarchical = hierarchical
        self.average = average
        self.use_pallas = use_pallas

    def reify(self, process_group) -> ByteGradAlgorithmImpl:
        return ByteGradAlgorithmImpl(
            process_group, hierarchical=self.hierarchical, average=self.average,
            use_pallas=self.use_pallas,
        )
