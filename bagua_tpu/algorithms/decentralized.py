"""Decentralized SGD: full-precision and low-precision (ring) variants.

TPU-native analog of the reference's ``decentralized.py`` and the Rust ops
``decentralized_full_precision_synchronous.rs`` /
``decentralized_low_precision_synchronous.rs``.

**Full precision** (reference ``decentralized.py:12-110``): each step the
*weights* (one fused bucket, ``decentralized.py:52-61``) are exchanged with
peers — either ``all`` (allreduce-AVG into a peer buffer) or ``shift_one``
(symmetric pairing that cycles with the step counter,
``decentralized_full_precision_synchronous.rs:80-86``) — and the averaged
peer weights replace the parameters before the optimizer update (the
reference starts the exchange at forward-pre and copies back post-backward;
dataflow-wise that is exactly "grads at w_t, update applied to avg(w_t)",
and XLA overlaps the exchange with the backward pass on its own).

**Low precision** (reference ``decentralized.py:112-214``, Rust op above):
runs *after* the optimizer step.  Each rank keeps three replicas per bucket —
``weight`` (own weights at last sync), ``left``/``right`` (ring neighbors'),
— compresses the mixed difference

    diff = (t - w) + (L - w)/3 + (R - w)/3      [t = fresh post-optimizer]

with MinMaxUInt8 (whole bucket = one chunk), exchanges it both ways around
the ring, accumulates the received diffs into the neighbor replicas, and
overwrites both ``w`` and the live parameters with ``w + dequant(own diff)``
so every rank's view of every replica stays bitwise-consistent.

``hierarchical=True`` (the reference default) averages over the ``intra``
axis first and runs the decentralized exchange over the ``inter`` axis only,
so "peers" are machines, not chips.

**Eager gossip** (``staleness_tau=τ``, the BAGUA sync/async relaxation axis
applied to this weight exchange): each round a rank still enters its
step-indexed exchange — the collective program is unconditional, identical
to the τ=None trace — but a rank flagged by the host-side degradation
directive may *publish its last-synced weights* and skip folding the peer
average into its live parameters for up to τ consecutive rounds.  Per-rank
``staleness`` counters ride the algorithm state in-graph; at staleness τ the
gate closes and the rank rejoins with a full exchange on round τ+1, so
divergence is bounded by construction.  Participation is gated elementwise
on the payload with ``jnp.where`` (a rank-varying ``lax.cond`` around a
ppermute would deadlock SPMD), and every gossip exchange is traced under a
``bagua_stale/tau=<τ>`` sanction frame for the static verifier.
"""

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from bagua_tpu.algorithms.base import Algorithm, AlgorithmImpl, OverlapCapability, StepContext
from bagua_tpu.observability.scope_grammar import format_stale_scope
from bagua_tpu.bucket import flatten_bucket_leaves, split_bucket_flat
from bagua_tpu.communication import (
    ALL_AXES,
    INTER_AXIS,
    INTRA_AXIS,
    ReduceOp,
    allreduce_inplace,
    axis_size,
    ppermute_apply,
    ppermute_shift,
)
from bagua_tpu.kernels.minmax_uint8 import get_compressors


def _shift_one_perm(step: int, n: int) -> List[Tuple[int, int]]:
    """The reference's step-indexed symmetric pairing
    (``decentralized_full_precision_synchronous.rs:80-86``): rank < n/2 pairs
    with ``((step + rank) % (n/2)) + n/2``."""
    h = n // 2
    perm = []
    for r in range(n):
        if r < h:
            peer = ((step + r) % h) + h
        else:
            peer = (r - h - step) % h
        perm.append((r, peer))
    return perm


def _exchange(flat: jnp.ndarray, step, mode: str, axes) -> jnp.ndarray:
    """One decentralized exchange returning the averaged peer weight."""
    n = axis_size(axes)
    if n == 1:
        return flat
    if mode == "all":
        return allreduce_inplace(flat, op=ReduceOp.AVG, axis=axes)
    if mode == "shift_one":
        if n % 2 != 0:
            raise ValueError(
                "shift_one requires an even number of peers: world size "
                f"{n} cannot be symmetrically paired (ranks split into "
                "lower/upper halves, and the middle rank would land in "
                "both schedules). Resize the gang to an even world size "
                f"(e.g. {n - 1} or {n + 1}) or use "
                "peer_selection_mode='all' — see reference "
                "decentralized_full_precision_synchronous.rs:71-79"
            )
        h = n // 2
        branches = [
            (lambda x, perm=_shift_one_perm(s, n): ppermute_apply(x, perm, axes))
            for s in range(h)
        ]
        recv = jax.lax.switch(step % h, branches, flat)
        return (flat + recv) * 0.5
    raise ValueError(f"unknown peer_selection_mode {mode!r}")


class DecentralizedAlgorithmImpl(AlgorithmImpl):
    supports_overlap = True
    algo_name = "decentralized"
    #: the exchange moves *weights*, which don't data-depend on the backward —
    #: the engine anchors each bucket's collective on its cotangents instead
    #: of wrapping params in a custom_vjp (see OverlapCapability).
    overlap_mode = "weight"

    def __init__(
        self,
        process_group,
        hierarchical: bool = True,
        peer_selection_mode: str = "all",
        communication_interval: int = 1,
        staleness_tau: Optional[int] = None,
    ):
        super().__init__(process_group, hierarchical=hierarchical)
        self.peer_selection_mode = peer_selection_mode
        self.communication_interval = communication_interval
        if peer_selection_mode == "shift_one":
            # Construction-time fence for the step-indexed symmetric pairing:
            # _shift_one_perm partitions ranks into lower/upper halves, so an
            # odd peer count would silently mis-pair (rank n//2 lands in both
            # schedules).  Failing here names the mesh; the trace-time check
            # in _exchange stays as the backstop for hand-built groups.
            peers = (
                process_group.inter_size
                if hierarchical and process_group.intra_size > 1
                else process_group.exchange_size
            )
            if peers > 1 and peers % 2 != 0:
                raise ValueError(
                    "peer_selection_mode='shift_one' requires an even number "
                    f"of peers: this group exchanges across {peers} peers "
                    f"(group {process_group!r}), which cannot be "
                    "symmetrically paired. Resize the gang to an even peer "
                    f"count (e.g. {peers - 1} or {peers + 1}) or use "
                    "peer_selection_mode='all' — see reference "
                    "decentralized_full_precision_synchronous.rs:71-79"
                )
        if staleness_tau is not None:
            staleness_tau = int(staleness_tau)
            if staleness_tau < 0:
                raise ValueError(f"staleness_tau must be >= 0, got {staleness_tau}")
            if hierarchical:
                raise ValueError(
                    "gossip staleness (staleness_tau=...) requires "
                    "hierarchical=False: the per-rank staleness gate is "
                    "defined on the full exchange, not the intra/inter split"
                )
            if communication_interval != 1:
                raise ValueError(
                    "gossip staleness (staleness_tau=...) requires "
                    "communication_interval=1: skipped rounds are what the "
                    "staleness counter accounts for"
                )
            # published replicas are laid out per-bucket on the bound plan —
            # instance attr (not class) so plain decentralized keeps its
            # stateless rebucket/autotune freedom.
            self.holds_bucketized_state = True
        self.staleness_tau = staleness_tau

    def set_staleness_tau(self, tau) -> None:
        """Host-side τ switch (the engine's ``apply_staleness``); only valid
        on instances constructed in gossip mode — the published/staleness
        state must exist from init for the re-trace to see it."""
        if self.staleness_tau is None:
            raise ValueError(
                "this DecentralizedAlgorithmImpl was not constructed with "
                "staleness_tau; gossip state must be allocated at init "
                "(pass staleness_tau=0 to construct the knob disabled)"
            )
        tau = int(tau)
        if tau < 0:
            raise ValueError(f"staleness_tau must be >= 0, got {tau}")
        self.staleness_tau = tau

    def tensors_to_buckets(self, tree, bucket_size_bytes=None, filter_fn=None):
        # The reference puts ALL weights in one bucket (``decentralized.py:
        # 52-61``) — one giant collective minimizes launch overhead when
        # nothing overlaps.  Under overlap the whole point is per-bucket
        # granularity (each peer-weight ppermute issues as its bucket's
        # cotangents arrive), so keep the default multi-bucket split then.
        # All exchanges are elementwise, so the split never changes numerics.
        if getattr(self, "overlap_hint", False):
            return super().tensors_to_buckets(
                tree, bucket_size_bytes=bucket_size_bytes, filter_fn=filter_fn
            )
        return super().tensors_to_buckets(tree, bucket_size_bytes=1 << 62, filter_fn=filter_fn)

    def _exchange_flat(self, flat, comm_round):
        if self.hierarchical and self.process_group.intra_size > 1:
            flat = allreduce_inplace(flat, op=ReduceOp.AVG, axis=INTRA_AXIS)
            return _exchange(flat, comm_round, self.peer_selection_mode, (INTER_AXIS,))
        return _exchange(flat, comm_round, self.peer_selection_mode, ALL_AXES)

    def overlap_capability(self) -> OverlapCapability:
        if self.staleness_tau is None:
            return super().overlap_capability()
        # Gossip holds per-bucket published replicas (normally an overlap
        # veto), but they are laid out ON the bound plan and the gate is
        # elementwise — the bucket split never changes numerics, same as the
        # stateless weight exchange.
        return OverlapCapability(True, mode="weight", auto=True)

    def init_state(self, params):
        if self.staleness_tau is None:
            return super().init_state(params)
        # Last-published weights start equal to the live weights (everyone is
        # freshly synced at init), plus the per-rank staleness counter and the
        # host-flipped degradation directive (both stacked to (n,) by the
        # engine).
        plan = getattr(self, "_bound_plan", None) or self.tensors_to_buckets(params)
        return {
            "published": tuple(plan.bucketize(params)),
            "staleness": jnp.zeros((), jnp.int32),
            "directive": jnp.zeros((), jnp.int32),
        }

    def _gossip_gate(self, state):
        tau = int(self.staleness_tau)
        return (state["directive"] > 0) & (state["staleness"] < tau)

    def overlap_exchange(
        self, bucket_idx: int, grads, ctx: StepContext, params_leaves=None
    ):
        # One bucket's peer-weight exchange, anchored on the bucket's
        # cotangents: weights don't data-depend on the backward, so without
        # the barrier XLA would hoist (or sink) the collective freely.  Tying
        # the weight buffer to this bucket's gradients makes the ppermute /
        # allreduce issuable exactly when the bucket's backward finishes —
        # the early-issue the reference gets from starting the exchange at
        # forward-pre and syncing post-backward.
        spec = ctx.plan.specs[bucket_idx]
        if self.staleness_tau is not None:
            # Gossip: the collective itself is unconditional (same ppermute /
            # allreduce as τ=None); a gossiping-stale rank ships its published
            # replica instead of its live weights and discards the received
            # average, all via elementwise where on the payload.  The updated
            # replica is stashed in ctx.extras for finalize_overlap — per-
            # bucket state cannot return through this hook (it must hand back
            # exactly the bucket's parameter leaves).
            state = ctx.extras["algo_state"]
            use_stale = self._gossip_gate(state)
            with self.annotate(bucket_idx, "overlap"), jax.named_scope(
                format_stale_scope(self.staleness_tau)
            ):
                flat = flatten_bucket_leaves(params_leaves, spec)
                flat = jax.lax.optimization_barrier((flat,) + tuple(grads))[0]
                payload = jnp.where(use_stale, state["published"][bucket_idx], flat)
                avg = self._exchange_flat(payload, ctx.step)
                new = jnp.where(use_stale, flat, avg)
                ctx.extras.setdefault("gossip_published", {})[bucket_idx] = jnp.where(
                    use_stale, state["published"][bucket_idx], new
                )
                return split_bucket_flat(new, spec)
        with self.annotate(bucket_idx, "overlap"):
            flat = flatten_bucket_leaves(params_leaves, spec)
            flat = jax.lax.optimization_barrier((flat,) + tuple(grads))[0]
            comm_round = ctx.step // self.communication_interval

            if self.communication_interval > 1:
                flat = jax.lax.cond(
                    ctx.step % self.communication_interval == 0,
                    lambda f: self._exchange_flat(f, comm_round),
                    lambda f: f,
                    flat,
                )
            else:
                flat = self._exchange_flat(flat, comm_round)
            return split_bucket_flat(flat, spec)

    def finalize_overlap(self, grads, params, state, ctx: StepContext):
        if self.staleness_tau is None:
            return super().finalize_overlap(grads, params, state, ctx)
        stashed = ctx.extras.pop("gossip_published", None)
        if stashed is None:
            return grads, params, state
        use_stale = self._gossip_gate(state)
        published = tuple(
            stashed.get(i, p) for i, p in enumerate(state["published"])
        )
        staleness = jnp.where(
            use_stale, state["staleness"] + 1, jnp.zeros_like(state["staleness"])
        )
        return grads, params, {**state, "published": published, "staleness": staleness}

    def transform_gradients(self, grads, params, state, ctx: StepContext):
        if self.staleness_tau is not None:
            return self._gossip_transform(grads, params, state, ctx)
        # The reference op keeps its own counter incremented once per executed
        # exchange (the `step` Mutex in decentralized_full_precision_
        # synchronous.rs), so the shift_one schedule cycles through every peer
        # even when communication_interval skips steps.
        comm_round = ctx.step // self.communication_interval

        def communicate(params):
            flats = ctx.plan.bucketize(params)
            out = []
            for i, flat in enumerate(flats):
                with self.annotate(i, "mono"):
                    out.append(self._exchange_flat(flat, comm_round))
            return ctx.plan.debucketize(out, params)

        if self.communication_interval > 1:
            params = jax.lax.cond(
                ctx.step % self.communication_interval == 0, communicate, lambda p: p, params
            )
        else:
            params = communicate(params)
        return grads, params, state

    def _gossip_transform(self, grads, params, state, ctx: StepContext):
        # Monolithic gossip round (interval fenced to 1 at construction): at
        # τ=0 the gate is constant-False and every where() is the identity —
        # params come out bitwise-equal to the τ=None path.
        use_stale = self._gossip_gate(state)
        flats = ctx.plan.bucketize(params)
        out, new_pub = [], []
        for i, flat in enumerate(flats):
            with self.annotate(i, "mono"), jax.named_scope(
                format_stale_scope(self.staleness_tau)
            ):
                payload = jnp.where(use_stale, state["published"][i], flat)
                avg = self._exchange_flat(payload, ctx.step)
                new = jnp.where(use_stale, flat, avg)
            out.append(new)
            new_pub.append(jnp.where(use_stale, state["published"][i], new))
        params = ctx.plan.debucketize(out, params)
        state = {
            **state,
            "published": tuple(new_pub),
            "staleness": jnp.where(
                use_stale, state["staleness"] + 1, jnp.zeros_like(state["staleness"])
            ),
        }
        return grads, params, state


class DecentralizedAlgorithm(Algorithm):
    def __init__(
        self,
        hierarchical: bool = True,
        peer_selection_mode: str = "all",
        communication_interval: int = 1,
        staleness_tau: Optional[int] = None,
    ):
        self.hierarchical = hierarchical
        self.peer_selection_mode = peer_selection_mode
        self.communication_interval = communication_interval
        self.staleness_tau = staleness_tau

    def reify(self, process_group) -> DecentralizedAlgorithmImpl:
        return DecentralizedAlgorithmImpl(
            process_group,
            hierarchical=self.hierarchical,
            peer_selection_mode=self.peer_selection_mode,
            communication_interval=self.communication_interval,
            staleness_tau=self.staleness_tau,
        )


# ---------------------------------------------------------------------------
# Low-precision (ring, compressed weight diffs)
# ---------------------------------------------------------------------------


class LowPrecisionDecentralizedAlgorithmImpl(AlgorithmImpl):
    #: replicas in algo_state are laid out per-bucket; re-bucketing would
    #: desync them (DistributedDataParallel.rebucket refuses).
    holds_bucketized_state = True
    supports_overlap = True
    overlap_mode = "post_step"
    algo_name = "low_precision_decentralized"

    def __init__(
        self, process_group, hierarchical: bool = True,
        communication_interval: int = 1, use_pallas=None,
    ):
        super().__init__(process_group, hierarchical=hierarchical)
        self.communication_interval = communication_interval
        self.use_pallas = use_pallas  # compressor impl (kernels.get_compressors)
        # resolved once; the evidence-file lookup must not run per trace
        self._compressors = get_compressors(use_pallas)

    def overlap_capability(self) -> OverlapCapability:
        # ``holds_bucketized_state`` normally vetoes overlap (the base
        # heuristic), but here the replicas are laid out ON the bound plan —
        # per-bucket native — and the ring exchange already runs bucket by
        # bucket in on_step_end.  Overlap therefore only switches the plan to
        # multi-bucket granularity ("post_step" mode) so each bucket's
        # compress→ppermute chain issues as soon as its own update finishes.
        # auto=False: splitting the mega-bucket moves the quantizer's min/max
        # granularity (per-bucket instead of whole-model), so results are NOT
        # bitwise-identical to the monolithic row — auto must never change
        # numerics, overlap stays explicit opt-in.
        return OverlapCapability(
            True, mode="post_step", auto=False,
            reason="LowPrecisionDecentralizedAlgorithmImpl overlap changes "
            "quantization granularity (per-bucket min/max); enable explicitly "
            "with overlap=True",
        )

    def tensors_to_buckets(self, tree, bucket_size_bytes=None, filter_fn=None):
        # Mega-bucket by default (one ring exchange, whole-model min/max —
        # the reference layout); multi-bucket under overlap so the per-bucket
        # chains interleave with the optimizer update's tail.
        if getattr(self, "overlap_hint", False):
            return super().tensors_to_buckets(
                tree, bucket_size_bytes=bucket_size_bytes, filter_fn=filter_fn
            )
        return super().tensors_to_buckets(tree, bucket_size_bytes=1 << 62, filter_fn=filter_fn)

    def _axes(self):
        if self.hierarchical and self.process_group.intra_size > 1:
            return (INTER_AXIS,)
        return ALL_AXES

    def init_state(self, params):
        # weight / left / right replicas, one flat array per bucket
        # (reference ``decentralized.py:186-197`` initializes the replicas
        # from the freshly-broadcast weights, so all three start equal).
        # Use the engine's plan when bound so any dp_filter is respected.
        plan = getattr(self, "_bound_plan", None) or self.tensors_to_buckets(params)
        flats = plan.bucketize(params)
        return {
            "weight": [f for f in flats],
            "left": [f for f in flats],
            "right": [f for f in flats],
        }

    def on_step_end(self, params, state, ctx: StepContext):
        axes = self._axes()

        compress_minmax_uint8, decompress_minmax_uint8 = self._compressors

        def communicate(operand):
            params, state = operand
            flats = ctx.plan.bucketize(params)
            if self.hierarchical and self.process_group.intra_size > 1:
                flats = [
                    allreduce_inplace(f, op=ReduceOp.AVG, axis=INTRA_AXIS) for f in flats
                ]
            new_flats, new_w, new_l, new_r = [], [], [], []
            for i, (t, w, left, right) in enumerate(zip(
                flats, state["weight"], state["left"], state["right"]
            )):
                with self.annotate(i, "post_step"):
                    # diff = t + L/3 + R/3 - 5w/3, the reference's addmul
                    # sequence
                    diff = t + left / 3.0 + right / 3.0 - w * (5.0 / 3.0)
                    q, mm = compress_minmax_uint8(diff[None])
                    # ring exchange both directions: send to left & right,
                    # recv from left & right (shift +1 receives from the left
                    # peer)
                    lq = ppermute_shift(q, 1, axes)
                    lmm = ppermute_shift(mm, 1, axes)
                    rq = ppermute_shift(q, -1, axes)
                    rmm = ppermute_shift(mm, -1, axes)
                    left = left + decompress_minmax_uint8(lq, lmm)[0]
                    right = right + decompress_minmax_uint8(rq, rmm)[0]
                    own = decompress_minmax_uint8(q, mm)[0]
                    t_new = own + w
                    new_flats.append(t_new.astype(t.dtype))
                    new_w.append(t_new.astype(t.dtype))
                    new_l.append(left.astype(t.dtype))
                    new_r.append(right.astype(t.dtype))
            params = ctx.plan.debucketize(new_flats, params)
            return params, {"weight": new_w, "left": new_l, "right": new_r}

        if self.communication_interval > 1:
            params, state = jax.lax.cond(
                ctx.step % self.communication_interval == 0,
                communicate,
                lambda o: o,
                (params, state),
            )
        else:
            params, state = communicate((params, state))
        return params, state


class LowPrecisionDecentralizedAlgorithm(Algorithm):
    def __init__(
        self, hierarchical: bool = True, communication_interval: int = 1,
        use_pallas=None,
    ):
        self.hierarchical = hierarchical
        self.communication_interval = communication_interval
        self.use_pallas = use_pallas

    def reify(self, process_group) -> LowPrecisionDecentralizedAlgorithmImpl:
        return LowPrecisionDecentralizedAlgorithmImpl(
            process_group,
            hierarchical=self.hierarchical,
            communication_interval=self.communication_interval,
            use_pallas=self.use_pallas,
        )
