"""Stale-sync: bounded-staleness relaxation of the bucketed gradient ring.

The sync/async axis of the BAGUA design space (paper §"system relaxations":
synchronous ⟷ bounded-async), applied to the centralized gradient path: the
gang stays bulk-synchronous — every rank enters every collective every round,
so the compiled program and the per-round wire bytes are EXACTLY those of
``gradient_allreduce`` — but a rank indicted by the gang straggler score may
contribute its *previous-round* bucket payload for up to ``τ`` consecutive
rounds instead of blocking the ring on its late gradients.

Mechanics (all in-graph, no rank-varying control flow — a rank-conditional
``lax.cond`` around a collective would deadlock SPMD, so participation is
gated elementwise on the *payload* with ``jnp.where``):

* ``directive`` — per-rank int32 scalar in the algorithm state (stacked to
  ``(n,)`` by the engine), flipped host-side by
  ``DistributedDataParallel.apply_degradation_directive`` without a
  recompile (it is data, not code).
* ``staleness`` — per-rank consecutive-stale-round counter.  A rank replays
  its stale payload only while ``directive > 0 AND staleness < τ``; at
  ``staleness == τ`` the gate closes and the rank is forced back to a fresh
  contribution on round ``τ+1`` — divergence is bounded by construction.
* ``stale`` — the payload this rank last pushed into the ring, one f32 flat
  buffer per bucket (what a replay re-sends).
* ``residual`` — error feedback: the gradient a stale round *didn't* send is
  accumulated and re-enters the next fresh contribution, so the gradient
  signal telescopes instead of being dropped (same algebra as the int4
  ring's requantization residual).  Uniform update, no branch:

      contrib = where(use_stale, stale_prev, g + residual)
      residual' = residual + g - contrib     # fresh → 0, stale → accrues g
      stale'    = where(use_stale, stale_prev, g)

  The replay payload is the rank's last *raw fresh gradient*, never the
  residual-corrected contribution: replaying the correction would feed it
  back into the next correction (``B_k = S_k − 2·B_{k−1}`` — an
  exponentially divergent recursion), while replaying the raw gradient
  keeps the telescoping sum exact AND every payload bounded by a real
  measured gradient.

``τ`` is a compile-time constant of the traced step (it shapes the gate);
``DistributedDataParallel.apply_staleness`` switches it through the same
single-recompile machinery as a precision-plan switch.  At ``τ == 0`` the
transform delegates verbatim to :class:`GradientAllReduceAlgorithmImpl` —
bitwise-identical to the synchronous engine, pinned in CI.

The exchange is f32-only (``set_bucket_precision`` refuses): the replay
algebra is defined on exact flat buckets, and stacking staleness on top of
wire quantization would compound two error-feedback loops.  Every exchange
is traced under a ``bagua_stale/tau=<τ>`` frame
(:func:`bagua_tpu.observability.scope_grammar.format_stale_scope`) — the
sanction marker the static verifier keys off.
"""

import jax
import jax.numpy as jnp

from bagua_tpu.algorithms.base import Algorithm, OverlapCapability, StepContext
from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithmImpl
from bagua_tpu.communication import (
    ReduceOp,
    allreduce_inplace,
    hierarchical_allreduce_inplace,
)
from bagua_tpu.observability.scope_grammar import format_stale_scope


class StaleSyncAlgorithmImpl(GradientAllReduceAlgorithmImpl):
    #: stale/residual replicas are laid out per-bucket on the bound plan;
    #: re-bucketing would desync them (rebucket + autotune refuse).
    holds_bucketized_state = True
    supports_overlap = True
    #: the exchange program is identical with overlap on or off (monolithic
    #: transform_gradients either way; finalize_overlap is the identity) —
    #: overlap only keeps the engine's multi-bucket plan granularity.
    overlap_mode = "post_step"
    algo_name = "stale"

    def __init__(
        self,
        process_group,
        hierarchical: bool = False,
        average: bool = True,
        fuse: str = "tuple",
        staleness_tau: int = 0,
    ):
        super().__init__(
            process_group,
            hierarchical=hierarchical,
            average=average,
            fuse=fuse,
            wire_precision="f32",
        )
        tau = int(staleness_tau)
        if tau < 0:
            raise ValueError(f"staleness_tau must be >= 0, got {staleness_tau}")
        self.staleness_tau = tau

    def set_staleness_tau(self, tau) -> None:
        """Host-side τ switch — the engine's ``apply_staleness`` calls this
        then re-traces (τ is baked into the compiled gate)."""
        tau = int(tau)
        if tau < 0:
            raise ValueError(f"staleness_tau must be >= 0, got {tau}")
        self.staleness_tau = tau

    def set_bucket_precision(self, precisions) -> None:
        raise ValueError(
            "StaleSyncAlgorithmImpl exchanges are f32-only: the stale-replay "
            "error-feedback algebra is defined on exact flat buckets; use "
            "gradient_allreduce for wire quantization"
        )

    def overlap_capability(self) -> OverlapCapability:
        # holds_bucketized_state normally vetoes overlap (base heuristic),
        # but the replicas here are laid out ON the bound plan and the
        # exchange stays monolithic under overlap ("post_step": the engine
        # calls transform_gradients either way) — overlap only preserves
        # multi-bucket granularity, so the compiled program is identical and
        # auto is safe.
        return OverlapCapability(True, mode="post_step", auto=True, reason="")

    def init_state(self, params):
        # Allocated unconditionally (even at τ=0) so a later apply_staleness
        # switch re-traces against the SAME state layout — the τ=0 fast path
        # simply passes the state through untouched.
        plan = getattr(self, "_bound_plan", None) or self.tensors_to_buckets(params)
        zeros = tuple(jnp.zeros((spec.numel,), jnp.float32) for spec in plan.specs)
        return {
            "stale": zeros,
            "residual": zeros,
            "staleness": jnp.zeros((), jnp.int32),
            "directive": jnp.zeros((), jnp.int32),
        }

    def transform_gradients(self, grads, params, state, ctx: StepContext):
        if self.staleness_tau <= 0:
            # Bulk sync: exactly the parent's all-f32 program (state untouched).
            return super().transform_gradients(grads, params, state, ctx)
        tau = int(self.staleness_tau)
        op = ReduceOp.AVG if self.average else ReduceOp.SUM
        reduce = hierarchical_allreduce_inplace if self.hierarchical else allreduce_inplace
        staleness = state["staleness"]
        use_stale = (state["directive"] > 0) & (staleness < tau)
        flats = ctx.plan.bucketize(grads)
        out, new_stale, new_resid = [], [], []
        for i, flat in enumerate(flats):
            g = flat.astype(jnp.float32)
            contrib = jnp.where(use_stale, state["stale"][i], g + state["residual"][i])
            with self.annotate(i, "mono"), jax.named_scope(format_stale_scope(tau)):
                avg = reduce(contrib, op=op)
            out.append(avg.astype(flat.dtype))
            # replay payload = last raw fresh gradient (NOT contrib: the
            # residual correction must never re-enter a replay, or the
            # correction-of-correction recursion diverges exponentially)
            new_stale.append(jnp.where(use_stale, state["stale"][i], g))
            new_resid.append(state["residual"][i] + g - contrib)
        grads = ctx.plan.debucketize(out, grads)
        state = {
            **state,
            "stale": tuple(new_stale),
            "residual": tuple(new_resid),
            "staleness": jnp.where(use_stale, staleness + 1, jnp.zeros_like(staleness)),
        }
        return grads, params, state


class StaleSyncAlgorithm(Algorithm):
    def __init__(
        self,
        hierarchical: bool = False,
        average: bool = True,
        fuse: str = "tuple",
        staleness_tau: int = 0,
    ):
        self.hierarchical = hierarchical
        self.average = average
        self.fuse = fuse
        self.staleness_tau = staleness_tau

    def reify(self, process_group) -> StaleSyncAlgorithmImpl:
        return StaleSyncAlgorithmImpl(
            process_group,
            hierarchical=self.hierarchical,
            average=self.average,
            fuse=self.fuse,
            staleness_tau=self.staleness_tau,
        )
