"""QAdam: quantized-momentum Adam (centralized, synchronous).

TPU-native analog of the reference's ``q_adam.py``.  Two phases:

* **warmup** (``step_id < warmup_steps``): gradients are allreduce-averaged
  (flat, like the reference's warmup op ``q_adam.py:205-212``) and both Adam
  moments update normally.
* **compression**: the *first momentum* is updated locally from the raw
  gradient (the reference's ``calculate_momentum`` python op,
  ``q_adam.py:214-221``), then exchanged with the MinMaxUInt8 scatter-gather
  pipeline (hierarchical by default); the second moment is frozen
  (``q_adam.py:88-96`` only updates moments during warmup).

The reference rebuilds bucket ops at the warmup boundary via ``need_reset``
(``q_adam.py:136-143``); here the boundary is a ``lax.cond`` on the traced
step counter, so there is no recompilation.

Faithful quirk: ``weight_decay`` only affects the update during warmup — in
the reference's compression phase the momentum op reads ``tensor.grad``
directly and the optimizer's decayed gradient is never consumed.

The Adam update itself (``q_adam.py:97-103``):

    denom = sqrt(v) / sqrt(1 - b2^t) + eps
    param -= lr / (1 - b1^t) * m / denom

which the engine applies by returning ``m / ((1 - b1^t) * denom)`` as the
transformed gradient and pairing the algorithm with plain ``optax.sgd(lr)``
(exposed via :meth:`QAdamOptimizer.to_optax`).
"""

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import optax

from bagua_tpu.algorithms.base import Algorithm, AlgorithmImpl, StepContext
from bagua_tpu.algorithms.bytegrad import compressed_allreduce
from bagua_tpu.bucket import flatten_bucket_leaves, split_bucket_flat
from bagua_tpu.communication import (
    ALL_AXES,
    INTER_AXIS,
    INTRA_AXIS,
    ReduceOp,
    allreduce_inplace,
)
from bagua_tpu.kernels.minmax_uint8 import get_compressors, get_fused_reducer


@dataclasses.dataclass
class QAdamOptimizer:
    """Hyperparameter bundle mirroring the reference ``QAdamOptimizer``
    constructor (``q_adam.py:14-56``)."""

    lr: float = 1e-3
    warmup_steps: int = 100
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0

    def __post_init__(self):
        if self.lr < 0:
            raise ValueError(f"Invalid learning rate: {self.lr}")
        if self.eps < 0:
            raise ValueError(f"Invalid epsilon value: {self.eps}")
        for i, b in enumerate(self.betas):
            if not 0.0 <= b < 1.0:
                raise ValueError(f"Invalid beta parameter at index {i}: {b}")
        if self.warmup_steps <= 0:
            raise ValueError(
                f"Invalid warmup_steps parameter, must be larger than 0: {self.warmup_steps}"
            )

    def to_optax(self) -> optax.GradientTransformation:
        """The engine-side update rule: plain SGD consuming the
        algorithm-preconditioned direction."""
        return optax.sgd(self.lr)


class QAdamAlgorithmImpl(AlgorithmImpl):
    supports_overlap = True
    algo_name = "q_adam"

    def __init__(self, process_group, q_adam_optimizer: QAdamOptimizer, hierarchical: bool = True):
        super().__init__(process_group, hierarchical=hierarchical)
        self.optimizer = q_adam_optimizer
        self.warmup_steps = q_adam_optimizer.warmup_steps
        # Resolved once here so the evidence-file lookup stays off the traced
        # per-bucket path (same hoist as ByteGrad).
        self._compressors = get_compressors(None)
        self._fused_reducer = get_fused_reducer(None)

    def init_state(self, params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return {"exp_avg": zeros, "exp_avg_sq": jax.tree.map(jnp.zeros_like, params)}

    def _exchange_flat(self, flat, compressed: bool):
        """One bucket's wire program, shared by the monolithic and overlap
        paths (bitwise-identical outputs)."""
        if compressed:
            if self.hierarchical and self.process_group.intra_size > 1:
                intra = allreduce_inplace(flat, op=ReduceOp.SUM, axis=INTRA_AXIS)
                red = compressed_allreduce(
                    intra, (INTER_AXIS,), average=False,
                    compressors=self._compressors,
                    fused_reducer=self._fused_reducer,
                )
                return red / self.process_group.size
            return compressed_allreduce(
                flat, ALL_AXES, average=True,
                compressors=self._compressors, fused_reducer=self._fused_reducer,
            )
        return allreduce_inplace(flat, op=ReduceOp.AVG)

    def _allreduce_tree(self, tree, ctx, compressed: bool):
        flats = ctx.plan.bucketize(tree)
        out = []
        for i, flat in enumerate(flats):
            with self.annotate(i, "mono"):
                out.append(self._exchange_flat(flat, compressed))
        return ctx.plan.debucketize(out, tree)

    def transform_gradients(self, grads, params, state, ctx: StepContext):
        b1, b2 = self.optimizer.betas
        wd = self.optimizer.weight_decay
        step_id = (ctx.step + 1).astype(jnp.float32)
        m, v = state["exp_avg"], state["exp_avg_sq"]

        def warmup(operand):
            grads, params, m, v = operand
            g = self._allreduce_tree(grads, ctx, compressed=False)
            if wd != 0.0:
                g = jax.tree.map(lambda gg, p: gg + wd * p, g, params)
            m2 = jax.tree.map(lambda mm, gg: b1 * mm + (1 - b1) * gg, m, g)
            v2 = jax.tree.map(lambda vv, gg: b2 * vv + (1 - b2) * gg * gg, v, g)
            # Reference quirk: the optimizer only updates the moments while
            # ``step_id < warmup_steps`` (``q_adam.py:88-96``), while the comm
            # phase switches one step later (``optimizer_step_id < warmup``,
            # ``q_adam.py:205``) — so the last warmup step allreduces grads
            # but leaves the moments untouched.
            moments_pred = ctx.step + 1 < self.warmup_steps
            m2 = jax.tree.map(lambda a, b: jnp.where(moments_pred, a, b), m2, m)
            v2 = jax.tree.map(lambda a, b: jnp.where(moments_pred, a, b), v2, v)
            return m2, v2

        def compression(operand):
            grads, params, m, v = operand
            m = jax.tree.map(lambda mm, gg: b1 * mm + (1 - b1) * gg, m, grads)
            m = self._allreduce_tree(m, ctx, compressed=True)
            return m, v

        m, v = jax.lax.cond(
            ctx.step < self.warmup_steps, warmup, compression, (grads, params, m, v)
        )

        bc1 = 1.0 - jnp.power(b1, step_id)
        bc2 = 1.0 - jnp.power(b2, step_id)
        eps = self.optimizer.eps
        direction = jax.tree.map(
            lambda mm, vv: mm / (bc1 * (jnp.sqrt(vv) / jnp.sqrt(bc2) + eps)), m, v
        )
        return direction, params, {"exp_avg": m, "exp_avg_sq": v}

    # -- overlap execution mode ---------------------------------------------

    def overlap_exchange(
        self, bucket_idx: int, grads, ctx: StepContext, params_leaves=None
    ):
        # One bucket's exchange from inside its custom_vjp backward rule.
        # The warmup↔compression boundary is the SAME traced ``lax.cond``
        # as the monolithic path — the phase switches per step without a
        # retrace, so the anchored collective program is stable across the
        # boundary.  Warmup leg: flat full-precision AVG of the bucket's
        # gradients.  Compression leg: local momentum update from the raw
        # cotangents, then the hierarchical/compressed pipeline over the
        # momentum — chunk boundaries identical to bucketize's layout, so
        # outputs are bitwise-identical to transform_gradients.
        spec = ctx.plan.specs[bucket_idx]
        b1 = self.optimizer.betas[0]
        m_group = ctx.plan.group_leaves(ctx.extras["algo_state"]["exp_avg"])[bucket_idx]
        m_leaves = [m_group[s.name] for s in spec.slots]

        def warmup(operand):
            g_leaves, _ = operand
            flat = flatten_bucket_leaves(g_leaves, spec)
            return split_bucket_flat(self._exchange_flat(flat, compressed=False), spec)

        def compression(operand):
            g_leaves, m_leaves = operand
            m2 = [b1 * mm + (1 - b1) * gg for mm, gg in zip(m_leaves, g_leaves)]
            flat = flatten_bucket_leaves(m2, spec)
            return split_bucket_flat(self._exchange_flat(flat, compressed=True), spec)

        with self.annotate(bucket_idx, "overlap"):
            return jax.lax.cond(
                ctx.step < self.warmup_steps, warmup, compression, (list(grads), m_leaves)
            )

    def finalize_overlap(self, grads, params, state, ctx: StepContext):
        # ``grads`` holds each bucket's per-bucket exchange output assembled
        # back into the gradient tree: averaged gradients in warmup, the
        # exchanged momentum in compression.  Leaves outside every bucket
        # (dp_filter) carry their raw local gradients — exactly what the
        # monolithic path's debucketize fallback leaves there in warmup; the
        # compression branch recomputes the local momentum for those leaves.
        b1, b2 = self.optimizer.betas
        wd = self.optimizer.weight_decay
        step_id = (ctx.step + 1).astype(jnp.float32)
        m, v = state["exp_avg"], state["exp_avg_sq"]
        covered = {s.name for spec in ctx.plan.specs for s in spec.slots}

        def warmup(operand):
            g, params, m, v = operand
            if wd != 0.0:
                g = jax.tree.map(lambda gg, p: gg + wd * p, g, params)
            m2 = jax.tree.map(lambda mm, gg: b1 * mm + (1 - b1) * gg, m, g)
            v2 = jax.tree.map(lambda vv, gg: b2 * vv + (1 - b2) * gg * gg, v, g)
            moments_pred = ctx.step + 1 < self.warmup_steps
            m2 = jax.tree.map(lambda a, b: jnp.where(moments_pred, a, b), m2, m)
            v2 = jax.tree.map(lambda a, b: jnp.where(moments_pred, a, b), v2, v)
            return m2, v2

        def compression(operand):
            exch, params, m, v = operand
            m2 = jax.tree_util.tree_map_with_path(
                lambda path, e, mm: e
                if jax.tree_util.keystr(path) in covered
                else b1 * mm + (1 - b1) * e,
                exch, m,
            )
            return m2, v

        m, v = jax.lax.cond(
            ctx.step < self.warmup_steps, warmup, compression, (grads, params, m, v)
        )

        bc1 = 1.0 - jnp.power(b1, step_id)
        bc2 = 1.0 - jnp.power(b2, step_id)
        eps = self.optimizer.eps
        direction = jax.tree.map(
            lambda mm, vv: mm / (bc1 * (jnp.sqrt(vv) / jnp.sqrt(bc2) + eps)), m, v
        )
        return direction, params, {"exp_avg": m, "exp_avg_sq": v}


class QAdamAlgorithm(Algorithm):
    def __init__(self, q_adam_optimizer: QAdamOptimizer, hierarchical: bool = True):
        self.optimizer = q_adam_optimizer
        self.hierarchical = hierarchical

    def reify(self, process_group) -> QAdamAlgorithmImpl:
        return QAdamAlgorithmImpl(
            process_group, q_adam_optimizer=self.optimizer, hierarchical=self.hierarchical
        )
