"""Gradient accumulation without communication — the ``no_sync`` analog.

The reference's torch-DDP-compatible wrapper exposes ``no_sync()``
(``data_parallel/distributed.py:174-195``): gradients accumulate locally for
k-1 steps with NO inter-worker communication, and the k-th step communicates
the accumulated gradient and applies one optimizer update — the standard
large-batch recipe when the per-step batch doesn't fit.

Context managers don't map onto a jitted step, so the same contract is a
declarative wrapper around any inner algorithm::

    ddp = DistributedDataParallel(
        loss_fn, optax.adam(1e-3),
        GradientAccumulation(Algorithm.init("bytegrad"), every=4),
        process_group=group,
    )

Per step: the local gradient folds into an accumulator carried in the
algorithm state; on non-boundary steps the step performs **zero collectives
and no optimizer update** (the engine skips the update via
``skips_optimizer_update`` + ``is_update_step``); on every ``every``-th step
the inner algorithm's full communication pipeline runs on the accumulated
mean and the optimizer applies once.  Numerically, k accumulated microbatches
equal one step on their concatenation (for mean-style losses).
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from bagua_tpu.algorithms.base import Algorithm, AlgorithmImpl, StepContext


class GradientAccumulationImpl(AlgorithmImpl):
    def __init__(self, inner: AlgorithmImpl, every: int):
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        # inner must exist before super().__init__: the base assigns
        # self.hierarchical, which this class forwards to the inner impl
        # (pass the inner's own value so the write is a no-op).
        self.inner = inner
        self.every = every
        super().__init__(inner.process_group, hierarchical=inner.hierarchical)

    # the engine gates the optimizer update on is_update_step
    skips_optimizer_update = True

    def is_update_step(self, step):
        """Traced predicate: does this step communicate + update?"""
        return (step % self.every) == (self.every - 1)

    def _inner_ctx(self, ctx: StepContext) -> StepContext:
        """The inner algorithm's schedules (QAdam warmup, shift_one peer
        cycling, Adam bias correction) count OPTIMIZER steps, not
        microbatches — hand it the update-step counter."""
        return dataclasses.replace(ctx, step=ctx.step // self.every)

    # -- attribute protocols the engine reads off the impl -------------------

    @property
    def holds_bucketized_state(self):
        # re-bucketing safety guard must see the inner algorithm's flag
        return getattr(self.inner, "holds_bucketized_state", False)

    @property
    def optimizer(self):
        # QAdam bundles its own optimizer; the engine discovers it here
        return getattr(self.inner, "optimizer", None)

    @property
    def hierarchical(self):
        return self.inner.hierarchical

    @hierarchical.setter
    def hierarchical(self, value):
        # autotune toggles this on ddp.impl; the inner impl's collectives
        # read it, so the write must land there
        self.inner.hierarchical = value

    # -- delegate structure --------------------------------------------------

    def tensors_to_buckets(self, tree, bucket_size_bytes=None, filter_fn=None):
        return self.inner.tensors_to_buckets(
            tree, bucket_size_bytes=bucket_size_bytes, filter_fn=filter_fn
        )

    def bind_plan(self, plan):
        super().bind_plan(plan)
        self.inner.bind_plan(plan)

    def init_state(self, params) -> Any:
        return {
            "acc": jax.tree.map(lambda x: jnp.zeros_like(x, jnp.float32), params),
            "inner": self.inner.init_state(params),
        }

    # -- traced stages -------------------------------------------------------

    def on_step_start(self, params, state, ctx: StepContext):
        # The reference's no_sync disables ALL hook machinery off-boundary;
        # the inner stages (some communicate here, e.g. async averaging)
        # likewise only run on update steps.
        inner_ctx = self._inner_ctx(ctx)
        params, inner_state = jax.lax.cond(
            self.is_update_step(ctx.step),
            lambda op: self.inner.on_step_start(op[0], op[1], inner_ctx),
            lambda op: op,
            (params, state["inner"]),
        )
        return params, {"acc": state["acc"], "inner": inner_state}

    def transform_gradients(self, grads, params, state, ctx: StepContext):
        acc = jax.tree.map(
            lambda a, g: a + g.astype(jnp.float32), state["acc"], grads
        )
        boundary = self.is_update_step(ctx.step)

        inner_ctx = self._inner_ctx(ctx)

        def flush(operand):
            acc, params, inner_state = operand
            mean = jax.tree.map(lambda a: a / self.every, acc)
            g, params, inner_state = self.inner.transform_gradients(
                mean, params, inner_state, inner_ctx
            )
            zeroed = jax.tree.map(jnp.zeros_like, acc)
            return g, params, inner_state, zeroed

        def hold(operand):
            acc, params, inner_state = operand
            # grads are unused (the engine skips the update off-boundary)
            return jax.tree.map(jnp.zeros_like, acc), params, inner_state, acc

        g, params, inner_state, acc = jax.lax.cond(
            boundary, flush, hold, (acc, params, state["inner"])
        )
        grads = jax.tree.map(lambda g_, t: g_.astype(t.dtype), g, grads)
        return grads, params, {"acc": acc, "inner": inner_state}

    def on_step_end(self, params, state, ctx: StepContext):
        inner_ctx = self._inner_ctx(ctx)
        params, inner_state = jax.lax.cond(
            self.is_update_step(ctx.step),
            lambda op: self.inner.on_step_end(op[0], op[1], inner_ctx),
            lambda op: op,
            (params, state["inner"]),
        )
        return params, {"acc": state["acc"], "inner": inner_state}

    # -- host-side / control: delegate ---------------------------------------

    def need_reset(self, step: int) -> bool:
        return self.inner.need_reset(step // self.every)

    def step_variant(self, step: int) -> str:
        return self.inner.step_variant(step // self.every)

    def abort(self):
        if hasattr(self.inner, "abort"):
            self.inner.abort()

    def resume(self):
        if hasattr(self.inner, "resume"):
            self.inner.resume()

    @property
    def host_dispatch_lock(self):
        return self.inner.host_dispatch_lock

    def host_pre_dispatch(self, state):
        return self.inner.host_pre_dispatch(state)

    def host_post_dispatch(self, state, step: int) -> None:
        # The inner impl counts optimizer steps, not microbatch steps — the
        # traced inner stages see step // every, so the host hooks must too
        # (otherwise async warmup gates trip ``every``x early).
        self.inner.host_post_dispatch(state, step // self.every)

    def host_shutdown(self) -> None:
        self.inner.host_shutdown()


class GradientAccumulation(Algorithm):
    """Wrap ``inner`` so communication + the optimizer update run every
    ``every``-th step on the accumulated gradient mean (``no_sync`` analog)."""

    def __init__(self, inner: Algorithm, every: int):
        self.inner = inner
        self.every = every

    def reify(self, process_group) -> GradientAccumulationImpl:
        inner_impl = (
            self.inner.reify(process_group)
            if isinstance(self.inner, Algorithm)
            else self.inner
        )
        return GradientAccumulationImpl(inner_impl, self.every)
