"""Shared ``wire_precision`` plumbing for the gradient-exchange algorithms.

Both the full-precision allreduce engine and the zero (reduce-scatter)
engine expose ``wire_precision="f32"|"int8"|"int4"|"auto"``: the quantized
settings route each bucket's padded flat buffer through the in-collective
blockwise ring (:mod:`bagua_tpu.kernels.quantized_ring`) instead of the
plain collective.  This mixin centralizes the pieces that are identical on
both sides:

* validation + one-time ring-hop resolution (the evidence-gated Pallas
  dispatch must run at construction, never inside a trace);
* the per-bucket precision resolution — explicit per-bucket plan
  (``bucket_precision``, set by the service planner under ``"auto"``) >
  the uniform ``wire_precision`` > ``"f32"`` for non-float buckets;
* the error-feedback policy: ``"int4"`` (and ``"auto"``, which may resolve
  to int4 per bucket) carries a persistent f32 residual per bucket in the
  algorithm state, which makes the algorithm *hold bucketized state* —
  overlap and mid-training re-bucketing are disabled for those settings
  (the residual cannot ride the stateless per-bucket backward hook);
* the modelled per-precision wire-byte accounting the telemetry counters
  are fed from.

``"auto"`` with no adopted plan resolves every bucket to f32 — the engine
never quantizes until the planner's guardrail-gated choice lands.
"""

from typing import List, Optional, Sequence

from bagua_tpu.kernels.quantized_ring import (
    WIRE_PRECISIONS,
    get_ring_hop,
    ring_wire_bytes,
)

#: bagua datatype names eligible for blockwise quantization (the ring
#: operates in f32; non-float buckets always take the exact path)
FLOAT_DTYPES = ("f32", "f16", "bf16")

VALID_WIRE_PRECISIONS = WIRE_PRECISIONS + ("auto",)

#: bits on the wire per quantized precision
PRECISION_BITS = {"int8": 8, "int4": 4}


class WirePrecisionMixin:
    """Per-bucket wire-precision resolution + error-feedback policy.

    Classes mixing this in call :meth:`_init_wire_precision` from their
    ``__init__`` and read :meth:`_precision_for_bucket` /
    :meth:`bucket_precisions` inside their exchange."""

    def _init_wire_precision(self, wire_precision: str, use_pallas=None) -> None:
        if wire_precision not in VALID_WIRE_PRECISIONS:
            raise ValueError(
                f"wire_precision must be one of {VALID_WIRE_PRECISIONS}, "
                f"got {wire_precision!r}"
            )
        self.wire_precision = wire_precision
        #: planner-chosen per-bucket precision (aligned with plan.specs);
        #: only consulted under wire_precision="auto"
        self.bucket_precision: Optional[List[str]] = None
        # Resolve the fused hop once at construction — resolve_use_pallas
        # reads the evidence file and must never run inside a trace.
        self._ring_hops = (
            {b: get_ring_hop(b, use_pallas) for b in (8, 4)}
            if wire_precision != "f32"
            else {}
        )

    @property
    def holds_bucketized_state(self) -> bool:
        """The int4 error-feedback residual is genuinely per-bucket state:
        re-bucketing would desync it and the stateless overlap hook cannot
        thread it, so those paths are fenced off (``"auto"`` may resolve to
        int4 at any time, so it is fenced too)."""
        return self._ef_enabled()

    def _ef_enabled(self) -> bool:
        return self.wire_precision in ("int4", "auto")

    def _precision_for_bucket(self, bucket_idx: int, spec) -> str:
        if spec.dtype not in FLOAT_DTYPES:
            return "f32"
        if self.wire_precision == "auto":
            if self.bucket_precision is None:
                return "f32"  # no plan adopted yet: never quantize silently
            return self.bucket_precision[bucket_idx]
        return self.wire_precision

    def bucket_precisions(self, plan) -> List[str]:
        """Resolved wire precision per bucket — what the traced step uses."""
        return [
            self._precision_for_bucket(i, spec) for i, spec in enumerate(plan.specs)
        ]

    def set_bucket_precision(self, precisions: Optional[Sequence[str]]) -> None:
        """Adopt a planner-chosen per-bucket precision plan (``None`` clears
        it).  Requires ``wire_precision="auto"`` — a user-pinned uniform
        precision is never silently overridden."""
        if precisions is None:
            self.bucket_precision = None
            return
        if self.wire_precision != "auto":
            raise ValueError(
                "per-bucket precision plans require wire_precision='auto' "
                f"(this algorithm is pinned to {self.wire_precision!r})"
            )
        precisions = list(precisions)
        bad = sorted(set(p for p in precisions if p not in WIRE_PRECISIONS))
        if bad:
            raise ValueError(
                f"unknown wire precisions {bad}; valid: {WIRE_PRECISIONS}"
            )
        plan = getattr(self, "_bound_plan", None)
        if plan is not None and len(precisions) != len(plan.specs):
            raise ValueError(
                f"precision plan has {len(precisions)} entries for "
                f"{len(plan.specs)} buckets"
            )
        self.bucket_precision = precisions

    def wire_bytes_by_precision(self, plan) -> dict:
        """Modelled wire bytes one rank moves per step, keyed by precision —
        ring model throughout: an N-byte f32 bucket's allreduce moves
        ``2*N*(n-1)/n``; a quantized bucket moves the compressed payload +
        the per-block (min, max) sidecar on each of its ``2*(n-1)`` hops
        (:func:`~bagua_tpu.kernels.quantized_ring.ring_wire_bytes`)."""
        n = self.process_group.size
        out: dict = {}
        for spec, prec in zip(plan.specs, self.bucket_precisions(plan)):
            if prec == "f32":
                nb = 2 * spec.nbytes * (n - 1) // n
            else:
                nb = ring_wire_bytes(spec.numel, n, PRECISION_BITS[prec])
            out[prec] = out.get(prec, 0) + nb
        return out
