"""Algorithm registry (reference ``algorithms/__init__.py:8-33``)."""

from bagua_tpu.algorithms.base import (  # noqa: F401
    Algorithm,
    AlgorithmImpl,
    GlobalAlgorithmRegistry,
    StepContext,
)
from bagua_tpu.algorithms.gradient_allreduce import (  # noqa: F401
    GradientAllReduceAlgorithm,
    GradientAllReduceAlgorithmImpl,
)

GlobalAlgorithmRegistry.register(
    "gradient_allreduce",
    GradientAllReduceAlgorithm,
    "centralized synchronous full-precision gradient allreduce",
)
