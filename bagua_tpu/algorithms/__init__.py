"""Algorithm registry (reference ``algorithms/__init__.py:8-33``)."""

from bagua_tpu.algorithms.base import (  # noqa: F401
    Algorithm,
    AlgorithmImpl,
    GlobalAlgorithmRegistry,
    StepContext,
)
from bagua_tpu.algorithms.gradient_allreduce import (  # noqa: F401
    GradientAllReduceAlgorithm,
    GradientAllReduceAlgorithmImpl,
)

from bagua_tpu.algorithms.bytegrad import (  # noqa: F401
    ByteGradAlgorithm,
    ByteGradAlgorithmImpl,
)

GlobalAlgorithmRegistry.register(
    "gradient_allreduce",
    GradientAllReduceAlgorithm,
    "centralized synchronous full-precision gradient allreduce",
)
GlobalAlgorithmRegistry.register(
    "bytegrad",
    ByteGradAlgorithm,
    "centralized synchronous 8-bit compressed gradient allreduce",
)
