"""Algorithm registry (reference ``algorithms/__init__.py:8-33``)."""

from bagua_tpu.algorithms.base import (  # noqa: F401
    Algorithm,
    AlgorithmImpl,
    GlobalAlgorithmRegistry,
    OverlapCapability,
    StepContext,
)
from bagua_tpu.algorithms.gradient_allreduce import (  # noqa: F401
    GradientAllReduceAlgorithm,
    GradientAllReduceAlgorithmImpl,
)

from bagua_tpu.algorithms.bytegrad import (  # noqa: F401
    ByteGradAlgorithm,
    ByteGradAlgorithmImpl,
)
from bagua_tpu.algorithms.decentralized import (  # noqa: F401
    DecentralizedAlgorithm,
    DecentralizedAlgorithmImpl,
    LowPrecisionDecentralizedAlgorithm,
    LowPrecisionDecentralizedAlgorithmImpl,
)

GlobalAlgorithmRegistry.register(
    "gradient_allreduce",
    GradientAllReduceAlgorithm,
    "centralized synchronous full-precision gradient allreduce",
)
GlobalAlgorithmRegistry.register(
    "bytegrad",
    ByteGradAlgorithm,
    "centralized synchronous 8-bit compressed gradient allreduce",
)
GlobalAlgorithmRegistry.register(
    "decentralized",
    DecentralizedAlgorithm,
    "decentralized synchronous full-precision weight averaging",
)
GlobalAlgorithmRegistry.register(
    "low_precision_decentralized",
    LowPrecisionDecentralizedAlgorithm,
    "decentralized synchronous 8-bit compressed ring weight-diff exchange",
)

from bagua_tpu.algorithms.stale import (  # noqa: F401,E402
    StaleSyncAlgorithm,
    StaleSyncAlgorithmImpl,
)

GlobalAlgorithmRegistry.register(
    "stale",
    StaleSyncAlgorithm,
    "bounded-staleness gradient allreduce: degraded ranks replay their "
    "previous-round buckets (error-feedback accumulated) for up to tau rounds",
)

from bagua_tpu.algorithms.q_adam import (  # noqa: F401,E402
    QAdamAlgorithm,
    QAdamAlgorithmImpl,
    QAdamOptimizer,
)

GlobalAlgorithmRegistry.register(
    "qadam",
    QAdamAlgorithm,
    "centralized synchronous quantized-momentum Adam",
)

from bagua_tpu.algorithms.async_model_average import (  # noqa: F401,E402
    AsyncModelAverageAlgorithm,
    AsyncModelAverageAlgorithmImpl,
)

GlobalAlgorithmRegistry.register(
    "async",
    AsyncModelAverageAlgorithm,
    "asynchronous model averaging by a background averager thread",
)


class NoCommAlgorithm(Algorithm):
    """No gradient communication: every stage is the identity.  Pair with an
    optimizer that owns the communication itself (ZeRO-2's reduce-scatter,
    ``contrib.zero.zero2_optimizer``), or use it to debug single-rank math
    inside the distributed engine."""

    def reify(self, process_group) -> AlgorithmImpl:
        return AlgorithmImpl(process_group)


GlobalAlgorithmRegistry.register(
    "none",
    NoCommAlgorithm,
    "no communication (optimizer-owned comm, e.g. ZeRO-2, or debugging)",
)


def _zero_factory(**kwargs):
    # Imported lazily: bagua_tpu.sharded.algorithm itself imports
    # algorithms.base, so an eager import here would make
    # ``import bagua_tpu.sharded`` (which triggers this package's __init__
    # mid-flight) circular.
    from bagua_tpu.sharded.algorithm import ZeroAlgorithm

    return ZeroAlgorithm(**kwargs)


GlobalAlgorithmRegistry.register(
    "zero",
    _zero_factory,
    "ZeRO-sharded exchange: reduce-scatter grads, shard-only optimizer "
    "update, deferred all-gather overlapped into the next forward",
)

from bagua_tpu.algorithms.grad_accumulation import (  # noqa: F401,E402
    GradientAccumulation,
    GradientAccumulationImpl,
)

#: algorithms whose schedule is wall-clock-driven (not bitwise-deterministic
#: across runs by design) — determinism gates skip these.
WALL_CLOCK_ALGORITHMS = frozenset({"async"})


def build_algorithm(name: str, lr: float = 1e-3, qadam_warmup_steps: int = 10, **kwargs) -> Algorithm:
    """Construct any registered algorithm, defaulting required constructor
    kwargs (QAdam needs its bundled optimizer).  The one-stop builder for
    benches/CI/tests so per-algorithm special cases live in one place."""
    if name == "qadam" and "q_adam_optimizer" not in kwargs:
        kwargs["q_adam_optimizer"] = QAdamOptimizer(lr=lr, warmup_steps=qadam_warmup_steps)
    return Algorithm.init(name, **kwargs)
