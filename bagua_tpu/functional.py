"""Autograd-integrated collectives (reference ``data_parallel/functional.py``).

The reference wraps ``all_reduce`` in a ``torch.autograd.Function`` whose
backward is another all_reduce (``functional.py:56-79``).  Under JAX every
collective primitive already has a transpose rule — ``psum``'s gradient is
``psum`` — so the differentiable form is the collective itself.  These
wrappers exist for API parity and for documentation: they are safe inside
``jax.grad``.
"""

from typing import Optional

import jax.numpy as jnp

from bagua_tpu.communication import (
    BaguaProcessGroup,
    ReduceOp,
    allreduce,
    allreduce_inplace,
)


def all_reduce(tensor, op: ReduceOp = ReduceOp.AVG, group: Optional[BaguaProcessGroup] = None):
    """Differentiable eager all_reduce over stacked per-rank arrays: the
    gradient of the output w.r.t. each rank's input is the same reduction of
    the output cotangents (matching the reference's symmetric backward)."""
    return allreduce(tensor, op=op, comm=group)


def all_reduce_inplace(x, op: ReduceOp = ReduceOp.AVG, axis=None):
    """Differentiable in-step collective (use inside shard_map); ``psum`` /
    ``pmean`` transpose rules make this correct under ``jax.grad``."""
    return allreduce_inplace(x, op=op, axis=axis)
