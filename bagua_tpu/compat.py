"""JAX version graft: present one API surface across the JAX versions the
container fleet actually ships.

The codebase targets the current public names (``jax.shard_map`` with its
``check_vma`` knob, ``jax.lax.axis_size``).  Older runtimes (<= 0.4.x) only
have ``jax.experimental.shard_map.shard_map`` (whose knob is spelled
``check_rep``) and no ``axis_size`` — on those, importing :mod:`bagua_tpu`
installs thin forwarders onto the ``jax`` namespace so every call site (the
engine, the parallel layers, the test-suite's direct ``jax.shard_map`` uses)
works unmodified.  On runtimes that already provide the names this module is
a no-op, so upgrading JAX silently sheds the graft.
"""

import jax


def _shard_map_forwarder():
    from jax.experimental.shard_map import shard_map as _shard_map

    import inspect

    accepts_check_rep = "check_rep" in inspect.signature(_shard_map).parameters

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kwargs):
        if check_vma is not None and accepts_check_rep:
            # same semantics, pre-rename spelling (check_rep -> check_vma)
            kwargs.setdefault("check_rep", check_vma)
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )

    return shard_map


def _axis_size(axis_name):
    """``lax.axis_size`` backfill: ``psum(1, axis)`` folds to the static
    mesh-axis size at trace time (the long-standing idiom the primitive
    replaced)."""
    if isinstance(axis_name, (tuple, list)):
        n = 1
        for a in axis_name:
            n *= _axis_size(a)
        return n
    return jax.lax.psum(1, axis_name)


def _distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized`` backfill: the distributed client
    lives on the private global state in older runtimes."""
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except (ImportError, AttributeError):
        return False


def install() -> None:
    """Idempotently graft missing public names onto ``jax``."""
    if not hasattr(jax, "shard_map"):
        try:
            jax.shard_map = _shard_map_forwarder()
        except ImportError:  # no experimental fallback either: leave as-is
            pass
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = _axis_size
    if not hasattr(jax.distributed, "is_initialized"):
        jax.distributed.is_initialized = _distributed_is_initialized


install()
