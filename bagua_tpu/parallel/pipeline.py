"""Pipeline parallelism: microbatch streaming over a ``pp`` mesh axis.

Not in the reference (SURVEY §2.4: PP "no") — provided as a first-class mesh
capability.  SPMD formulation: every rank holds ONE stage's parameters
(stages must share a structure, e.g. uniform transformer blocks).
Activations hop to the next stage with a single neighbor ``ppermute`` per
tick.

Three schedules:

* **GPipe** (:func:`pipeline_apply` / :func:`pipeline_loss`): time is
  ``T = n_stages + n_microbatches - 1`` ticks; at tick ``t`` stage ``s`` is
  active for microbatch ``m = t - s``.  The whole schedule is one traced
  ``fori_loop``, so ``jax.grad`` differentiates straight through it — the
  backward pipeline (reverse ``ppermute``s) falls out of autodiff.  Autodiff
  stores residuals for every tick, so activation memory grows with the
  microbatch count (``remat=True`` shrinks the per-tick residual to the
  stage *input*).

* **Interleaved / virtual chunks** (:func:`pipeline_loss_interleaved`):
  each rank holds ``V`` stage chunks (global stage ``v*S + r``), so
  microbatches circle the ring ``V`` times and the bubble fraction shrinks
  ~``V``x vs GPipe at equal model depth ((S-1)/(M*V) vs (S-1)/M).  Forward-only closed-form
  schedule (see ``_interleaved_collect``); autodiff runs the backward, and
  ``V = 1`` reduces exactly to GPipe.

* **1F1B** (:func:`pipeline_train_1f1b`): the forward AND backward pipelines
  are hand-scheduled into one loop — at tick ``t`` stage ``s`` runs forward
  for microbatch ``t - s`` and backward for ``t - (2(S-1) - s)``, so the
  last stage alternates F/B immediately (the classic one-forward-one-backward
  steady state).  Only stage *inputs* are stashed, in a ring buffer of
  ``2S - 1`` slots — live memory is **independent of the microbatch count**,
  and the backward recomputes the stage forward from the stashed input
  (rematerialization is built into the schedule, the standard 1F1B+remat
  pairing).  Loss cotangents seed at the last stage and ride the reverse
  neighbor ``ppermute``; no activation is ever broadcast — the only
  cross-stage value outside the hops is the scalar loss (one ``psum``).
"""

from typing import Callable, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp


class PipelineGrads(NamedTuple):
    """Gradients from :func:`pipeline_train_1f1b`.

    ``stage``: THIS rank's stage-parameter grads.
    ``inputs``: d(loss)/d(microbatches) — real on pipeline rank 0, zeros
        elsewhere (``psum`` over the pp axis recovers it; only requested via
        ``with_input_grads``).  Feeds the backward of whatever produced the
        microbatches (e.g. an embedding outside the pipeline).
    ``loss_params``: grads of ``loss_params`` (e.g. an LM head applied inside
        ``loss_fn``) — real on the LAST rank, zeros elsewhere (``psum`` over
        pp recovers)."""

    stage: object
    inputs: Optional[jnp.ndarray] = None
    loss_params: Optional[object] = None


def _pipeline_axes(axis_name) -> Tuple[Tuple[str, ...], int]:
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    try:
        n_stages = 1
        for a in axes:
            n_stages *= jax.lax.axis_size(a)
    except NameError:
        n_stages = 1
    return axes, n_stages


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    microbatches: jnp.ndarray,
    axis_name: Union[str, Tuple[str, ...]] = "pp",
    remat: bool = False,
):
    """Run ``microbatches`` through the pipeline (GPipe schedule).

    Args:
        stage_fn: ``stage_fn(stage_params, x) -> y``; both ``x`` and ``y``
            must have the microbatch shape (stage widths must agree).
        stage_params: THIS rank's stage parameters.
        microbatches: ``(n_microbatches, mb, ...)``, consumed by stage 0
            (other ranks ignore the values but must pass the same shape).
        axis_name: the pipeline mesh axis.
        remat: wrap ``stage_fn`` in ``jax.checkpoint`` so autodiff through
            the schedule stores only each tick's stage input, recomputing
            internals in the backward pass.

    Returns:
        ``(n_microbatches, mb, ...)`` outputs of the LAST stage, broadcast to
        every pp rank (so the loss can be computed anywhere).  Training loops
        that only need the loss should use :func:`pipeline_loss` (scalar
        traffic) or :func:`pipeline_train_1f1b` (bounded memory) instead.
    """
    from bagua_tpu.communication import broadcast_inplace

    axes, n_stages = _pipeline_axes(axis_name)
    if n_stages == 1:
        if remat:
            stage_fn = jax.checkpoint(stage_fn)
        return jax.vmap(lambda x: stage_fn(stage_params, x))(microbatches)
    collected = _gpipe_collect(stage_fn, stage_params, microbatches, axes, remat)
    # Ship the last stage's outputs to every pp rank.  Every rank then
    # computes an IDENTICAL loss on them (the natural SPMD usage); since the
    # broadcast's psum-transpose would sum those replicated cotangents,
    # scale the backward by 1/n_stages so gradients match the sequential
    # program exactly.
    out = broadcast_inplace(collected, src_rank=n_stages - 1, axis=axes)
    return _scale_grad(out, 1.0 / n_stages)


@jax.custom_vjp
def _scale_grad(x, scale):
    return x


def _scale_grad_fwd(x, scale):
    return x, scale


def _scale_grad_bwd(scale, g):
    return g * scale, None


_scale_grad.defvjp(_scale_grad_fwd, _scale_grad_bwd)


def pipeline_loss(
    stage_fn: Callable,
    stage_params,
    microbatches: jnp.ndarray,
    targets: jnp.ndarray,
    loss_fn: Callable,
    axis_name: Union[str, Tuple[str, ...]] = "pp",
    remat: bool = False,
):
    """Mean microbatch loss of the pipeline — GPipe schedule, but only a
    SCALAR crosses stages: the last stage's per-microbatch losses are summed
    and ``psum``'d, so no ``(n_micro, mb, ...)`` activation broadcast happens
    (the round-2 ``pipeline_apply`` perf note).  Differentiable:
    ``jax.grad(pipeline_loss)`` runs the reverse pipeline; the psum transpose
    seeds cotangents only at the last stage (masked by rank)."""
    from bagua_tpu.communication import allreduce_inplace, rank_id
    from bagua_tpu.defs import ReduceOp

    axes, n_stages = _pipeline_axes(axis_name)
    if n_stages == 1:
        out = pipeline_apply(stage_fn, stage_params, microbatches, axis_name, remat)
        return jnp.mean(jax.vmap(loss_fn)(out, targets))
    collected = _gpipe_collect(stage_fn, stage_params, microbatches, axes, remat)
    per_mb = jax.vmap(loss_fn)(collected, targets)  # real only on the last stage
    mine = jnp.where(rank_id(axes) == n_stages - 1, jnp.mean(per_mb), 0.0)
    total = allreduce_inplace(mine, op=ReduceOp.SUM, axis=axes)
    # Every rank returns the replicated scalar, so jax.grad seeds a cotangent
    # of 1 on each of the n_stages ranks and the psum transpose sums them —
    # scale the backward by 1/n_stages so gradients match the sequential
    # program (same trick as pipeline_apply's broadcast).
    return _scale_grad(total, 1.0 / n_stages)


def _gpipe_collect(stage_fn, stage_params, microbatches, axes, remat):
    """The GPipe forward loop without the output broadcast: returns the
    collected last-stage outputs (zeros on every other rank)."""
    from bagua_tpu.communication import ppermute_shift, rank_id

    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    _, n_stages = _pipeline_axes(axes)
    n_micro = microbatches.shape[0]
    my = rank_id(axes)
    ticks = n_stages + n_micro - 1
    mb_shape = microbatches.shape[1:]

    def tick(t, carry):
        outbuf, collected = carry
        recv = ppermute_shift(outbuf, 1, axes)
        m = t - my
        active = (m >= 0) & (m < n_micro)
        m_clipped = jnp.clip(m, 0, n_micro - 1)
        x_first = jax.lax.dynamic_index_in_dim(
            microbatches, m_clipped, axis=0, keepdims=False
        )
        x_in = jnp.where(my == 0, x_first, recv)
        y = stage_fn(stage_params, x_in)
        y = jnp.where(active, y, jnp.zeros_like(y))
        collected = jax.lax.cond(
            active & (my == n_stages - 1),
            lambda c: jax.lax.dynamic_update_index_in_dim(c, y, m_clipped, axis=0),
            lambda c: c,
            collected,
        )
        return y, collected

    out0 = jnp.zeros(mb_shape, microbatches.dtype)
    collected0 = jnp.zeros((n_micro,) + mb_shape, microbatches.dtype)
    _, collected = jax.lax.fori_loop(0, ticks, tick, (out0, collected0))
    return collected


def _interleaved_collect(stage_fn, stacked_params, microbatches, axes, remat, n_chunks):
    """Interleaved (virtual-chunks) forward loop: rank ``r`` holds chunks
    ``{v}``, i.e. global stages ``v*S + r`` — microbatches circle the ring
    ``V`` times.  Returns the final chunk's outputs (zeros off the last rank).

    Collision-free closed-form schedule: decompose ``u = t - r`` as
    ``g = u // (S*V)``, ``v = (u % (S*V)) // S``, ``o = u % S`` — rank ``r``
    at tick ``t`` runs chunk ``v`` for microbatch ``m = g*S + o``.  Each item
    ``(m, v)`` lands at ``t = (m//S)*S*V + v*S + (m%S) + r``, all distinct
    per rank, and every rank emits exactly one value per tick, so the single
    neighbor ``ppermute`` register carries both intra-circuit hops
    (rank r -> r+1, same chunk) and the wrap (rank S-1 chunk v -> rank 0
    chunk v+1).  ``V = 1`` reduces to the GPipe loop.  Total ticks are
    ``M*V + S - 1``: each rank does ``M*V`` work ticks and idles ``S-1``
    ticks total (rank ``r``: ``r`` warmup + ``S-1-r`` drain), a bubble
    fraction of ~``(S-1)/(M*V)`` — ``V``x smaller than GPipe's ``(S-1)/M``
    at equal total work."""
    from bagua_tpu.communication import ppermute_shift, rank_id

    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    _, n_stages = _pipeline_axes(axes)
    n_micro = microbatches.shape[0]
    if n_micro % n_stages:
        raise ValueError(
            f"interleaved schedule needs n_microbatches ({n_micro}) divisible "
            f"by n_stages ({n_stages})"
        )
    S, V = n_stages, n_chunks
    my = rank_id(axes)
    groups = n_micro // S
    u_max = (groups - 1) * S * V + (V - 1) * S + (S - 1)
    ticks = u_max + S  # last rank finishes at t = u_max + (S-1)
    mb_shape = microbatches.shape[1:]

    def tick(t, carry):
        outbuf, collected = carry
        recv = ppermute_shift(outbuf, 1, axes)
        u = t - my
        active = (u >= 0) & (u <= u_max)
        uc = jnp.clip(u, 0, u_max)
        g = uc // (S * V)
        v = (uc % (S * V)) // S
        m = g * S + (uc % S)
        x_first = jax.lax.dynamic_index_in_dim(microbatches, m, axis=0, keepdims=False)
        x_in = jnp.where((my == 0) & (v == 0), x_first, recv)
        params_v = jax.tree.map(
            lambda p: jax.lax.dynamic_index_in_dim(p, v, axis=0, keepdims=False),
            stacked_params,
        )
        y = stage_fn(params_v, x_in)
        y = jnp.where(active, y, jnp.zeros_like(y))
        collected = jax.lax.cond(
            active & (my == S - 1) & (v == V - 1),
            lambda c: jax.lax.dynamic_update_index_in_dim(c, y, m, axis=0),
            lambda c: c,
            collected,
        )
        return y, collected

    out0 = jnp.zeros(mb_shape, microbatches.dtype)
    collected0 = jnp.zeros((n_micro,) + mb_shape, microbatches.dtype)
    _, collected = jax.lax.fori_loop(0, ticks, tick, (out0, collected0))
    return collected


def pipeline_loss_interleaved(
    stage_fn: Callable,
    stacked_params,
    microbatches: jnp.ndarray,
    targets: jnp.ndarray,
    loss_fn: Callable,
    axis_name: Union[str, Tuple[str, ...]] = "pp",
    remat: bool = False,
):
    """Mean microbatch loss under the interleaved (virtual-chunks) schedule.

    Like :func:`pipeline_loss` but each rank holds ``V`` stage *chunks*
    (``stacked_params`` leaves carry a leading ``V`` axis): rank ``r`` owns
    global stages ``{v*S + r : v < V}``, so microbatches circle the ring
    ``V`` times and the pipeline bubble shrinks ~``V``x relative to GPipe at
    equal model depth ((S-1)/(M*V) vs (S-1)/M).  Only a scalar crosses stages for the loss;
    ``jax.grad`` runs the reverse schedule (autodiff through the loop), and
    ``remat`` bounds the per-tick residual to the chunk input.

    Constraint: ``n_microbatches % n_stages == 0`` (the collision-free
    schedule interleaves chunk circuits in groups of ``n_stages``
    microbatches).
    """
    from bagua_tpu.communication import allreduce_inplace, rank_id
    from bagua_tpu.defs import ReduceOp

    axes, n_stages = _pipeline_axes(axis_name)
    leaves = jax.tree.leaves(stacked_params)
    if not leaves:
        raise ValueError("stacked_params is empty")
    n_chunks = leaves[0].shape[0]
    for l in leaves:
        if l.shape[0] != n_chunks:
            raise ValueError(
                "every stacked_params leaf needs the same leading V axis; "
                f"got {l.shape[0]} vs {n_chunks}"
            )
    if n_stages == 1:
        # single device: apply the V chunks sequentially
        def full(x):
            def chunk(x, p):
                fn = jax.checkpoint(stage_fn) if remat else stage_fn
                return fn(p, x), None

            y, _ = jax.lax.scan(lambda c, p: chunk(c, p), x, stacked_params)
            return y

        out = jax.vmap(full)(microbatches)
        return jnp.mean(jax.vmap(loss_fn)(out, targets))
    collected = _interleaved_collect(
        stage_fn, stacked_params, microbatches, axes, remat, n_chunks
    )
    per_mb = jax.vmap(loss_fn)(collected, targets)  # real only on the last rank
    mine = jnp.where(rank_id(axes) == n_stages - 1, jnp.mean(per_mb), 0.0)
    total = allreduce_inplace(mine, op=ReduceOp.SUM, axis=axes)
    return _scale_grad(total, 1.0 / n_stages)


def pipeline_train_1f1b(
    stage_fn: Callable,
    stage_params,
    microbatches: jnp.ndarray,
    targets: jnp.ndarray,
    loss_fn: Callable,
    axis_name: Union[str, Tuple[str, ...]] = "pp",
    loss_params=None,
    with_input_grads: bool = False,
):
    """One-forward-one-backward pipeline training step.

    Hand-scheduled forward+backward (NOT autodiff through the loop): at tick
    ``t`` stage ``s`` runs the forward for microbatch ``mf = t - s`` and the
    backward for ``mb = t - (2(S-1) - s)`` — on the last stage ``mf == mb``,
    the classic 1F1B cadence.  Only the stage *input* of each in-flight
    microbatch is stashed (ring buffer, ``2S - 1`` slots); the backward
    re-runs ``stage_fn`` from the stash under ``jax.vjp``
    (rematerialization).  Peak live activations are therefore ``O(S)``
    microbatches per rank regardless of ``n_micro`` — vs the GPipe autodiff
    path whose residual stack grows with ``n_micro + S``.

    Args:
        stage_fn: ``stage_fn(stage_params, x) -> y`` (uniform stages).
        stage_params: THIS rank's stage parameters.
        microbatches: ``(n_micro, mb, ...)`` consumed by stage 0.
        targets: ``(n_micro, ...)`` consumed by the LAST stage
            (other ranks must pass the same shape).
        loss_fn: ``loss_fn(y, target) -> scalar``, or — with ``loss_params``
            — ``loss_fn(loss_params, y, target) -> scalar`` (e.g. an LM head
            + cross entropy evaluated on the last stage's output).
        axis_name: the pipeline mesh axis (or tuple of axes).
        loss_params: optional parameters used inside ``loss_fn``; their
            grads come back in ``PipelineGrads.loss_params``.
        with_input_grads: also return d(loss)/d(microbatches) (for a model
            front like an embedding living outside the pipeline).

    Returns:
        ``(loss, grads)``: the scalar mean microbatch loss (identical on
        every pp rank — one scalar psum), and this rank's gradients.
        ``grads`` is the bare stage pytree in the simple case, or a
        :class:`PipelineGrads` when ``loss_params``/``with_input_grads``
        are used.  Values match ``jax.grad(pipeline_loss)`` exactly.
    """
    from bagua_tpu.communication import allreduce_inplace, ppermute_shift, rank_id
    from bagua_tpu.defs import ReduceOp

    extended = loss_params is not None or with_input_grads
    if loss_params is None:
        full_loss_fn = lambda _none, y, t: loss_fn(y, t)  # noqa: E731
        loss_params = ()
    else:
        full_loss_fn = loss_fn

    axes, n_stages = _pipeline_axes(axis_name)
    n_micro = microbatches.shape[0]
    if n_stages == 1:
        def total(p, lp, mbs):
            out = jax.vmap(lambda x: stage_fn(p, x))(mbs)
            return jnp.mean(jax.vmap(lambda y, t: full_loss_fn(lp, y, t))(out, targets))

        loss, (dstage, dlp, dmb) = jax.value_and_grad(total, argnums=(0, 1, 2))(
            stage_params, loss_params, microbatches
        )
        if not extended:
            return loss, dstage
        return loss, PipelineGrads(
            stage=dstage,
            inputs=dmb if with_input_grads else None,
            loss_params=dlp,
        )

    my = rank_id(axes)
    is_first = my == 0
    is_last = my == n_stages - 1
    mb_shape = microbatches.shape[1:]
    stash_slots = 2 * n_stages - 1  # max in-flight microbatches per rank + 1
    ticks = n_micro + 2 * n_stages - 2

    def tick(t, carry):
        y_prev, dx_prev, stash, dgrads, dlp_acc, dinputs, loss_acc = carry
        # neighbor hops from LAST tick's compute: activations go s-1 -> s,
        # cotangents go s+1 -> s
        recv_f = ppermute_shift(y_prev, 1, axes)
        recv_g = ppermute_shift(dx_prev, -1, axes)

        # ---- forward: microbatch mf = t - s --------------------------------
        mf = t - my
        active_f = (mf >= 0) & (mf < n_micro)
        mf_c = jnp.clip(mf, 0, n_micro - 1)
        x_first = jax.lax.dynamic_index_in_dim(microbatches, mf_c, 0, keepdims=False)
        x_in = jnp.where(is_first, x_first, recv_f)
        y = stage_fn(stage_params, x_in)
        y = jnp.where(active_f, y, jnp.zeros_like(y))
        stash = jax.lax.cond(
            active_f,
            lambda s_: jax.lax.dynamic_update_index_in_dim(
                s_, x_in, mf_c % stash_slots, axis=0
            ),
            lambda s_: s_,
            stash,
        )

        # ---- backward: microbatch mb = t - (2(S-1) - s) --------------------
        mb = t - (2 * (n_stages - 1) - my)
        active_b = (mb >= 0) & (mb < n_micro)
        mb_c = jnp.clip(mb, 0, n_micro - 1)
        x_saved = jax.lax.dynamic_index_in_dim(
            stash, mb_c % stash_slots, 0, keepdims=False
        )
        target = jax.lax.dynamic_index_in_dim(targets, mb_c, 0, keepdims=False)

        # Cotangent feeding this stage: the last stage seeds from the loss on
        # the y it just computed (mf == mb there); others take the hop.
        loss_m, (dlp, dy_loss) = jax.value_and_grad(full_loss_fn, argnums=(0, 1))(
            loss_params, y, target
        )
        g_in = jnp.where(is_last, dy_loss / n_micro, recv_g)

        # Recompute the stage forward from the stashed input and pull back
        # (the remat: nothing but x_in was kept from the forward pass).
        _, pullback = jax.vjp(stage_fn, stage_params, x_saved)
        dp, dx = pullback(g_in)
        # where (select), NOT a 0/1 multiply: inactive ticks can produce
        # non-finite dp (e.g. a loss gradient undefined at the zero
        # placeholder y), and 0 * inf = NaN would poison the accumulator.
        dgrads = jax.tree.map(
            lambda a, d: a + jnp.where(active_b, d, jnp.zeros_like(d)), dgrads, dp
        )
        seed_b = active_b & is_last
        dlp_acc = jax.tree.map(
            lambda a, d: a + jnp.where(seed_b, d / n_micro, jnp.zeros_like(d)),
            dlp_acc, dlp,
        )
        dx = jnp.where(active_b, dx, jnp.zeros_like(dx))
        if dinputs is not None:
            dinputs = jax.lax.cond(
                active_b,
                lambda b: jax.lax.dynamic_update_index_in_dim(
                    b, jnp.where(is_first, dx, jnp.zeros_like(dx)), mb_c, axis=0
                ),
                lambda b: b,
                dinputs,
            )
        loss_acc = loss_acc + jnp.where(active_b & is_last, loss_m, 0.0)
        return y, dx, stash, dgrads, dlp_acc, dinputs, loss_acc

    y0 = jnp.zeros(mb_shape, microbatches.dtype)
    stash0 = jnp.zeros((stash_slots,) + mb_shape, microbatches.dtype)
    dgrads0 = jax.tree.map(jnp.zeros_like, stage_params)
    dlp0 = jax.tree.map(jnp.zeros_like, loss_params)
    dinputs0 = jnp.zeros_like(microbatches) if with_input_grads else None
    _, _, _, dgrads, dlp_acc, dinputs, loss_acc = jax.lax.fori_loop(
        0, ticks, tick,
        (y0, y0, stash0, dgrads0, dlp0, dinputs0, jnp.zeros((), jnp.float32)),
    )
    loss = allreduce_inplace(
        jnp.where(is_last, loss_acc / n_micro, 0.0), op=ReduceOp.SUM, axis=axes
    )
    if not extended:
        return loss, dgrads
    return loss, PipelineGrads(stage=dgrads, inputs=dinputs, loss_params=dlp_acc)
