"""Pipeline parallelism: GPipe-style microbatch streaming over a ``pp`` axis.

Not in the reference (SURVEY §2.4: PP "no") — provided as a first-class mesh
capability.  SPMD formulation: every rank holds ONE stage's parameters
(stages must share a structure, e.g. uniform transformer blocks).  Time is
``T = n_stages + n_microbatches - 1`` ticks; at tick ``t`` stage ``s`` is
active for microbatch ``m = t - s``.  Activations hop to the next stage with
a single neighbor ``ppermute`` per tick, so in-flight memory per chip is one
microbatch and the wire pattern is the classic pipeline bubble.

Because the whole schedule is one traced ``fori_loop``, ``jax.grad``
differentiates straight through it — the backward pipeline (reverse
``ppermute``s) falls out of autodiff instead of hand-written scheduling.
"""

from typing import Callable, Tuple, Union

import jax
import jax.numpy as jnp


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    microbatches: jnp.ndarray,
    axis_name: Union[str, Tuple[str, ...]] = "pp",
):
    """Run ``microbatches`` through the pipeline.

    Args:
        stage_fn: ``stage_fn(stage_params, x) -> y``; both ``x`` and ``y``
            must have the microbatch shape (stage widths must agree).
        stage_params: THIS rank's stage parameters.
        microbatches: ``(n_microbatches, mb, ...)``, consumed by stage 0
            (other ranks ignore the values but must pass the same shape).
        axis_name: the pipeline mesh axis.

    Returns:
        ``(n_microbatches, mb, ...)`` outputs of the LAST stage, broadcast to
        every pp rank (so the loss can be computed anywhere).
    """
    from bagua_tpu.communication import broadcast_inplace, ppermute_shift, rank_id

    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    try:
        n_stages = 1
        for a in axes:
            n_stages *= jax.lax.axis_size(a)
    except NameError:
        n_stages = 1
    n_micro = microbatches.shape[0]
    if n_stages == 1:
        return jax.vmap(lambda x: stage_fn(stage_params, x))(microbatches)

    my = rank_id(axes)
    ticks = n_stages + n_micro - 1
    mb_shape = microbatches.shape[1:]

    def tick(t, carry):
        outbuf, collected = carry
        # activation from the previous stage (computed last tick)
        recv = ppermute_shift(outbuf, 1, axes)
        m = t - my  # microbatch index this stage works on now
        active = (m >= 0) & (m < n_micro)
        m_clipped = jnp.clip(m, 0, n_micro - 1)
        x_first = jax.lax.dynamic_index_in_dim(
            microbatches, m_clipped, axis=0, keepdims=False
        )
        x_in = jnp.where(my == 0, x_first, recv)
        y = stage_fn(stage_params, x_in)
        y = jnp.where(active, y, jnp.zeros_like(y))
        is_last = my == n_stages - 1
        collected = jax.lax.cond(
            active & is_last,
            lambda c: jax.lax.dynamic_update_index_in_dim(c, y, m_clipped, axis=0),
            lambda c: c,
            collected,
        )
        return y, collected

    out0 = jnp.zeros(mb_shape, microbatches.dtype)
    collected0 = jnp.zeros((n_micro,) + mb_shape, microbatches.dtype)
    _, collected = jax.lax.fori_loop(0, ticks, tick, (out0, collected0))
    # Ship the last stage's outputs to every pp rank.  Every rank then
    # computes an IDENTICAL loss on them (the natural SPMD usage); since the
    # broadcast's psum-transpose would sum those replicated cotangents,
    # scale the backward by 1/n_stages so gradients match the sequential
    # program exactly.
    out = broadcast_inplace(collected, src_rank=n_stages - 1, axis=axes)
    return _scale_grad(out, 1.0 / n_stages)


@jax.custom_vjp
def _scale_grad(x, scale):
    return x


def _scale_grad_fwd(x, scale):
    return x, scale


def _scale_grad_bwd(scale, g):
    return g * scale, None


_scale_grad.defvjp(_scale_grad_fwd, _scale_grad_bwd)
