"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context support absent from the reference (SURVEY §5.7) but first-class
here: the sequence is sharded over the ``sp`` axis, and attention runs
blockwise — each rank computes attention of its local queries against one
K/V block at a time while the K/V blocks rotate around the ring via
``lax.ppermute`` (one neighbor send/recv per step, so the memory per chip is
O(T/sp) and the collective traffic rides ICI neighbor links).

Numerics use the online-softmax (flash-attention style) accumulation:
running max ``m``, running normalizer ``l``, running output ``o``; each block
contributes exactly once, so the result equals full attention on the
gathered sequence up to float roundoff.
"""

from functools import partial
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp


def _axis_and_size(axis_name):
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    bound = []
    n = 1
    for a in axes:
        try:
            n *= jax.lax.axis_size(a)
            bound.append(a)
        except NameError:
            pass  # axis not bound here (single-device / outside shard_map)
    return tuple(bound), n


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: Union[str, Tuple[str, ...]] = "sp",
    causal: bool = False,
    kv_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Blockwise ring attention.

    Args:
        q, k, v: local blocks, shape ``(batch, t_local, heads, head_dim)``.
            The global sequence is the concatenation of blocks in rank order.
        axis_name: the sequence-parallel mesh axis.
        causal: apply a causal mask over *global* positions.
        kv_mask: optional key-padding mask for the LOCAL block, shape
            ``(batch, t_local)``; True = attend.  It rotates around the ring
            together with its K/V block.

    Returns:
        Attention output for the local queries, same shape as ``q``.
    """
    axes, sp = _axis_and_size(axis_name)
    if sp == 1:
        return _block_attention_local(q, k, v, causal=causal, kv_mask=kv_mask)

    from bagua_tpu.communication import ppermute_shift, rank_id

    my = rank_id(axes)
    b, t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, q.dtype))
    qf = (q * scale).astype(jnp.float32)
    if kv_mask is None:
        kv_mask = jnp.ones((b, t), bool)

    def body(i, carry):
        o, l, m, k_blk, v_blk, mask_blk = carry
        # block currently held came from rank (my - i) mod sp
        src = (my - i) % sp
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
        s = jnp.where(mask_blk[:, None, None, :], s, -jnp.inf)
        if causal:
            q_pos = my * t + jnp.arange(t)
            k_pos = src * t + jnp.arange(t)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # guard fully-masked rows: exp(-inf - -inf) -> use safe max
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), 0.0, m) - m_safe)
        corr = jnp.where(jnp.isneginf(m), 0.0, corr)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )
        k_next = ppermute_shift(k_blk, 1, axes)
        v_next = ppermute_shift(v_blk, 1, axes)
        mask_next = ppermute_shift(mask_blk, 1, axes)
        return o_new, l_new, m_new, k_next, v_next, mask_next

    o0 = jnp.zeros((b, h, t, d), jnp.float32)
    l0 = jnp.zeros((b, h, t), jnp.float32)
    m0 = jnp.full((b, h, t), -jnp.inf, jnp.float32)
    o, l, m, _, _, _ = jax.lax.fori_loop(0, sp, body, (o0, l0, m0, k, v, kv_mask))
    l = jnp.where(l == 0.0, 1.0, l)
    out = (o / l[..., None]).astype(q.dtype)
    return jnp.transpose(out, (0, 2, 1, 3))  # (b, t, h, d)


def _block_attention_local(q, k, v, causal=False, kv_mask=None):
    b, t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, -jnp.inf)
    if causal:
        mask = jnp.arange(t)[:, None] >= jnp.arange(k.shape[1])[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
