"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context support absent from the reference (SURVEY §5.7) but first-class
here: the sequence is sharded over the ``sp`` axis, and attention runs
blockwise — each rank computes attention of its local queries against one
K/V block at a time while the K/V blocks rotate around the ring via
``lax.ppermute`` (one neighbor send/recv per step, so the memory per chip is
O(T/sp) and the collective traffic rides ICI neighbor links).

Numerics use the online-softmax (flash-attention style) accumulation, with
the per-block compute factored into ``kernels.flash_attention``:

* ``block_attention`` — fused jnp (XLA) implementation;
* ``block_attention_pallas`` — Pallas TPU kernel keeping the (t_q, t_k)
  score matrix entirely in VMEM (``use_pallas=None`` auto-selects it on
  TPU backends);
* ``merge_blocks`` — the cheap elementwise combine.

Each block contributes exactly once, so the result equals full attention on
the gathered sequence up to float roundoff.
"""

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from bagua_tpu.kernels.flash_attention import (
    NEG,
    block_attention,
    block_attention_pallas,
    merge_blocks,
)


def _axis_and_size(axis_name):
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    bound = []
    n = 1
    for a in axes:
        try:
            n *= jax.lax.axis_size(a)
            bound.append(a)
        except NameError:
            pass  # axis not bound here (single-device / outside shard_map)
    return tuple(bound), n


def _pick_block_fn(use_pallas, interpret):
    from bagua_tpu.kernels._config import resolve_use_pallas

    if resolve_use_pallas(use_pallas, "BAGUA_PALLAS_ATTENTION"):
        return lambda qf, k, v, mask: block_attention_pallas(
            qf, k, v, mask, interpret=interpret
        )
    return block_attention


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: Union[str, Tuple[str, ...]] = "sp",
    causal: bool = False,
    kv_mask: Optional[jnp.ndarray] = None,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Blockwise ring attention.

    Args:
        q, k, v: local blocks, shape ``(batch, t_local, heads, head_dim)``.
            The global sequence is the concatenation of blocks in rank order.
        axis_name: the sequence-parallel mesh axis.
        causal: apply a causal mask over *global* positions.
        kv_mask: optional key-padding mask for the LOCAL block, shape
            ``(batch, t_local)``; True = attend.  It rotates around the ring
            together with its K/V block.
        use_pallas: force the Pallas TPU block kernel on/off (None = auto:
            on for TPU backends).  ``interpret`` runs the kernel in
            interpreter mode (CPU testing).

    Returns:
        Attention output for the local queries, same shape as ``q``.
    """
    axes, sp = _axis_and_size(axis_name)
    b, t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    qf = q.astype(jnp.float32) * scale
    if kv_mask is None:
        kv_mask = jnp.ones((b, k.shape[1]), bool)
    block_fn = _pick_block_fn(use_pallas, interpret)

    if sp == 1:
        t_k = k.shape[1]
        mask = jnp.broadcast_to(kv_mask[:, None, :], (b, t, t_k))
        if causal:
            mask = mask & (jnp.arange(t)[:, None] >= jnp.arange(t_k)[None, :])[None]
        o, l, m = block_fn(qf, k, v, mask)
        l = jnp.where(l == 0.0, 1.0, l)
        out = (o / l[..., None]).astype(q.dtype)
        return jnp.transpose(out, (0, 2, 1, 3))

    from bagua_tpu.communication import ppermute_shift, rank_id

    my = rank_id(axes)

    def body(i, carry):
        o, l, m, k_blk, v_blk, mask_blk = carry
        # block currently held came from rank (my - i) mod sp
        src = (my - i) % sp
        mask = jnp.broadcast_to(mask_blk[:, None, :], (b, t, t))
        if causal:
            q_pos = my * t + jnp.arange(t)
            k_pos = src * t + jnp.arange(t)
            mask = mask & (q_pos[:, None] >= k_pos[None, :])[None]
        o, l, m = merge_blocks((o, l, m), block_fn(qf, k_blk, v_blk, mask))
        k_next = ppermute_shift(k_blk, 1, axes)
        v_next = ppermute_shift(v_blk, 1, axes)
        mask_next = ppermute_shift(mask_blk, 1, axes)
        return o, l, m, k_next, v_next, mask_next

    o0 = jnp.zeros((b, h, t, d), jnp.float32)
    l0 = jnp.zeros((b, h, t), jnp.float32)
    m0 = jnp.full((b, h, t), NEG, jnp.float32)
    o, l, m, _, _, _ = jax.lax.fori_loop(0, sp, body, (o0, l0, m0, k, v, kv_mask))
    l = jnp.where(l == 0.0, 1.0, l)
    out = (o / l[..., None]).astype(q.dtype)
    return jnp.transpose(out, (0, 2, 1, 3))  # (b, t, h, d)


def _block_attention_local(q, k, v, causal=False, kv_mask=None):
    """Plain (quadratic) single-device attention — the test oracle."""
    b, t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, -jnp.inf)
    if causal:
        mask = jnp.arange(t)[:, None] >= jnp.arange(k.shape[1])[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
