"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context support absent from the reference (SURVEY §5.7) but first-class
here: the sequence is sharded over the ``sp`` axis, and attention runs
blockwise — each rank computes attention of its local queries against one
K/V block at a time while the K/V blocks rotate around the ring via
``lax.ppermute`` (one neighbor send/recv per step, so the memory per chip is
O(T/sp) and the collective traffic rides ICI neighbor links).

Numerics use the online-softmax (flash-attention style) accumulation, with
the per-block compute factored into ``kernels.flash_attention``:

* ``block_attention`` — fused jnp (XLA) implementation;
* ``block_attention_fused`` — the tiled Pallas TPU kernel (VMEM use
  independent of shard length) wrapped with a custom VJP so training
  differentiates through it (``use_pallas=None`` auto-selects it on TPU
  backends once the hardware-validation record approves);
* ``merge_blocks`` — the cheap elementwise combine.

Each block contributes exactly once, so the result equals full attention on
the gathered sequence up to float roundoff.
"""

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from bagua_tpu.kernels.flash_attention import (
    NEG,
    block_attention,
    merge_blocks,
)


def _axis_and_size(axis_name):
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    bound = []
    n = 1
    for a in axes:
        try:
            n *= jax.lax.axis_size(a)
            bound.append(a)
        except NameError:
            pass  # axis not bound here (single-device / outside shard_map)
    return tuple(bound), n


def _pick_block_fn(use_pallas, interpret):
    """Returns ``(block_fn, gqa_native)``: ``gqa_native`` means the fn takes
    grouped (unrepeated) K/V directly — the fused kernel maps each query
    head's grid step to its shared K/V tile, so the ``jnp.repeat``
    materialization is skipped entirely on the pallas path."""
    from bagua_tpu.kernels._config import resolve_use_pallas

    if resolve_use_pallas(use_pallas, "BAGUA_PALLAS_ATTENTION",
                          kernel="flash_attention_block"):
        # The _fused wrapper carries the custom VJP: the raw pallas_call has
        # no autodiff rule, and ring attention's main consumer is TRAINING.
        from bagua_tpu.kernels.flash_attention import block_attention_fused

        return (lambda qf, k, v, mask: block_attention_fused(
            qf, k, v, mask, interpret=interpret
        )), True
    return block_attention, False


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: Union[str, Tuple[str, ...]] = "sp",
    causal: bool = False,
    kv_mask: Optional[jnp.ndarray] = None,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
    layout: str = "contiguous",
    kv_groups: int = 1,
) -> jnp.ndarray:
    """Blockwise ring attention.

    Args:
        q, k, v: local blocks, shape ``(batch, t_local, heads, head_dim)``.
            The global sequence is the concatenation of blocks in rank order
            (``layout="contiguous"``) or in zigzag order (see below).
            With ``kv_groups > 1`` (grouped-query attention) K/V carry
            ``heads // kv_groups`` heads instead: the ring hops ship the
            *unrepeated* K/V blocks and each head group is expanded only
            inside the per-block computation — the GQA bandwidth saving
            applies to the ring traffic itself.
        axis_name: the sequence-parallel mesh axis.
        causal: apply a causal mask over *global* positions.  Ring steps
            whose K/V block lies entirely in this rank's future are skipped
            under ``lax.cond`` — real time saved on TPU, not just masked.
        kv_mask: optional key-padding mask for the LOCAL block, shape
            ``(batch, t_local)``; True = attend.  It rotates around the ring
            together with its K/V block.
        use_pallas: force the Pallas TPU block kernel on/off (None = auto:
            on for TPU backends).  ``interpret`` runs the kernel in
            interpreter mode (CPU testing).
        layout: ``"contiguous"`` — rank r holds global block r.  With
            ``causal`` the skip leaves a load imbalance (rank 0 computes 1
            block, rank sp-1 computes sp; the ring waits for the last rank).
            ``"zigzag"`` — rank r holds global HALF-blocks ``(r, 2sp-1-r)``
            concatenated (permute with :func:`zigzag_order` before sharding);
            every rank then computes exactly ``2sp+1`` unmasked half-block
            pairs, the balanced causal schedule.

    Returns:
        Attention output for the local queries, same shape as ``q``.
    """
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    axes, sp = _axis_and_size(axis_name)
    b, t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    qf = q.astype(jnp.float32) * scale
    if kv_mask is None:
        kv_mask = jnp.ones((b, k.shape[1]), bool)
    block_fn, gqa_native = _pick_block_fn(use_pallas, interpret)
    if kv_groups > 1:
        if k.shape[2] * kv_groups != h:
            raise ValueError(
                f"kv_groups={kv_groups} needs K/V with {h // kv_groups} heads, "
                f"got {k.shape[2]} (q has {h})"
            )
        if not gqa_native:
            inner = block_fn
            # jnp path: expand the shared K/V heads at compute time only;
            # everything that travels (the ring hops below) stays at the
            # grouped head count.  The fused kernel needs no expansion at
            # all — its K/V BlockSpecs index the shared tiles directly.
            block_fn = lambda qf_, k_, v_, m_: inner(  # noqa: E731
                qf_, jnp.repeat(k_, kv_groups, axis=2),
                jnp.repeat(v_, kv_groups, axis=2), m_
            )

    if sp == 1:
        # zigzag of 1 rank is the identity layout
        t_k = k.shape[1]
        mask = jnp.broadcast_to(kv_mask[:, None, :], (b, t, t_k))
        if causal:
            mask = mask & (jnp.arange(t)[:, None] >= jnp.arange(t_k)[None, :])[None]
        o, l, m = block_fn(qf, k, v, mask)
        l = jnp.where(l == 0.0, 1.0, l)
        out = (o / l[..., None]).astype(q.dtype)
        return jnp.transpose(out, (0, 2, 1, 3))

    if layout == "zigzag" and causal:
        # Zigzag exists to balance the *causal* triangle across ranks.
        # Non-causal attention is invariant to the kv block order (each
        # block's kv_mask travels with it), so the contiguous path below
        # computes the identical result with one full-size kernel launch per
        # ring step instead of zigzag's four quarter-size ones.
        return _ring_attention_zigzag(
            qf, k, v, kv_mask, axes, sp, causal, block_fn, q.dtype
        )

    from bagua_tpu.communication import ppermute_shift, rank_id

    my = rank_id(axes)

    def body(i, carry):
        o, l, m, k_blk, v_blk, mask_blk = carry
        # block currently held came from rank (my - i) mod sp
        src = (my - i) % sp
        mask = jnp.broadcast_to(mask_blk[:, None, :], (b, t, t))
        if causal:
            q_pos = my * t + jnp.arange(t)
            k_pos = src * t + jnp.arange(t)
            mask = mask & (q_pos[:, None] >= k_pos[None, :])[None]

            def compute(olm):
                return merge_blocks(olm, block_fn(qf, k_blk, v_blk, mask))

            # a block from a strictly-future rank contributes nothing: skip
            # the whole block computation, not just mask it
            o, l, m = jax.lax.cond(src <= my, compute, lambda olm: olm, (o, l, m))
        else:
            o, l, m = merge_blocks((o, l, m), block_fn(qf, k_blk, v_blk, mask))
        k_next = ppermute_shift(k_blk, 1, axes)
        v_next = ppermute_shift(v_blk, 1, axes)
        mask_next = ppermute_shift(mask_blk, 1, axes)
        return o, l, m, k_next, v_next, mask_next

    o0 = jnp.zeros((b, h, t, d), jnp.float32)
    l0 = jnp.zeros((b, h, t), jnp.float32)
    m0 = jnp.full((b, h, t), NEG, jnp.float32)
    o, l, m, _, _, _ = jax.lax.fori_loop(0, sp, body, (o0, l0, m0, k, v, kv_mask))
    l = jnp.where(l == 0.0, 1.0, l)
    out = (o / l[..., None]).astype(q.dtype)
    return jnp.transpose(out, (0, 2, 1, 3))  # (b, t, h, d)


def _ring_attention_zigzag(qf, k, v, kv_mask, axes, sp, causal, block_fn, out_dtype):
    """Zigzag-layout ring: rank r's local sequence is global half-blocks
    ``(r, 2sp-1-r)``.  Work is skipped per (q-half, k-half) pair — the pair
    ``(qg, kg)`` contributes iff ``qg >= kg`` — which makes the causal load
    uniform: every rank computes ``(r+1) + (2sp-r) = 2sp+1`` pairs."""
    from bagua_tpu.communication import ppermute_shift, rank_id

    b, t, h, d = qf.shape
    if t % 2 != 0:
        raise ValueError(f"zigzag needs an even local length, got {t}")
    t2 = t // 2
    my = rank_id(axes)
    q_halves = (qf[:, :t2], qf[:, t2:])
    qg = (my, 2 * sp - 1 - my)  # global half-block id of each local q half

    def pair(o, l, m, q_h, q_gid, k_h, v_h, mask_h, k_gid):
        """Merge one (q-half x k-half) attention block, skipped when the
        k half lies strictly in the q half's future."""
        k_pos = k_gid * t2 + jnp.arange(t2)
        q_pos = q_gid * t2 + jnp.arange(t2)
        mask = jnp.broadcast_to(mask_h[:, None, :], (b, t2, t2))
        if causal:
            mask = mask & (q_pos[:, None] >= k_pos[None, :])[None]

            def compute(olm):
                return merge_blocks(olm, block_fn(q_h, k_h, v_h, mask))

            return jax.lax.cond(q_gid >= k_gid, compute, lambda olm: olm, (o, l, m))
        return merge_blocks((o, l, m), block_fn(q_h, k_h, v_h, mask))

    def body(i, carry):
        acc, k_blk, v_blk, mask_blk = carry
        src = (my - i) % sp
        kg = (src, 2 * sp - 1 - src)
        new_acc = []
        for qh in range(2):
            o, l, m = acc[qh]
            for kh in range(2):
                o, l, m = pair(
                    o, l, m,
                    q_halves[qh], qg[qh],
                    k_blk[:, kh * t2 : (kh + 1) * t2],
                    v_blk[:, kh * t2 : (kh + 1) * t2],
                    mask_blk[:, kh * t2 : (kh + 1) * t2],
                    kg[kh],
                )
            new_acc.append((o, l, m))
        k_next = ppermute_shift(k_blk, 1, axes)
        v_next = ppermute_shift(v_blk, 1, axes)
        mask_next = ppermute_shift(mask_blk, 1, axes)
        return tuple(new_acc), k_next, v_next, mask_next

    def zeros():
        return (
            jnp.zeros((b, h, t2, d), jnp.float32),
            jnp.zeros((b, h, t2), jnp.float32),
            jnp.full((b, h, t2), NEG, jnp.float32),
        )

    acc, _, _, _ = jax.lax.fori_loop(
        0, sp, body, ((zeros(), zeros()), k, v, kv_mask)
    )
    outs = []
    for o, l, m in acc:
        l = jnp.where(l == 0.0, 1.0, l)
        outs.append(o / l[..., None])
    out = jnp.concatenate(outs, axis=2).astype(out_dtype)  # (b, h, t, d)
    return jnp.transpose(out, (0, 2, 1, 3))


def zigzag_order(seq_len: int, sp: int):
    """Global index permutation laying a length-``seq_len`` sequence out so
    that contiguous per-rank shards hold global half-blocks ``(r, 2sp-1-r)``
    (the balanced causal layout).  Apply with ``x[:, zigzag_order(T, sp)]``
    before sharding; invert with :func:`zigzag_inverse`."""
    if seq_len % (2 * sp) != 0:
        raise ValueError(f"seq_len {seq_len} not divisible by 2*sp={2 * sp}")
    t2 = seq_len // (2 * sp)
    import numpy as _np

    order = []
    for r in range(sp):
        order.extend(range(r * t2, (r + 1) * t2))
        order.extend(range((2 * sp - 1 - r) * t2, (2 * sp - r) * t2))
    return _np.asarray(order)


def zigzag_inverse(seq_len: int, sp: int):
    """Inverse permutation of :func:`zigzag_order` (maps zigzag-laid-out
    positions back to natural order)."""
    import numpy as _np

    order = zigzag_order(seq_len, sp)
    inv = _np.empty_like(order)
    inv[order] = _np.arange(seq_len)
    return inv


def _block_attention_local(q, k, v, causal=False, kv_mask=None):
    """Plain (quadratic) single-device attention — the test oracle."""
    b, t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, -jnp.inf)
    if causal:
        mask = jnp.arange(t)[:, None] >= jnp.arange(k.shape[1])[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
