"""FSDP / ZeRO-3: parameters sharded at rest, gathered at use — the pjit way.

Under GSPMD, ZeRO-3 is a *sharding annotation*, not an optimizer wrapper:
declare every parameter (and optimizer-state) leaf sharded along one of its
axes over the data-parallel mesh, shard the batch, and XLA inserts the
all-gathers before each use and reduce-scatters behind each gradient — the
FSDP wire pattern, scheduled by the compiler's latency-hiding scheduler.
This module packages that recipe against a :class:`BaguaProcessGroup` mesh
(it is also the auto-parallel alternative to the engine's explicit
``shard_map``: same mesh, constraint-driven instead of rank-explicit).

    fsdp = FSDP(loss_fn, optax.adam(1e-3), group)
    params, opt_state = fsdp.init(params)       # leaves land sharded
    (params, opt_state), loss = fsdp.train_step(params, opt_state, batch)

Memory per chip: parameters, gradients and optimizer state all ~``P / n``
(plus transient gathered layers).
"""

from typing import Callable, Optional

import jax
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from bagua_tpu.communication import ALL_AXES, BaguaProcessGroup, get_default_group


def shard_leaf_spec(shape, mesh_size: int) -> P:
    """Pick the PartitionSpec for one leaf: shard the first axis divisible by
    the mesh size over the (flattened) DP axes; replicate if none divides."""
    for dim, extent in enumerate(shape):
        if extent % mesh_size == 0 and extent >= mesh_size:
            return P(*([None] * dim + [ALL_AXES]))
    return P()


def fsdp_shardings(tree, group: BaguaProcessGroup):
    """A NamedSharding per leaf of ``tree`` (ZeRO-3 layout)."""
    n = group.size

    def one(leaf):
        shape = getattr(leaf, "shape", ())
        return NamedSharding(group.mesh, shard_leaf_spec(tuple(shape), n))

    return jax.tree.map(one, tree)


class FSDP:
    """Fully-sharded data parallelism over a group's mesh (ZeRO-3 analog)."""

    def __init__(
        self,
        loss_fn: Callable,
        optimizer: optax.GradientTransformation,
        group: Optional[BaguaProcessGroup] = None,
    ):
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.group = group or get_default_group()
        self._step = None

    def init(self, params):
        """Place parameters and fresh optimizer state in the sharded layout."""
        shardings = fsdp_shardings(params, self.group)
        params = jax.tree.map(jax.device_put, params, shardings)
        opt_state = jax.jit(
            self.optimizer.init,
            out_shardings=fsdp_shardings(
                jax.eval_shape(self.optimizer.init, params), self.group
            ),
        )(params)
        return params, opt_state

    def _build(self, params, opt_state):
        batch_sharding = NamedSharding(self.group.mesh, P(ALL_AXES))
        param_sh = fsdp_shardings(params, self.group)
        opt_sh = fsdp_shardings(opt_state, self.group)

        def step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(self.loss_fn)(params, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        return jax.jit(
            step,
            in_shardings=(param_sh, opt_sh, batch_sharding),
            out_shardings=((param_sh, opt_sh), None),
            donate_argnums=(0, 1),
        )

    def train_step(self, params, opt_state, batch):
        """One step on the global batch (leading dim sharded over the mesh).
        The loss is the global-batch mean; gradients reduce across chips via
        the compiler-inserted reduce-scatters (no explicit collectives)."""
        if self._step is None:
            self._step = self._build(params, opt_state)
        return self._step(params, opt_state, batch)
