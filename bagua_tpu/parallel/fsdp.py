"""FSDP / ZeRO-3: parameters sharded at rest, gathered at use — the pjit way.

Under GSPMD, ZeRO-3 is a *sharding annotation*, not an optimizer wrapper:
declare every parameter (and optimizer-state) leaf sharded along one of its
axes over the data-parallel mesh, shard the batch, and XLA inserts the
all-gathers before each use and reduce-scatters behind each gradient — the
FSDP wire pattern, scheduled by the compiler's latency-hiding scheduler.
This module packages that recipe against a :class:`BaguaProcessGroup` mesh
(it is also the auto-parallel alternative to the engine's explicit
``shard_map``: same mesh, constraint-driven instead of rank-explicit).

    fsdp = FSDP(loss_fn, optax.adam(1e-3), group, compute_dtype=jnp.bfloat16)
    params, opt_state = fsdp.init(params)       # leaves land sharded
    (params, opt_state), loss = fsdp.train_step(params, opt_state, batch)

Memory per chip: parameters, gradients and optimizer state all ~``P / n``
(plus transient gathered layers).

**Mixed precision** (``compute_dtype``): master parameters and optimizer
state stay float32; inside the step, floating-point params and batch leaves
are cast to ``compute_dtype`` (bfloat16 feeds the MXU at twice the f32
rate), and the cast's transpose re-accumulates gradients back in float32 for
the update — the standard master-weights AMP recipe.

**Scanned layers** (:func:`scan_layers`): stack homogeneous blocks on a
leading layer axis and ``lax.scan`` over it — one compiled block body
regardless of depth, and with the stack's layer axis sharded (ZeRO-3) each
scan iteration all-gathers exactly one layer: the classic per-layer
gather-at-use pattern.

Note on wire-pattern verification: the all-gather-at-use structure is
asserted in ``tests/test_zero.py`` against the compiled HLO.  XLA:CPU (the
test backend) lowers the gradient reduction to ``all-reduce`` +
``dynamic-slice``; the ``reduce-scatter`` fusion of that pair is an
accelerator-pipeline pass, so its materialization is checked on real TPU
(PERF_AUDIT).
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from bagua_tpu.communication import ALL_AXES, BaguaProcessGroup, get_default_group


def cast_floating(tree, dtype):
    """Cast every inexact-dtype leaf of ``tree`` to ``dtype`` (ints, bools
    and rng keys pass through)."""
    if dtype is None:
        return tree

    def one(leaf):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.inexact):
            return leaf.astype(dtype)
        return leaf

    return jax.tree.map(one, tree)


def scan_layers(block_fn: Callable, stacked_params, x, *, unroll: int = 1):
    """Apply a stack of homogeneous layers with ``lax.scan``.

    ``stacked_params``: pytree whose leaves carry a leading layer axis
    ``(L, ...)``; ``block_fn(layer_params, x) -> x`` is one layer.  Compiles
    the block once for any depth; under FSDP shardings the layer axis is the
    first divisible axis, so each iteration gathers exactly one layer's
    parameters (per-layer gather-at-use)."""

    def body(carry, layer):
        return block_fn(layer, carry), None

    out, _ = jax.lax.scan(body, x, stacked_params, unroll=unroll)
    return out


def shard_leaf_spec(shape, mesh_size: int) -> P:
    """Pick the PartitionSpec for one leaf: shard the first axis divisible by
    the mesh size over the (flattened) DP axes; replicate if none divides."""
    for dim, extent in enumerate(shape):
        if extent % mesh_size == 0 and extent >= mesh_size:
            return P(*([None] * dim + [ALL_AXES]))
    return P()


def fsdp_shardings(tree, group: BaguaProcessGroup):
    """A NamedSharding per leaf of ``tree`` (ZeRO-3 layout)."""
    n = group.size

    def one(leaf):
        shape = getattr(leaf, "shape", ())
        return NamedSharding(group.mesh, shard_leaf_spec(tuple(shape), n))

    return jax.tree.map(one, tree)


class FSDP:
    """Fully-sharded data parallelism over a group's mesh (ZeRO-3 analog)."""

    def __init__(
        self,
        loss_fn: Callable,
        optimizer: optax.GradientTransformation,
        group: Optional[BaguaProcessGroup] = None,
        compute_dtype=None,
        cast_batch: bool = True,
    ):
        """``compute_dtype``: AMP compute precision (params are cast per
        step; master copies stay f32).  ``cast_batch``: also cast the
        batch's floating leaves — needed for bf16 dots when inputs arrive
        f32, but it rounds regression *targets* too; pass ``False`` and cast
        inputs inside ``loss_fn`` when the loss reduction must stay f32."""
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.group = group or get_default_group()
        self.compute_dtype = compute_dtype
        self.cast_batch = cast_batch
        self._step = None

    def init(self, params):
        """Place parameters and fresh optimizer state in the sharded layout."""
        shardings = fsdp_shardings(params, self.group)
        params = jax.tree.map(jax.device_put, params, shardings)
        opt_state = jax.jit(
            self.optimizer.init,
            out_shardings=fsdp_shardings(
                jax.eval_shape(self.optimizer.init, params), self.group
            ),
        )(params)
        return params, opt_state

    def _build(self, params, opt_state):
        batch_sharding = NamedSharding(self.group.mesh, P(ALL_AXES))
        param_sh = fsdp_shardings(params, self.group)
        opt_sh = fsdp_shardings(opt_state, self.group)

        def step(params, opt_state, batch):
            def compute_loss(master):
                # The cast's transpose accumulates the gradient back in f32
                # against the master params (AMP master-weights recipe).
                cast_p = cast_floating(master, self.compute_dtype)
                cast_b = (
                    cast_floating(batch, self.compute_dtype)
                    if self.cast_batch else batch
                )
                return self.loss_fn(cast_p, cast_b)

            loss, grads = jax.value_and_grad(compute_loss)(params)
            loss = loss.astype(jnp.float32)  # consistent reporting dtype
            # Land gradients in the parameters' sharded layout before the
            # update, so the full-size gradient buffers die early and the
            # optimizer touches only this chip's 1/n shard.
            grads = jax.lax.with_sharding_constraint(grads, param_sh)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        return jax.jit(
            step,
            in_shardings=(param_sh, opt_sh, batch_sharding),
            out_shardings=((param_sh, opt_sh), None),
            donate_argnums=(0, 1),
        )

    def train_step(self, params, opt_state, batch):
        """One step on the global batch (leading dim sharded over the mesh).
        The loss is the global-batch mean; gradients reduce across chips via
        the compiler-inserted reduce-scatters (no explicit collectives)."""
        if self._step is None:
            self._step = self._build(params, opt_state)
        return self._step(params, opt_state, batch)
