"""Parallelism strategies beyond data parallel: expert (MoE), tensor,
sequence/context parallelism over named mesh axes."""
