"""Tensor parallelism: column/row-parallel layers over a mesh axis.

Not present in the reference (SURVEY §2.4 marks TP "no") but a natural
extension the mesh substrate gives nearly for free: a ``tp`` axis shards the
hidden dimension.  Megatron-style pairing:

* :class:`ColumnParallelDense` — weight columns sharded; local output is this
  rank's slice of the features (no collective on the forward path).
* :class:`RowParallelDense` — weight rows sharded; consumes the sliced
  features and ``psum``s the partial products over the ``tp`` axis.

A Column→(nonlinearity)→Row pair therefore costs exactly one allreduce
forward (and one for the gradient of the input, which ``psum``'s transpose
rule inserts automatically under autodiff).

The opt-in ``fused`` knob replaces those exposed collectives with the
computation-collective rings of :mod:`bagua_tpu.kernels.collective_matmul`:
the Row forward becomes :func:`~bagua_tpu.kernels.collective_matmul.matmul_rs`
(ring-accumulated partial products — **zero** standalone ``psum``; a tiled
``all_gather`` restores the replicated output unless ``scatter_output``), and
a row-sharded Column input (``gather_input``, the sequence-parallel layout)
becomes :func:`~bagua_tpu.kernels.collective_matmul.ag_matmul`.  ``"auto"``
enables the ring wherever its divisibility constraint holds and silently
falls back to the ``psum`` path otherwise; ``True`` makes an impossible ring
an error.  Both values resolve the tile GEMM through the evidence-gated
``get_collective_matmul`` dispatch, so the Pallas kernel only engages on
validated hardware — the ring (and the overlap it buys XLA's scheduler) is
the same either way, and all collectives carry
``bagua_ex/axis=tp/phase=...`` labels for the trace analyzer.

``tp_size`` is static (it fixes parameter shapes so ``init`` can run outside
``shard_map``); the bound axis is checked at apply time.
"""

from typing import Any, Optional, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from bagua_tpu.kernels.collective_matmul import get_collective_matmul
from bagua_tpu.observability.annotations import mp_scope


def _check_axis(tp_size: int, axis_name, initializing: bool):
    if tp_size == 1 or initializing:
        return
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    n = 1
    for a in axes:
        n *= jax.lax.axis_size(a)
    if n != tp_size:
        raise ValueError(f"tp_size={tp_size} but bound axes {axes} have size {n}")


def _single_axis(axis_name) -> str:
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    if len(axes) != 1:
        raise ValueError(
            f"fused collective matmul needs a single mesh axis, got {axes}"
        )
    return axes[0]


def _resolve_fused(fused, tp_size: int, initializing: bool) -> bool:
    """Tri-state ``fused`` knob: ``False`` (default) keeps the classic
    collectives, ``True``/``"auto"`` enable the ring decomposition (``"auto"``
    additionally falls back per call when a ring constraint doesn't hold;
    ``True`` raises instead).  Inactive at init and at ``tp_size == 1``."""
    if fused not in (False, True, "auto"):
        raise ValueError(f"fused must be False, True or 'auto', got {fused!r}")
    if tp_size == 1 or initializing:
        return False
    return bool(fused)


class ColumnParallelDense(nn.Module):
    """y_local = x @ W[:, rank-slice] (+ b slice).  Output dim is
    ``features // tp_size`` per rank.

    ``gather_input=True`` consumes a *row-sharded* ``x`` (the
    sequence-parallel layout: each rank holds its block of the tokens) and
    gathers it on the fly — via :func:`ag_matmul`'s compute-overlapped ring
    when ``fused``, or a plain ``all_gather`` + dot otherwise."""

    features: int
    tp_size: int = 1
    axis_name: Union[str, Tuple[str, ...]] = "tp"
    use_bias: bool = True
    dtype: Any = jnp.float32
    fused: Union[bool, str] = False
    gather_input: bool = False

    @nn.compact
    def __call__(self, x):
        if self.features % self.tp_size != 0:
            raise ValueError(
                f"features ({self.features}) must divide by tp_size ({self.tp_size})"
            )
        _check_axis(self.tp_size, self.axis_name, self.is_initializing())
        local = self.features // self.tp_size
        w = self.param(
            "kernel", nn.initializers.lecun_normal(), (x.shape[-1], local), self.dtype
        )
        use_fused = _resolve_fused(self.fused, self.tp_size, self.is_initializing())
        if self.gather_input and self.tp_size > 1 and not self.is_initializing():
            axis = _single_axis(self.axis_name)
            x2 = x.astype(self.dtype).reshape(-1, x.shape[-1])
            if use_fused:
                ag_mm, _ = get_collective_matmul()
                y = ag_mm(x2, w, axis, axis_tag="tp")
            else:
                with mp_scope("tp", "col_allgather"):
                    xg = jax.lax.all_gather(x2, axis, axis=0, tiled=True)
                y = xg @ w
        else:
            y = x.astype(self.dtype) @ w
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros, (local,), self.dtype)
        return y


class RowParallelDense(nn.Module):
    """y = psum_tp(x_local @ W[rank-slice, :]) (+ b).  Input dim is the
    sliced hidden; output is replicated across the ``tp`` axis.

    When ``fused``, the GEMM+psum is replaced by the :func:`matmul_rs` ring:
    each ring step's partial product accumulates into the travelling shard,
    so **no standalone psum/all-reduce is emitted** and all but one transfer
    hide under tile compute.  The replicated-output contract is restored by a
    tiled ``all_gather`` of the row blocks; ``scatter_output=True`` skips it
    and returns this rank's ``(tokens // tp_size, features)`` row shard (the
    sequence-parallel layout — feed it to the next layer's
    ``gather_input``)."""

    features: int
    tp_size: int = 1
    axis_name: Union[str, Tuple[str, ...]] = "tp"
    use_bias: bool = True
    dtype: Any = jnp.float32
    fused: Union[bool, str] = False
    scatter_output: bool = False

    @nn.compact
    def __call__(self, x):
        _check_axis(self.tp_size, self.axis_name, self.is_initializing())
        w = self.param(
            "kernel", nn.initializers.lecun_normal(), (x.shape[-1], self.features), self.dtype
        )
        use_fused = _resolve_fused(self.fused, self.tp_size, self.is_initializing())
        lead = x.shape[:-1]
        tokens = 1
        for d in lead:
            tokens *= d
        if use_fused and tokens % self.tp_size != 0:
            if self.fused == "auto":
                use_fused = False
            else:
                raise ValueError(
                    f"fused RowParallelDense needs the token count ({tokens}) "
                    f"to divide by tp_size ({self.tp_size}); use fused='auto' "
                    "to fall back to the psum path"
                )
        if use_fused:
            axis = _single_axis(self.axis_name)
            x2 = x.astype(self.dtype).reshape(tokens, x.shape[-1])
            _, mm_rs = get_collective_matmul()
            y = mm_rs(x2, w, axis, axis_tag="tp")  # this rank's row block
            if not self.scatter_output:
                with mp_scope("tp", "row_allgather"):
                    y = jax.lax.all_gather(y, axis, axis=0, tiled=True)
                y = y.reshape(lead + (self.features,))
        else:
            y = x.astype(self.dtype) @ w
            if self.tp_size > 1 and not self.is_initializing():
                with mp_scope("tp", "row_psum"):
                    y = jax.lax.psum(y, self.axis_name)
            if self.scatter_output and self.tp_size > 1 and not self.is_initializing():
                if tokens % self.tp_size != 0:
                    raise ValueError(
                        f"scatter_output needs the token count ({tokens}) to "
                        f"divide by tp_size ({self.tp_size})"
                    )
                axis = _single_axis(self.axis_name)
                y = y.reshape(tokens, self.features)
                blk = tokens // self.tp_size
                y = jax.lax.dynamic_slice_in_dim(
                    y, jax.lax.axis_index(axis) * blk, blk, axis=0
                )
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros, (self.features,), self.dtype)
        return y


class ParallelMLP(nn.Module):
    """Column→activation→Row FFN: one forward allreduce total — or, with
    ``fused``, zero: the Row projection runs the ``matmul_rs`` ring (partial
    products accumulated across ``ppermute`` steps) and only the concluding
    row-block ``all_gather`` touches the wire exposed."""

    hidden_features: int
    out_features: int
    tp_size: int = 1
    axis_name: Union[str, Tuple[str, ...]] = "tp"
    activation: str = "gelu"
    dtype: Any = jnp.float32
    fused: Union[bool, str] = False

    @nn.compact
    def __call__(self, x):
        h = ColumnParallelDense(
            self.hidden_features, self.tp_size, self.axis_name, dtype=self.dtype
        )(x)
        h = getattr(jax.nn, self.activation)(h)
        return RowParallelDense(
            self.out_features, self.tp_size, self.axis_name, dtype=self.dtype,
            fused=self.fused,
        )(h)
