"""Tensor parallelism: column/row-parallel layers over a mesh axis.

Not present in the reference (SURVEY §2.4 marks TP "no") but a natural
extension the mesh substrate gives nearly for free: a ``tp`` axis shards the
hidden dimension.  Megatron-style pairing:

* :class:`ColumnParallelDense` — weight columns sharded; local output is this
  rank's slice of the features (no collective on the forward path).
* :class:`RowParallelDense` — weight rows sharded; consumes the sliced
  features and ``psum``s the partial products over the ``tp`` axis.

A Column→(nonlinearity)→Row pair therefore costs exactly one allreduce
forward (and one for the gradient of the input, which ``psum``'s transpose
rule inserts automatically under autodiff).

``tp_size`` is static (it fixes parameter shapes so ``init`` can run outside
``shard_map``); the bound axis is checked at apply time.
"""

from typing import Any, Optional, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp


def _check_axis(tp_size: int, axis_name, initializing: bool):
    if tp_size == 1 or initializing:
        return
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    n = 1
    for a in axes:
        n *= jax.lax.axis_size(a)
    if n != tp_size:
        raise ValueError(f"tp_size={tp_size} but bound axes {axes} have size {n}")


class ColumnParallelDense(nn.Module):
    """y_local = x @ W[:, rank-slice] (+ b slice).  Output dim is
    ``features // tp_size`` per rank."""

    features: int
    tp_size: int = 1
    axis_name: Union[str, Tuple[str, ...]] = "tp"
    use_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        if self.features % self.tp_size != 0:
            raise ValueError(
                f"features ({self.features}) must divide by tp_size ({self.tp_size})"
            )
        _check_axis(self.tp_size, self.axis_name, self.is_initializing())
        local = self.features // self.tp_size
        w = self.param(
            "kernel", nn.initializers.lecun_normal(), (x.shape[-1], local), self.dtype
        )
        y = x.astype(self.dtype) @ w
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros, (local,), self.dtype)
        return y


class RowParallelDense(nn.Module):
    """y = psum_tp(x_local @ W[rank-slice, :]) (+ b).  Input dim is the
    sliced hidden; output is replicated across the ``tp`` axis."""

    features: int
    tp_size: int = 1
    axis_name: Union[str, Tuple[str, ...]] = "tp"
    use_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        _check_axis(self.tp_size, self.axis_name, self.is_initializing())
        w = self.param(
            "kernel", nn.initializers.lecun_normal(), (x.shape[-1], self.features), self.dtype
        )
        y = x.astype(self.dtype) @ w
        if self.tp_size > 1 and not self.is_initializing():
            y = jax.lax.psum(y, self.axis_name)
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros, (self.features,), self.dtype)
        return y


class ParallelMLP(nn.Module):
    """Column→activation→Row FFN: one forward allreduce total."""

    hidden_features: int
    out_features: int
    tp_size: int = 1
    axis_name: Union[str, Tuple[str, ...]] = "tp"
    activation: str = "gelu"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = ColumnParallelDense(
            self.hidden_features, self.tp_size, self.axis_name, dtype=self.dtype
        )(x)
        h = getattr(jax.nn, self.activation)(h)
        return RowParallelDense(
            self.out_features, self.tp_size, self.axis_name, dtype=self.dtype
        )(h)
