"""Token-to-expert routing (gating) for the expert-parallel MoE block.

Computes the same routing function as the reference's DeepSpeed-derived
gating (``model_parallel/moe/sharded_moe.py:93-239``): softmax router,
top-1/top-2 expert choice, per-expert capacity truncation, load-balancing
auxiliary loss, and dense (tokens, experts, capacity) combine/dispatch
tensors.  The structure here is its own: both routers share three primitives
— :func:`_claim_slots` (capacity-limited slot assignment via masked cumsum),
:func:`_combine` (slot one-hots folded into the combine tensor) and
:func:`_balance_loss` — and return a :class:`Routing` record instead of a
bare tuple.

One deliberate deviation, as in round 1: the reference's top-1 capacity
tie-break draws uniform noise from a hidden global RNG; randomness is
explicit here, so pass ``rng`` to randomize slot claims (``rng=None`` claims
in token-position order, the rule top-2 always uses).
"""

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class Routing(NamedTuple):
    """Routing decision for one batch of tokens.

    combine_weights/dispatch_mask have shape (tokens, experts, capacity);
    tokens_per_expert is the pre-truncation demand histogram (int32, (E,)).
    """

    balance_loss: jnp.ndarray
    combine_weights: jnp.ndarray
    dispatch_mask: jnp.ndarray
    tokens_per_expert: jnp.ndarray


def expert_capacity(num_tokens: int, num_experts: int, factor: float, k: int = 1,
                    floor: int = 0) -> int:
    """Slots each expert can accept: ``ceil(k * tokens/experts * factor)``,
    at least ``floor``."""
    return max(int(math.ceil(k * num_tokens / num_experts * factor)), floor)


def _balance_loss(router_probs, chosen_mask, num_experts: int, scale: float):
    """Mean router probability x mean routed fraction, summed over experts —
    pushes the router toward uniform expert load."""
    prob_share = jnp.mean(router_probs, axis=0)
    routed_share = jnp.mean(chosen_mask, axis=0)
    return jnp.sum(prob_share * routed_share) * scale


def _claim_slots(chosen_mask, capacity: int, *, start_at=None, priority=None):
    """Assign capacity slots within each expert column.

    Tokens claim slots in position order (masked cumsum), or — when a
    ``priority`` array is given — the ``capacity`` highest-priority tokens
    win.  ``start_at`` (per-expert, e.g. the top-1 column's demand) offsets
    the slot numbering for second-choice tokens.  Returns
    ``(kept_mask, slot_of_token)``: the mask with over-capacity tokens
    dropped, and each surviving token's slot index (int32, (S,))."""
    if priority is not None:
        ranked = chosen_mask * priority
        kth = jnp.sort(ranked, axis=0)[-capacity][None, :]
        chosen_mask = chosen_mask * ((ranked >= jnp.maximum(kth, 1e-38)) & (chosen_mask > 0))
        slots = jnp.cumsum(chosen_mask, axis=0) - 1
    else:
        slots = jnp.cumsum(chosen_mask, axis=0) - 1
        if start_at is not None:
            slots = slots + start_at[None, :]
        chosen_mask = chosen_mask * (slots < capacity)
        slots = jnp.cumsum(chosen_mask, axis=0) - 1
        if start_at is not None:
            slots = slots + start_at[None, :]
    slot_of_token = jnp.sum(slots * chosen_mask, axis=1).astype(jnp.int32)
    return chosen_mask, slot_of_token


def _combine(weight_of_token, chosen_mask, slot_of_token, capacity: int):
    """(S,) weights + (S,E) mask + (S,) slots -> (S,E,C) combine tensor."""
    slot_one_hot = jax.nn.one_hot(slot_of_token, capacity, dtype=jnp.float32)
    return jnp.einsum("se,sc->sec", weight_of_token[:, None] * chosen_mask, slot_one_hot)


def route_top1(
    logits: jnp.ndarray,
    capacity_factor: float,
    min_capacity: int = 4,
    used_token: Optional[jnp.ndarray] = None,
    noisy_gate_policy: Optional[str] = None,
    rng: Optional[jax.Array] = None,
) -> Routing:
    """Top-1 routing (reference ``sharded_moe.py:93-165``): every token goes
    to its argmax expert, capacity-truncated."""
    probs = jax.nn.softmax(logits, axis=1)
    num_tokens, num_experts = probs.shape
    capacity = expert_capacity(num_tokens, num_experts, capacity_factor, floor=min_capacity)

    if noisy_gate_policy == "RSample":
        if rng is None:
            raise ValueError("noisy_gate_policy='RSample' requires an rng key")
        choice_scores = logits + jax.random.gumbel(rng, logits.shape, dtype=logits.dtype)
    else:
        choice_scores = probs
    chosen = jax.nn.one_hot(jnp.argmax(choice_scores, axis=1), num_experts, dtype=jnp.float32)
    if used_token is not None:
        chosen = used_token[:, None] * chosen

    demand = jnp.sum(chosen, axis=0).astype(jnp.int32)
    loss = _balance_loss(probs, chosen, num_experts, scale=float(num_experts))

    priority = None
    if rng is not None:
        # random capacity tie-break, like the reference's uniform sample
        priority = jax.random.uniform(jax.random.fold_in(rng, 1), chosen.shape)
    kept, slot_of_token = _claim_slots(chosen, capacity, priority=priority)

    weight_of_token = jnp.sum(probs * kept, axis=1)
    combine = _combine(weight_of_token, kept, slot_of_token, capacity)
    return Routing(loss, combine, combine > 0, demand)


def route_top2(
    logits: jnp.ndarray,
    capacity_factor: float,
    rng: Optional[jax.Array] = None,
    used_token: Optional[jnp.ndarray] = None,
) -> Routing:
    """Top-2 routing (reference ``sharded_moe.py:168-239``): each token's two
    best experts share it, with renormalized weights; second choices queue
    behind every first choice in the capacity count.

    ``used_token`` masks tokens out of routing entirely — a deliberate
    extension: the reference's ``top2gating`` silently ignores the mask its
    ``TopKGate.forward`` accepts (``sharded_moe.py:298-303``)."""
    probs = jax.nn.softmax(logits, axis=1)
    num_tokens, num_experts = probs.shape
    capacity = expert_capacity(num_tokens, num_experts, capacity_factor, k=2)

    first = jax.nn.one_hot(jnp.argmax(probs, axis=1), num_experts, dtype=jnp.float32)
    second_scores = logits if rng is None else (
        logits + jax.random.gumbel(rng, logits.shape, dtype=logits.dtype)
    )
    second_scores = jnp.where(first > 0, -jnp.inf, second_scores)
    second = jax.nn.one_hot(jnp.argmax(second_scores, axis=1), num_experts, dtype=jnp.float32)
    if used_token is not None:
        first = used_token[:, None] * first
        second = used_token[:, None] * second

    demand = jnp.sum(first, axis=0).astype(jnp.int32)
    # top-2 scaling: mean over experts of (prob share x routed share) x E^2
    loss = jnp.mean(jnp.mean(probs, axis=0) * jnp.mean(first, axis=0)) * num_experts ** 2

    kept1, slot1 = _claim_slots(first, capacity)
    kept2, slot2 = _claim_slots(second, capacity, start_at=jnp.sum(first, axis=0))

    w1 = jnp.einsum("se,se->s", probs, kept1)
    w2 = jnp.einsum("se,se->s", probs, kept2)
    norm = jnp.clip(w1 + w2, jnp.finfo(probs.dtype).eps, None)
    combine = _combine(w1 / norm, kept1, slot1, capacity) + _combine(
        w2 / norm, kept2, slot2, capacity
    )
    return Routing(loss, combine, combine > 0, demand)
