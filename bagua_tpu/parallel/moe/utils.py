"""MoE parameter classification (reference ``moe/utils.py:4-7`` tags expert
params with ``allreduce=False`` so DP excludes them; here expert leaves are
identified by path)."""

from typing import List, Tuple

import jax


def is_moe_param_path(path: str) -> bool:
    return "experts" in path


def is_moe_param(path_or_leaf) -> bool:
    if isinstance(path_or_leaf, str):
        return is_moe_param_path(path_or_leaf)
    return False


def split_moe_params(params) -> Tuple[dict, dict]:
    """Split a param tree into (non-expert, expert) trees by leaf path —
    the analog of the reference excluding MoE params from DP bucketing
    (``bagua_distributed.py:172``)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    non_expert, expert = {}, {}
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        (expert if is_moe_param_path(key) else non_expert)[key] = leaf
    return non_expert, expert
