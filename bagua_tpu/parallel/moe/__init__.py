"""Expert-parallel Mixture of Experts (reference ``model_parallel/moe/``)."""

from bagua_tpu.parallel.moe.sharded_moe import (  # noqa: F401
    top1gating,
    top2gating,
    TopKGate,
    MOELayer,
    Experts,
)
from bagua_tpu.parallel.moe.layer import MoE  # noqa: F401
from bagua_tpu.parallel.moe.utils import is_moe_param  # noqa: F401
