"""Expert-parallel Mixture of Experts (reference ``model_parallel/moe/``)."""

from bagua_tpu.parallel.moe.routing import (  # noqa: F401
    Routing,
    expert_capacity,
    route_top1,
    route_top2,
)
from bagua_tpu.parallel.moe.layer import (  # noqa: F401
    Experts,
    ExpertParallelFFN,
    MoE,
    Router,
)
from bagua_tpu.parallel.moe.utils import is_moe_param  # noqa: F401
