"""GShard-style gating + expert-parallel MoE layer.

TPU-native reimplementation of the reference's DeepSpeed-derived
``model_parallel/moe/sharded_moe.py`` (top1/top2 gating ``:93-239``, MOELayer
``:306-375``).  The math is the same — softmax gates, top-k expert choice,
capacity truncation, load-balancing aux loss, (S,E,C) combine/dispatch
tensors — expressed in jnp; the expert-parallel token exchange is
``lax.all_to_all`` over whichever mesh axes are bound (the reference uses
``dist.all_to_all_single``, ``sharded_moe.py:77-91``).

One deliberate deviation: the reference's top-1 capacity tie-break samples
uniform noise (``:130-147``) from a global RNG.  Here randomness must be
explicit, so ``top1gating`` takes an optional ``rng``; with ``rng=None``
tokens win capacity slots in position order (the same rule top-2 uses).
"""

import math
from typing import Optional, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp


def _one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


def _bound_axes(axis_name) -> Tuple[str, ...]:
    if axis_name is None:
        return ()
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    bound = []
    for a in axes:
        try:
            jax.lax.axis_size(a)
            bound.append(a)
        except NameError:
            pass
    return tuple(bound)


def top1gating(
    logits: jnp.ndarray,
    capacity_factor: float,
    min_capacity: int = 4,
    used_token: Optional[jnp.ndarray] = None,
    noisy_gate_policy: Optional[str] = None,
    rng: Optional[jax.Array] = None,
):
    """Top-1 gating (reference ``sharded_moe.py:93-165``).

    Returns ``(l_aux, combine_weights (S,E,C), dispatch_mask (S,E,C),
    exp_counts (E,))``.
    """
    if noisy_gate_policy == "RSample":
        if rng is None:
            raise ValueError("noisy_gate_policy='RSample' requires an rng key")
        noise = jax.random.gumbel(rng, logits.shape, dtype=logits.dtype)
        logits_w_noise = logits + noise
    gates = jax.nn.softmax(logits, axis=1)

    num_tokens, num_experts = gates.shape
    capacity = max(
        int(math.ceil(num_tokens / num_experts * capacity_factor)), min_capacity
    )

    indices1_s = jnp.argmax(
        logits_w_noise if noisy_gate_policy == "RSample" else gates, axis=1
    )
    mask1 = _one_hot(indices1_s, num_experts)
    if used_token is not None:
        mask1 = used_token[:, None] * mask1

    exp_counts = jnp.sum(mask1, axis=0).astype(jnp.int32)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * num_experts

    if rng is not None:
        # Random capacity tie-break, like the reference's uniform sample.
        rand = jax.random.uniform(jax.random.fold_in(rng, 1), mask1.shape)
        priority = mask1 * rand
        # per expert, keep the `capacity` highest-priority tokens
        kth = jnp.sort(priority, axis=0)[-capacity][None, :]
        keep = (priority >= jnp.maximum(kth, 1e-38)) & (mask1 > 0)
        new_mask1 = mask1 * keep
    else:
        locations = jnp.cumsum(mask1, axis=0) - 1
        new_mask1 = mask1 * (locations < capacity)

    locations1 = jnp.cumsum(new_mask1, axis=0) - 1
    locations1_s = jnp.sum(locations1 * new_mask1, axis=1).astype(jnp.int32)

    gates = gates * new_mask1
    locations1_sc = _one_hot(locations1_s, capacity)
    combine_weights = jnp.einsum("se,sc->sec", gates, locations1_sc)
    dispatch_mask = combine_weights > 0
    return l_aux, combine_weights, dispatch_mask, exp_counts


def top2gating(logits: jnp.ndarray, capacity_factor: float, rng: Optional[jax.Array] = None):
    """Top-2 gating (reference ``sharded_moe.py:168-239``)."""
    gates = jax.nn.softmax(logits, axis=1)
    num_tokens, num_experts = gates.shape
    capacity = int(math.ceil(2 * num_tokens / num_experts * capacity_factor))

    indices1_s = jnp.argmax(gates, axis=1)
    mask1 = _one_hot(indices1_s, num_experts)

    if rng is not None:
        noise = jax.random.gumbel(rng, logits.shape, dtype=logits.dtype)
    else:
        noise = jnp.zeros_like(logits)
    logits_except1 = jnp.where(mask1 > 0, -jnp.inf, logits + noise)
    indices2_s = jnp.argmax(logits_except1, axis=1)
    mask2 = _one_hot(indices2_s, num_experts)

    locations1 = jnp.cumsum(mask1, axis=0) - 1
    locations2 = jnp.cumsum(mask2, axis=0) - 1 + jnp.sum(mask1, axis=0, keepdims=True)

    exp_counts = jnp.sum(mask1, axis=0).astype(jnp.int32)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.mean(me * ce) * num_experts * num_experts

    mask1 = mask1 * (locations1 < capacity)
    mask2 = mask2 * (locations2 < capacity)

    locations1_s = jnp.sum(locations1 * mask1, axis=1).astype(jnp.int32)
    locations2_s = jnp.sum(locations2 * mask2, axis=1).astype(jnp.int32)

    gates1_s = jnp.einsum("se,se->s", gates, mask1)
    gates2_s = jnp.einsum("se,se->s", gates, mask2)
    denom_s = jnp.clip(gates1_s + gates2_s, jnp.finfo(gates.dtype).eps, None)
    gates1_s = gates1_s / denom_s
    gates2_s = gates2_s / denom_s

    gates1 = gates1_s[:, None] * mask1
    gates2 = gates2_s[:, None] * mask2
    locations1_sc = _one_hot(locations1_s, capacity)
    locations2_sc = _one_hot(locations2_s, capacity)
    combine_weights = jnp.einsum("se,sc->sec", gates1, locations1_sc) + jnp.einsum(
        "se,sc->sec", gates2, locations2_sc
    )
    dispatch_mask = combine_weights > 0
    return l_aux, combine_weights, dispatch_mask, exp_counts


class TopKGate(nn.Module):
    """Gate network (reference ``sharded_moe.py:241-303``)."""

    num_experts: int
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True, used_token=None, rng=None):
        if self.k not in (1, 2):
            raise ValueError("Only top-1 and top-2 gatings are supported.")
        logits = nn.Dense(self.num_experts, use_bias=False, dtype=jnp.float32)(x)
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return top1gating(
                logits, cf, self.min_capacity, used_token,
                self.noisy_gate_policy if train else None, rng,
            )
        return top2gating(logits, cf, rng)


class Experts(nn.Module):
    """Per-expert FFN stack, vmapped over the local experts
    (reference ``experts.py:16``)."""

    hidden_dim: int
    num_local_experts: int
    activation: str = "relu"

    @nn.compact
    def __call__(self, x):
        # x: (local_experts, tokens, model_dim)
        dense = nn.vmap(
            nn.Dense,
            in_axes=0,
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )
        h = dense(self.hidden_dim)(x)
        h = getattr(jax.nn, self.activation)(h)
        out = dense(x.shape[-1])(h)
        return out


class MOELayer(nn.Module):
    """Dispatch → expert-parallel all_to_all → experts → return → combine
    (reference ``sharded_moe.py:306-375``).

    ``ep_size`` is declared statically (it fixes the *shape* of the expert
    parameters: each rank owns ``num_experts // ep_size`` experts), so
    ``init`` can run outside ``shard_map``; at apply time the bound
    ``ep_axis`` axes must multiply to exactly ``ep_size``.
    """

    num_experts: int
    hidden_dim: int
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    ep_size: int = 1
    ep_axis: Union[str, Tuple[str, ...], None] = ("inter", "intra")

    @nn.compact
    def __call__(self, x, train: bool = True, rng=None):
        # x: (..., model_dim) -> tokens (S, M)
        orig_shape = x.shape
        model_dim = x.shape[-1]
        tokens = x.reshape(-1, model_dim)

        ep_size = self.ep_size
        if self.num_experts % ep_size != 0:
            raise ValueError(
                f"num_experts ({self.num_experts}) must divide evenly by "
                f"ep_size ({ep_size})"
            )
        ep_axes = _bound_axes(self.ep_axis) if ep_size > 1 else ()
        if ep_size > 1 and not self.is_initializing():
            bound_size = 1
            for a in ep_axes:
                bound_size *= jax.lax.axis_size(a)
            if bound_size != ep_size:
                raise ValueError(
                    f"ep_size={ep_size} but the bound mesh axes {ep_axes} "
                    f"have total size {bound_size}"
                )
        local_experts = self.num_experts // ep_size

        l_aux, combine, dispatch, exp_counts = TopKGate(
            num_experts=self.num_experts,
            k=self.k,
            capacity_factor=self.capacity_factor,
            eval_capacity_factor=self.eval_capacity_factor,
            min_capacity=self.min_capacity,
            noisy_gate_policy=self.noisy_gate_policy,
            name="gate",
        )(tokens, train=train, rng=rng)

        # (S,E,C) x (S,M) -> (E,C,M)
        dispatched = jnp.einsum("sec,sm->ecm", dispatch.astype(tokens.dtype), tokens)

        # Group experts by owner rank: (ep, local_e, C, M).
        dispatched = dispatched.reshape(ep_size, local_experts, -1, model_dim)
        if ep_axes:
            # Each rank sends chunk g of its tokens to the rank owning expert
            # group g, receiving tokens from every rank for OUR experts
            # (reference dist.all_to_all_single, sharded_moe.py:77-91).
            dispatched = jax.lax.all_to_all(
                dispatched, ep_axes, split_axis=0, concat_axis=0, tiled=True
            ).reshape(ep_size, local_experts, -1, model_dim)
        # (local_e, ep*C, M) for the expert compute
        expert_in = jnp.moveaxis(dispatched, 0, 1).reshape(local_experts, -1, model_dim)

        expert_out = Experts(
            hidden_dim=self.hidden_dim,
            num_local_experts=local_experts,
            name="experts",
        )(expert_in)

        back = jnp.moveaxis(
            expert_out.reshape(local_experts, ep_size, -1, model_dim), 0, 1
        )  # (ep, local_e, C, M)
        if ep_axes:
            back = jax.lax.all_to_all(
                back, ep_axes, split_axis=0, concat_axis=0, tiled=True
            )
        back = back.reshape(self.num_experts, -1, model_dim)

        out = jnp.einsum("sec,ecm->sm", combine.astype(tokens.dtype), back)
        self.sow("intermediates", "l_aux", l_aux)
        self.sow("intermediates", "exp_counts", exp_counts)
        return out.reshape(orig_shape), l_aux
