"""Expert-parallel MoE block: router + all-to-all token exchange + experts.

User API is :class:`MoE` (reference ``model_parallel/moe/layer.py:22``); the
machinery below it:

* :class:`Router` — linear gate feeding :mod:`bagua_tpu.parallel.moe.routing`.
* :class:`Experts` — the per-expert FFN stack, vmapped over local experts.
* :class:`ExpertParallelFFN` — dispatch → all-to-all over the expert-parallel
  mesh axes → expert compute → all-to-all back → combine (the reference's
  MOELayer, ``sharded_moe.py:306-375``, with ``dist.all_to_all_single``
  replaced by ``lax.all_to_all`` over whichever mesh axes are bound).

``ep_size`` is declared statically — it fixes the *shape* of the expert
parameters (each rank owns ``num_experts // ep_size`` experts) so ``init``
can run outside ``shard_map``; at apply time the bound ``ep_axis`` axes must
multiply to exactly ``ep_size``.
"""

from typing import Optional, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from bagua_tpu.observability.annotations import mp_scope
from bagua_tpu.parallel.moe.routing import Routing, route_top1, route_top2


def _bound_axes(axis_name, *, expect_any: bool = False) -> Tuple[str, ...]:
    """The subset of ``axis_name`` actually bound by an enclosing shard_map.

    ``expect_any=True`` distinguishes "axes legitimately unbound" (init time,
    single-rank) from a typo'd axis name: when *none* of the declared names
    resolve it raises instead of silently degrading to a single-rank layout —
    a misspelled ``ep_axis`` would otherwise skip the all-to-alls and train
    each rank on its local experts only."""
    if axis_name is None:
        return ()
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    bound = []
    for a in axes:
        try:
            jax.lax.axis_size(a)
            bound.append(a)
        except NameError:
            pass
    if expect_any and axes and not bound:
        raise ValueError(
            f"none of the declared expert-parallel axes {axes} are bound by an "
            "enclosing shard_map — check the ep_axis spelling against the mesh "
            "axis names (a typo here would silently degrade to single-rank "
            "expert compute)"
        )
    return tuple(bound)


class Router(nn.Module):
    """Linear gate + top-k routing (reference ``TopKGate``,
    ``sharded_moe.py:241-303``)."""

    num_experts: int
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None

    @nn.compact
    def __call__(self, tokens, train: bool = True, used_token=None, rng=None) -> Routing:
        if self.k not in (1, 2):
            raise ValueError("Only top-1 and top-2 gatings are supported.")
        if self.noisy_gate_policy not in (None, "Jitter", "RSample"):
            raise ValueError(
                f"unknown noisy_gate_policy {self.noisy_gate_policy!r} "
                "(expected None, 'Jitter' or 'RSample')"
            )
        if self.noisy_gate_policy == "Jitter" and train and not self.is_initializing():
            # Input jittering (reference multiplicative_jitter,
            # sharded_moe.py:37-59 via TopKGate.forward:288-289): multiply the
            # gate input by uniform(1-eps, 1+eps), eps=1e-2.
            if rng is None:
                raise ValueError("noisy_gate_policy='Jitter' requires an rng key")
            jitter_rng = jax.random.fold_in(rng, 2)
            tokens = tokens * jax.random.uniform(
                jitter_rng, tokens.shape, tokens.dtype, 1.0 - 1e-2, 1.0 + 1e-2
            )
        logits = nn.Dense(self.num_experts, use_bias=False, dtype=jnp.float32)(tokens)
        factor = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return route_top1(
                logits, factor, self.min_capacity, used_token,
                self.noisy_gate_policy if train else None, rng,
            )
        return route_top2(logits, factor, rng, used_token)


class Experts(nn.Module):
    """Per-expert FFN stack, vmapped over the local experts
    (reference ``experts.py:16``)."""

    hidden_dim: int
    num_local_experts: int
    activation: str = "relu"

    @nn.compact
    def __call__(self, x):
        # x: (local_experts, tokens, model_dim)
        dense = nn.vmap(
            nn.Dense,
            in_axes=0,
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )
        h = dense(self.hidden_dim)(x)
        h = getattr(jax.nn, self.activation)(h)
        return dense(x.shape[-1])(h)


class ExpertParallelFFN(nn.Module):
    """Route tokens to experts sharded over the ``ep_axis`` mesh axes.

    ``a2a_chunks > 1`` enables the fused computation-collective schedule: the
    capacity axis is split into chunks and each chunk's dispatch all-to-all →
    expert FFN → combine all-to-all is issued independently (the loop is
    unrolled, so XLA's scheduler overlaps chunk *j+1*'s in-flight all-to-all
    with chunk *j*'s expert GEMMs — the same wire-under-compute decomposition
    :mod:`bagua_tpu.kernels.collective_matmul` applies to tensor parallelism).
    The expert FFN is position-wise, so chunking the token axis is exact; the
    requested chunk count is clamped to the nearest divisor of the capacity.
    """

    num_experts: int
    hidden_dim: int
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    ep_size: int = 1
    ep_axis: Union[str, Tuple[str, ...], None] = ("inter", "intra")
    a2a_chunks: int = 1

    def _resolve_chunks(self, capacity: int) -> int:
        c = max(1, min(int(self.a2a_chunks), capacity))
        while capacity % c:
            c -= 1
        return c

    @nn.compact
    def __call__(self, x, train: bool = True, used_token=None, rng=None):
        orig_shape = x.shape
        model_dim = x.shape[-1]
        tokens = x.reshape(-1, model_dim)
        if used_token is not None:
            used_token = used_token.reshape(-1).astype(jnp.float32)

        if self.num_experts % self.ep_size != 0:
            raise ValueError(
                f"num_experts ({self.num_experts}) must divide evenly by "
                f"ep_size ({self.ep_size})"
            )
        ep_axes = (
            _bound_axes(self.ep_axis, expect_any=not self.is_initializing())
            if self.ep_size > 1
            else ()
        )
        if self.ep_size > 1 and not self.is_initializing():
            bound = 1
            for a in ep_axes:
                bound *= jax.lax.axis_size(a)
            if bound != self.ep_size:
                raise ValueError(
                    f"ep_size={self.ep_size} but the bound mesh axes "
                    f"{ep_axes} have total size {bound}"
                )
        local_experts = self.num_experts // self.ep_size

        routing = Router(
            num_experts=self.num_experts,
            k=self.k,
            capacity_factor=self.capacity_factor,
            eval_capacity_factor=self.eval_capacity_factor,
            min_capacity=self.min_capacity,
            noisy_gate_policy=self.noisy_gate_policy,
            name="gate",
        )(tokens, train=train, used_token=used_token, rng=rng)

        experts = Experts(
            hidden_dim=self.hidden_dim,
            num_local_experts=local_experts,
            name="experts",
        )

        # (S,E,C) x (S,M) -> (E,C,M), grouped by owning rank
        outbound = jnp.einsum(
            "sec,sm->ecm", routing.dispatch_mask.astype(tokens.dtype), tokens
        ).reshape(self.ep_size, local_experts, -1, model_dim)
        capacity = outbound.shape[2]

        def exchange(ob):
            # one dispatch → expert compute → combine round over a slice of
            # the capacity axis; ob: (ep_size, local_experts, c, model_dim)
            c = ob.shape[2]
            if ep_axes:
                # chunk g of every rank's tokens travels to the rank owning
                # expert group g (reference dist.all_to_all_single,
                # sharded_moe.py:77-91)
                with mp_scope("ep", "dispatch"):
                    ob = jax.lax.all_to_all(
                        ob, ep_axes, split_axis=0, concat_axis=0, tiled=True
                    )
                ob = ob.reshape(self.ep_size, local_experts, c, model_dim)
            expert_in = jnp.moveaxis(ob, 0, 1).reshape(local_experts, -1, model_dim)
            expert_out = experts(expert_in)
            ib = jnp.moveaxis(
                expert_out.reshape(local_experts, self.ep_size, c, model_dim), 0, 1
            )
            if ep_axes:
                with mp_scope("ep", "combine"):
                    ib = jax.lax.all_to_all(
                        ib, ep_axes, split_axis=0, concat_axis=0, tiled=True
                    )
            return ib.reshape(self.num_experts, c, model_dim)

        chunks = self._resolve_chunks(capacity) if (ep_axes and capacity) else 1
        if chunks > 1:
            # unrolled over capacity chunks: chunk j+1's all-to-all becomes
            # issuable while chunk j's expert GEMMs are still executing (the
            # expert FFN is position-wise, so the chunked result is exact; the
            # single `experts` instance keeps the parameters shared)
            cblk = capacity // chunks
            inbound = jnp.concatenate(
                [
                    exchange(outbound[:, :, i * cblk:(i + 1) * cblk])
                    for i in range(chunks)
                ],
                axis=1,
            )
        else:
            inbound = exchange(outbound)

        out = jnp.einsum(
            "sec,ecm->sm", routing.combine_weights.astype(tokens.dtype), inbound
        )
        self.sow("intermediates", "l_aux", routing.balance_loss)
        self.sow("intermediates", "exp_counts", routing.tokens_per_expert)
        return out.reshape(orig_shape), routing.balance_loss


class MoE(nn.Module):
    """A mixture-of-experts FFN block: ``out, l_aux = MoE(...)(x)``.

    Mirrors the reference constructor (``layer.py:22``): ``num_experts`` total
    experts sharded over the expert-parallel axes, top-``k`` gating with
    capacity factors.  Add ``l_aux`` (scaled by your chosen coefficient) to
    the training loss for load balancing.
    """

    hidden_size: int
    num_experts: int = 1
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    ep_size: int = 1
    ep_axis: Union[str, Tuple[str, ...], None] = ("inter", "intra")
    a2a_chunks: int = 1

    @nn.compact
    def __call__(self, x, train: bool = True, used_token=None, rng=None):
        """``used_token``: optional 0/1 mask over tokens (any shape reshaping
        to ``x``'s token count) — masked-out tokens are not routed (reference
        ``MoE.forward``'s ``used_token``, ``layer.py:90-96``)."""
        return ExpertParallelFFN(
            num_experts=self.num_experts,
            hidden_dim=self.hidden_size,
            k=self.k,
            capacity_factor=self.capacity_factor,
            eval_capacity_factor=self.eval_capacity_factor,
            min_capacity=self.min_capacity,
            noisy_gate_policy=self.noisy_gate_policy,
            ep_size=self.ep_size,
            ep_axis=self.ep_axis,
            a2a_chunks=self.a2a_chunks,
            name="moe_layer",
        )(x, train=train, used_token=used_token, rng=rng)
