"""User-facing MoE module (reference ``model_parallel/moe/layer.py:22``)."""

from typing import Optional, Tuple, Union

import flax.linen as nn

from bagua_tpu.parallel.moe.sharded_moe import MOELayer


class MoE(nn.Module):
    """A mixture-of-experts FFN block: ``out, l_aux = MoE(...)(x)``.

    Mirrors the reference constructor (``layer.py:22``): ``num_experts`` total
    experts sharded over the expert-parallel axes, top-``k`` gating with
    capacity factors.  Add ``l_aux`` (scaled by your chosen coefficient) to
    the training loss for load balancing.
    """

    hidden_size: int
    num_experts: int = 1
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    ep_size: int = 1
    ep_axis: Union[str, Tuple[str, ...], None] = ("inter", "intra")

    @nn.compact
    def __call__(self, x, train: bool = True, rng=None):
        return MOELayer(
            num_experts=self.num_experts,
            hidden_dim=self.hidden_size,
            k=self.k,
            capacity_factor=self.capacity_factor,
            eval_capacity_factor=self.eval_capacity_factor,
            min_capacity=self.min_capacity,
            noisy_gate_policy=self.noisy_gate_policy,
            ep_size=self.ep_size,
            ep_axis=self.ep_axis,
            name="moe_layer",
        )(x, train=train, rng=rng)
