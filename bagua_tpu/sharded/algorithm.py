"""The ``zero`` algorithm: reduce-scatter / sharded update / deferred gather.

The wire half of the ZeRO-fused exchange (arXiv:2004.13336).  Three legs per
bucket, two of them here:

1. **reduce-scatter** (``phase="rs"``) replaces the all-reduce: each rank
   receives only the reduced values for its contiguous flat shard — half the
   ring bytes of an all-reduce for the gradient exchange.  Anchored inside
   backward by the engine's per-bucket ``custom_vjp`` identities exactly like
   every other gradient-mode algorithm (``overlap=True``), or run monolithic
   after backward (``overlap=False``) — same wire program either way.
2. The optimizer update runs on the shard only — that lives in
   :mod:`bagua_tpu.sharded.updater`, invoked by the engine's sharded-update
   phase; it hands back per-bucket *update shards* stashed in this
   algorithm's state.
3. **all-gather** (``phase="ag"``) of the *updated parameter shards* is
   deferred to :meth:`on_step_start` of the *next* step: parameters are
   completed right before the forward consumes them, so XLA hides the gather
   behind the step's first compute.  The pending shards carry post-update
   parameters (the updater applies ``p + u`` in the same fusion cluster as
   the optimizer math, so rounding — FMA contraction included — matches a
   standalone optax jit bitwise); the gather therefore *replaces* the stale
   replicated params.  Step 0 still runs the gather — the compiled
   wire program is identical every step — but a ``step == 0`` gate keeps the
   initial params instead of the zero-initialized pending.

The exchanged gradient tree keeps full leaf shapes — rank me's shard slice
holds the reduced values, everything else is zero-filled.  The engine's
sharded updater re-flattens and slices the shard back out, so the monolithic
and overlap paths share one contract and ``debucketize`` never changes.

ByteGrad composition (``compression="bytegrad"``): the compressed pipeline's
scatter stage already ends with each rank holding its reduced chunk
(compress → all-to-all → fused decompress-reduce-requantize); the sharded
path simply STOPS there and decompresses locally, dropping the u8 gather of
the gradient leg entirely.  Bitwise-identical to rank me's slice of the
monolithic ByteGrad output because the reference decompress is row-wise.
"""

from typing import Any, Dict

import jax
import jax.numpy as jnp

from bagua_tpu.algorithms.base import Algorithm, AlgorithmImpl, StepContext
from bagua_tpu.bucket import flatten_bucket_leaves, split_bucket_flat
from bagua_tpu.communication import (
    ReduceOp,
    allgather_inplace,
    alltoall_inplace,
    axis_size,
    rank_id,
    reduce_scatter_inplace,
)
from bagua_tpu.kernels.minmax_uint8 import get_compressors, get_fused_reducer
from bagua_tpu.sharded.layout import ShardLayout, reshard_bucket_rows
from bagua_tpu.utils import from_bagua_datatype

_FLOAT_DTYPES = ("f32", "f16", "bf16")


class ZeroAlgorithmImpl(AlgorithmImpl):
    supports_overlap = True
    overlap_mode = "gradient"
    algo_name = "zero"
    #: tells the engine to run the sharded-update phase (ShardedOptimizerUpdater)
    #: instead of the whole-tree optimizer update
    sharded_update = True

    def __init__(
        self, process_group, hierarchical: bool = False, average: bool = True,
        compression: str = None, use_pallas=None,
    ):
        super().__init__(process_group, hierarchical=hierarchical)
        if compression not in (None, "bytegrad"):
            raise ValueError(
                f"zero compression must be None or 'bytegrad', got {compression!r}"
            )
        self.average = average
        self.compression = compression
        if compression == "bytegrad":
            # Resolved once at construction (evidence-file lookup must not run
            # inside a trace) — same policy as ByteGradAlgorithmImpl.
            self._compressors = get_compressors(use_pallas)
            self._fused_reducer = get_fused_reducer(use_pallas)

    # -- state ---------------------------------------------------------------

    def init_state(self, params) -> Dict[str, Any]:
        """Per-bucket pending updated-parameter shards (bucket dtype,
        ``numel/n`` each), zero until the first sharded update lands — the
        step-0 gate in :meth:`on_step_start` keeps them from ever being
        applied."""
        n = self.process_group.size
        return {
            "pending": tuple(
                jnp.zeros((spec.numel // n,), from_bagua_datatype(spec.dtype))
                for spec in self._bound_plan.specs
            )
        }

    def stash_updates(self, state, pending):
        """Called by the engine's sharded-update phase with this step's
        per-bucket *updated parameter* shards; they ride the algorithm state
        to the next step's :meth:`on_step_start`."""
        return {**state, "pending": tuple(pending)}

    def reshard_host_state(self, state, old: ShardLayout, new: ShardLayout):
        """Host-side migration of the rank-stacked ``pending`` shards between
        shard layouts (mid-training rebucket, elastic world-size remap)."""
        return {"pending": tuple(reshard_bucket_rows(list(state["pending"]), old, new))}

    # -- leg 3: deferred all-gather -------------------------------------------

    def on_step_start(self, params, state, ctx: StepContext):
        """Complete the parameters: gather every bucket's pending
        updated-parameter shard and swap it in right before the forward
        consumes the params.  Replace semantics (not add) — applying the same
        pending twice is idempotent, so a post-training flush
        (``finalize_pending_updates``) or a resume re-application is always
        safe, and pending is deliberately NOT cleared here."""
        plan = ctx.plan
        groups = plan.group_leaves(params)
        new_groups = []
        for bi, spec in enumerate(plan.specs):
            with self.annotate(bi, "ag"):
                full = allgather_inplace(state["pending"][bi], tiled=True)
            leaves = [groups[bi][s.name] for s in spec.slots]
            gathered = split_bucket_flat(full, spec)
            # Step 0 has no pending update yet: the gather above still runs
            # (uniform wire program) but the gate keeps the initial params.
            new_groups.append({
                s.name: jnp.where(ctx.step == 0, l, g.astype(l.dtype))
                for s, l, g in zip(spec.slots, leaves, gathered)
            })
        params = plan.ungroup_leaves(new_groups, params)
        return params, state

    # -- leg 1: reduce-scatter ------------------------------------------------

    def _reduce_scatter_flat(self, flat, spec):
        """Rank me's reduced shard of one bucket's padded flat buffer."""
        if self.compression == "bytegrad" and spec.dtype in _FLOAT_DTYPES:
            n = axis_size()
            chunk = flat.shape[0] // n
            compress, decompress = self._compressors
            q, mm = compress(flat.reshape(n, chunk))
            q_recv = alltoall_inplace(q)  # (n, chunk): everyone's chunk for me
            mm_recv = alltoall_inplace(mm)  # (n, 2)
            q2, mm2 = self._fused_reducer(q_recv, mm_recv, average=self.average)
            # The monolithic pipeline would all-gather (q2, mm2) here; the
            # sharded path stops and decompresses its own chunk locally —
            # bitwise row me of the reference output, zero gather bytes.
            return decompress(q2, mm2).reshape(-1).astype(flat.dtype)
        op = ReduceOp.AVG if self.average else ReduceOp.SUM
        return reduce_scatter_inplace(flat, op=op)

    def _exchange_bucket(self, bucket_idx, grads, ctx: StepContext):
        """One bucket's exchange: reduce-scatter, then embed the shard back
        into a zero-filled full-shape image so the leaves keep their
        shapes/dtypes (the sharded updater slices the shard back out)."""
        spec = ctx.plan.specs[bucket_idx]
        n = self.process_group.size
        with self.annotate(bucket_idx, "rs"):
            flat = flatten_bucket_leaves(grads, spec)
            shard = self._reduce_scatter_flat(flat, spec)
            buf = jax.lax.dynamic_update_slice(
                jnp.zeros_like(flat), shard.astype(flat.dtype),
                (rank_id() * (spec.numel // n),),
            )
        return split_bucket_flat(buf, spec)

    def transform_gradients(self, grads, params, state, ctx: StepContext):
        groups = ctx.plan.group_leaves(grads)
        out = []
        for bi, spec in enumerate(ctx.plan.specs):
            leaves = [groups[bi][s.name] for s in spec.slots]
            exchanged = self._exchange_bucket(bi, leaves, ctx)
            out.append({s.name: l for s, l in zip(spec.slots, exchanged)})
        return ctx.plan.ungroup_leaves(out, grads), params, state

    def overlap_exchange(
        self, bucket_idx: int, grads, ctx: StepContext, params_leaves=None
    ):
        # Same wire program as transform_gradients, anchored at the ops
        # producing this bucket's cotangents by the engine's custom_vjp rule.
        return self._exchange_bucket(bucket_idx, list(grads), ctx)


class ZeroAlgorithm(Algorithm):
    """ZeRO-sharded data parallelism: reduce-scatter gradients, update only
    this rank's shard (optimizer state at ``1/n`` per chip), all-gather the
    updates into the next step's forward."""

    def __init__(
        self, hierarchical: bool = False, average: bool = True,
        compression: str = None, use_pallas=None,
    ):
        self.hierarchical = hierarchical
        self.average = average
        self.compression = compression
        self.use_pallas = use_pallas

    def reify(self, process_group) -> ZeroAlgorithmImpl:
        return ZeroAlgorithmImpl(
            process_group, hierarchical=self.hierarchical, average=self.average,
            compression=self.compression, use_pallas=self.use_pallas,
        )
