"""The ``zero`` algorithm: reduce-scatter / sharded update / deferred gather.

The wire half of the ZeRO-fused exchange (arXiv:2004.13336).  Three legs per
bucket, two of them here:

1. **reduce-scatter** (``phase="rs"``) replaces the all-reduce: each rank
   receives only the reduced values for its contiguous flat shard — half the
   ring bytes of an all-reduce for the gradient exchange.  Anchored inside
   backward by the engine's per-bucket ``custom_vjp`` identities exactly like
   every other gradient-mode algorithm (``overlap=True``), or run monolithic
   after backward (``overlap=False``) — same wire program either way.
2. The optimizer update runs on the shard only — that lives in
   :mod:`bagua_tpu.sharded.updater`, invoked by the engine's sharded-update
   phase; it hands back per-bucket *update shards* stashed in this
   algorithm's state.
3. **all-gather** (``phase="ag"``) of the *updated parameter shards* is
   deferred to :meth:`on_step_start` of the *next* step: parameters are
   completed right before the forward consumes them, so XLA hides the gather
   behind the step's first compute.  The pending shards carry post-update
   parameters (the updater applies ``p + u`` in the same fusion cluster as
   the optimizer math, so rounding — FMA contraction included — matches a
   standalone optax jit bitwise); the gather therefore *replaces* the stale
   replicated params.  Step 0 still runs the gather — the compiled
   wire program is identical every step — but a ``step == 0`` gate keeps the
   initial params instead of the zero-initialized pending.

The exchanged gradient tree keeps full leaf shapes — rank me's shard slice
holds the reduced values, everything else is zero-filled.  The engine's
sharded updater re-flattens and slices the shard back out, so the monolithic
and overlap paths share one contract and ``debucketize`` never changes.

ByteGrad composition (``compression="bytegrad"``): the compressed pipeline's
scatter stage already ends with each rank holding its reduced chunk
(compress → all-to-all → fused decompress-reduce-requantize); the sharded
path simply STOPS there and decompresses locally, dropping the u8 gather of
the gradient leg entirely.  Bitwise-identical to rank me's slice of the
monolithic ByteGrad output because the reference decompress is row-wise.

``wire_precision`` composition: the gradient leg's reduce-scatter runs as
the blockwise-quantized ring (:mod:`bagua_tpu.kernels.quantized_ring`) —
int8 or packed-int4 levels per hop with a fused dequant-reduce-requant at
every rank.  ``"int4"`` threads a persistent per-bucket error-feedback
residual through the algorithm state (monolithic path only — the residual
makes the algorithm hold bucketized state, fencing off overlap and
re-bucketing); ``"int8"`` is stateless and keeps overlap.  The deferred
parameter all-gather (leg 3) always stays full precision — it ships
*parameters*, not gradients, and quantizing it would bias the weights.
Mutually exclusive with ``compression="bytegrad"``.
"""

from typing import Any, Dict

import jax
import jax.numpy as jnp

from bagua_tpu.algorithms._precision import WirePrecisionMixin
from bagua_tpu.algorithms.base import Algorithm, AlgorithmImpl, StepContext
from bagua_tpu.bucket import flatten_bucket_leaves, split_bucket_flat
from bagua_tpu.communication import (
    ReduceOp,
    allgather_inplace,
    alltoall_inplace,
    axis_size,
    rank_id,
    reduce_scatter_inplace,
)
from bagua_tpu.kernels.minmax_uint8 import get_compressors, get_fused_reducer
from bagua_tpu.kernels.quantized_ring import quantized_ring_reduce_scatter
from bagua_tpu.sharded.layout import ShardLayout, reshard_bucket_rows
from bagua_tpu.utils import from_bagua_datatype

_FLOAT_DTYPES = ("f32", "f16", "bf16")


class ZeroAlgorithmImpl(WirePrecisionMixin, AlgorithmImpl):
    supports_overlap = True
    overlap_mode = "gradient"
    algo_name = "zero"
    #: tells the engine to run the sharded-update phase (ShardedOptimizerUpdater)
    #: instead of the whole-tree optimizer update
    sharded_update = True

    def __init__(
        self, process_group, hierarchical: bool = False, average: bool = True,
        compression: str = None, use_pallas=None, wire_precision: str = "f32",
    ):
        super().__init__(process_group, hierarchical=hierarchical)
        if compression not in (None, "bytegrad"):
            raise ValueError(
                f"zero compression must be None or 'bytegrad', got {compression!r}"
            )
        if compression is not None and wire_precision != "f32":
            raise ValueError(
                "compression and a quantized wire_precision are mutually "
                "exclusive — pick one compression rung"
            )
        self.average = average
        self.compression = compression
        if compression == "bytegrad":
            # Resolved once at construction (evidence-file lookup must not run
            # inside a trace) — same policy as ByteGradAlgorithmImpl.
            self._compressors = get_compressors(use_pallas)
            self._fused_reducer = get_fused_reducer(use_pallas)
        self._init_wire_precision(wire_precision, use_pallas)

    # -- state ---------------------------------------------------------------

    def init_state(self, params) -> Dict[str, Any]:
        """Per-bucket pending updated-parameter shards (bucket dtype,
        ``numel/n`` each), zero until the first sharded update lands — the
        step-0 gate in :meth:`on_step_start` keeps them from ever being
        applied."""
        n = self.process_group.exchange_size
        state = {
            "pending": tuple(
                jnp.zeros((spec.numel // n,), from_bagua_datatype(spec.dtype))
                for spec in self._bound_plan.specs
            )
        }
        if self._ef_enabled():
            # int4 error-feedback residuals, one f32 flat per bucket (see
            # WirePrecisionMixin) — full bucket length: the reduce-scatter
            # charges this rank wherever its hops requantized.
            state["qr_residual"] = tuple(
                jnp.zeros((spec.numel,), jnp.float32)
                for spec in self._bound_plan.specs
            )
        return state

    def stash_updates(self, state, pending):
        """Called by the engine's sharded-update phase with this step's
        per-bucket *updated parameter* shards; they ride the algorithm state
        to the next step's :meth:`on_step_start`."""
        return {**state, "pending": tuple(pending)}

    def reshard_host_state(self, state, old: ShardLayout, new: ShardLayout):
        """Host-side migration of the rank-stacked ``pending`` shards between
        shard layouts (mid-training rebucket, elastic world-size remap).
        Error-feedback residuals do not migrate — dropping them loses one
        step of compensation, not correctness — so they restart at zero in
        the new layout."""
        out = {"pending": tuple(reshard_bucket_rows(list(state["pending"]), old, new))}
        if "qr_residual" in state:
            import numpy as np

            out["qr_residual"] = tuple(
                np.zeros((new.n_shards,) + np.asarray(r).shape[1:], np.float32)
                for r in state["qr_residual"]
            )
        return out

    # -- leg 3: deferred all-gather -------------------------------------------

    def on_step_start(self, params, state, ctx: StepContext):
        """Complete the parameters: gather every bucket's pending
        updated-parameter shard and swap it in right before the forward
        consumes the params.  Replace semantics (not add) — applying the same
        pending twice is idempotent, so a post-training flush
        (``finalize_pending_updates``) or a resume re-application is always
        safe, and pending is deliberately NOT cleared here."""
        plan = ctx.plan
        groups = plan.group_leaves(params)
        new_groups = []
        for bi, spec in enumerate(plan.specs):
            with self.annotate(bi, "ag"):
                full = allgather_inplace(state["pending"][bi], tiled=True)
            leaves = [groups[bi][s.name] for s in spec.slots]
            gathered = split_bucket_flat(full, spec)
            # Step 0 has no pending update yet: the gather above still runs
            # (uniform wire program) but the gate keeps the initial params.
            new_groups.append({
                s.name: jnp.where(ctx.step == 0, l, g.astype(l.dtype))
                for s, l, g in zip(spec.slots, leaves, gathered)
            })
        params = plan.ungroup_leaves(new_groups, params)
        return params, state

    # -- leg 1: reduce-scatter ------------------------------------------------

    def _reduce_scatter_flat(self, flat, spec, precision="f32", residual=None):
        """Rank me's reduced shard of one bucket's padded flat buffer.
        Returns ``(shard, new_residual)`` — ``new_residual`` is None except
        on the quantized-ring path with error feedback enabled."""
        if precision in ("int8", "int4") and spec.dtype in _FLOAT_DTYPES:
            bits = 8 if precision == "int8" else 4
            x = flat.astype(jnp.float32)
            if residual is not None:
                x = x + residual
            shard, err = quantized_ring_reduce_scatter(
                x, bits=bits, average=self.average, hop=self._ring_hops[bits]
            )
            return shard.astype(flat.dtype), (err if residual is not None else None)
        if self.compression == "bytegrad" and spec.dtype in _FLOAT_DTYPES:
            n = axis_size()
            chunk = flat.shape[0] // n
            compress, decompress = self._compressors
            q, mm = compress(flat.reshape(n, chunk))
            q_recv = alltoall_inplace(q)  # (n, chunk): everyone's chunk for me
            mm_recv = alltoall_inplace(mm)  # (n, 2)
            q2, mm2 = self._fused_reducer(q_recv, mm_recv, average=self.average)
            # The monolithic pipeline would all-gather (q2, mm2) here; the
            # sharded path stops and decompresses its own chunk locally —
            # bitwise row me of the reference output, zero gather bytes.
            return decompress(q2, mm2).reshape(-1).astype(flat.dtype), None
        op = ReduceOp.AVG if self.average else ReduceOp.SUM
        return reduce_scatter_inplace(flat, op=op), None

    def _exchange_bucket(self, bucket_idx, grads, ctx: StepContext, residual=None):
        """One bucket's exchange: reduce-scatter, then embed the shard back
        into a zero-filled full-shape image so the leaves keep their
        shapes/dtypes (the sharded updater slices the shard back out)."""
        spec = ctx.plan.specs[bucket_idx]
        n = self.process_group.exchange_size
        prec = self._precision_for_bucket(bucket_idx, spec)
        with self.annotate(bucket_idx, "rs"):
            flat = flatten_bucket_leaves(grads, spec)
            shard, new_resid = self._reduce_scatter_flat(
                flat, spec, precision=prec, residual=residual
            )
            buf = jax.lax.dynamic_update_slice(
                jnp.zeros_like(flat), shard.astype(flat.dtype),
                (rank_id() * (spec.numel // n),),
            )
        return split_bucket_flat(buf, spec), new_resid

    def transform_gradients(self, grads, params, state, ctx: StepContext):
        groups = ctx.plan.group_leaves(grads)
        resid = list(state["qr_residual"]) if "qr_residual" in state else None
        out = []
        for bi, spec in enumerate(ctx.plan.specs):
            leaves = [groups[bi][s.name] for s in spec.slots]
            r = (
                resid[bi]
                if resid is not None
                and self._precision_for_bucket(bi, spec) == "int4"
                else None
            )
            exchanged, new_r = self._exchange_bucket(bi, leaves, ctx, residual=r)
            if new_r is not None:
                resid[bi] = new_r
            out.append({s.name: l for s, l in zip(spec.slots, exchanged)})
        grads = ctx.plan.ungroup_leaves(out, grads)
        if resid is not None:
            state = {**state, "qr_residual": tuple(resid)}
        return grads, params, state

    def overlap_exchange(
        self, bucket_idx: int, grads, ctx: StepContext, params_leaves=None
    ):
        # Same wire program as transform_gradients, anchored at the ops
        # producing this bucket's cotangents by the engine's custom_vjp rule.
        # Error feedback never reaches here: int4/auto hold bucketized state,
        # which reports overlap unsupported.
        exchanged, _ = self._exchange_bucket(bucket_idx, list(grads), ctx)
        return exchanged


class ZeroAlgorithm(Algorithm):
    """ZeRO-sharded data parallelism: reduce-scatter gradients, update only
    this rank's shard (optimizer state at ``1/n`` per chip), all-gather the
    updates into the next step's forward."""

    def __init__(
        self, hierarchical: bool = False, average: bool = True,
        compression: str = None, use_pallas=None, wire_precision: str = "f32",
    ):
        self.hierarchical = hierarchical
        self.average = average
        self.compression = compression
        self.use_pallas = use_pallas
        self.wire_precision = wire_precision

    def reify(self, process_group) -> ZeroAlgorithmImpl:
        return ZeroAlgorithmImpl(
            process_group, hierarchical=self.hierarchical, average=self.average,
            compression=self.compression, use_pallas=self.use_pallas,
            wire_precision=self.wire_precision,
        )
