"""Shard layout: how each bucket's flat payload splits across ranks.

The ZeRO exchange (:mod:`bagua_tpu.sharded.algorithm`) reduce-scatters every
bucket, so rank ``r`` owns the contiguous flat slice
``[r * numel/n, (r+1) * numel/n)`` of each bucket — the same row-major chunk
order ``psum_scatter(tiled=True)`` scatters and ``all_gather(tiled=True)``
concatenates.  Bucket ``numel`` is always divisible by ``n``: the engine
builds every plan with ``align_elems = group.size``
(:meth:`~bagua_tpu.algorithms.base.AlgorithmImpl.tensors_to_buckets` and
``BucketPlan.from_declarations`` call sites both pad the tail slot).

This module is the *geometry* half of the subsystem: a frozen description of
the shard boundaries derived from a :class:`~bagua_tpu.bucket.BucketPlan`
(or from a snapshot manifest's plan payload + its recorded world size), plus
host-side numpy resharding that is **element-value-preserving**: stacked
shard rows are reassembled into full bucket flats, mapped to per-tensor
values by slot name, and re-sliced under a different plan and/or shard
count.  Both mid-training ``rebucket`` and elastic resume into a resized
gang go through the same two functions, so there is exactly one place where
shard arithmetic can be wrong.
"""

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from bagua_tpu.utils import align_size, from_bagua_datatype

__all__ = [
    "ShardSlot",
    "BucketShard",
    "DtypeGroup",
    "ShardLayout",
    "reshard_bucket_rows",
    "reshard_group_flat",
    "assemble_full_flats",
]


@dataclasses.dataclass(frozen=True)
class ShardSlot:
    """One tensor's flat placement inside its bucket."""

    name: str
    numel: int
    offset: int


@dataclasses.dataclass(frozen=True)
class BucketShard:
    """One bucket's shard geometry (``numel`` includes alignment padding)."""

    slots: Tuple[ShardSlot, ...]
    numel: int
    shard_numel: int
    dtype: str  # bagua dtype string ("f32", ...)

    def np_dtype(self):
        return np.dtype(from_bagua_datatype(self.dtype))


@dataclasses.dataclass(frozen=True)
class DtypeGroup:
    """The per-dtype fusion unit of the sharded optimizer update: every
    bucket of one dtype contributes its rank shard to ONE concatenated inner
    optimizer call (the engine-native absorption of
    ``contrib.fuse_optimizer``'s dtype-group fusion)."""

    dtype: str
    buckets: Tuple[int, ...]  # bucket indices, plan order
    shard_total: int  # sum of member shard_numels

    def np_dtype(self):
        return np.dtype(from_bagua_datatype(self.dtype))


@dataclasses.dataclass(frozen=True)
class ShardLayout:
    """Shard geometry of one bucket plan at one world size."""

    n_shards: int
    buckets: Tuple[BucketShard, ...]
    groups: Tuple[DtypeGroup, ...]

    @classmethod
    def _build(cls, n_shards: int, raw: Sequence[Tuple[List[ShardSlot], int, str]]):
        buckets = []
        by_dtype: Dict[str, List[int]] = {}
        order: List[str] = []
        for bi, (slots, numel, dtype) in enumerate(raw):
            if numel % n_shards != 0:
                raise ValueError(
                    f"bucket {bi} numel {numel} not divisible by {n_shards} "
                    "shards — the plan was not aligned to the group size"
                )
            buckets.append(
                BucketShard(tuple(slots), numel, numel // n_shards, dtype)
            )
            if dtype not in by_dtype:
                order.append(dtype)
            by_dtype.setdefault(dtype, []).append(bi)
        groups = tuple(
            DtypeGroup(
                dtype=dt,
                buckets=tuple(by_dtype[dt]),
                shard_total=sum(buckets[bi].shard_numel for bi in by_dtype[dt]),
            )
            for dt in order
        )
        return cls(n_shards=n_shards, buckets=tuple(buckets), groups=groups)

    @classmethod
    def from_plan(cls, plan, n_shards: int) -> "ShardLayout":
        raw = [
            (
                [ShardSlot(s.name, s.numel, s.offset) for s in spec.slots],
                spec.numel,
                spec.dtype,
            )
            for spec in plan.specs
        ]
        return cls._build(n_shards, raw)

    @classmethod
    def from_payload(cls, plan_payload: Dict, n_shards: int) -> "ShardLayout":
        """Rebuild the layout a *snapshot* was written under: the manifest's
        plan payload (``DistributedDataParallel.export_plan_payload``) plus
        the manifest's recorded world size.  Padding is recomputed exactly as
        ``BucketPlan.from_declarations(align_elems=n_shards)`` did."""
        raw = []
        for bucket in plan_payload.get("buckets", []):
            slots, offset = [], 0
            for td in bucket:
                slots.append(ShardSlot(td["name"], int(td["num_elements"]), offset))
                offset += int(td["num_elements"])
            raw.append((slots, align_size(offset, n_shards), bucket[0]["dtype"]))
        return cls._build(n_shards, raw)

    def payload(self) -> Dict:
        """JSON-serializable shard record for snapshot manifests (auditable
        geometry; reconstruction uses the plan payload + world size)."""
        return {
            "n_shards": self.n_shards,
            "buckets": [
                {"numel": b.numel, "shard_numel": b.shard_numel, "dtype": b.dtype}
                for b in self.buckets
            ],
        }

    def group_for(self, dtype: str) -> Optional[DtypeGroup]:
        for g in self.groups:
            if g.dtype == dtype:
                return g
        return None


# -- host-side (numpy) resharding ---------------------------------------------


def _slot_values(rows_list: Sequence[np.ndarray], layout: ShardLayout):
    """Stacked shard rows -> ``{tensor_name: flat values}`` (padding dropped
    implicitly: slots never cover the alignment tail)."""
    values: Dict[str, np.ndarray] = {}
    for rows, b in zip(rows_list, layout.buckets):
        full = np.asarray(rows).reshape(-1)  # row r == flat[r*shard:(r+1)*shard]
        for s in b.slots:
            values[s.name] = full[s.offset : s.offset + s.numel]
    return values


def _build_rows(values: Dict[str, np.ndarray], layout: ShardLayout, indices=None):
    out = []
    for bi in range(len(layout.buckets)) if indices is None else indices:
        b = layout.buckets[bi]
        full = np.zeros((b.numel,), dtype=b.np_dtype())
        for s in b.slots:
            v = values.get(s.name)
            if v is not None:
                m = min(s.numel, v.size)
                full[s.offset : s.offset + m] = v[:m].astype(full.dtype, copy=False)
        out.append(full.reshape(layout.n_shards, b.shard_numel))
    return out


def assemble_full_flats(rows_list: Sequence[np.ndarray], layout: ShardLayout):
    """Stacked shard rows -> full per-bucket flats (tests/debugging)."""
    return [np.asarray(rows).reshape(-1) for rows in rows_list]


def build_shard_rows(
    values: Dict[str, np.ndarray], layout: ShardLayout, indices=None
) -> List[np.ndarray]:
    """Per-tensor flat values -> rank-stacked per-bucket shard rows
    ``(layout.n_shards, shard_numel)`` — row ``r`` is exactly rank ``r``'s
    contiguous flat shard of the bucket, alignment padding (and any tensor
    missing from ``values``) zero-filled.  The scatter half of the
    element-value-preserving contract: feeding a whole tree's values here
    produces the state a sharded gang would hold, so an algorithm switch
    can seed ``pending`` parameter shards / optimizer moments without ever
    running an exchange.  ``indices`` restricts to a subset of buckets
    (plan order), e.g. one dtype group's members."""
    return _build_rows(values, layout, indices=indices)


def flat_tree_values(tree) -> Dict[str, np.ndarray]:
    """``{keystr(path): flattened numpy leaf}`` for a (single-rank) pytree —
    the name-keyed form both resharding directions speak (slot names are
    ``jax.tree_util.keystr`` paths by construction)."""
    import jax

    return {
        jax.tree_util.keystr(p): np.asarray(l).reshape(-1)
        for p, l in jax.tree_util.tree_flatten_with_path(tree)[0]
    }


def reshard_bucket_rows(
    rows_list: Sequence[np.ndarray], old: ShardLayout, new: ShardLayout
) -> List[np.ndarray]:
    """Re-shard per-bucket stacked rows ``(old.n_shards, old_shard_numel)``
    into the new layout's ``(new.n_shards, new_shard_numel)`` arrays.
    Element-value-preserving by slot name; tensors absent from the old layout
    (and all alignment padding) land as zeros."""
    return _build_rows(_slot_values(rows_list, old), new)


def reshard_group_flat(
    flat: np.ndarray, old: ShardLayout, new: ShardLayout, dtype: str
) -> np.ndarray:
    """Re-shard one dtype group's stacked optimizer-state vector.

    ``flat`` is ``(old.n_shards, old_group.shard_total)`` — the rank-stacked
    concatenation of each member bucket's rank shard, in group bucket order
    (the exact layout :class:`~bagua_tpu.sharded.updater.
    ShardedOptimizerUpdater` feeds the inner optimizer).  Returns
    ``(new.n_shards, new_group.shard_total)``."""
    og, ng = old.group_for(dtype), new.group_for(dtype)
    if og is None or ng is None:
        raise ValueError(f"dtype group {dtype!r} missing from a shard layout")
    flat = np.asarray(flat)
    rows_list, col = [], 0
    for bi in og.buckets:
        sh = old.buckets[bi].shard_numel
        rows_list.append(flat[:, col : col + sh])
        col += sh
    values = _slot_values(rows_list, dataclasses.replace(old, buckets=tuple(
        old.buckets[bi] for bi in og.buckets
    ), groups=()))
    new_rows = _build_rows(values, new, indices=ng.buckets)
    if not new_rows:
        return np.zeros((new.n_shards, 0), dtype=flat.dtype)
    return np.concatenate(new_rows, axis=1)
