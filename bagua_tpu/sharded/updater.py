"""Sharded optimizer update: each rank updates only its shard of every bucket.

The compute half of the ZeRO exchange (arXiv:2004.13336; reference
``contrib/zero.py`` prototyped the wrapper form).  After the per-bucket
reduce-scatter each rank holds the reduced gradients for its contiguous flat
slice of every bucket; this module runs the inner optax transformation on
exactly those slices and hands back per-bucket *update shards* for the
deferred all-gather.  Optimizer state therefore exists only for ``1/n`` of
every parameter on each chip — Adam's ``2P`` of moments becomes ``2P/n``.

Fusion is engine-native here: all of a dtype group's bucket shards are
concatenated into ONE flat vector per rank, so the inner optimizer runs once
per dtype — the dtype-group fusion ``contrib/fuse_optimizer.py`` provided as
a wrapper, absorbed into the engine (``fuse_optimizer`` itself now lives
here, with a deprecated shim left behind in contrib).

Bitwise contract: for elementwise optimizers (SGD/momentum/Adam/...) the
update computed on a shard slice equals the corresponding slice of the
update computed on the full tree, and alignment-padding slots carry zero
gradients so their moments stay zero — concatenating the gathered shards
reproduces the unsharded trajectory bit-for-bit (``tests/test_zero.py``).

Leaves excluded from the plan by a ``dp_filter`` never ride a collective;
they keep a small replicated "local" optimizer state and are updated in
place each step, exactly as on the unsharded path.
"""

from typing import Any, Dict, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bagua_tpu.bucket import BucketPlan, flatten_bucket_leaves
from bagua_tpu.communication import rank_id
from bagua_tpu.sharded.layout import ShardLayout, build_shard_rows
from bagua_tpu.utils import from_bagua_datatype

__all__ = ["ShardedOptState", "ShardedOptimizerUpdater", "FusedState", "fuse_optimizer"]


class ShardedOptState(NamedTuple):
    """Engine-side optimizer state under the zero algorithm: one inner state
    per dtype group (shard-sized — the memory win), plus a replicated inner
    state for dp_filter-excluded leaves."""

    sharded: Tuple[Any, ...]
    local: Any


class ShardedOptimizerUpdater:
    """Runs the inner optimizer on each rank's bucket shards only.

    Built by the engine whenever the bound algorithm reports
    ``sharded_update=True``; rebuilt on every ``rebucket`` (the layout is a
    pure function of the plan + group size, and host-side resharding in
    :mod:`bagua_tpu.sharded.layout` migrates live state between layouts).
    """

    def __init__(self, inner: optax.GradientTransformation, plan: BucketPlan, group):
        self.inner = inner
        self.plan = plan
        self.group = group
        # Shards are per exchange-ring slot: on a named mesh with model axes
        # the reduce-scatter splits each bucket across the data axes only
        # (each tp peer group keeps a full ring), so the layout follows
        # exchange_size, not the full mesh size.
        self.layout = ShardLayout.from_plan(
            plan, getattr(group, "exchange_size", group.size)
        )
        self._covered = {s.name for spec in plan.specs for s in spec.slots}

    # -- helpers -------------------------------------------------------------

    def _named_leaves(self, tree) -> Dict[str, Any]:
        return {
            jax.tree_util.keystr(p): l
            for p, l in jax.tree_util.tree_flatten_with_path(tree)[0]
        }

    def _uncovered(self, tree) -> Dict[str, Any]:
        return {
            n: l for n, l in self._named_leaves(tree).items() if n not in self._covered
        }

    def _bucket_shards(self, tree, me) -> List[jnp.ndarray]:
        """Rank ``me``'s flat slice of every bucket, plan order."""
        groups = self.plan.group_leaves(tree)
        shards = []
        for bi, spec in enumerate(self.plan.specs):
            leaves = [groups[bi][s.name] for s in spec.slots]
            flat = flatten_bucket_leaves(leaves, spec)
            sh = self.layout.buckets[bi].shard_numel
            shards.append(jax.lax.dynamic_slice(flat, (me * sh,), (sh,)))
        return shards

    # -- API -----------------------------------------------------------------

    def init(self, params) -> ShardedOptState:
        """Shard-sized inner states (zeros are the correct shard values for
        every optax init: moments start at zero, counts are shape-free)."""
        sharded = tuple(
            self.inner.init(jnp.zeros((g.shard_total,), from_bagua_datatype(g.dtype)))
            for g in self.layout.groups
        )
        return ShardedOptState(sharded=sharded, local=self.inner.init(self._uncovered(params)))

    def update_shards(self, grads, params, opt_state: ShardedOptState):
        """One sharded optimizer phase (traced, inside shard_map).

        ``grads`` is the exchanged tree: every bucket's flat image holds the
        reduced values in rank-me's shard slice (the exchange zero-fills the
        rest).  Returns ``(pending, new_opt_state, new_params)`` where
        ``pending`` is one *updated parameter shard* per bucket — COVERED
        PARAMS ARE NOT TOUCHED in ``new_params``; the algorithm all-gathers
        the pending shards at the start of the next step and swaps them in
        right before the forward, hiding the gather behind that step's
        compute.  Pending carries post-update parameters (not raw updates)
        so the ``p + u`` application happens HERE, in the same fusion
        cluster as the optimizer math — rounding (FMA contraction included)
        matches a standalone optax jit bitwise, keeping the trajectory
        bitwise-identical to the plain-optax unsharded reference;
        materializing raw updates across the gather boundary and adding
        them later rounds differently.  Excluded leaves are updated in
        place.
        """
        me = rank_id()
        g_shards = self._bucket_shards(grads, me)
        p_shards = self._bucket_shards(params, me)

        pending: List[Any] = [None] * self.plan.num_buckets
        new_sharded = []
        for gi, grp in enumerate(self.layout.groups):
            g_cat = jnp.concatenate([g_shards[bi] for bi in grp.buckets])
            p_cat = jnp.concatenate([p_shards[bi] for bi in grp.buckets])
            # Materialize contiguous inputs so the optimizer math forms its
            # own fusion cluster, pinning it to the same codegen (FMA
            # contraction included) as a standalone optax jit — the bitwise
            # contract is against the plain-optax unsharded trajectory, and
            # letting XLA fuse the math with the slice/concat data movement
            # above would make rounding depend on the surrounding graph.
            g_cat, p_cat = jax.lax.optimization_barrier((g_cat, p_cat))
            upd_cat, st = self.inner.update(g_cat, opt_state.sharded[gi], p_cat)
            newp_cat = optax.apply_updates(p_cat, upd_cat)
            new_sharded.append(st)
            off = 0
            for bi in grp.buckets:
                sh = self.layout.buckets[bi].shard_numel
                pending[bi] = jax.lax.dynamic_slice(newp_cat, (off,), (sh,))
                off += sh

        # dp_filter-excluded leaves: local (replicated) update, applied now.
        local_g = self._uncovered(grads)
        new_local = opt_state.local
        new_params = params
        if local_g:
            local_p = self._uncovered(params)
            upd, new_local = self.inner.update(local_g, opt_state.local, local_p)
            applied = optax.apply_updates(local_p, upd)
            named = self._named_leaves(params)
            named.update(applied)
            paths, treedef = jax.tree_util.tree_flatten_with_path(params)
            new_params = treedef.unflatten(
                [named[jax.tree_util.keystr(p)] for p, _ in paths]
            )
        return (
            tuple(pending),
            ShardedOptState(sharded=tuple(new_sharded), local=new_local),
            new_params,
        )

    # -- full-state remap (host, numpy) --------------------------------------
    #
    # The bitwise contract above means the sharded state IS the unsharded
    # state, just re-laid-out: mu/nu shard rows are flat slices of the full
    # moments, counts are replicated.  gather/scatter below make that
    # isomorphism executable so ``switch_algorithm`` can move live optimizer
    # state between zero and any unsharded algorithm (or between two plans)
    # element-value-preservingly without running a collective.

    def _inner_state_index(self) -> Dict[str, str]:
        """``{keystr: "param"|"scalar"}`` over the inner state of a single
        flat parameter vector.  Probing two sizes separates leaves that
        mirror the parameter (moments — shape tracks the input) from
        shape-free leaves (step counts)."""

        def probe(n):
            return jax.eval_shape(
                self.inner.init, jax.ShapeDtypeStruct((n,), jnp.float32)
            )

        a = jax.tree_util.tree_flatten_with_path(probe(3))[0]
        b = jax.tree_util.tree_flatten_with_path(probe(5))[0]
        return {
            jax.tree_util.keystr(pa): "param" if la.shape != lb.shape else "scalar"
            for (pa, la), (_, lb) in zip(a, b)
        }

    def _group_slot_values(self, grp, leaf: np.ndarray) -> Dict[str, np.ndarray]:
        """One rank-stacked per-element state leaf ``(n, shard_total)`` ->
        ``{tensor_name: flat values}`` (row r is rank r's shard, so each
        member bucket's rows reassemble into its full flat)."""
        values: Dict[str, np.ndarray] = {}
        col = 0
        for bi in grp.buckets:
            b = self.layout.buckets[bi]
            full = np.ascontiguousarray(leaf[:, col : col + b.shard_numel]).reshape(-1)
            for s in b.slots:
                values[s.name] = full[s.offset : s.offset + s.numel]
            col += b.shard_numel
        return values

    def gather_full_state(self, opt_state: ShardedOptState, params) -> Any:
        """Rank-stacked sharded optimizer state -> the single unsharded inner
        state over the full parameter tree (host numpy), exactly what
        ``inner.init(params)`` + the same update history would hold.

        ``opt_state`` leaves must be host/numpy-coercible and rank-stacked
        (leading axis = ``layout.n_shards``); ``params`` is a single-rank
        template (shapes/dtypes only).  Matching is structural: an unsharded
        state leaf at keystr ``kf + <tensor name>`` is the per-element leaf
        ``kf`` of that tensor's dtype group (slot-sliced), an exact-``kf``
        leaf is shape-free and taken from row 0.  Optimizers whose state
        isn't a params-mirror plus shape-free leaves (e.g. ``masked``
        wrappers) are rejected rather than silently misfiled."""
        index = self._inner_state_index()
        uncovered = set(self._uncovered(params).keys())

        values: Dict[str, Dict[str, np.ndarray]] = {}
        scalars: Dict[str, np.ndarray] = {}
        for gi, grp in enumerate(self.layout.groups):
            for p, leaf in jax.tree_util.tree_flatten_with_path(
                opt_state.sharded[gi]
            )[0]:
                kf = jax.tree_util.keystr(p)
                leaf = np.asarray(leaf)
                if index.get(kf) == "param":
                    values.setdefault(kf, {}).update(
                        self._group_slot_values(grp, leaf)
                    )
                else:
                    scalars[kf] = leaf[0]
        for p, leaf in jax.tree_util.tree_flatten_with_path(opt_state.local)[0]:
            leaf = np.asarray(leaf)
            if (
                p
                and isinstance(p[-1], jax.tree_util.DictKey)
                and p[-1].key in uncovered
            ):
                kf = jax.tree_util.keystr(p[:-1])
                values.setdefault(kf, {})[p[-1].key] = leaf[0].reshape(-1)
            else:
                scalars.setdefault(jax.tree_util.keystr(p), leaf[0])

        u_shape = jax.eval_shape(self.inner.init, params)
        leaves, treedef = jax.tree_util.tree_flatten_with_path(u_shape)
        param_keys = sorted(values, key=len, reverse=True)  # longest prefix wins
        out = []
        for p, leaf in leaves:
            ku = jax.tree_util.keystr(p)
            if index.get(ku) == "scalar":
                out.append(
                    np.asarray(scalars[ku]).reshape(leaf.shape).astype(leaf.dtype)
                )
                continue
            flat = None
            for kf in param_keys:
                if ku.startswith(kf) and ku[len(kf) :] in values[kf]:
                    flat = values[kf][ku[len(kf) :]]
                    break
            if flat is None:
                raise ValueError(
                    f"optimizer state leaf {ku!r} has no sharded counterpart — "
                    "full-state remap supports inner optimizers whose state is "
                    "a params-mirror plus shape-free leaves"
                )
            out.append(flat.reshape(leaf.shape).astype(leaf.dtype, copy=False))
        return jax.tree_util.tree_unflatten(treedef, out)

    def scatter_full_state(self, full_state, params) -> ShardedOptState:
        """Inverse of :meth:`gather_full_state`: one unsharded inner state ->
        the rank-stacked :class:`ShardedOptState` this updater's layout would
        hold (host numpy) — per-element leaves sliced into shard rows by slot
        name (alignment padding zero, matching init semantics), shape-free
        leaves replicated across ranks."""
        n = self.layout.n_shards
        index = self._inner_state_index()
        u_named = {
            jax.tree_util.keystr(p): np.asarray(l)
            for p, l in jax.tree_util.tree_flatten_with_path(full_state)[0]
        }

        def stacked(kf, leaf_shape, leaf_dtype):
            if kf not in u_named:
                raise ValueError(f"full optimizer state is missing leaf {kf!r}")
            v = u_named[kf].reshape(leaf_shape).astype(leaf_dtype, copy=False)
            return np.broadcast_to(v, (n,) + tuple(leaf_shape)).copy()

        sharded = []
        for grp in self.layout.groups:
            f_shape = jax.eval_shape(
                self.inner.init,
                jax.ShapeDtypeStruct((grp.shard_total,), grp.np_dtype()),
            )
            leaves, treedef = jax.tree_util.tree_flatten_with_path(f_shape)
            built = []
            for p, leaf in leaves:
                kf = jax.tree_util.keystr(p)
                if index.get(kf) == "param":
                    vals = {}
                    for bi in grp.buckets:
                        for s in self.layout.buckets[bi].slots:
                            if kf + s.name not in u_named:
                                raise ValueError(
                                    f"full optimizer state is missing leaf "
                                    f"{kf + s.name!r}"
                                )
                            vals[s.name] = u_named[kf + s.name].reshape(-1)
                    rows = build_shard_rows(vals, self.layout, indices=grp.buckets)
                    built.append(
                        np.concatenate(rows, axis=1).astype(leaf.dtype, copy=False)
                        if rows
                        else np.zeros((n, 0), leaf.dtype)
                    )
                else:
                    built.append(stacked(kf, leaf.shape, leaf.dtype))
            sharded.append(jax.tree_util.tree_unflatten(treedef, built))

        l_shape = jax.eval_shape(self.inner.init, self._uncovered(params))
        leaves, treedef = jax.tree_util.tree_flatten_with_path(l_shape)
        uncovered = set(self._uncovered(params).keys())
        built = []
        for p, leaf in leaves:
            if (
                p
                and isinstance(p[-1], jax.tree_util.DictKey)
                and p[-1].key in uncovered
            ):
                kf = jax.tree_util.keystr(p[:-1]) + p[-1].key
            else:
                kf = jax.tree_util.keystr(p)
            built.append(stacked(kf, leaf.shape, leaf.dtype))
        local = jax.tree_util.tree_unflatten(treedef, built)
        return ShardedOptState(sharded=tuple(sharded), local=local)


# -- fused (unsharded) optimizer ----------------------------------------------
# Moved verbatim from contrib/fuse_optimizer.py (which now re-exports with a
# DeprecationWarning): the dtype-group fusion idea whose engine-native form is
# ShardedOptimizerUpdater above, kept as a standalone wrapper for unsharded
# use.


class FusedState(NamedTuple):
    inner: optax.OptState


def _plan_cache(params) -> BucketPlan:
    # One bucket per dtype: single fused array per dtype group.
    return BucketPlan.from_tree(params, bucket_size_bytes=1 << 62)


def fuse_optimizer(inner: optax.GradientTransformation) -> optax.GradientTransformation:
    """Wrap an optax transformation to run on fused flat arrays.

    Exact: bitwise-identical updates to ``inner`` for any elementwise
    optimizer, because the fused arrays are just a re-layout of the leaves.
    """
    plans = {}

    def get_plan(tree):
        leaves, structure = jax.tree.flatten(tree)
        key = (structure, tuple((tuple(l.shape), str(l.dtype)) for l in leaves))
        if key not in plans:
            plans[key] = _plan_cache(tree)
        return plans[key]

    def init_fn(params):
        plan = get_plan(params)
        fused_params = plan.bucketize(params)
        return FusedState(inner=inner.init(fused_params))

    def update_fn(updates, state, params=None):
        plan = get_plan(updates)
        fused_updates = plan.bucketize(updates)
        fused_params = plan.bucketize(params) if params is not None else None
        new_fused, new_inner = inner.update(fused_updates, state.inner, fused_params)
        return plan.debucketize(new_fused), FusedState(inner=new_inner)

    return optax.GradientTransformation(init_fn, update_fn)
