"""Sharded optimizer update: each rank updates only its shard of every bucket.

The compute half of the ZeRO exchange (arXiv:2004.13336; reference
``contrib/zero.py`` prototyped the wrapper form).  After the per-bucket
reduce-scatter each rank holds the reduced gradients for its contiguous flat
slice of every bucket; this module runs the inner optax transformation on
exactly those slices and hands back per-bucket *update shards* for the
deferred all-gather.  Optimizer state therefore exists only for ``1/n`` of
every parameter on each chip — Adam's ``2P`` of moments becomes ``2P/n``.

Fusion is engine-native here: all of a dtype group's bucket shards are
concatenated into ONE flat vector per rank, so the inner optimizer runs once
per dtype — the dtype-group fusion ``contrib/fuse_optimizer.py`` provided as
a wrapper, absorbed into the engine (``fuse_optimizer`` itself now lives
here, with a deprecated shim left behind in contrib).

Bitwise contract: for elementwise optimizers (SGD/momentum/Adam/...) the
update computed on a shard slice equals the corresponding slice of the
update computed on the full tree, and alignment-padding slots carry zero
gradients so their moments stay zero — concatenating the gathered shards
reproduces the unsharded trajectory bit-for-bit (``tests/test_zero.py``).

Leaves excluded from the plan by a ``dp_filter`` never ride a collective;
they keep a small replicated "local" optimizer state and are updated in
place each step, exactly as on the unsharded path.
"""

from typing import Any, Dict, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from bagua_tpu.bucket import BucketPlan, flatten_bucket_leaves
from bagua_tpu.communication import rank_id
from bagua_tpu.sharded.layout import ShardLayout
from bagua_tpu.utils import from_bagua_datatype

__all__ = ["ShardedOptState", "ShardedOptimizerUpdater", "FusedState", "fuse_optimizer"]


class ShardedOptState(NamedTuple):
    """Engine-side optimizer state under the zero algorithm: one inner state
    per dtype group (shard-sized — the memory win), plus a replicated inner
    state for dp_filter-excluded leaves."""

    sharded: Tuple[Any, ...]
    local: Any


class ShardedOptimizerUpdater:
    """Runs the inner optimizer on each rank's bucket shards only.

    Built by the engine whenever the bound algorithm reports
    ``sharded_update=True``; rebuilt on every ``rebucket`` (the layout is a
    pure function of the plan + group size, and host-side resharding in
    :mod:`bagua_tpu.sharded.layout` migrates live state between layouts).
    """

    def __init__(self, inner: optax.GradientTransformation, plan: BucketPlan, group):
        self.inner = inner
        self.plan = plan
        self.group = group
        # Shards are per exchange-ring slot: on a named mesh with model axes
        # the reduce-scatter splits each bucket across the data axes only
        # (each tp peer group keeps a full ring), so the layout follows
        # exchange_size, not the full mesh size.
        self.layout = ShardLayout.from_plan(
            plan, getattr(group, "exchange_size", group.size)
        )
        self._covered = {s.name for spec in plan.specs for s in spec.slots}

    # -- helpers -------------------------------------------------------------

    def _named_leaves(self, tree) -> Dict[str, Any]:
        return {
            jax.tree_util.keystr(p): l
            for p, l in jax.tree_util.tree_flatten_with_path(tree)[0]
        }

    def _uncovered(self, tree) -> Dict[str, Any]:
        return {
            n: l for n, l in self._named_leaves(tree).items() if n not in self._covered
        }

    def _bucket_shards(self, tree, me) -> List[jnp.ndarray]:
        """Rank ``me``'s flat slice of every bucket, plan order."""
        groups = self.plan.group_leaves(tree)
        shards = []
        for bi, spec in enumerate(self.plan.specs):
            leaves = [groups[bi][s.name] for s in spec.slots]
            flat = flatten_bucket_leaves(leaves, spec)
            sh = self.layout.buckets[bi].shard_numel
            shards.append(jax.lax.dynamic_slice(flat, (me * sh,), (sh,)))
        return shards

    # -- API -----------------------------------------------------------------

    def init(self, params) -> ShardedOptState:
        """Shard-sized inner states (zeros are the correct shard values for
        every optax init: moments start at zero, counts are shape-free)."""
        sharded = tuple(
            self.inner.init(jnp.zeros((g.shard_total,), from_bagua_datatype(g.dtype)))
            for g in self.layout.groups
        )
        return ShardedOptState(sharded=sharded, local=self.inner.init(self._uncovered(params)))

    def update_shards(self, grads, params, opt_state: ShardedOptState):
        """One sharded optimizer phase (traced, inside shard_map).

        ``grads`` is the exchanged tree: every bucket's flat image holds the
        reduced values in rank-me's shard slice (the exchange zero-fills the
        rest).  Returns ``(pending, new_opt_state, new_params)`` where
        ``pending`` is one *updated parameter shard* per bucket — COVERED
        PARAMS ARE NOT TOUCHED in ``new_params``; the algorithm all-gathers
        the pending shards at the start of the next step and swaps them in
        right before the forward, hiding the gather behind that step's
        compute.  Pending carries post-update parameters (not raw updates)
        so the ``p + u`` application happens HERE, in the same fusion
        cluster as the optimizer math — rounding (FMA contraction included)
        matches a standalone optax jit bitwise, keeping the trajectory
        bitwise-identical to the plain-optax unsharded reference;
        materializing raw updates across the gather boundary and adding
        them later rounds differently.  Excluded leaves are updated in
        place.
        """
        me = rank_id()
        g_shards = self._bucket_shards(grads, me)
        p_shards = self._bucket_shards(params, me)

        pending: List[Any] = [None] * self.plan.num_buckets
        new_sharded = []
        for gi, grp in enumerate(self.layout.groups):
            g_cat = jnp.concatenate([g_shards[bi] for bi in grp.buckets])
            p_cat = jnp.concatenate([p_shards[bi] for bi in grp.buckets])
            # Materialize contiguous inputs so the optimizer math forms its
            # own fusion cluster, pinning it to the same codegen (FMA
            # contraction included) as a standalone optax jit — the bitwise
            # contract is against the plain-optax unsharded trajectory, and
            # letting XLA fuse the math with the slice/concat data movement
            # above would make rounding depend on the surrounding graph.
            g_cat, p_cat = jax.lax.optimization_barrier((g_cat, p_cat))
            upd_cat, st = self.inner.update(g_cat, opt_state.sharded[gi], p_cat)
            newp_cat = optax.apply_updates(p_cat, upd_cat)
            new_sharded.append(st)
            off = 0
            for bi in grp.buckets:
                sh = self.layout.buckets[bi].shard_numel
                pending[bi] = jax.lax.dynamic_slice(newp_cat, (off,), (sh,))
                off += sh

        # dp_filter-excluded leaves: local (replicated) update, applied now.
        local_g = self._uncovered(grads)
        new_local = opt_state.local
        new_params = params
        if local_g:
            local_p = self._uncovered(params)
            upd, new_local = self.inner.update(local_g, opt_state.local, local_p)
            applied = optax.apply_updates(local_p, upd)
            named = self._named_leaves(params)
            named.update(applied)
            paths, treedef = jax.tree_util.tree_flatten_with_path(params)
            new_params = treedef.unflatten(
                [named[jax.tree_util.keystr(p)] for p, _ in paths]
            )
        return (
            tuple(pending),
            ShardedOptState(sharded=tuple(new_sharded), local=new_local),
            new_params,
        )


# -- fused (unsharded) optimizer ----------------------------------------------
# Moved verbatim from contrib/fuse_optimizer.py (which now re-exports with a
# DeprecationWarning): the dtype-group fusion idea whose engine-native form is
# ShardedOptimizerUpdater above, kept as a standalone wrapper for unsharded
# use.


class FusedState(NamedTuple):
    inner: optax.OptState


def _plan_cache(params) -> BucketPlan:
    # One bucket per dtype: single fused array per dtype group.
    return BucketPlan.from_tree(params, bucket_size_bytes=1 << 62)


def fuse_optimizer(inner: optax.GradientTransformation) -> optax.GradientTransformation:
    """Wrap an optax transformation to run on fused flat arrays.

    Exact: bitwise-identical updates to ``inner`` for any elementwise
    optimizer, because the fused arrays are just a re-layout of the leaves.
    """
    plans = {}

    def get_plan(tree):
        leaves, structure = jax.tree.flatten(tree)
        key = (structure, tuple((tuple(l.shape), str(l.dtype)) for l in leaves))
        if key not in plans:
            plans[key] = _plan_cache(tree)
        return plans[key]

    def init_fn(params):
        plan = get_plan(params)
        fused_params = plan.bucketize(params)
        return FusedState(inner=inner.init(fused_params))

    def update_fn(updates, state, params=None):
        plan = get_plan(updates)
        fused_updates = plan.bucketize(updates)
        fused_params = plan.bucketize(params) if params is not None else None
        new_fused, new_inner = inner.update(fused_updates, state.inner, fused_params)
        return plan.debucketize(new_fused), FusedState(inner=new_inner)

    return optax.GradientTransformation(init_fn, update_fn)
