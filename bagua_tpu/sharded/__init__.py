"""ZeRO-fused bucketed exchange: reduce-scatter, sharded optimizer update,
all-gather overlapped into the next step's forward.

See ``docs/zero.md`` for the memory math and wire pattern.  The subsystem
splits cleanly in three:

* :mod:`~bagua_tpu.sharded.layout` — shard geometry + host-side resharding
  (rebucket and elastic world-size remap share one code path);
* :mod:`~bagua_tpu.sharded.updater` — the shard-only optimizer phase with
  engine-native dtype-group fusion (absorbs ``contrib/fuse_optimizer``);
* :mod:`~bagua_tpu.sharded.algorithm` — the registered ``zero`` algorithm
  (reduce-scatter leg + deferred all-gather leg, ByteGrad-composable).
"""

from bagua_tpu.sharded.algorithm import ZeroAlgorithm, ZeroAlgorithmImpl
from bagua_tpu.sharded.layout import (
    BucketShard,
    DtypeGroup,
    ShardLayout,
    ShardSlot,
    assemble_full_flats,
    reshard_bucket_rows,
    reshard_group_flat,
)
from bagua_tpu.sharded.updater import (
    FusedState,
    ShardedOptState,
    ShardedOptimizerUpdater,
    fuse_optimizer,
)

__all__ = [
    "ZeroAlgorithm",
    "ZeroAlgorithmImpl",
    "ShardLayout",
    "ShardSlot",
    "BucketShard",
    "DtypeGroup",
    "ShardedOptState",
    "ShardedOptimizerUpdater",
    "FusedState",
    "fuse_optimizer",
    "assemble_full_flats",
    "reshard_bucket_rows",
    "reshard_group_flat",
]
