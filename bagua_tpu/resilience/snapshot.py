"""Async double-buffered device→host snapshots with atomic manifests.

The checkpoint problem on preemptible pools: the synchronous Orbax path
(``bagua_tpu.checkpoint``) blocks the step loop for the full device→host
transfer + serialization, so operators stretch the interval and eat the
lost work on every preemption.  The snapshotter moves the whole cost off
the critical path:

1. **On-device double buffer** — the step function *donates* its state
   (``donate_argnums=(0,)``), so a background thread reading the live state
   would race the next step's buffer reuse.  ``maybe_snapshot`` instead
   dispatches a ``jnp.copy`` of every leaf (pure device work, enqueued
   asynchronously behind the in-flight step, never donated) and hands *the
   copy* to the writer thread.  The hot path pays one dispatch, not a sync.
2. **Background writer** — a daemon thread pulls the buffered copy to host
   (``device_get`` of this process's addressable slice) and serializes it.
   If a snapshot is still being written when the next cadence tick fires,
   the tick is *skipped* (counted, never queued) — snapshots are
   best-effort freshness, not a backlog.
3. **Atomic completeness** — every file is written to a ``.tmp`` path and
   ``os.replace``d; the manifest is written last and *names* every process
   file, so a snapshot is complete iff its manifest exists **and** every
   file it names exists.  A reader can never observe a torn snapshot; a
   writer killed mid-stream leaves garbage that ``latest_complete`` skips.

Snapshot layout (one directory per step, shared filesystem across the gang)::

    <dir>/step_0000010/proc0.npz       # process 0's slice of every leaf
    <dir>/step_0000010/proc1.npz
    <dir>/step_0000010/manifest.json   # written last, atomically

Leaves are stored flat (``leaf_00000`` … in pytree-flatten order) with their
``keystr`` paths recorded in the manifest — restore rebuilds against a
template treedef, which every resume path has (the freshly ``init()``-ed
state), so no pickled structure rides in the artifact.

**Sharded (ZeRO) engines** need no special casing on the write path: the
engine's state is rank-stacked on the leading axis and each process writes
only its addressable rows, so under the ``zero`` algorithm a process
serializes exactly its own optimizer-state *shard* — per-chip snapshot
bytes scale as ``1/n`` for the optimizer state, matching its residency.
The shard layout itself rides in the manifest via ``manifest_extra_fn``
(the engine's ``export_plan_payload`` includes a ``"shard"`` section), and
:class:`~bagua_tpu.resilience.resume.ElasticResumeCoordinator` uses it to
re-shard the optimizer state when the gang resizes.
"""

import json
import logging
import os
import queue
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

MANIFEST_FILENAME = "manifest.json"

__all__ = ["SnapshotStore", "AsyncSnapshotter", "MANIFEST_FILENAME"]


def _step_dirname(step: int) -> str:
    return f"step_{step:07d}"


def local_slice(x) -> np.ndarray:
    """This process's contiguous slice of a leading-axis-sharded array.

    Single-process (fully addressable) arrays convert directly.  On a
    multi-process group each local device holds one shard of the leading
    axis; they are concatenated in index order (deduplicating replicated
    shards) into the process's contiguous ``[offset, offset+local)`` rows.
    """
    import jax

    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        by_start: Dict[int, np.ndarray] = {}
        for s in x.addressable_shards:
            start = s.index[0].start or 0 if s.index else 0
            if start not in by_start:
                by_start[start] = np.asarray(s.data)
        parts = [by_start[k] for k in sorted(by_start)]
        return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)
    return np.asarray(x)


class SnapshotStore:
    """Filesystem layout + completeness rules for step snapshots."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def step_dir(self, step: int) -> str:
        return os.path.join(self.directory, _step_dirname(step))

    # -- writing -------------------------------------------------------------

    def write_process_arrays(
        self, step: int, process_index: int, arrays: List[np.ndarray]
    ) -> str:
        """Atomically write one process's slice of every leaf (flat order)."""
        d = self.step_dir(step)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"proc{process_index}.npz")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:  # file handle: savez must not append ".npz"
            np.savez(f, **{f"leaf_{i:05d}": a for i, a in enumerate(arrays)})
        os.replace(tmp, path)
        return path

    def write_manifest(self, step: int, manifest: Dict[str, Any]) -> str:
        """Atomically publish the manifest — the snapshot's commit record.
        It must name every process file (``files``); completeness is judged
        against that list, so ranks that die before writing leave the
        snapshot incomplete rather than torn."""
        d = self.step_dir(step)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, MANIFEST_FILENAME)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(manifest, f, sort_keys=True)
        os.replace(tmp, path)
        return path

    # -- reading -------------------------------------------------------------

    def read_manifest(self, step: int) -> Optional[Dict[str, Any]]:
        path = os.path.join(self.step_dir(step), MANIFEST_FILENAME)
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def is_complete(self, step: int) -> bool:
        manifest = self.read_manifest(step)
        if manifest is None:
            return False
        d = self.step_dir(step)
        return all(os.path.exists(os.path.join(d, f)) for f in manifest["files"])

    def steps(self) -> List[int]:
        """All step directories present (complete or not), ascending."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if name.startswith("step_"):
                try:
                    out.append(int(name[len("step_"):]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_complete(self) -> Optional[int]:
        """Newest step whose manifest AND every named file exist — the only
        snapshot a resume may trust (torn/partial directories are skipped,
        never errors)."""
        for step in reversed(self.steps()):
            if self.is_complete(step):
                return step
        return None

    def load_stacked(self, step: int) -> Tuple[Dict[str, Any], List[np.ndarray]]:
        """Load a complete snapshot as full ``(world_size, ...)`` host
        arrays: every process file named by the manifest, concatenated along
        the leading (rank) axis in process order."""
        manifest = self.read_manifest(step)
        if manifest is None or not self.is_complete(step):
            raise FileNotFoundError(
                f"snapshot step {step} in {self.directory} is missing or incomplete"
            )
        d = self.step_dir(step)
        per_proc = []
        for fname in manifest["files"]:
            with np.load(os.path.join(d, fname)) as z:
                per_proc.append([z[k] for k in sorted(z.files)])
        n_leaves = len(per_proc[0])
        if any(len(p) != n_leaves for p in per_proc):
            raise ValueError(f"snapshot step {step}: process files disagree on leaf count")
        leaves = [
            np.concatenate([p[i] for p in per_proc], axis=0)
            if len(per_proc) > 1 else per_proc[0][i]
            for i in range(n_leaves)
        ]
        return manifest, leaves

    # -- retention -----------------------------------------------------------

    def gc(self, keep: int = 2) -> None:
        """Drop all but the newest ``keep`` *complete* snapshots, plus any
        incomplete directory older than the newest complete one (garbage
        from a killed writer; an incomplete directory *newer* than the
        latest complete snapshot may still be in flight, so it stays)."""
        complete = [s for s in self.steps() if self.is_complete(s)]
        if not complete:
            return
        newest = complete[-1]
        doomed = set(complete[:-keep] if keep > 0 else complete)
        doomed.update(
            s for s in self.steps() if s < newest and not self.is_complete(s)
        )
        doomed.discard(newest)
        for step in doomed:
            shutil.rmtree(self.step_dir(step), ignore_errors=True)


class AsyncSnapshotter:
    """Cadenced, off-critical-path state snapshots (see module docstring).

    Args:
        store: the :class:`SnapshotStore` (or a directory path).
        every: snapshot cadence in steps — the lost-work bound K.  0 disables
            (``maybe_snapshot`` becomes a no-op).
        process_index / num_processes: this process's position in the gang
            (defaults to the JAX runtime's).  Process 0 writes the manifest.
        world_size: the rank-stacked leading-axis size recorded in manifests
            (defaults to total device count — ``group.size`` for the default
            group).
        telemetry: optional hub; every written snapshot emits ``on_snapshot``
            (wall ms, bytes, kind) and every skipped cadence tick bumps
            ``snapshot_skipped_total``.
        keep: complete snapshots retained (older ones garbage-collected).
        manifest_extra_fn: called at write time for extra manifest fields —
            the engine's bucket-plan payload rides here so resume can adopt
            it without a planner cold-start.
    """

    def __init__(
        self,
        store,
        every: int,
        process_index: Optional[int] = None,
        num_processes: Optional[int] = None,
        world_size: Optional[int] = None,
        telemetry=None,
        keep: int = 2,
        manifest_extra_fn: Optional[Callable[[], Dict[str, Any]]] = None,
    ):
        import jax

        self.store = store if isinstance(store, SnapshotStore) else SnapshotStore(store)
        self.every = int(every)
        self.process_index = (
            jax.process_index() if process_index is None else process_index
        )
        self.num_processes = (
            jax.process_count() if num_processes is None else num_processes
        )
        self.world_size = jax.device_count() if world_size is None else world_size
        self.telemetry = telemetry
        self.keep = keep
        self.manifest_extra_fn = manifest_extra_fn
        self.skipped = 0
        self.written = 0
        self.last_step: Optional[int] = None
        self._queue: "queue.Queue" = queue.Queue(maxsize=1)
        self._idle = threading.Event()
        self._idle.set()
        self._stop = False
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._writer_loop, name="bagua-snapshotter", daemon=True
        )
        self._thread.start()

    # -- hot path ------------------------------------------------------------

    def maybe_snapshot(self, state, step: int) -> bool:
        """Cadence gate + non-blocking hand-off.  Returns True when a
        snapshot of this step was enqueued."""
        if self.every <= 0 or step % self.every != 0 or step == self.last_step:
            return False
        return self.snapshot(state, step, blocking=False)

    def snapshot(self, state, step: int, blocking: bool = False, kind: str = "async") -> bool:
        """Buffer ``state`` on device and enqueue it for background writing.
        Non-blocking calls skip (and count) when the writer is busy;
        ``blocking=True`` waits for the writer and for this snapshot to land
        (the preemption-drain path)."""
        import jax
        import jax.numpy as jnp

        if not blocking and not self._idle.is_set():
            self.skipped += 1
            if self.telemetry is not None:
                self.telemetry.registry.counter(
                    "snapshot_skipped_total",
                    help="cadence ticks skipped because the previous snapshot was still writing",
                ).inc()
            return False
        if blocking:
            self._idle.wait()
        # The double buffer: a device-side copy dispatched behind the
        # in-flight step.  The copy is never donated, so the writer thread's
        # device_get cannot race the next step's buffer reuse.
        buffered = jax.tree.map(jnp.copy, state)
        self._idle.clear()
        self.last_step = step
        self._queue.put((buffered, step, kind))
        if blocking:
            self._idle.wait()
            if self._error is not None:
                err, self._error = self._error, None
                raise err
        return True

    def force_snapshot(self, state, step: int) -> bool:
        """Synchronous snapshot — returns only once the manifest is on disk.
        The preemption watcher calls this after draining the in-flight step."""
        return self.snapshot(state, step, blocking=True, kind="final")

    # -- background writer ---------------------------------------------------

    def _writer_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            buffered, step, kind = item
            try:
                self._write(buffered, step, kind)
                self.written += 1
            except Exception as e:  # surface on the next blocking call
                logger.exception("snapshot at step %d failed", step)
                self._error = e
            finally:
                del buffered
                self._idle.set()

    def _write(self, buffered, step: int, kind: str) -> None:
        import jax

        t0 = time.perf_counter()
        jax.block_until_ready(buffered)
        flat = jax.tree_util.tree_flatten_with_path(buffered)[0]
        arrays = [local_slice(leaf) for _, leaf in flat]
        self.store.write_process_arrays(step, self.process_index, arrays)
        if self.process_index == 0:
            manifest = {
                "step": int(step),
                "world_size": int(self.world_size),
                "num_processes": int(self.num_processes),
                "files": [f"proc{p}.npz" for p in range(self.num_processes)],
                "leaf_keys": [jax.tree_util.keystr(path) for path, _ in flat],
                "kind": kind,
            }
            if self.manifest_extra_fn is not None:
                try:
                    manifest.update(self.manifest_extra_fn() or {})
                except Exception:
                    logger.exception("manifest_extra_fn failed; manifest has no extras")
            self.store.write_manifest(step, manifest)
            self.store.gc(keep=self.keep)
        wall_ms = (time.perf_counter() - t0) * 1e3
        n_bytes = sum(a.nbytes for a in arrays)
        logger.info(
            "snapshot step %d (%s): %.1f MiB in %.1f ms (off critical path)",
            step, kind, n_bytes / 2**20, wall_ms,
        )
        if self.telemetry is not None:
            self.telemetry.on_snapshot(
                step=step, wall_ms=wall_ms, n_bytes=n_bytes, kind=kind
            )

    # -- teardown ------------------------------------------------------------

    def drain(self, timeout_s: float = 60.0) -> None:
        """Wait for any in-flight snapshot write to land."""
        self._idle.wait(timeout_s)

    def close(self) -> None:
        """Flush and stop the writer thread (idempotent)."""
        if self._stop:
            return
        self._stop = True
        self.drain()
        self._queue.put(None)
        self._thread.join(timeout=60.0)
