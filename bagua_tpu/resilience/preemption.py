"""Preemption watcher: turn SIGTERM into a drained, resumable exit.

Preemptible TPU pools deliver a termination signal with a short grace
window.  The naive outcome is a worker killed mid-step: the newest snapshot
is up to K steps old and anything in flight is lost.  The watcher converts
the signal into a *cooperative* stop:

1. the handler only sets a flag (async-signal-safe; no I/O, no JAX calls —
   the runtime is not reentrant from a signal context);
2. the training loop polls :meth:`should_stop` once per step, finishes the
   in-flight step (drain), forces a final synchronous snapshot, and writes a
   **resumable marker** before exiting cleanly;
3. the restarted gang (same or different size) finds the marker + the final
   snapshot and resumes with *zero* lost steps instead of up-to-K.

The marker is advisory — resume never requires it (a hard kill leaves no
marker, and the newest complete snapshot still bounds the loss at K) — but
CI asserts it to prove the drain path ran.
"""

import json
import logging
import os
import signal
import threading
import time
from typing import Iterable, Optional

logger = logging.getLogger(__name__)

RESUMABLE_MARKER = "RESUMABLE.json"

__all__ = [
    "PreemptionWatcher",
    "RESUMABLE_MARKER",
    "write_resumable_marker",
    "read_resumable_marker",
    "clear_resumable_marker",
]


class PreemptionWatcher:
    """Installable signal → flag bridge (SIGTERM by default; pass e.g.
    ``(signal.SIGTERM, signal.SIGUSR1)`` for pools that deliver a distinct
    maintenance signal).  Chains any previously installed Python handler so
    stacking watchers (or test harnesses) keeps both behaviors."""

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,)):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._prior = {}
        self._installed = False
        self.signaled_at: Optional[float] = None
        self.signum: Optional[int] = None

    def install(self) -> "PreemptionWatcher":
        """Must run on the main thread (CPython restricts ``signal.signal``);
        idempotent."""
        if self._installed:
            return self
        for sig in self.signals:
            self._prior[sig] = signal.signal(sig, self._handle)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prior in self._prior.items():
            try:
                signal.signal(sig, prior)
            except (ValueError, TypeError):  # non-main thread / exotic prior
                pass
        self._prior.clear()
        self._installed = False

    def _handle(self, signum, frame) -> None:
        # Flag only — everything else happens on the training thread.
        self.signaled_at = time.monotonic()
        self.signum = signum
        self._event.set()
        prior = self._prior.get(signum)
        if callable(prior):
            prior(signum, frame)

    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    def should_stop(self) -> bool:
        """Poll point for the training loop (one ``Event.is_set`` — ns)."""
        return self._event.is_set()

    def trigger(self) -> None:
        """Programmatic preemption (tests; also lets an orchestrator sidecar
        flip the flag without a signal)."""
        self.signaled_at = time.monotonic()
        self._event.set()


def write_resumable_marker(directory: str, step: int, reason: str = "preempted") -> str:
    """Atomically record that this exit drained + snapshotted and the job can
    be resumed with no lost steps."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, RESUMABLE_MARKER)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(
            {"step": int(step), "reason": reason, "pid": os.getpid(), "ts": time.time()},
            f,
        )
    os.replace(tmp, path)
    logger.info("resumable marker written at step %d (%s)", step, reason)
    return path


def read_resumable_marker(directory: str) -> Optional[dict]:
    try:
        with open(os.path.join(directory, RESUMABLE_MARKER)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def clear_resumable_marker(directory: str) -> None:
    """Resume consumes the marker (it describes the *previous* incarnation)."""
    try:
        os.remove(os.path.join(directory, RESUMABLE_MARKER))
    except OSError:
        pass
