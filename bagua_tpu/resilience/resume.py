"""Elastic resume: agree on the newest complete snapshot, replay it into
the (possibly resized) gang, carry the bucket plan over.

Resume is the half of elasticity the launcher can't do alone: after a
preemption or crash the gang re-forms — maybe smaller (a node benched),
maybe larger (capacity returned) — and every rank must (1) pick the *same*
snapshot, (2) remap the rank-stacked state to the new world size
(:func:`bagua_tpu.checkpoint.remap_world_size`), and (3) keep the autotune
investment: the bucket plan the tuner had converged on rides in the
snapshot manifest and is re-adopted here, so the restarted gang starts at
the tuned operating point instead of the cold greedy split.

Snapshot choice: the local scan (``SnapshotStore.latest_complete``) is
authoritative on a shared filesystem.  When a rendezvous store is
reachable *and* the group spans processes, ranks additionally publish their
local view and take the **minimum** — a rank whose filesystem view lags
(NFS attribute caching) must not be resumed past what it can actually
read.  Store outages degrade to the local scan (retry + breaker from
:mod:`bagua_tpu.resilience.retry`), never block the restart.
"""

import json
import logging
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from bagua_tpu.resilience.retry import CircuitBreaker, RetryPolicy, retry_call
from bagua_tpu.resilience.snapshot import SnapshotStore

logger = logging.getLogger(__name__)

__all__ = ["ElasticResumeCoordinator", "ResumeResult"]


class ResumeResult:
    """What a resume did: the committed state + provenance for telemetry."""

    def __init__(self, state, step: int, old_world_size: int, new_world_size: int,
                 plan_source: str):
        self.state = state
        self.step = step
        self.old_world_size = old_world_size
        self.new_world_size = new_world_size
        #: ``"carried"`` when the manifest's bucket plan was re-adopted,
        #: ``"autopilot"`` when that carried configuration was
        #: autopilot-chosen, ``"fresh"`` when the engine kept its cold-start
        #: plan
        self.plan_source = plan_source


class ElasticResumeCoordinator:
    """One resume attempt for one engine.

    Args:
        store: :class:`SnapshotStore` (or directory path) the snapshotter
            wrote into.
        rendezvous_client: optional
            :class:`~bagua_tpu.distributed.rendezvous.RendezvousClient` for
            the cross-rank snapshot agreement (multi-process gangs only).
        expert_filter: forwarded to ``remap_world_size`` (MoE leaves).
        telemetry: optional hub; a successful resume emits ``on_restart``.
        fleet_plan_fn: optional zero-arg callable returning a plan payload
            from the fleet's cross-gang cache (or None on a miss) — e.g.
            ``lambda: fleet.lookup_plan(**key)["plan"]``.  Consulted by
            :meth:`fleet_warm_start` when there is no snapshot to resume.
        fleet_directive_fn: optional zero-arg callable returning the gang's
            oldest pending remediation directive (or None) — e.g.
            ``lambda: fleet.gang_directive(gang_id)``.  Consulted by
            :meth:`directed_world_size` so a RemediationEngine ``resize``
            directive steers the re-formed gang's target world size.
    """

    def __init__(
        self,
        store,
        rendezvous_client=None,
        expert_filter=None,
        telemetry=None,
        agreement_timeout_s: float = 30.0,
        fleet_plan_fn=None,
        fleet_directive_fn=None,
    ):
        self.store = store if isinstance(store, SnapshotStore) else SnapshotStore(store)
        self.rendezvous_client = rendezvous_client
        self.expert_filter = expert_filter
        self.telemetry = telemetry
        self.agreement_timeout_s = agreement_timeout_s
        self.fleet_plan_fn = fleet_plan_fn
        self.fleet_directive_fn = fleet_directive_fn

    # -- snapshot agreement --------------------------------------------------

    def agreed_resume_step(self, nonce: str = "0") -> Optional[int]:
        """The step every rank will resume from (None = cold start).

        ``nonce`` namespaces the agreement round in the rendezvous KV (pass
        the launcher's attempt counter / rendezvous epoch) so a second
        restart never reads the first restart's stale views."""
        import jax

        local = self.store.latest_complete()
        client = self.rendezvous_client
        nprocs = jax.process_count()
        if client is None or nprocs <= 1:
            return local
        policy = RetryPolicy()
        breaker = CircuitBreaker(name="rendezvous-kv")
        rank = jax.process_index()
        try:
            retry_call(
                client.kv_set,
                f"resilience/resume/{nonce}/rank{rank}",
                json.dumps(local),
                policy=policy, breaker=breaker,
            )
            deadline = time.monotonic() + self.agreement_timeout_s
            views: Dict[int, Optional[int]] = {}
            while time.monotonic() < deadline and len(views) < nprocs:
                for r in range(nprocs):
                    if r in views:
                        continue
                    raw = retry_call(
                        client.kv_get,
                        f"resilience/resume/{nonce}/rank{r}",
                        policy=policy, breaker=breaker,
                    )
                    if raw is not None:
                        views[r] = json.loads(raw)
                if len(views) < nprocs:
                    time.sleep(0.1)
            if len(views) < nprocs:
                logger.warning(
                    "snapshot agreement timed out (%d/%d views); using local scan",
                    len(views), nprocs,
                )
                return local
            if any(v is None for v in views.values()):
                return None  # some rank sees no snapshot: cold start everywhere
            agreed = min(views.values())
            if agreed != local:
                logger.info(
                    "snapshot agreement chose step %s (local view was %s)",
                    agreed, local,
                )
            return agreed
        except (OSError, ConnectionError) as e:
            logger.warning("rendezvous store unreachable for agreement (%s); "
                           "using local scan", e)
            return local

    # -- the resume ----------------------------------------------------------

    def resume(self, ddp, init_state, nonce: str = "0") -> Optional[ResumeResult]:
        """Replay the agreed snapshot into ``ddp``'s gang.

        ``init_state`` is the freshly built :class:`~bagua_tpu.ddp.TrainState`
        from ``ddp.init(...)`` — it supplies the treedef, leaf dtypes and the
        target sharding.  Returns None when there is nothing to resume."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from bagua_tpu.checkpoint import remap_world_size

        step = self.agreed_resume_step(nonce=nonce)
        if step is None:
            return None
        manifest, leaves = self.store.load_stacked(step)
        old_world = int(manifest["world_size"])
        new_world = ddp.group.size
        # Adopt the carried plan BEFORE interpreting the snapshot leaves: a
        # sharded (``zero``) engine's state STRUCTURE — per-bucket pending
        # shards, per-dtype-group optimizer states — depends on the bucket
        # plan, so even the leaf count is only meaningful once the engine is
        # on the snapshot's layout.
        plan_payload = manifest.get("plan")
        plan_source = "carried" if self._adopt_plan(ddp, plan_payload) else "fresh"
        if (
            plan_source == "carried"
            and (plan_payload.get("config") or {}).get("source") == "autopilot"
        ):
            # The configuration the snapshot ran was autopilot-chosen — say
            # so, so dashboards can tell a tuned resume from an operator one.
            plan_source = "autopilot"
        if hasattr(ddp, "clear_pending_reshard"):
            # The adoption above goes through ``rebucket``, which queues an
            # in-band state migration — but the snapshot was *taken* in the
            # carried layout, so there is nothing to migrate.
            ddp.clear_pending_reshard()
        # The template is re-derived from the engine (not ``init_state``)
        # because plan adoption above may have changed sharded-state shapes.
        like_state = (
            ddp.state_template() if hasattr(ddp, "state_template") else init_state
        )
        treedef = jax.tree_util.tree_structure(like_state)
        if treedef.num_leaves != len(leaves):
            raise ValueError(
                f"snapshot step {step} holds {len(leaves)} leaves but the "
                f"engine's state has {treedef.num_leaves} — model/optimizer "
                "definition changed since the snapshot was taken"
            )
        host_state = jax.tree_util.tree_unflatten(treedef, leaves)
        if old_world != new_world:
            logger.info(
                "remapping snapshot step %d from world size %d to %d",
                step, old_world, new_world,
            )
            sharded = bool(
                plan_payload
                and plan_payload.get("shard")
                and getattr(ddp, "_sharded_updater", None) is not None
            )
            if sharded:
                # Optimizer-shard rows genuinely diverge per rank: replicate-
                # row-0 remapping would corrupt them.  Reassemble full flats
                # from the old shard layout and re-slice for the new world.
                host_state = ddp.reshard_host_state(
                    host_state, plan_payload, old_world
                )
            else:
                kwargs = {}
                if self.expert_filter is not None:
                    kwargs["expert_filter"] = self.expert_filter
                host_state = remap_world_size(host_state, new_world, **kwargs)
        # Match the engine state's leaf dtypes (remap's broadcast goes through
        # jnp and can weak-type) and commit to the step function's sharding —
        # each process materializes exactly its addressable shards.
        sharding = NamedSharding(ddp.group.mesh, P(ddp.group.all_axes))

        def commit(host, like):
            arr = np.asarray(host, dtype=like.dtype)
            if arr.shape != like.shape:
                raise ValueError(
                    f"snapshot leaf shape {arr.shape} != engine state {like.shape}"
                )
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx, a=arr: a[idx]
            )

        state = jax.tree.map(commit, host_state, like_state)
        # Lost work: the drained exit's marker records the step the previous
        # incarnation actually reached; without one (hard kill) the loss is
        # unknown but bounded by the snapshot cadence K.
        from bagua_tpu.resilience.preemption import (
            clear_resumable_marker, read_resumable_marker,
        )

        marker = read_resumable_marker(self.store.directory)
        lost = max(0, int(marker["step"]) - step) if marker else 0
        clear_resumable_marker(self.store.directory)
        if self.telemetry is not None:
            self.telemetry.on_restart(
                step=step,
                old_world_size=old_world,
                new_world_size=new_world,
                plan_source=plan_source,
                lost_steps=lost,
            )
        logger.info(
            "resumed at step %d (world %d -> %d, plan %s)",
            step, old_world, new_world, plan_source,
        )
        return ResumeResult(state, step, old_world, new_world, plan_source)

    def fleet_warm_start(self, ddp) -> Optional[str]:
        """Step-0 plan adoption from the fleet's cross-gang cache — the
        cold-start counterpart of :meth:`resume`'s manifest carry-over.

        Call when :meth:`resume` returned None (no snapshot: a brand-new
        gang): if ``fleet_plan_fn`` produces a payload that fits, the
        engine adopts it before the first step and the method returns
        ``"fleet"`` (the ``plan_source`` generalizing ``"carried"``),
        emitting the ``restart`` telemetry event at step 0 with
        ``plan_source="fleet"``.  Advisory: every failure path returns
        None and the gang runs its fresh plan."""
        if self.fleet_plan_fn is None:
            return None
        try:
            payload = self.fleet_plan_fn()
        except Exception as e:
            logger.warning("fleet plan lookup failed (advisory): %s", e)
            return None
        if not payload or not self._adopt_plan(ddp, payload):
            return None
        if hasattr(ddp, "clear_pending_reshard"):
            # Nothing to migrate: the gang has no live state yet.
            ddp.clear_pending_reshard()
        logger.info("cold start adopted a fleet-cached plan (plan_source=fleet)")
        if self.telemetry is not None:
            self.telemetry.on_restart(
                step=0,
                old_world_size=ddp.group.size,
                new_world_size=ddp.group.size,
                plan_source="fleet",
                lost_steps=0,
            )
        return "fleet"

    # -- fleet remediation directives -----------------------------------------

    def fleet_directive(self) -> Optional[Dict[str, Any]]:
        """The gang's oldest pending remediation directive, or None.
        Advisory and exception-fenced: an unreachable fleet never blocks a
        restart."""
        if self.fleet_directive_fn is None:
            return None
        try:
            directive = self.fleet_directive_fn()
        except Exception as e:
            logger.warning("fleet directive poll failed (advisory): %s", e)
            return None
        return directive if isinstance(directive, dict) else None

    def directed_world_size(self, default: int) -> int:
        """The world size the re-forming gang should target: a pending
        ``resize`` directive's ``to_world_size`` when the RemediationEngine
        diagnosed this gang (desync/host_wedge) and directed it smaller;
        ``default`` (the launcher's own count) otherwise."""
        directive = self.fleet_directive()
        if not directive or directive.get("action") != "resize":
            return int(default)
        to_world = (directive.get("detail") or {}).get("to_world_size")
        if isinstance(to_world, int) and to_world >= 1:
            logger.warning(
                "fleet resize directive #%s (%s): targeting world size %d "
                "instead of %d",
                directive.get("id"), directive.get("reason"), to_world,
                int(default),
            )
            return to_world
        return int(default)

    def _adopt_plan(self, ddp, payload: Optional[Dict[str, Any]]) -> bool:
        """Re-adopt the snapshot's bucket plan (no planner cold-start).  Best
        effort: a payload that no longer matches the model (leaf renames,
        bucketized-state algorithms) keeps the engine's fresh plan."""
        if not payload:
            return False
        try:
            return bool(ddp.adopt_plan_payload(payload))
        except Exception as e:
            logger.warning("could not carry bucket plan over (%s); keeping "
                           "the fresh plan", e)
            return False
