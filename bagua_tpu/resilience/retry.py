"""Jittered-exponential retry + circuit breaking for service RPCs.

The resilience contract for every out-of-process dependency (the autotune
service, the rendezvous store): a *transient* failure is retried with
jittered exponential backoff; a *persistent* failure trips a circuit
breaker so subsequent calls fail fast instead of stacking timeouts — a
flapping sidecar service must degrade the job to its local defaults, never
hang the gang (the reference's autotune client likewise treats the service
as advisory).

Knobs are env-carried like everything else (``bagua_tpu.env``):
``BAGUA_RPC_RETRIES``, ``BAGUA_RPC_BACKOFF_BASE_S``,
``BAGUA_RPC_BACKOFF_MAX_S``, ``BAGUA_RPC_BREAKER_THRESHOLD``,
``BAGUA_RPC_BREAKER_COOLDOWN_S``.
"""

import logging
import random
import threading
import time
from typing import Callable, Optional, Tuple, Type

logger = logging.getLogger(__name__)

__all__ = ["CircuitOpenError", "CircuitBreaker", "RetryPolicy", "retry_call"]


class CircuitOpenError(ConnectionError):
    """Raised (fast, no I/O) while a circuit breaker is open."""


class CircuitBreaker:
    """Consecutive-failure circuit breaker (thread-safe).

    CLOSED: calls pass through; ``failure_threshold`` consecutive failures
    open the circuit.  OPEN: :meth:`before_call` raises
    :class:`CircuitOpenError` immediately.  After ``cooldown_s`` the next
    call is admitted as a half-open probe — its success closes the circuit,
    its failure re-opens it for another cooldown.  ``failure_threshold <= 0``
    disables the breaker entirely.
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        cooldown_s: float = 30.0,
        name: str = "rpc",
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._probing = False
        self.times_opened = 0

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if self._clock() - self._opened_at >= self.cooldown_s:
                return "half-open"
            return "open"

    def before_call(self) -> None:
        """Gate one call attempt; raises :class:`CircuitOpenError` while
        open.  In the half-open window exactly one probe is admitted at a
        time (concurrent callers keep failing fast until it resolves)."""
        if self.failure_threshold <= 0:
            return
        with self._lock:
            if self._opened_at is None:
                return
            if self._clock() - self._opened_at < self.cooldown_s or self._probing:
                raise CircuitOpenError(
                    f"{self.name} circuit open "
                    f"({self._consecutive_failures} consecutive failures); "
                    f"failing fast for {self.cooldown_s}s cooldowns"
                )
            self._probing = True  # half-open: admit this caller as the probe

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._opened_at = None
            self._probing = False

    def record_failure(self) -> None:
        if self.failure_threshold <= 0:
            return
        with self._lock:
            self._consecutive_failures += 1
            was_open = self._opened_at is not None
            if self._probing or self._consecutive_failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._probing = False
                if not was_open or self._consecutive_failures == self.failure_threshold:
                    self.times_opened += 1
                    logger.warning(
                        "%s circuit OPEN after %d consecutive failures; "
                        "degrading to local defaults for %.1fs",
                        self.name, self._consecutive_failures, self.cooldown_s,
                    )


class RetryPolicy:
    """Jittered exponential backoff: attempt ``i`` (0-based) sleeps
    ``uniform(0, min(max_s, base_s * 2**i))`` before retrying — full jitter,
    so a gang of workers retrying a recovering service doesn't stampede it
    in lockstep."""

    def __init__(
        self,
        retries: Optional[int] = None,
        base_s: Optional[float] = None,
        max_s: Optional[float] = None,
        seed: Optional[int] = None,
    ):
        from bagua_tpu.env import (
            get_rpc_backoff_base_s, get_rpc_backoff_max_s, get_rpc_retries,
        )

        self.retries = get_rpc_retries() if retries is None else retries
        self.base_s = get_rpc_backoff_base_s() if base_s is None else base_s
        self.max_s = get_rpc_backoff_max_s() if max_s is None else max_s
        self._rng = random.Random(seed)

    def backoff_s(self, attempt: int) -> float:
        return self._rng.uniform(0.0, min(self.max_s, self.base_s * (2 ** attempt)))


def retry_call(
    fn: Callable,
    *args,
    policy: Optional[RetryPolicy] = None,
    breaker: Optional[CircuitBreaker] = None,
    retry_on: Tuple[Type[BaseException], ...] = (OSError, ConnectionError),
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)`` under the retry policy + breaker.

    :class:`CircuitOpenError` from the breaker is never retried (the whole
    point is to fail fast); any other ``retry_on`` exception is retried up
    to ``policy.retries`` times with jittered backoff, and every outcome is
    reported to the breaker so persistent flapping opens the circuit."""
    policy = policy or RetryPolicy()
    last: Optional[BaseException] = None
    for attempt in range(policy.retries + 1):
        if breaker is not None:
            breaker.before_call()  # raises CircuitOpenError while open
        try:
            out = fn(*args, **kwargs)
        except retry_on as e:
            if breaker is not None:
                breaker.record_failure()
            last = e
            if attempt >= policy.retries:
                break
            delay = policy.backoff_s(attempt)
            if on_retry is not None:
                on_retry(attempt, e)
            logger.debug(
                "retry %d/%d after %s (backoff %.3fs)",
                attempt + 1, policy.retries, e, delay,
            )
            sleep(delay)
        else:
            if breaker is not None:
                breaker.record_success()
            return out
    assert last is not None
    raise last
