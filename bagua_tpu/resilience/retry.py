"""Jittered-exponential retry + circuit breaking for service RPCs.

The resilience contract for every out-of-process dependency (the autotune
service, the rendezvous store): a *transient* failure is retried with
jittered exponential backoff; a *persistent* failure trips a circuit
breaker so subsequent calls fail fast instead of stacking timeouts — a
flapping sidecar service must degrade the job to its local defaults, never
hang the gang (the reference's autotune client likewise treats the service
as advisory).

Knobs are env-carried like everything else (``bagua_tpu.env``):
``BAGUA_RPC_RETRIES``, ``BAGUA_RPC_BACKOFF_BASE_S``,
``BAGUA_RPC_BACKOFF_MAX_S``, ``BAGUA_RPC_BREAKER_THRESHOLD``,
``BAGUA_RPC_BREAKER_COOLDOWN_S``.
"""

import logging
import random
import threading
import time
from typing import Callable, Optional, Tuple, Type

logger = logging.getLogger(__name__)

__all__ = [
    "CircuitOpenError",
    "BackpressureError",
    "retry_after_hint",
    "CircuitBreaker",
    "RetryPolicy",
    "retry_call",
    "seed_backoff",
    "set_retry_observer",
    "get_retry_observer",
]

#: process-wide backoff jitter source.  Every seedless :class:`RetryPolicy`
#: draws from this one RNG (instead of re-seeding a private ``Random`` per
#: call site), so a test can pin the whole process's retry timing with one
#: :func:`seed_backoff` call.  An explicit ``RetryPolicy(seed=...)`` still
#: gets its own isolated stream.
_backoff_rng = random.Random()


def seed_backoff(seed: Optional[int]) -> None:
    """Re-seed the process-wide backoff jitter RNG (deterministic retry
    timing for tests; ``None`` re-seeds from the OS)."""
    _backoff_rng.seed(seed)


#: process-wide retry observer: ``observer(endpoint, attempt, delay_s,
#: reason, retry_after_s)`` called on every backoff sleep.  The Telemetry
#: hub installs one so retry sleeps — otherwise invisible dead time — land
#: in the metrics/event stream; None (default) keeps retry_call silent.
_retry_observer: Optional[Callable] = None


def set_retry_observer(observer: Optional[Callable]) -> None:
    global _retry_observer
    _retry_observer = observer


def get_retry_observer() -> Optional[Callable]:
    return _retry_observer


class CircuitOpenError(ConnectionError):
    """Raised (fast, no I/O) while a circuit breaker is open."""


class BackpressureError(ConnectionError):
    """The server answered 429: alive but shedding load.

    Carries the server-supplied ``Retry-After`` hint so :func:`retry_call`
    can pace itself to the server's recovery estimate.  Deliberately *not*
    a breaker-counted failure — a 429 proves the service is up, and opening
    the circuit on it would turn transient overload into a full outage for
    this client."""

    def __init__(self, message: str = "backpressure", retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = max(0.0, float(retry_after_s))


def retry_after_hint(exc: BaseException) -> Optional[float]:
    """Extract a server-supplied backpressure hint from an exception.

    Returns the Retry-After delay in seconds, or None when the exception
    carries no backpressure signal.  Understands :class:`BackpressureError`
    (``retry_after_s`` attribute) and raw ``urllib.error.HTTPError`` 429s
    (``Retry-After`` header; absent/garbled headers degrade to 0.0 — still
    backpressure, just no pacing hint)."""
    hint = getattr(exc, "retry_after_s", None)
    if hint is not None:
        try:
            return max(0.0, float(hint))
        except (TypeError, ValueError):
            return 0.0
    if getattr(exc, "code", None) == 429:
        headers = getattr(exc, "headers", None)
        raw = headers.get("Retry-After") if headers is not None else None
        try:
            return max(0.0, float(raw))
        except (TypeError, ValueError):
            return 0.0
    return None


class CircuitBreaker:
    """Consecutive-failure circuit breaker (thread-safe).

    CLOSED: calls pass through; ``failure_threshold`` consecutive failures
    open the circuit.  OPEN: :meth:`before_call` raises
    :class:`CircuitOpenError` immediately.  After ``cooldown_s`` the next
    call is admitted as a half-open probe — its success closes the circuit,
    its failure re-opens it for another cooldown.  ``failure_threshold <= 0``
    disables the breaker entirely.

    ``listener`` (or :meth:`bagua_tpu.observability.telemetry.Telemetry.bind_breaker`)
    receives ``(name, old_state, new_state)`` on every evented transition —
    closed→open, open→half-open (probe admission), half-open→closed,
    half-open→open — fired outside the breaker lock.
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        cooldown_s: float = 30.0,
        name: str = "rpc",
        clock: Callable[[], float] = time.monotonic,
        listener: Optional[Callable[[str, str, str], None]] = None,
    ):
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.name = name
        self.listener = listener
        self._clock = clock
        self._lock = threading.Lock()
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._probing = False
        self.times_opened = 0

    def _state_locked(self) -> str:
        if self._opened_at is None:
            return "closed"
        if self._probing or self._clock() - self._opened_at >= self.cooldown_s:
            return "half-open"
        return "open"

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _notify(self, old_state: str, new_state: str) -> None:
        # Called with the lock released; a listener that RPCs or logs must
        # never be able to deadlock the breaker or its callers.
        if self.listener is None or old_state == new_state:
            return
        try:
            self.listener(self.name, old_state, new_state)
        except Exception:
            logger.exception("breaker %s transition listener failed", self.name)

    def before_call(self) -> None:
        """Gate one call attempt; raises :class:`CircuitOpenError` while
        open.  In the half-open window exactly one probe is admitted at a
        time (concurrent callers keep failing fast until it resolves)."""
        if self.failure_threshold <= 0:
            return
        with self._lock:
            if self._opened_at is None:
                return
            if self._clock() - self._opened_at < self.cooldown_s or self._probing:
                raise CircuitOpenError(
                    f"{self.name} circuit open "
                    f"({self._consecutive_failures} consecutive failures); "
                    f"failing fast for {self.cooldown_s}s cooldowns"
                )
            self._probing = True  # half-open: admit this caller as the probe
        self._notify("open", "half-open")

    def record_success(self) -> None:
        with self._lock:
            old = self._state_locked()
            self._consecutive_failures = 0
            self._opened_at = None
            self._probing = False
        self._notify(old, "closed")

    def record_failure(self) -> None:
        if self.failure_threshold <= 0:
            return
        notify = None
        with self._lock:
            old = self._state_locked()
            self._consecutive_failures += 1
            was_open = self._opened_at is not None
            if self._probing or self._consecutive_failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._probing = False
                notify = (old, "open")
                if not was_open or self._consecutive_failures == self.failure_threshold:
                    self.times_opened += 1
                    logger.warning(
                        "%s circuit OPEN after %d consecutive failures; "
                        "degrading to local defaults for %.1fs",
                        self.name, self._consecutive_failures, self.cooldown_s,
                    )
        if notify is not None:
            self._notify(*notify)


class RetryPolicy:
    """Jittered exponential backoff: attempt ``i`` (0-based) sleeps
    ``uniform(0, min(max_s, base_s * 2**i))`` before retrying — full jitter,
    so a gang of workers retrying a recovering service doesn't stampede it
    in lockstep."""

    def __init__(
        self,
        retries: Optional[int] = None,
        base_s: Optional[float] = None,
        max_s: Optional[float] = None,
        seed: Optional[int] = None,
    ):
        from bagua_tpu.env import (
            get_rpc_backoff_base_s, get_rpc_backoff_max_s, get_rpc_retries,
        )

        self.retries = get_rpc_retries() if retries is None else retries
        self.base_s = get_rpc_backoff_base_s() if base_s is None else base_s
        self.max_s = get_rpc_backoff_max_s() if max_s is None else max_s
        # seedless policies share the module-level RNG (seed_backoff pins
        # it); an explicit seed keeps a private, isolated stream
        self._rng = _backoff_rng if seed is None else random.Random(seed)

    def backoff_s(self, attempt: int) -> float:
        return self._rng.uniform(0.0, min(self.max_s, self.base_s * (2 ** attempt)))


def retry_call(
    fn: Callable,
    *args,
    policy: Optional[RetryPolicy] = None,
    breaker: Optional[CircuitBreaker] = None,
    retry_on: Tuple[Type[BaseException], ...] = (OSError, ConnectionError),
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    label: str = "rpc",
    **kwargs,
):
    """Call ``fn(*args, **kwargs)`` under the retry policy + breaker.

    :class:`CircuitOpenError` from the breaker is never retried (the whole
    point is to fail fast); any other ``retry_on`` exception is retried up
    to ``policy.retries`` times with jittered backoff, and every outcome is
    reported to the breaker so persistent flapping opens the circuit.

    Server-signalled backpressure (:func:`retry_after_hint` returns a value:
    a :class:`BackpressureError` or a raw HTTP 429) is special-cased: it is
    recorded as a breaker *success* (the server is alive — a 429 must never
    push the circuit open), and the backoff becomes
    ``min(max(hint, jitter), policy.max_s)`` so the client honors the
    server's Retry-After estimate while the cap bounds a hostile hint.

    ``label`` names the endpoint in telemetry: every backoff sleep is
    reported to the process-wide observer (:func:`set_retry_observer`) and
    annotated onto the ambient trace span when tracing is on — both fenced
    so instrumentation can never break a live retry."""
    policy = policy or RetryPolicy()
    last: Optional[BaseException] = None
    for attempt in range(policy.retries + 1):
        if breaker is not None:
            breaker.before_call()  # raises CircuitOpenError while open
        try:
            out = fn(*args, **kwargs)
        except retry_on as e:
            hint = retry_after_hint(e)
            if breaker is not None:
                if hint is None:
                    breaker.record_failure()
                else:
                    breaker.record_success()  # alive, just shedding load
            last = e
            if attempt >= policy.retries:
                break
            delay = policy.backoff_s(attempt)
            if hint is not None:
                delay = min(max(hint, delay), policy.max_s)
            reason = "backpressure" if hint is not None else "error"
            observer = _retry_observer
            if observer is not None:
                try:
                    observer(label, attempt, delay, reason, hint)
                except Exception:
                    logger.exception("retry observer failed for %s", label)
            try:
                from bagua_tpu.observability.tracing import get_global_tracer

                tracer = get_global_tracer()
                sp = tracer.current_span() if tracer is not None else None
                if sp is not None:
                    ann = {"attempt": attempt, "delay_s": round(delay, 4)}
                    if hint is not None:
                        ann["retry_after_s"] = round(hint, 3)
                    sp.annotate(f"retry:{reason}", **ann)
            except Exception:
                logger.exception("retry span annotation failed for %s", label)
            if on_retry is not None:
                on_retry(attempt, e)
            logger.debug(
                "retry %d/%d after %s (backoff %.3fs)",
                attempt + 1, policy.retries, e, delay,
            )
            sleep(delay)
        else:
            if breaker is not None:
                breaker.record_success()
            return out
    assert last is not None
    raise last
