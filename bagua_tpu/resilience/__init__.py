"""Resilience: preemption-aware async checkpointing + elastic resume.

The subsystem that keeps a BAGUA-style job alive on preemptible pools —
see ``docs/elastic.md`` for the operator story.  Four pieces:

* :class:`AsyncSnapshotter` / :class:`SnapshotStore` — double-buffered
  device→host state copies every K steps, off the critical path, with
  atomic (write-temp + rename) manifests;
* :class:`PreemptionWatcher` — SIGTERM → drain the in-flight step, force a
  final snapshot, exit with a resumable marker;
* :class:`ElasticResumeCoordinator` — ranks agree on the newest *complete*
  snapshot, remap into the (possibly resized) gang, carry the tuned bucket
  plan over;
* :func:`retry_call` / :class:`CircuitBreaker` — jittered-exponential
  retries with circuit breaking for the autotune + rendezvous RPCs.
"""

from bagua_tpu.resilience.preemption import (
    RESUMABLE_MARKER,
    PreemptionWatcher,
    clear_resumable_marker,
    read_resumable_marker,
    write_resumable_marker,
)
from bagua_tpu.resilience.resume import ElasticResumeCoordinator, ResumeResult
from bagua_tpu.resilience.retry import (
    CircuitBreaker,
    CircuitOpenError,
    RetryPolicy,
    retry_call,
    seed_backoff,
)
from bagua_tpu.resilience.snapshot import (
    MANIFEST_FILENAME,
    AsyncSnapshotter,
    SnapshotStore,
)

__all__ = [
    "AsyncSnapshotter",
    "SnapshotStore",
    "MANIFEST_FILENAME",
    "PreemptionWatcher",
    "RESUMABLE_MARKER",
    "write_resumable_marker",
    "read_resumable_marker",
    "clear_resumable_marker",
    "ElasticResumeCoordinator",
    "ResumeResult",
    "RetryPolicy",
    "CircuitBreaker",
    "CircuitOpenError",
    "retry_call",
    "seed_backoff",
]
