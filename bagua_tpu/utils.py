"""Small shared utilities.

TPU-native analog of the reference's ``bagua/torch_api/utils.py``: flatten /
unflatten over pytrees of jax arrays (reference uses torch
``_flatten_dense_tensors``, ``utils.py:15-49``), dtype mapping
(``utils.py:81``), and the ``StatisticalAverage`` exponential-window speed
tracker (``utils.py:127-244``) used by the autotune metrics path.
"""

import math
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from bagua_tpu.defs import DType


def to_bagua_datatype(dtype) -> str:
    """Map a jnp dtype to the wire datatype name (reference ``utils.py:81-92``)."""
    d = jnp.dtype(dtype)
    if d == jnp.float32:
        return DType.F32.value
    if d == jnp.float16:
        return DType.F16.value
    if d == jnp.bfloat16:
        return DType.BF16.value
    if d == jnp.uint8:
        return DType.U8.value
    if d == jnp.int32:
        return DType.I32.value
    if d == jnp.int64:
        return DType.I64.value
    raise ValueError(f"unsupported data type {d}")


def from_bagua_datatype(name: str):
    return {
        DType.F32.value: jnp.float32,
        DType.F16.value: jnp.float16,
        DType.BF16.value: jnp.bfloat16,
        DType.U8.value: jnp.uint8,
        DType.I32.value: jnp.int32,
        DType.I64.value: jnp.int64,
    }[name]


def flatten(arrays: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Concatenate arrays into one flat 1-D array (same dtype required)."""
    if len(arrays) == 0:
        return jnp.zeros((0,))
    return jnp.concatenate([a.reshape(-1) for a in arrays])


def unflatten(flat: jnp.ndarray, shapes: Sequence[Tuple[int, ...]]) -> List[jnp.ndarray]:
    """Split a flat array back into arrays of the given shapes."""
    out = []
    offset = 0
    for shape in shapes:
        n = int(np.prod(shape)) if len(shape) else 1
        out.append(flat[offset : offset + n].reshape(shape))
        offset += n
    return out


def check_contiguous(sizes: Sequence[int], total: int) -> bool:
    return sum(sizes) == total


def align_size(numel: int, align: int) -> int:
    """Round ``numel`` up to a multiple of ``align``."""
    return int(math.ceil(numel / align) * align)


class StatisticalAverage:
    """Power-of-two-window running mean of a value (reference ``utils.py:127-244``).

    ``records[i]`` approximates the mean of the recorded value over the last
    ``2**i`` seconds; memory stays O(log T).  ``record(v)`` states that the
    value has been ``v`` since the previous ``record`` call.
    """

    def __init__(
        self,
        last_update_time: Optional[float] = None,
        records: Optional[List[float]] = None,
        tail: float = 0.0,
    ):
        self.last_update_time = last_update_time if last_update_time is not None else time.time()
        self.records: List[float] = list(records) if records else []
        self.tail = tail  # history (seconds) older than the largest window

    def record_seconds(self) -> float:
        return 2.0 ** (len(self.records) - 1) if self.records else 0.0

    def total_recording_time(self) -> float:
        return self.record_seconds() + self.tail

    def get_records_mean(self, last_seconds: float) -> float:
        if last_seconds <= 0 or not self.records:
            return 0.0
        if last_seconds >= self.record_seconds():
            return self.records[-1]
        # Smallest power-of-two window covering last_seconds.
        level = max(0, int(math.ceil(math.log2(max(last_seconds, 1e-9)))))
        level = min(level, len(self.records) - 1)
        return self.records[level]

    def record(self, value: float) -> None:
        now = time.time()
        elapsed = max(now - self.last_update_time, 1e-9)
        total_time = min(elapsed + self.total_recording_time(), 2.0 ** 48)
        new_records: List[float] = []
        level = 0
        while True:
            span = 2.0 ** level
            # Only keep windows no larger than the actual history, so
            # record_seconds() never overclaims (level 0 always kept).
            if level > 0 and span > total_time:
                break
            if span <= elapsed:
                new_records.append(value)
            else:
                old = self.get_records_mean(span - elapsed)
                new_records.append((value * elapsed + old * (span - elapsed)) / span)
            if level >= 48:
                break
            level += 1
        self.records = new_records
        self.tail = max(0.0, total_time - self.record_seconds())
        self.last_update_time = now

    def get(self, last_seconds: float) -> float:
        elapsed = time.time() - self.last_update_time
        return self.get_records_mean(last_seconds + elapsed)

    def __str__(self) -> str:
        return f"StatisticalAverage(records={self.records})"


class SpeedMeter:
    """Units/sec meter over a sliding time window of (timestamp, total) pairs."""

    def __init__(self, window_seconds: float = 300.0):
        from collections import deque

        self._window = window_seconds
        self._events = deque()  # (timestamp, amount)
        self._start: Optional[float] = None

    def record(self, amount: float) -> None:
        now = time.time()
        if self._start is None:
            self._start = now
        self._events.append((now, amount))
        while self._events and now - self._events[0][0] > self._window:
            self._events.popleft()

    def speed(self, last_seconds: float = 60.0) -> float:
        if not self._events:
            return 0.0
        now = time.time()
        cutoff = now - last_seconds
        amount = sum(a for t, a in self._events if t >= cutoff)
        # If history is shorter than the window, normalize by actual elapsed time.
        span = min(last_seconds, max(now - self._start, 1e-9))
        return amount / span


def pytree_num_bytes(tree) -> int:
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    return sum(leaf.size * leaf.dtype.itemsize for leaf in leaves)
