"""Gaussian-process Bayesian optimizer over a small discrete space.

Replaces the reference's scikit-optimize dependency
(``service/bayesian_optimizer.py:34-57``: skopt.Optimizer over
``bucket_size_2p ∈ [10, 31]`` × ``is_hierarchical_reduce ∈ {0,1}``).  The
space is tiny (≤ a few dozen points), so the acquisition (expected
improvement) is maximized exhaustively over the grid; the GP itself is a
plain numpy RBF-kernel regression.
"""

import dataclasses
import itertools
import math
from typing import Dict, List, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class IntParam:
    name: str
    low: int
    high: int  # inclusive

    def grid(self) -> List[int]:
        return list(range(self.low, self.high + 1))


@dataclasses.dataclass(frozen=True)
class BoolParam:
    name: str

    def grid(self) -> List[int]:
        return [0, 1]


class BayesianOptimizer:
    """ask/tell optimizer maximizing score over the parameter grid."""

    def __init__(self, params: Sequence, n_initial_points: int = 5, seed: int = 0):
        self.params = list(params)
        self.rng = np.random.RandomState(seed)
        self.n_initial_points = n_initial_points
        self._grid = np.array(
            list(itertools.product(*[p.grid() for p in self.params])), dtype=np.float64
        )
        self._scales = self._grid.max(axis=0) - self._grid.min(axis=0)
        self._scales[self._scales == 0] = 1.0
        self.xs: List[np.ndarray] = []
        self.ys: List[float] = []
        # Initial sampling walks the seeded draw sequence *deduplicated*
        # (first-appearance order of with-replacement draws, then whatever
        # the draws missed): deterministic under ``seed`` and free of
        # duplicate proposals — each re-proposed point wastes a recompile on
        # the client, which re-jits for a plan it already measured.
        self._initial_order = self._dedup_draw_order()
        self._initial_idx = 0
        # Warm-start queue: externally ranked proposals (the trace-driven
        # planner's top-k) served before the cold permutation walk.
        self._pending: List[np.ndarray] = []

    def _dedup_draw_order(self) -> np.ndarray:
        n = len(self._grid)
        draws = self.rng.randint(n, size=4 * n)  # coupon-collector headroom
        seen = set()
        order = []
        for i in draws:
            if i not in seen:
                seen.add(int(i))
                order.append(int(i))
        order.extend(i for i in range(n) if i not in seen)
        return np.array(order)

    # -- API ------------------------------------------------------------

    def warm_start(self, param_dicts: Sequence[Dict[str, int]]) -> None:
        """Queue proposals for ``ask`` to serve first, in order — already-told
        points are skipped at ask time, so telling between asks stays safe."""
        for d in param_dicts:
            self._pending.append(
                np.array([float(d.get(p.name, 0)) for p in self.params])
            )

    def _explored(self):
        return {tuple(x) for x in self.xs}

    def ask(self) -> Dict[str, int]:
        explored = self._explored()
        x = None
        while self._pending:
            cand = self._pending.pop(0)
            if tuple(cand) not in explored:
                x = cand
                break
        if x is None and len(self.xs) < self.n_initial_points:
            while self._initial_idx < len(self._initial_order):
                cand = self._grid[self._initial_order[self._initial_idx]]
                self._initial_idx += 1
                if tuple(cand) not in explored:
                    x = cand
                    break
        if x is None:
            # EI needs at least one observation; before any tell, fall back
            # to the head of the deterministic permutation.
            x = self._ask_ei() if self.xs else self._grid[self._initial_order[0]]
        return {p.name: int(v) for p, v in zip(self.params, x)}

    def tell(self, param_dict: Dict[str, int], score: float) -> None:
        x = np.array([float(param_dict[p.name]) for p in self.params])
        self.xs.append(x)
        self.ys.append(float(score))

    def best(self) -> Tuple[Dict[str, int], float]:
        if not self.ys:
            return self.ask(), -math.inf
        i = int(np.argmax(self.ys))
        return (
            {p.name: int(v) for p, v in zip(self.params, self.xs[i])},
            self.ys[i],
        )

    # -- GP internals -----------------------------------------------------

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        # RBF with lengthscale 0.25 in unit-normalized parameter space.
        d = (a[:, None, :] - b[None, :, :]) / self._scales
        return np.exp(-0.5 * np.sum(d * d, axis=-1) / 0.25 ** 2)

    def _ask_ei(self) -> np.ndarray:
        X = np.stack(self.xs)
        y = np.array(self.ys)
        y_mean, y_std = y.mean(), y.std() + 1e-9
        yn = (y - y_mean) / y_std
        K = self._kernel(X, X) + 1e-4 * np.eye(len(X))
        Ks = self._kernel(self._grid, X)
        try:
            L = np.linalg.cholesky(K)
            alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
            v = np.linalg.solve(L, Ks.T)
        except np.linalg.LinAlgError:
            return self._grid[self.rng.randint(len(self._grid))]
        mu = Ks @ alpha
        var = np.clip(1.0 - np.sum(v * v, axis=0), 1e-9, None)
        sigma = np.sqrt(var)
        best = yn.max()
        z = (mu - best) / sigma
        ei = sigma * (z * _norm_cdf(z) + _norm_pdf(z))
        # Never re-propose an explored point unless everything is explored.
        explored = {tuple(x) for x in self.xs}
        order = np.argsort(-ei)
        for i in order:
            if tuple(self._grid[i]) not in explored:
                return self._grid[i]
        return self._grid[order[0]]


def _norm_pdf(z):
    return np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)


def _norm_cdf(z):
    from scipy.special import ndtr

    return ndtr(z)
