"""Trace-driven bucket planner: an analytical partitioner over measured spans.

BAGUA's central claim (arXiv:2107.01499) is that bucket partitioning should
be tuned from *execution telemetry*, not a fixed byte threshold.  The service
already learns tensor-ready order from reported spans; this module closes
the loop analytically, T3-style (arXiv:2401.16677: schedule collectives
against the measured compute timeline):

* **Inputs** — per-tensor cotangent arrival times (seconds into the
  backward pass, from ``DistributedDataParallel.profile_bucket_order``'s
  single-probe capture) and per-bucket measured wire timings / hidden
  fractions (from ``observability.trace_analysis`` rows, shipped as
  ``bucket_wire`` spans).
* **Cost model** — an α–β fit per wire path (latency + bytes/bandwidth);
  hierarchical reduction is modeled as two legs (intra-axis psum + inter-axis
  exchange over ``bytes/intra_size``) fitted separately from leg-tagged
  samples.
* **Solver** — dynamic programming over *contiguous* partitions of the
  arrival-ordered tensor timeline, minimizing predicted **exposed**
  (un-hidden) communication time.  Buckets stay dtype-homogeneous
  (``BucketPlan.from_declarations`` rejects mixed dtypes) and a
  ``max_bucket_bytes`` cap can constrain the partition so the Bayesian
  optimizer's ``bucket_size_2p`` dimension keeps meaning.

The exposed-time model: collectives serialize on the wire; bucket *b* may
start once its last tensor has arrived and the previous collective finished,
so with arrival-sorted buckets::

    finish_b = max(finish_{b-1}, ready_b) + wire_time(bytes_b)
    tail     = max(0, finish_last - backward_end)

``tail`` is what XLA's latency-hiding scheduler cannot hide.  A measured
``overlap_efficiency`` η ∈ [0, 1] (aggregate ``measured_overlap_frac`` from
the device trace) calibrates how much of the in-backward wire time the
backend actually hides::

    predicted_exposed = η · tail + (1 − η) · total_wire

η = 1 (default) trusts the scheduler fully — minimize the tail; η = 0 models
a backend that serializes everything — minimize total wire (fewest launches).
The DP tracks a Pareto frontier over (cost, finish) per prefix, so the
returned partition is optimal for this objective, not just greedy.

``holds_bucketized_state`` algorithms cannot re-bucket mid-training
(``DistributedDataParallel.rebucket`` raises); callers gate on that before
adopting a plan — the :class:`~bagua_tpu.ddp.AutotuneSession` already does.
"""

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from bagua_tpu.defs import TensorDeclaration, dtype_itemsize

__all__ = [
    "WireSample",
    "AlphaBeta",
    "CostModel",
    "BucketPlanner",
    "PlanResult",
    "fit_alpha_beta",
    "quantized_hop_bytes",
]


@dataclasses.dataclass(frozen=True)
class WireSample:
    """One measured collective: ``nbytes`` on the wire took ``seconds``.

    ``leg`` tags the wire path: ``"flat"`` (single-level exchange),
    ``"intra"`` (hierarchical intra-axis reduce), ``"inter"``
    (hierarchical cross-axis exchange), ``"rs"`` (sharded reduce-scatter,
    the ``zero`` algorithm's in-backward leg), ``"ag"`` (the deferred
    parameter all-gather riding the next step's forward), ``"pp"`` (one
    neighbor ``ppermute`` hop of a fused collective-matmul ring — see
    :mod:`bagua_tpu.kernels.collective_matmul`) or ``"qr8"`` / ``"qr4"``
    (one hop of the blockwise-quantized ring — the compressed-payload
    ``ppermute`` plus the fused dequant-reduce-requant kernel, see
    :mod:`bagua_tpu.kernels.quantized_ring`; ``nbytes`` is the hop's
    compressed payload + sidecar).  ``hidden_frac`` is the span's measured
    overlap fraction from the device trace, if attributed.

    ``axis`` tags the named mesh axis the collective rode (``"dp"``,
    ``"tp"``, ...) on named-mesh engines; :meth:`CostModel.from_samples`
    fits one α–β leg per tagged axis so a dp-ring exchange and a tp
    activation exchange are priced on their own links.  ``None`` (legacy
    meshes) keeps the sample on its ``leg`` fit."""

    nbytes: float
    seconds: float
    leg: str = "flat"
    hidden_frac: Optional[float] = None
    axis: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class AlphaBeta:
    """``time(n) = alpha + n / beta`` — launch latency plus bandwidth term."""

    alpha: float  # seconds
    beta: float  # bytes / second
    n_samples: int = 0

    def predict(self, nbytes: float) -> float:
        return self.alpha + max(0.0, nbytes) / self.beta


# Priors used until measurements arrive (v5e-flavored: ~100 µs collective
# launch, ~40 GB/s effective ring bandwidth; the intra leg is ICI-rich, the
# inter leg DCN-ish).  Only the *relative* ranking of partitions matters
# before real samples are reported.
DEFAULT_FLAT = AlphaBeta(alpha=100e-6, beta=40e9)
DEFAULT_INTRA = AlphaBeta(alpha=30e-6, beta=100e9)
DEFAULT_INTER = AlphaBeta(alpha=200e-6, beta=25e9)
# Sharded (ZeRO) legs: a reduce-scatter or all-gather moves (n-1)/n of the
# payload around the ring — half an allreduce's traffic each — so the
# effective bandwidth prior sits above the flat allreduce prior.
DEFAULT_RS = AlphaBeta(alpha=100e-6, beta=80e9)
DEFAULT_AG = AlphaBeta(alpha=100e-6, beta=80e9)
# One ring hop of a fused collective matmul: a single neighbor-to-neighbor
# ppermute over ICI — no reduction tree, no cross-rank synchronization beyond
# the neighbor, so the launch latency prior sits well below a full collective
# and the bandwidth prior at the per-link ICI rate.
DEFAULT_PP = AlphaBeta(alpha=20e-6, beta=90e9)
# One hop of the blockwise-quantized ring: the same neighbor ppermute as pp
# but carrying a compressed payload AND running the fused
# dequant-reduce-requant kernel before the send, so the latency prior sits
# above pp (quantization math per hop) while the bandwidth prior stays near
# the per-link rate.  int4 pays extra nibble pack/unpack arithmetic per byte.
DEFAULT_QR8 = AlphaBeta(alpha=30e-6, beta=90e9)
DEFAULT_QR4 = AlphaBeta(alpha=40e-6, beta=80e9)

#: quantization block size mirrored from
#: :data:`bagua_tpu.kernels.quantized_ring.DEFAULT_BLOCK` — the planner is
#: deliberately jax-free, so it re-states the constant instead of importing
#: the kernel module (parity is pinned by ``tests/test_planner.py``).
QR_BLOCK = 4096


def quantized_hop_bytes(numel: int, n_ranks: int, bits: int, block: int = QR_BLOCK) -> int:
    """Bytes of one quantized-ring hop (compressed shard payload + f32
    min/max sidecar) — the pure-Python mirror of
    :func:`bagua_tpu.kernels.quantized_ring.ring_wire_bytes` divided by its
    ``2 * (n - 1)`` hops, kept import-free so the planner stays device-less."""
    n = int(n_ranks)
    if n <= 1:
        return 0
    shard = -(-(int(numel) // n) // block) * block  # padded shard elems
    nblocks = shard // block
    payload = shard // (1 if bits == 8 else 2)
    return payload + nblocks * 8


def fit_alpha_beta(
    samples: Sequence[WireSample], default: AlphaBeta = DEFAULT_FLAT
) -> AlphaBeta:
    """Least-squares α–β fit over measured (bytes, seconds) pairs.

    Degenerate inputs degrade gracefully: no samples → the prior; all
    samples at one size → keep the prior's α and solve β from the mean;
    a fit with negative α is re-solved through the origin-latency clamp."""
    pts = [(float(s.nbytes), float(s.seconds)) for s in samples if s.seconds > 0]
    if not pts:
        return default
    n = len(pts)
    mean_b = sum(b for b, _ in pts) / n
    mean_t = sum(t for _, t in pts) / n
    var_b = sum((b - mean_b) ** 2 for b, _ in pts) / n
    if var_b <= 0.0:
        # single operating point: attribute the prior's latency, rest is wire
        bw_t = max(mean_t - default.alpha, 1e-9)
        return AlphaBeta(alpha=min(default.alpha, mean_t), beta=max(mean_b / bw_t, 1e3), n_samples=n)
    cov = sum((b - mean_b) * (t - mean_t) for b, t in pts) / n
    inv_beta = cov / var_b
    alpha = mean_t - inv_beta * mean_b
    if inv_beta <= 0.0:
        # bandwidth term indistinguishable from noise: pure-latency model
        return AlphaBeta(alpha=max(mean_t, 1e-9), beta=default.beta, n_samples=n)
    if alpha < 0.0:
        alpha, inv_beta = 0.0, mean_t / max(mean_b, 1.0)
    return AlphaBeta(alpha=alpha, beta=1.0 / max(inv_beta, 1e-15), n_samples=n)


class CostModel:
    """Per-wire-path α–β models; hierarchical legs are modeled separately.

    ``bucket_wire_time(nbytes, hierarchical)`` predicts one bucket's
    collective: the flat path is a single exchange; the hierarchical path is
    an intra-axis reduce over the full payload followed by an inter-axis
    exchange over ``nbytes / intra_size`` (each intra group contributes one
    reduced copy to the cross-axis leg).  ``wire_pattern="sharded"`` models
    the ``zero`` algorithm's in-backward leg instead — one reduce-scatter
    per bucket (the deferred all-gather rides the *next* step's forward and
    is priced separately by :meth:`ag_time`, not charged to the backward
    tail this planner minimizes)."""

    def __init__(
        self,
        flat: AlphaBeta = DEFAULT_FLAT,
        intra: AlphaBeta = DEFAULT_INTRA,
        inter: AlphaBeta = DEFAULT_INTER,
        intra_size: int = 1,
        rs: AlphaBeta = DEFAULT_RS,
        ag: AlphaBeta = DEFAULT_AG,
        pp: AlphaBeta = DEFAULT_PP,
        qr8: AlphaBeta = DEFAULT_QR8,
        qr4: AlphaBeta = DEFAULT_QR4,
        axis_legs: Optional[Dict[str, AlphaBeta]] = None,
    ):
        self.flat = flat
        self.intra = intra
        self.inter = inter
        self.intra_size = max(1, int(intra_size))
        self.rs = rs
        self.ag = ag
        self.pp = pp
        self.qr8 = qr8
        self.qr4 = qr4
        #: per-named-mesh-axis α–β legs (``{"dp": ..., "tp": ...}``); a
        #: collective riding exactly one named axis is priced on its axis
        #: leg when one was fitted, the generic ``flat`` leg otherwise.
        self.axis_legs: Dict[str, AlphaBeta] = dict(axis_legs or {})

    def axis_leg(self, axis: str) -> AlphaBeta:
        """The α–β model for a collective riding one named mesh axis —
        the fitted per-axis leg, falling back to ``flat``."""
        return self.axis_legs.get(axis, self.flat)

    @classmethod
    def from_samples(
        cls, samples: Sequence[WireSample], intra_size: int = 1
    ) -> "CostModel":
        by_leg: Dict[str, List[WireSample]] = {}
        by_axis: Dict[str, List[WireSample]] = {}
        for s in samples:
            if getattr(s, "axis", None):
                by_axis.setdefault(s.axis, []).append(s)
            else:
                by_leg.setdefault(s.leg, []).append(s)
        return cls(
            flat=fit_alpha_beta(by_leg.get("flat", []), DEFAULT_FLAT),
            intra=fit_alpha_beta(by_leg.get("intra", []), DEFAULT_INTRA),
            inter=fit_alpha_beta(by_leg.get("inter", []), DEFAULT_INTER),
            intra_size=intra_size,
            rs=fit_alpha_beta(by_leg.get("rs", []), DEFAULT_RS),
            ag=fit_alpha_beta(by_leg.get("ag", []), DEFAULT_AG),
            pp=fit_alpha_beta(by_leg.get("pp", []), DEFAULT_PP),
            qr8=fit_alpha_beta(by_leg.get("qr8", []), DEFAULT_QR8),
            qr4=fit_alpha_beta(by_leg.get("qr4", []), DEFAULT_QR4),
            axis_legs={
                ax: fit_alpha_beta(ss, DEFAULT_FLAT)
                for ax, ss in by_axis.items()
            },
        )

    def bucket_wire_time(
        self,
        nbytes: float,
        hierarchical: bool = False,
        wire_pattern: str = "allreduce",
    ) -> float:
        if wire_pattern == "sharded":
            return self.rs.predict(nbytes)
        if hierarchical:
            return self.intra.predict(nbytes) + self.inter.predict(
                nbytes / self.intra_size
            )
        return self.flat.predict(nbytes)

    def ag_time(self, nbytes: float) -> float:
        """Predicted time of the deferred parameter all-gather for one
        bucket's full payload (the sharded pattern's second leg)."""
        return self.ag.predict(nbytes)

    def quantized_ring_wire_time(
        self, numel: int, n_ranks: int, precision: str, block: int = QR_BLOCK
    ) -> float:
        """Predicted wire time of one bucket's blockwise-quantized ring
        allreduce (:func:`~bagua_tpu.kernels.quantized_ring.quantized_ring_allreduce`)
        over ``n_ranks``: ``2 * (n - 1)`` sequential hops (reduce-scatter then
        all-gather), each a neighbor exchange of the compressed shard priced
        through the fitted ``qr8`` / ``qr4`` leg."""
        leg = {"int8": self.qr8, "qr8": self.qr8, "int4": self.qr4, "qr4": self.qr4}[
            precision
        ]
        n = int(n_ranks)
        if n <= 1:
            return 0.0
        bits = 8 if leg is self.qr8 else 4
        hop = quantized_hop_bytes(numel, n, bits, block)
        return 2 * (n - 1) * leg.predict(hop)

    def ring_matmul_wire_time(self, nbytes: float, ring_size: int) -> float:
        """Total wire time of one fused collective-matmul ring
        (:func:`~bagua_tpu.kernels.collective_matmul.ag_matmul` /
        :func:`~bagua_tpu.kernels.collective_matmul.matmul_rs` over a
        ``ring_size`` axis): ``ring_size - 1`` neighbor ``ppermute`` hops,
        each carrying the per-rank shard (``nbytes / ring_size``).  This is
        the quantity the ring can hide under tile compute — compare it
        against ``flat.predict(nbytes)`` (the exposed psum it replaces) to
        decide whether fusing pays at a given payload size."""
        n = int(ring_size)
        if n <= 1:
            return 0.0
        return (n - 1) * self.pp.predict(nbytes / n)

    def describe(self) -> Dict:
        named = tuple(
            (f"axis:{ax}", m) for ax, m in sorted(self.axis_legs.items())
        )
        return {
            leg: {
                "alpha_us": round(m.alpha * 1e6, 3),
                "beta_gbps": round(m.beta / 1e9, 3),
                "n_samples": m.n_samples,
            }
            for leg, m in (
                ("flat", self.flat),
                ("intra", self.intra),
                ("inter", self.inter),
                ("rs", self.rs),
                ("ag", self.ag),
                ("pp", self.pp),
                ("qr8", self.qr8),
                ("qr4", self.qr4),
            ) + named
        }


@dataclasses.dataclass
class PlanResult:
    """A proposed partition plus its predicted timeline."""

    buckets: List[List[TensorDeclaration]]
    predicted_exposed_s: float
    predicted_tail_s: float
    total_wire_s: float
    n_buckets: int
    per_bucket: List[Dict]

    def summary(self) -> Dict:
        return {
            "n_buckets": self.n_buckets,
            "predicted_exposed_ms": round(self.predicted_exposed_s * 1e3, 4),
            "predicted_tail_ms": round(self.predicted_tail_s * 1e3, 4),
            "total_wire_ms": round(self.total_wire_s * 1e3, 4),
        }


def _decl_bytes(td: TensorDeclaration) -> int:
    return td.num_elements * dtype_itemsize(td.dtype)


class BucketPlanner:
    """DP bucket partitioner over the measured cotangent-arrival timeline.

    Args:
        declarations: communicable tensors (the registered tensor list).
        arrivals: ``{tensor_name: arrival_seconds}`` — when each cotangent
            becomes available in the backward pass.  Tensors without a
            measurement are conservatively placed at the latest arrival.
        cost_model: fitted :class:`CostModel` (default: priors only).
        overlap_efficiency: η calibration from the measured aggregate
            overlap fraction (see module docstring); clamped to [0, 1].
        wire_pattern: ``"allreduce"`` (default) or ``"sharded"`` — which
            per-bucket collective the cost model prices (the ``zero``
            algorithm's in-backward leg is a reduce-scatter).
    """

    def __init__(
        self,
        declarations: Sequence[TensorDeclaration],
        arrivals: Dict[str, float],
        cost_model: Optional[CostModel] = None,
        overlap_efficiency: float = 1.0,
        wire_pattern: str = "allreduce",
    ):
        self.declarations = list(declarations)
        self.cost_model = cost_model or CostModel()
        self.eta = min(1.0, max(0.0, float(overlap_efficiency)))
        self.wire_pattern = wire_pattern
        latest = max(arrivals.values(), default=0.0)
        self.arrivals = {
            td.name: float(arrivals.get(td.name, latest)) for td in self.declarations
        }
        # arrival-ordered timeline (stable on ties by declaration order)
        self.timeline: List[TensorDeclaration] = [
            td
            for _, td in sorted(
                enumerate(self.declarations),
                key=lambda it: (self.arrivals[it[1].name], it[0]),
            )
        ]
        self.compute_end = max(self.arrivals.values(), default=0.0)

    # -- evaluation ----------------------------------------------------------

    def evaluate(
        self, buckets: Sequence[Sequence[TensorDeclaration]], hierarchical: bool = False
    ) -> PlanResult:
        """Predicted exposed time of an *arbitrary* partition (it need not be
        contiguous on the arrival timeline — the seed greedy byte-threshold
        plan is evaluated through this same simulator, so DP-vs-greedy
        comparisons share one clock)."""
        rows = []
        for bi, bucket in enumerate(buckets):
            nbytes = sum(_decl_bytes(td) for td in bucket)
            ready = max((self.arrivals.get(td.name, self.compute_end) for td in bucket), default=0.0)
            rows.append({"bucket": bi, "nbytes": nbytes, "ready_s": ready})
        rows.sort(key=lambda r: r["ready_s"])
        t = 0.0
        total_wire = 0.0
        for r in rows:
            w = self.cost_model.bucket_wire_time(
                r["nbytes"], hierarchical, wire_pattern=self.wire_pattern
            )
            start = max(t, r["ready_s"])
            t = start + w
            total_wire += w
            r.update(
                {
                    "wire_s": round(w, 9),
                    "start_s": round(start, 9),
                    "finish_s": round(t, 9),
                }
            )
        tail = max(0.0, t - self.compute_end)
        exposed = self.eta * tail + (1.0 - self.eta) * total_wire
        return PlanResult(
            buckets=[list(b) for b in buckets],
            predicted_exposed_s=exposed,
            predicted_tail_s=tail,
            total_wire_s=total_wire,
            n_buckets=len(rows),
            per_bucket=rows,
        )

    # -- the DP solver -------------------------------------------------------

    def plan(
        self,
        max_bucket_bytes: Optional[int] = None,
        hierarchical: bool = False,
    ) -> PlanResult:
        """Optimal contiguous partition of the arrival timeline.

        Pareto DP: state per prefix is a frontier of (cost, finish) pairs —
        a prefix finishing later may still enable a cheaper total when η < 1,
        so a scalar DP would be lossy.  Buckets never span a dtype boundary
        and respect ``max_bucket_bytes`` (a single oversized tensor still
        gets its own bucket — the cap bounds fusion, not tensors)."""
        items = self.timeline
        n = len(items)
        if n == 0:
            return self.evaluate([])
        arr = [self.arrivals[td.name] for td in items]
        nbytes = [_decl_bytes(td) for td in items]
        t_end = self.compute_end
        eta = self.eta
        # frontier[j]: list of (cost, finish, i, parent_state) for prefix j
        frontier: List[List[Tuple[float, float, int, int]]] = [[] for _ in range(n + 1)]
        frontier[0] = [(0.0, 0.0, -1, -1)]
        for j in range(1, n + 1):
            cands: List[Tuple[float, float, int, int]] = []
            size = 0
            dtype = items[j - 1].dtype
            for i in range(j - 1, -1, -1):
                if items[i].dtype != dtype:
                    break  # dtype-homogeneous buckets only
                size += nbytes[i]
                if max_bucket_bytes and size > max_bucket_bytes and i < j - 1:
                    break  # cap bounds fusion; singletons are always feasible
                ready = arr[j - 1]  # arrival-sorted: last tensor arrives last
                w = self.cost_model.bucket_wire_time(
                    size, hierarchical, wire_pattern=self.wire_pattern
                )
                for si, (cost_i, fin_i, _, _) in enumerate(frontier[i]):
                    fin = max(fin_i, ready) + w
                    # tail increment telescopes to max(fin_n, T) - T
                    inc = eta * (max(fin, t_end) - max(fin_i, t_end)) + (1.0 - eta) * w
                    cands.append((cost_i + inc, fin, i, si))
            # Pareto-prune: keep states no other state beats on both axes
            cands.sort(key=lambda c: (c[0], c[1]))
            kept: List[Tuple[float, float, int, int]] = []
            best_fin = float("inf")
            for c in cands:
                if c[1] < best_fin - 1e-12:
                    kept.append(c)
                    best_fin = c[1]
            frontier[j] = kept
        # reconstruct from the min-cost final state (tiebreak: earliest finish)
        state = min(frontier[n], key=lambda c: (c[0], c[1]))
        cuts = []
        j = n
        while j > 0:
            _, _, i, si = state
            cuts.append((i, j))
            state = frontier[i][si] if i > 0 else frontier[0][0]
            j = i
        cuts.reverse()
        buckets = [[items[k] for k in range(i, j)] for i, j in cuts]
        return self.evaluate(buckets, hierarchical)

    # -- per-bucket wire precision (the quantized-ring chooser) --------------

    #: dtypes the quantized ring can carry (mirrors the engines' float set)
    QUANTIZABLE_DTYPES = ("f32", "f16", "bf16")

    def plan_precision(
        self,
        buckets: Sequence[Sequence[TensorDeclaration]],
        n_ranks: int,
        allowed: Sequence[str] = ("f32",),
        hierarchical: bool = False,
        block: int = QR_BLOCK,
    ) -> Dict:
        """Choose a wire precision per bucket, gated by a convergence
        allow-list.

        For every bucket of an (already chosen) partition, price the exact
        exchange each precision would run — the engine's f32 collective
        (flat / hierarchical / sharded, whatever this planner's
        ``wire_pattern`` says) against the blockwise-quantized ring through
        the fitted ``qr8`` / ``qr4`` legs — and pick the cheapest precision
        **from the allow-list**.  ``allowed`` is the convergence guardrail:
        only precisions that passed the loss-parity gate
        (``ci/perf_audit.py`` ``--wire`` lane) may be chosen; everything else
        is still priced and recorded as ``blocked`` so the decision trail
        shows what the guardrail cost.  ``"f32"`` is always implicitly
        allowed — exact exchange needs no parity evidence.

        Non-float buckets and degenerate rings (``n_ranks < 2``) stay f32,
        matching the engines' own resolution rules.  Returns a JSON-ready
        record: ``precisions`` (the adoptable per-bucket plan, in bucket
        order) plus per-bucket candidate timings and aggregate savings."""
        n = int(n_ranks)
        allow = {"f32"} | {p for p in allowed if p != "f32"}
        unknown = allow - {"f32", "int8", "int4"}
        if unknown:
            raise ValueError(f"unknown wire precisions in allow-list: {sorted(unknown)}")
        rows: List[Dict] = []
        precisions: List[str] = []
        total_f32 = total_chosen = 0.0
        for bi, bucket in enumerate(buckets):
            nbytes = sum(_decl_bytes(td) for td in bucket)
            numel = sum(td.num_elements for td in bucket)
            dtypes = {td.dtype for td in bucket}
            f32_time = self.cost_model.bucket_wire_time(
                nbytes, hierarchical, wire_pattern=self.wire_pattern
            )
            cand = {"f32": f32_time}
            quantizable = (
                n >= 2 and dtypes and dtypes <= set(self.QUANTIZABLE_DTYPES)
            )
            if quantizable:
                for prec in ("int8", "int4"):
                    ring = self.cost_model.quantized_ring_wire_time(
                        numel, n, prec, block
                    )
                    if self.wire_pattern == "sharded":
                        # zero's gradient leg is the reduce-scatter half of
                        # the ring (n-1 of the 2(n-1) hops); the deferred
                        # param all-gather stays f32 regardless of precision
                        t = ring / 2.0
                    elif hierarchical:
                        # exact f32 sum intra-node, quantized ring inter-node
                        t = self.cost_model.intra.predict(nbytes)
                        t += self.cost_model.quantized_ring_wire_time(
                            numel, max(1, n // self.cost_model.intra_size), prec, block
                        )
                    else:
                        t = ring
                    cand[prec] = t
            chosen = min(
                (p for p in cand if p in allow), key=lambda p: (cand[p], p)
            )
            precisions.append(chosen)
            total_f32 += f32_time
            total_chosen += cand[chosen]
            rows.append(
                {
                    "bucket": bi,
                    "nbytes": nbytes,
                    "numel": numel,
                    "dtype": sorted(dtypes)[0] if len(dtypes) == 1 else sorted(dtypes),
                    "candidate_us": {p: round(t * 1e6, 3) for p, t in cand.items()},
                    "chosen": chosen,
                    "blocked": sorted(
                        p for p in cand if p not in allow and cand[p] < cand[chosen]
                    ),
                }
            )
        return {
            "allow_list": sorted(allow),
            "n_ranks": n,
            "wire_pattern": self.wire_pattern,
            "hierarchical": bool(hierarchical),
            "precisions": precisions,
            "per_bucket": rows,
            "total_wire_ms_f32": round(total_f32 * 1e3, 4),
            "total_wire_ms": round(total_chosen * 1e3, 4),
            "saved_frac": round(1.0 - total_chosen / total_f32, 4) if total_f32 else 0.0,
        }

    # -- candidate ranking (warm-start input) --------------------------------

    def rank_caps(
        self,
        caps_2p: Iterable[int],
        hierarchical_options: Sequence[bool] = (False, True),
    ) -> List[Dict]:
        """Predicted cost of the DP plan at each ``2**p`` bucket-size cap ×
        hierarchical setting, best first — the planner's top-k proposals for
        warm-starting the Bayesian optimizer."""
        out = []
        for p in caps_2p:
            for hier in hierarchical_options:
                res = self.plan(max_bucket_bytes=1 << int(p), hierarchical=bool(hier))
                out.append(
                    {
                        "bucket_size_2p": int(p),
                        "is_hierarchical_reduce": int(bool(hier)),
                        **res.summary(),
                    }
                )
        out.sort(key=lambda c: c["predicted_exposed_ms"])
        return out
