"""The autotune HTTP service (rank-0 hosted).

Analog of the reference's Flask app (``service/autotune_service.py:154-298``)
on the stdlib ``ThreadingHTTPServer``.  Endpoints (same paths):

    POST /api/v1/register_tensors
    POST /api/v1/report_metrics
    POST /api/v1/ask_hyperparameters
    POST /api/v1/report_tensor_execution_order
    GET  /api/v1/health_check

Gating mirrors the reference: no tuning during the warmup window, at most one
sample per ``sampling_confidence_time``, and after ``max_samples`` the service
locks to the best observed hyperparameters
(``autotune_service.py:102-152``).
"""

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from bagua_tpu.defs import BaguaHyperparameter, TensorDeclaration
from bagua_tpu.service.autotune_task_manager import AutotuneTaskManager

logger = logging.getLogger(__name__)

#: POST route → AutotuneService method name; shared with the fleet control
#: plane (``bagua_tpu.fleet.server``), which serves the same API per gang
#: namespace under ``/g/<gang_id>/api/v1/...``.
AUTOTUNE_POST_ROUTES = {
    "/api/v1/register_tensors": "register_tensors",
    "/api/v1/report_metrics": "report_metrics",
    "/api/v1/ask_hyperparameters": "ask_hyperparameters",
    "/api/v1/report_tensor_execution_order": "report_tensor_execution_order",
    "/api/v1/planner_trail": "planner_trail",
}


class AutotuneService:
    def __init__(
        self,
        world_size: int,
        autotune_level: int = 0,
        max_samples: int = 60,
        sampling_confidence_time_s: float = 5.0,
        warmup_time_s: float = 30.0,
        is_output_autotune_log: bool = False,
        default_bucket_size: int = 10 * 1024 ** 2,
        tune_wire_dtype: bool = False,
        tune_overlap: bool = False,
    ):
        self.world_size = world_size
        self.autotune_level = autotune_level
        self.max_samples = max_samples
        self.sampling_confidence_time_s = sampling_confidence_time_s
        self.warmup_time_s = warmup_time_s
        self.is_output_autotune_log = is_output_autotune_log
        self.default_bucket_size = default_bucket_size
        self.tune_wire_dtype = tune_wire_dtype
        self.tune_overlap = tune_overlap

        self._lock = threading.Lock()
        self._managers: Dict[str, AutotuneTaskManager] = {}
        self._start_time: Dict[str, float] = {}
        self._last_sample_time: Dict[str, float] = {}
        # per-model, per-rank latest reported speed (averaged when sampling,
        # reference keeps a check board per rank, autotune_service.py:35-45)
        self._speeds: Dict[str, Dict[int, float]] = {}
        # Multi-process plan-change agreement.  Ranks must adopt a new bucket
        # plan at the SAME training step or their collective patterns desync
        # (hang).  The service tracks each rank's LATEST asked train_iter; a
        # sample fires only once every rank has asked, and the proposal
        # becomes *effective from* max(latest asked) + 1 — past the furthest
        # iter any rank has already been answered for, so every rank first
        # sees the new plan at the same (future) ask step, regardless of how
        # far ahead a fast host loop runs.
        self._rank_latest_ask: Dict[str, Dict[int, int]] = {}
        self._hp_effective: Dict[str, list] = {}  # [(effective_from, hp, final)]
        # The hp each gang is *currently running*: workers adopt the hp
        # returned by their latest ask, so the next reported speed was
        # measured under the last *answer*, not the newest proposal (which
        # only becomes effective — and adopted — one ask-round later).
        # Scores must be credited to this, or every sample shifts onto the
        # next point and the optimizer converges beside the optimum.
        self._measured_hp: Dict[str, object] = {}

    def _manager(self, model_name: str) -> AutotuneTaskManager:
        if model_name not in self._managers:
            self._managers[model_name] = AutotuneTaskManager(
                model_name, self.is_output_autotune_log,
                tune_wire_dtype=self.tune_wire_dtype,
                tune_overlap=self.tune_overlap,
            )
            self._start_time[model_name] = time.time()
            self._last_sample_time[model_name] = 0.0
            self._speeds[model_name] = {}
        return self._managers[model_name]

    # -- endpoint logic ------------------------------------------------------

    def register_tensors(self, payload: Dict) -> Dict:
        model_name = payload["model_name"]
        decls = [TensorDeclaration(**td) for td in payload["tensor_list"]]
        with self._lock:
            mgr = self._manager(model_name)
            mgr.tensor_list = decls
            if not mgr.hyperparameter.buckets:
                mgr.hyperparameter = mgr.recommended_from_param_dict(
                    {
                        "bucket_size_2p": max(10, self.default_bucket_size.bit_length() - 1),
                        "is_hierarchical_reduce": 0,
                        # label the pre-tuning samples with the wire dtype /
                        # execution mode they are actually measured under
                        # (the client may have preconfigured bf16 or overlap)
                        "wire_bf16": int(bool(payload.get("current_wire_bf16", False))),
                        "overlap": int(bool(payload.get("current_overlap", False))),
                    }
                )
                mgr.hyperparameter.bucket_size = self.default_bucket_size
            elif mgr.sampling_counter == 0:
                # Re-registration before any GP proposal: the restarted gang
                # may have changed its preconfigured wire dtype / execution
                # mode — refresh the labels so its pre-tuning samples credit
                # the right knob values.
                if self.tune_wire_dtype:
                    mgr.hyperparameter.wire_bf16 = bool(
                        payload.get("current_wire_bf16", False)
                    )
                if self.tune_overlap:
                    mgr.hyperparameter.overlap = bool(
                        payload.get("current_overlap", False)
                    )
            # (Re-)registration = a (re)started gang whose train_iter restarts
            # from 0: reset the per-rank ask ratchet and re-base the
            # effective-from history on the current hyperparameters, or new
            # proposals would only take effect past the pre-restart iteration
            # and speeds would be attributed to never-adopted plans.
            self._rank_latest_ask.pop(model_name, None)
            self._speeds[model_name] = {}
            self._hp_effective[model_name] = [
                (0, mgr.hyperparameter, mgr.sampling_counter >= self.max_samples)
            ]
            self._measured_hp[model_name] = mgr.hyperparameter
            return {"recommended_hyperparameters": mgr.hyperparameter.model_dump()}

    def report_metrics(self, payload: Dict) -> Dict:
        model_name = payload["model_name"]
        rank = int(payload["rank"])
        speed = float(payload["speed"])
        with self._lock:
            self._manager(model_name)
            self._speeds[model_name][rank] = speed
        return {"status": "ok"}

    def _effective_hp(self, model_name: str, train_iter: int, mgr):
        """The hyperparameters in force for asks at ``train_iter`` — the last
        history entry whose effective_from <= train_iter."""
        history = self._hp_effective.setdefault(
            model_name,
            # seed marks final when sampling is already closed (e.g.
            # max_samples=0 disables tuning -> completed from the first ask)
            [(0, mgr.hyperparameter, mgr.sampling_counter >= self.max_samples)],
        )
        current = history[0]
        for entry in history:
            if entry[0] <= train_iter:
                current = entry
        return current  # (effective_from, hp, is_final)

    def ask_hyperparameters(self, payload: Dict) -> Dict:
        model_name = payload["model_name"]
        rank = int(payload.get("rank", 0))
        train_iter = int(payload.get("train_iter", 0))
        with self._lock:
            mgr = self._manager(model_name)
            now = time.time()
            _, hp, is_final = self._effective_hp(model_name, train_iter, mgr)
            if self.autotune_level >= 1 and not is_final:
                latest = self._rank_latest_ask.setdefault(model_name, {})
                latest[rank] = max(latest.get(rank, 0), train_iter)
                in_warmup = now - self._start_time[model_name] < self.warmup_time_s
                confident = (
                    now - self._last_sample_time[model_name]
                    >= self.sampling_confidence_time_s
                )
                speeds = self._speeds[model_name]
                sampling_open = mgr.sampling_counter < self.max_samples
                if (
                    sampling_open
                    and not in_warmup
                    and confident
                    and len(speeds) >= self.world_size
                    and len(latest) >= self.world_size
                ):
                    score = sum(speeds.values()) / len(speeds)
                    mgr.tell_and_ask(
                        score,
                        train_iter,
                        measured_hp=self._measured_hp.get(
                            model_name, mgr.hyperparameter
                        ),
                    )
                    self._last_sample_time[model_name] = now
                    self._speeds[model_name] = {}
                    final = mgr.sampling_counter >= self.max_samples
                    new_hp = mgr.lock_best() if final else mgr.hyperparameter
                    self._hp_effective[model_name].append(
                        (max(latest.values()) + 1, new_hp, final)
                    )
            # whatever we answer is what this gang runs until its next ask —
            # the configuration the next reported speed is measured under
            self._measured_hp[model_name] = hp
            return {
                "recommended_hyperparameters": hp.model_dump(),
                "is_autotune_completed": is_final,
            }

    def report_tensor_execution_order(self, payload: Dict) -> Dict:
        model_name = payload["model_name"]
        with self._lock:
            self._manager(model_name).report_spans(payload.get("spans", []))
        return {"status": "ok"}

    def planner_trail(self, payload: Dict) -> Dict:
        """The trace-driven planner's decision record for one model: mode,
        fitted cost model, ranked candidates, warm-start points, DP-vs-greedy
        predicted costs and the chosen proposal (see
        ``AutotuneTaskManager.decision_trail``)."""
        model_name = payload["model_name"]
        with self._lock:
            return {"trail": self._manager(model_name).decision_trail}

    # -- HTTP plumbing ---------------------------------------------------------

    def make_handler(self):
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # silence
                logger.debug(fmt, *args)

            def _send(self, obj, code=200):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/api/v1/health_check":
                    self._send({"status": "ok"})
                else:
                    self._send({"error": "not found"}, 404)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                try:
                    payload = json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError:
                    self._send({"error": "bad json"}, 400)
                    return
                name = AUTOTUNE_POST_ROUTES.get(self.path)
                fn = getattr(service, name) if name is not None else None
                if fn is None:
                    self._send({"error": "not found"}, 404)
                    return
                try:
                    self._send(fn(payload))
                except Exception as e:  # surface errors to the client
                    logger.exception("autotune endpoint error")
                    self._send({"error": str(e)}, 500)

        return Handler


def start_autotune_server(
    service: AutotuneService, port: int = 0
) -> ThreadingHTTPServer:
    """Start the service in a daemon thread; returns the live server (its
    ``server_address[1]`` is the bound port).  Analog of the reference
    spawning a Flask process from ``init_process_group``
    (``communication.py:384-420``)."""
    # Bind all interfaces: workers on other hosts reach the service at
    # AUTO_TUNE_SERVER_ADDR (the reference's Flask service binds 0.0.0.0 too,
    # ``communication.py:399``).
    server = ThreadingHTTPServer(("0.0.0.0", port), service.make_handler())
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server
