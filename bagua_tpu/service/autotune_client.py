"""Client for the autotune service (reference ``AutotuneClient``,
``service/autotune_service.py:325``) — stdlib urllib, no requests dependency.

Every RPC goes through the resilience retry layer
(:func:`bagua_tpu.resilience.retry.retry_call`): transient connection
failures are retried with jittered exponential backoff
(``BAGUA_RPC_RETRIES`` x ``BAGUA_RPC_BACKOFF_BASE_S``), and persistent ones
trip the client's circuit breaker so a dead service fails fast instead of
stacking 10s timeouts on every tick.  The *caller* (``AutotuneSession``)
additionally degrades to its current local hyperparameters when the failure
surfaces — the service is advisory, never load-bearing.
"""

import json
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from bagua_tpu.defs import BaguaHyperparameter, TensorDeclaration
from bagua_tpu.env import get_bagua_service_port


class AutotuneClient:
    """``prefix`` prepends every route (no trailing slash) — the fleet
    control plane serves each gang's autotune API under
    ``/g/<gang_id>/api/v1/...``, so a fleet-attached client passes
    ``prefix="/g/<gang_id>"`` and everything else is unchanged.  ``timeout``
    defaults to the shared ``BAGUA_RPC_TIMEOUT_S`` knob."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: Optional[int] = None,
        timeout: Optional[float] = None,
        prefix: str = "",
    ):
        from bagua_tpu.env import (
            get_rpc_breaker_cooldown_s, get_rpc_breaker_threshold,
            get_rpc_timeout_s,
        )
        from bagua_tpu.resilience.retry import CircuitBreaker, RetryPolicy

        port = port if port is not None else get_bagua_service_port()
        self.base = f"http://{host}:{port}{prefix}"
        self.timeout = get_rpc_timeout_s() if timeout is None else timeout
        self.retry_policy = RetryPolicy()
        self.breaker = CircuitBreaker(
            failure_threshold=get_rpc_breaker_threshold(),
            cooldown_s=get_rpc_breaker_cooldown_s(),
            name="autotune-rpc",
        )

    def _post_once(self, path: str, payload: Dict) -> Dict:
        from bagua_tpu.observability.tracing import client_span

        with client_span(
            f"rpc {path}", component="autotune", endpoint=path
        ) as (_sp, trace_headers):
            req = urllib.request.Request(
                self.base + path,
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json", **trace_headers},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as e:
                if e.code == 429:
                    from bagua_tpu.resilience.retry import (
                        BackpressureError, retry_after_hint,
                    )

                    raise BackpressureError(
                        f"{self.base + path}: 429 backpressure",
                        retry_after_hint(e) or 0.0,
                    ) from e
                raise

    def _post(self, path: str, payload: Dict) -> Dict:
        from bagua_tpu.resilience.retry import retry_call

        return retry_call(
            self._post_once, path, payload,
            policy=self.retry_policy, breaker=self.breaker, label=path,
        )

    def health_check(self) -> bool:
        try:
            req = urllib.request.Request(self.base + "/api/v1/health_check")
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status == 200
        except (urllib.error.URLError, ConnectionError, OSError):
            return False

    def wait_until_ready(self, max_wait_s: float = 60.0) -> bool:
        deadline = time.time() + max_wait_s
        while time.time() < deadline:
            if self.health_check():
                return True
            time.sleep(0.2)
        return False

    def register_tensors(
        self, model_name: str, tensor_list: List[TensorDeclaration],
        current_wire_bf16: bool = False,
        current_overlap: bool = False,
    ) -> BaguaHyperparameter:
        resp = self._post(
            "/api/v1/register_tensors",
            {
                "model_name": model_name,
                "tensor_list": [td.model_dump() for td in tensor_list],
                # the wire dtype / execution mode the scores will initially
                # be measured under (a tuning service labels its first GP
                # sample with these, instead of assuming f32 / monolithic)
                "current_wire_bf16": bool(current_wire_bf16),
                "current_overlap": bool(current_overlap),
            },
        )
        return BaguaHyperparameter(**resp["recommended_hyperparameters"])

    def report_metrics(
        self, model_name: str, rank: int, train_iter: int, speed: float
    ) -> None:
        self._post(
            "/api/v1/report_metrics",
            {
                "model_name": model_name,
                "rank": rank,
                "train_iter": train_iter,
                "speed": speed,
            },
        )

    def ask_hyperparameters(
        self, model_name: str, rank: int, train_iter: int
    ):
        resp = self._post(
            "/api/v1/ask_hyperparameters",
            {"model_name": model_name, "rank": rank, "train_iter": train_iter},
        )
        return (
            BaguaHyperparameter(**resp["recommended_hyperparameters"]),
            bool(resp["is_autotune_completed"]),
        )

    def report_tensor_execution_order(self, model_name: str, spans: List[Dict]) -> None:
        self._post(
            "/api/v1/report_tensor_execution_order",
            {"model_name": model_name, "spans": spans},
        )

    def get_planner_trail(self, model_name: str) -> Dict:
        """The service-side trace-driven planner's decision record (mode,
        cost model, ranked candidates, warm-start points, chosen plan)."""
        resp = self._post("/api/v1/planner_trail", {"model_name": model_name})
        return resp.get("trail", {})


def get_hyperparameters_service_client() -> AutotuneClient:
    """Build a client pointing at the job's autotune service.

    Resolution order (reference ``env.py:get_autotune_server_addr``):
    ``AUTO_TUNE_SERVER_ADDR`` (``host:port``, exported by the launcher) >
    ``MASTER_ADDR`` + ``BAGUA_SERVICE_PORT`` > localhost + default port —
    so workers on non-master hosts reach the master's service.
    """
    import os

    addr = os.environ.get("AUTO_TUNE_SERVER_ADDR")
    if addr and ":" in addr:
        host, _, port_s = addr.rpartition(":")
        return AutotuneClient(host=host, port=int(port_s))
    return AutotuneClient(host=os.environ.get("MASTER_ADDR", "127.0.0.1"))
