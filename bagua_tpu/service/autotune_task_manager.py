"""Per-model autotune state machine.

Analog of the reference's ``AutotuneServiceTaskManager``
(``service/autotune_task_manager.py``): owns the Bayesian optimizer over
``bucket_size_2p ∈ [10, 31]`` × ``is_hierarchical_reduce``, the greedy
dtype-grouped bucket split, and the tensor re-ordering learned from reported
execution order.

Since the trace-driven planner (``service/planner.py``) landed, reported
spans do more than re-order: ``tensor_ready`` spans carry measured cotangent
arrival times and ``bucket_wire`` spans carry measured per-bucket wire
timings, from which the manager fits an α–β cost model and *warm-starts* the
Bayesian optimizer with the planner's top-k ranked proposals instead of a
cold grid walk (``BAGUA_AUTOTUNE_PLANNER=warmstart``, the default).  In
``"on"`` mode each proposal's bucket assignment is additionally the
planner's DP-optimal contiguous partition under the proposed size cap.  With
no spans reported the planner never activates and everything falls back to
pure BO — measured signal is a strict upgrade, never a requirement.
"""

import logging
import time
from typing import Dict, List, Optional

from bagua_tpu.bucket import split_declarations
from bagua_tpu.defs import BaguaHyperparameter, TensorDeclaration
from bagua_tpu.env import get_autotune_planner_mode
from bagua_tpu.service.bayesian_optimizer import BayesianOptimizer, BoolParam, IntParam
from bagua_tpu.service.planner import BucketPlanner, CostModel, WireSample

logger = logging.getLogger(__name__)


class AutotuneTaskManager:
    def __init__(
        self,
        model_name: str,
        is_output_autotune_log: bool = False,
        tune_wire_dtype: bool = False,
        tune_overlap: bool = False,
        planner_mode: Optional[str] = None,
    ):
        self.model_name = model_name
        self.tensor_list: List[TensorDeclaration] = []
        self.hyperparameter = BaguaHyperparameter()
        self.tune_wire_dtype = tune_wire_dtype
        self.tune_overlap = tune_overlap
        params = [IntParam("bucket_size_2p", 10, 31), BoolParam("is_hierarchical_reduce")]
        if tune_wire_dtype:
            # opt-in third dimension: bf16 wire exchange trades ~3 decimal
            # digits of gradient mantissa for half the allreduce bytes —
            # a numerics-affecting knob, so never explored silently
            params.append(BoolParam("wire_bf16"))
        if tune_overlap:
            # execution-mode dimension: backward-overlapped per-bucket
            # collectives vs one monolithic exchange.  Numerically neutral
            # but interacts with bucket_size (more buckets = finer overlap,
            # more collective launches), so it is worth co-tuning.
            params.append(BoolParam("overlap"))
        self._size_param = params[0]
        self.optimizer = BayesianOptimizer(params)
        self.sampling_counter = 0
        self.best_score = float("-inf")
        self.best_hyperparameter = self.hyperparameter
        self.tensor_partial_order: Dict[str, int] = {}
        # -- trace-driven planner state --------------------------------------
        self.planner_mode = planner_mode or get_autotune_planner_mode()
        self.planner: Optional[BucketPlanner] = None
        self.tensor_arrivals: Dict[str, float] = {}
        self.wire_samples: List[WireSample] = []
        self._intra_size = 1
        self._world_size = 1
        #: convergence guardrail for the quantized-ring wire: only precisions
        #: that passed the loss-parity gate (``ci/perf_audit.py`` ``--wire``
        #: lane, or an operator override) may be chosen per bucket.  "f32"
        #: alone = never quantize, the safe default.
        self.precision_allow_list: List[str] = ["f32"]
        #: the full planner decision record, surfaced over the
        #: ``planner_trail`` endpoint and into ``AUTOTUNE_RUN.json``
        self.decision_trail: Dict = {
            "mode": self.planner_mode,
            "spans_reported": False,
            "cost_model": None,
            "overlap_efficiency": None,
            "candidates": [],
            "warm_start": [],
            "dp_plan": None,
            "greedy_plan": None,
            "precision_plan": None,
            "proposals": [],
            "chosen": None,
        }
        self._log_path = (
            f"/tmp/bagua_autotune_{model_name}_{int(time.time())}.log"
            if is_output_autotune_log
            else None
        )

    # -- bucket computation ---------------------------------------------

    def ordered_tensor_list(self) -> List[TensorDeclaration]:
        if not self.tensor_partial_order:
            return self.tensor_list
        order = self.tensor_partial_order
        return sorted(self.tensor_list, key=lambda td: order.get(td.name, 1 << 30))

    def recommended_from_param_dict(self, param_dict: Dict[str, int]) -> BaguaHyperparameter:
        bucket_size = (1 << int(param_dict["bucket_size_2p"]))
        hierarchical = bool(param_dict["is_hierarchical_reduce"])
        predicted_ms: Optional[float] = None
        if self.planner_mode == "on" and self.planner is not None:
            # DP-optimal contiguous partition under the proposed size cap —
            # the BO keeps tuning bucket_size, but *within* each cap the
            # split is trace-optimal instead of greedy byte-threshold.
            res = self.planner.plan(
                max_bucket_bytes=bucket_size, hierarchical=hierarchical
            )
            buckets = res.buckets
            predicted_ms = round(res.predicted_exposed_s * 1e3, 4)
        else:
            decls = self.ordered_tensor_list()
            shapes = {td.name: (td.num_elements,) for td in decls}
            specs = split_declarations(decls, shapes, bucket_size)
            buckets = [spec.declarations() for spec in specs]
            if self.planner is not None:
                predicted_ms = round(
                    self.planner.evaluate(buckets, hierarchical).predicted_exposed_s
                    * 1e3,
                    4,
                )
        hp = BaguaHyperparameter(
            buckets=buckets,
            bucket_size=bucket_size,
            is_hierarchical_reduce=hierarchical,
            # None = dimension not tuned; the client must not touch a
            # user-configured wire dtype in that case
            wire_bf16=bool(param_dict.get("wire_bf16", 0)) if self.tune_wire_dtype else None,
            overlap=bool(param_dict.get("overlap", 0)) if self.tune_overlap else None,
            predicted_exposed_ms=predicted_ms,
        )
        if self.planner is not None:
            record = {
                "param_dict": {k: int(v) for k, v in param_dict.items()},
                "n_buckets": len(buckets),
                "predicted_exposed_ms": predicted_ms,
            }
            self.decision_trail["proposals"].append(record)
            self.decision_trail["chosen"] = record
        return hp

    # -- optimizer loop ----------------------------------------------------

    def tell_and_ask(
        self,
        score: float,
        train_iter: int,
        measured_hp: Optional[BaguaHyperparameter] = None,
    ) -> BaguaHyperparameter:
        """Record the score of the measured hyperparameters and propose new ones.

        ``measured_hp`` is the configuration the score was actually observed
        under.  With the effective-from history, proposals reach workers one
        ask-round late, so the service passes the hp in force at
        ``train_iter`` — crediting ``self.hyperparameter`` (the newest
        proposal) would shift every score onto the *next* sample and the
        optimizer would converge on a point one step away from the optimum."""
        measured = measured_hp or self.hyperparameter
        current = {
            "bucket_size_2p": max(10, measured.bucket_size.bit_length() - 1),
            "is_hierarchical_reduce": int(measured.is_hierarchical_reduce),
        }
        if self.tune_wire_dtype:
            current["wire_bf16"] = int(bool(measured.wire_bf16))
        if self.tune_overlap:
            current["overlap"] = int(bool(measured.overlap))
        self.optimizer.tell(current, score)
        self.sampling_counter += 1
        if score > self.best_score:
            self.best_score = score
            self.best_hyperparameter = measured
        if self._log_path:
            with open(self._log_path, "a") as f:
                f.write(f"{train_iter},{current},{score}\n")
        proposal = self.optimizer.ask()
        self.hyperparameter = self.recommended_from_param_dict(proposal)
        return self.hyperparameter

    def lock_best(self) -> BaguaHyperparameter:
        self.hyperparameter = self.best_hyperparameter
        return self.hyperparameter

    # -- execution-order learning -------------------------------------------

    def report_spans(self, spans: List[Dict]) -> None:
        """Distill tensor order AND planner inputs from reported spans.

        ``tensor_ready`` spans (reference ``autotune_service.py:274-294``
        consumes OTel spans; here any ordered (name, start) record works)
        give the partial order *and* the measured arrival times;
        ``bucket_wire`` spans (``SpanRecorder.record_wire_timings``) carry
        measured per-bucket wire seconds, bytes, leg tags and hidden
        fractions for the α–β cost model."""
        ready = [
            (s["start_time"], s["tensor_name"])
            for s in spans
            if s.get("action") == "tensor_ready" and "tensor_name" in s
        ]
        for i, (_, name) in enumerate(sorted(ready)):
            self.tensor_partial_order[name] = i
        for start, name in ready:
            self.tensor_arrivals[name] = float(start)
        for s in spans:
            if s.get("action") != "bucket_wire":
                continue
            try:
                self.wire_samples.append(
                    WireSample(
                        nbytes=float(s["nbytes"]),
                        seconds=float(s["seconds"]),
                        leg=str(s.get("leg", "flat")),
                        hidden_frac=(
                            float(s["hidden_frac"])
                            if s.get("hidden_frac") is not None
                            else None
                        ),
                    )
                )
            except (KeyError, TypeError, ValueError):
                logger.warning("ignoring malformed bucket_wire span: %r", s)
            if s.get("intra_size"):
                self._intra_size = max(1, int(s["intra_size"]))
            if s.get("world_size"):
                self._world_size = max(1, int(s["world_size"]))
        if ready or any(s.get("action") == "bucket_wire" for s in spans):
            self._refresh_planner()

    # -- planner integration --------------------------------------------------

    def set_precision_allow_list(self, allowed: List[str]) -> None:
        """Install the convergence-guardrail allow-list (the precisions the
        loss-parity gate certified) and refresh the precision plan in the
        decision trail if a planner is already live."""
        allow = sorted({"f32"} | set(allowed))
        unknown = set(allow) - {"f32", "int8", "int4"}
        if unknown:
            raise ValueError(f"unknown wire precisions: {sorted(unknown)}")
        self.precision_allow_list = allow
        if self.planner is not None:
            self._refresh_precision_plan()

    def _refresh_precision_plan(self) -> None:
        """Re-choose per-bucket wire precision over the DP partition at the
        live bucket-size cap and record it (allow-list included) in the
        decision trail."""
        dp = self.planner.plan(max_bucket_bytes=self.hyperparameter.bucket_size)
        self.decision_trail["precision_plan"] = self.planner.plan_precision(
            dp.buckets,
            n_ranks=self._world_size,
            allowed=self.precision_allow_list,
        )

    def _overlap_efficiency(self) -> float:
        """Aggregate measured overlap fraction across wire samples (η in the
        planner's exposed-time objective); 1.0 when nothing was measured —
        trust the latency-hiding scheduler until the trace says otherwise."""
        num = den = 0.0
        for s in self.wire_samples:
            if s.hidden_frac is not None and s.seconds > 0:
                num += s.hidden_frac * s.seconds
                den += s.seconds
        return num / den if den else 1.0

    def _refresh_planner(self) -> None:
        if self.planner_mode == "off" or not self.tensor_arrivals or not self.tensor_list:
            return
        cost_model = CostModel.from_samples(self.wire_samples, intra_size=self._intra_size)
        eta = self._overlap_efficiency()
        self.planner = BucketPlanner(
            self.tensor_list,
            self.tensor_arrivals,
            cost_model=cost_model,
            overlap_efficiency=eta,
        )
        trail = self.decision_trail
        trail["spans_reported"] = True
        trail["cost_model"] = cost_model.describe()
        trail["overlap_efficiency"] = round(eta, 4)
        # Rank the BO's bucket_size grid by the planner's predicted exposed
        # time and warm-start with the top-k (k = the optimizer's initial
        # sampling budget) — replacing the cold grid walk with measured-span
        # proposals, VERBATIM the points BO would otherwise burn recompiles
        # discovering.
        size = self._size_param
        candidates = self.planner.rank_caps(range(size.low, size.high + 1))
        trail["candidates"] = candidates[:16]
        warm = []
        for cand in candidates[: self.optimizer.n_initial_points]:
            point = {
                "bucket_size_2p": cand["bucket_size_2p"],
                "is_hierarchical_reduce": cand["is_hierarchical_reduce"],
            }
            if self.tune_wire_dtype:
                point["wire_bf16"] = int(bool(self.hyperparameter.wire_bf16))
            if self.tune_overlap:
                # the planner's objective is overlap-aware; propose overlap on
                point["overlap"] = 1
            warm.append(point)
        self.optimizer.warm_start(warm)
        trail["warm_start"] = warm
        # Record the unconstrained DP optimum and the seed greedy plan's
        # predicted cost — the decision the CI gate audits.
        dp = self.planner.plan()
        trail["dp_plan"] = dp.summary()
        self._refresh_precision_plan()
        decls = self.ordered_tensor_list()
        shapes = {td.name: (td.num_elements,) for td in decls}
        greedy_specs = split_declarations(decls, shapes, self.hyperparameter.bucket_size)
        greedy = self.planner.evaluate([s.declarations() for s in greedy_specs])
        trail["greedy_plan"] = greedy.summary()
        logger.info(
            "planner[%s] refreshed: dp %s vs greedy %s (eta=%.3f)",
            self.model_name, trail["dp_plan"], trail["greedy_plan"], eta,
        )
