"""Per-model autotune state machine.

Analog of the reference's ``AutotuneServiceTaskManager``
(``service/autotune_task_manager.py``): owns the Bayesian optimizer over
``bucket_size_2p ∈ [10, 31]`` × ``is_hierarchical_reduce``, the greedy
dtype-grouped bucket split, and the tensor re-ordering learned from reported
execution order.
"""

import logging
import time
from typing import Dict, List, Optional

from bagua_tpu.bucket import split_declarations
from bagua_tpu.defs import BaguaHyperparameter, TensorDeclaration
from bagua_tpu.service.bayesian_optimizer import BayesianOptimizer, BoolParam, IntParam

logger = logging.getLogger(__name__)


class AutotuneTaskManager:
    def __init__(
        self,
        model_name: str,
        is_output_autotune_log: bool = False,
        tune_wire_dtype: bool = False,
        tune_overlap: bool = False,
    ):
        self.model_name = model_name
        self.tensor_list: List[TensorDeclaration] = []
        self.hyperparameter = BaguaHyperparameter()
        self.tune_wire_dtype = tune_wire_dtype
        self.tune_overlap = tune_overlap
        params = [IntParam("bucket_size_2p", 10, 31), BoolParam("is_hierarchical_reduce")]
        if tune_wire_dtype:
            # opt-in third dimension: bf16 wire exchange trades ~3 decimal
            # digits of gradient mantissa for half the allreduce bytes —
            # a numerics-affecting knob, so never explored silently
            params.append(BoolParam("wire_bf16"))
        if tune_overlap:
            # execution-mode dimension: backward-overlapped per-bucket
            # collectives vs one monolithic exchange.  Numerically neutral
            # but interacts with bucket_size (more buckets = finer overlap,
            # more collective launches), so it is worth co-tuning.
            params.append(BoolParam("overlap"))
        self.optimizer = BayesianOptimizer(params)
        self.sampling_counter = 0
        self.best_score = float("-inf")
        self.best_hyperparameter = self.hyperparameter
        self.tensor_partial_order: Dict[str, int] = {}
        self._log_path = (
            f"/tmp/bagua_autotune_{model_name}_{int(time.time())}.log"
            if is_output_autotune_log
            else None
        )

    # -- bucket computation ---------------------------------------------

    def ordered_tensor_list(self) -> List[TensorDeclaration]:
        if not self.tensor_partial_order:
            return self.tensor_list
        order = self.tensor_partial_order
        return sorted(self.tensor_list, key=lambda td: order.get(td.name, 1 << 30))

    def recommended_from_param_dict(self, param_dict: Dict[str, int]) -> BaguaHyperparameter:
        bucket_size = (1 << int(param_dict["bucket_size_2p"]))
        decls = self.ordered_tensor_list()
        shapes = {td.name: (td.num_elements,) for td in decls}
        specs = split_declarations(decls, shapes, bucket_size)
        buckets = [spec.declarations() for spec in specs]
        return BaguaHyperparameter(
            buckets=buckets,
            bucket_size=bucket_size,
            is_hierarchical_reduce=bool(param_dict["is_hierarchical_reduce"]),
            # None = dimension not tuned; the client must not touch a
            # user-configured wire dtype in that case
            wire_bf16=bool(param_dict.get("wire_bf16", 0)) if self.tune_wire_dtype else None,
            overlap=bool(param_dict.get("overlap", 0)) if self.tune_overlap else None,
        )

    # -- optimizer loop ----------------------------------------------------

    def tell_and_ask(self, score: float, train_iter: int) -> BaguaHyperparameter:
        """Record the score of the current hyperparameters and propose new ones."""
        current = {
            "bucket_size_2p": max(10, self.hyperparameter.bucket_size.bit_length() - 1),
            "is_hierarchical_reduce": int(self.hyperparameter.is_hierarchical_reduce),
        }
        if self.tune_wire_dtype:
            current["wire_bf16"] = int(bool(self.hyperparameter.wire_bf16))
        if self.tune_overlap:
            current["overlap"] = int(bool(self.hyperparameter.overlap))
        self.optimizer.tell(current, score)
        self.sampling_counter += 1
        if score > self.best_score:
            self.best_score = score
            self.best_hyperparameter = self.hyperparameter
        if self._log_path:
            with open(self._log_path, "a") as f:
                f.write(f"{train_iter},{current},{score}\n")
        proposal = self.optimizer.ask()
        self.hyperparameter = self.recommended_from_param_dict(proposal)
        return self.hyperparameter

    def lock_best(self) -> BaguaHyperparameter:
        self.hyperparameter = self.best_hyperparameter
        return self.hyperparameter

    # -- execution-order learning -------------------------------------------

    def report_spans(self, spans: List[Dict]) -> None:
        """Distill a tensor partial order from (tensor_name, start_time) spans
        (reference ``autotune_service.py:274-294`` consumes OTel spans; here
        any ordered (name, start) record works)."""
        ready = [
            (s["start_time"], s["tensor_name"])
            for s in spans
            if s.get("action") == "tensor_ready" and "tensor_name" in s
        ]
        for i, (_, name) in enumerate(sorted(ready)):
            self.tensor_partial_order[name] = i
