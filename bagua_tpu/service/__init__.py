"""Hyperparameter (autotune) service.

TPU-native analog of the reference's ``bagua/service/`` tier: a rank-0 HTTP
service searching over communication hyperparameters (bucket size,
hierarchical reduction) to maximize reported training speed.  The reference
uses Flask + gevent + scikit-optimize; this build uses the Python stdlib
HTTP server and a small numpy Gaussian-process Bayesian optimizer, keeping
the same REST API surface (``register_tensors`` / ``report_metrics`` /
``ask_hyperparameters`` / ``report_tensor_execution_order`` /
``health_check``, reference ``service/autotune_service.py:154-298``).
"""

from bagua_tpu.service.autotune_service import (  # noqa: F401
    AutotuneService,
    start_autotune_server,
)
from bagua_tpu.service.autotune_client import (  # noqa: F401
    AutotuneClient,
    get_hyperparameters_service_client,
)
from bagua_tpu.service.bayesian_optimizer import (  # noqa: F401
    IntParam,
    BoolParam,
    BayesianOptimizer,
)
from bagua_tpu.service.planner import (  # noqa: F401
    AlphaBeta,
    BucketPlanner,
    CostModel,
    PlanResult,
    WireSample,
    fit_alpha_beta,
)
